"""Antrea flow-record schema, as a columnar/tensor-friendly definition.

This is the L1 data contract of the framework: the same logical schema the
reference defines as ClickHouse DDL (reference:
build/charts/theia/provisioning/datasources/create_table.sh:31-84 declares the
`flows_local` table; :363-384 declares `tadetector_local`; :353-360 declares
`recommendations_local`).

Design notes (TPU-first):
  * Every column maps onto a fixed-width numpy/JAX dtype so a batch of flow
    records is a struct-of-arrays that can be `device_put` as-is.
  * DateTime columns are int64 unix seconds (ClickHouse DateTime is a 32-bit
    epoch; we keep 64-bit on host, and cast to int32/float32 on device only
    where safe).
  * String columns are dictionary-encoded: the store owns one
    `StringDictionary` per string column and batches carry int32 codes.
    This is what makes string group-bys (pod labels, namespaces) expressible
    as integer segment reductions on device.
"""

from __future__ import annotations

import dataclasses
import enum

import numpy as np


class ColumnKind(enum.Enum):
    DATETIME = "datetime"  # int64 unix seconds
    U8 = "u8"
    U16 = "u16"
    U64 = "u64"
    F64 = "f64"
    STRING = "string"      # dictionary-encoded int32 code


_HOST_DTYPES = {
    ColumnKind.DATETIME: np.int64,
    ColumnKind.U8: np.int32,
    ColumnKind.U16: np.int32,
    ColumnKind.U64: np.int64,
    ColumnKind.F64: np.float64,
    ColumnKind.STRING: np.int32,
}

_CLICKHOUSE_TYPES = {
    ColumnKind.DATETIME: "DateTime",
    ColumnKind.U8: "UInt8",
    ColumnKind.U16: "UInt16",
    ColumnKind.U64: "UInt64",
    ColumnKind.F64: "Float64",
    ColumnKind.STRING: "String",
}


@dataclasses.dataclass(frozen=True)
class Column:
    name: str
    kind: ColumnKind

    @property
    def host_dtype(self):
        return _HOST_DTYPES[self.kind]

    @property
    def clickhouse_type(self) -> str:
        return _CLICKHOUSE_TYPES[self.kind]

    @property
    def is_string(self) -> bool:
        return self.kind == ColumnKind.STRING


def _cols(*specs) -> tuple:
    return tuple(Column(name, kind) for name, kind in specs)


K = ColumnKind

# The `flows` table — same 52 logical columns as the reference's flows_local
# (create_table.sh:31-84), in declaration order.
FLOW_SCHEMA: tuple = _cols(
    ("timeInserted", K.DATETIME),
    ("flowStartSeconds", K.DATETIME),
    ("flowEndSeconds", K.DATETIME),
    ("flowEndSecondsFromSourceNode", K.DATETIME),
    ("flowEndSecondsFromDestinationNode", K.DATETIME),
    ("flowEndReason", K.U8),
    ("sourceIP", K.STRING),
    ("destinationIP", K.STRING),
    ("sourceTransportPort", K.U16),
    ("destinationTransportPort", K.U16),
    ("protocolIdentifier", K.U8),
    ("packetTotalCount", K.U64),
    ("octetTotalCount", K.U64),
    ("packetDeltaCount", K.U64),
    ("octetDeltaCount", K.U64),
    ("reversePacketTotalCount", K.U64),
    ("reverseOctetTotalCount", K.U64),
    ("reversePacketDeltaCount", K.U64),
    ("reverseOctetDeltaCount", K.U64),
    ("sourcePodName", K.STRING),
    ("sourcePodNamespace", K.STRING),
    ("sourceNodeName", K.STRING),
    ("destinationPodName", K.STRING),
    ("destinationPodNamespace", K.STRING),
    ("destinationNodeName", K.STRING),
    ("destinationClusterIP", K.STRING),
    ("destinationServicePort", K.U16),
    ("destinationServicePortName", K.STRING),
    ("ingressNetworkPolicyName", K.STRING),
    ("ingressNetworkPolicyNamespace", K.STRING),
    ("ingressNetworkPolicyRuleName", K.STRING),
    ("ingressNetworkPolicyRuleAction", K.U8),
    ("ingressNetworkPolicyType", K.U8),
    ("egressNetworkPolicyName", K.STRING),
    ("egressNetworkPolicyNamespace", K.STRING),
    ("egressNetworkPolicyRuleName", K.STRING),
    ("egressNetworkPolicyRuleAction", K.U8),
    ("egressNetworkPolicyType", K.U8),
    ("tcpState", K.STRING),
    ("flowType", K.U8),
    ("sourcePodLabels", K.STRING),
    ("destinationPodLabels", K.STRING),
    ("throughput", K.U64),
    ("reverseThroughput", K.U64),
    ("throughputFromSourceNode", K.U64),
    ("throughputFromDestinationNode", K.U64),
    ("reverseThroughputFromSourceNode", K.U64),
    ("reverseThroughputFromDestinationNode", K.U64),
    ("clusterUUID", K.STRING),
    ("egressName", K.STRING),
    ("egressIP", K.STRING),
    ("trusted", K.U8),
)

FLOW_COLUMNS = tuple(c.name for c in FLOW_SCHEMA)
STRING_COLUMNS = tuple(c.name for c in FLOW_SCHEMA if c.is_string)
NUMERIC_COLUMNS = tuple(c.name for c in FLOW_SCHEMA if not c.is_string)

_BY_NAME = {c.name: c for c in FLOW_SCHEMA}


def column(name: str) -> Column:
    return _BY_NAME[name]


# Result table for throughput anomaly detection — matches the reference's
# tadetector_local (create_table.sh:363-384).
TADETECTOR_SCHEMA: tuple = _cols(
    ("sourceIP", K.STRING),
    ("sourceTransportPort", K.U16),
    ("destinationIP", K.STRING),
    ("destinationTransportPort", K.U16),
    ("protocolIdentifier", K.U16),
    ("flowStartSeconds", K.DATETIME),
    ("podNamespace", K.STRING),
    ("podLabels", K.STRING),
    ("podName", K.STRING),
    ("destinationServicePortName", K.STRING),
    ("direction", K.STRING),
    ("flowEndSeconds", K.DATETIME),
    ("throughputStandardDeviation", K.F64),
    ("aggType", K.STRING),
    ("algoType", K.STRING),
    ("algoCalc", K.F64),
    ("throughput", K.F64),
    ("anomaly", K.STRING),
    # Effective ARIMA refit cadence the job ran with (1 = the
    # reference's exact refit-per-step, k>1 = grouped-refit
    # approximation). 0 = no cadence recorded: non-ARIMA rows, or
    # ARIMA rows migrated from pre-v5 stores (disambiguate via
    # algoType). Extension beyond the reference schema so the
    # approximation is observable in results.
    ("refitEvery", K.U64),
    ("id", K.STRING),
)

# Result table for NetworkPolicy recommendation — matches the reference's
# recommendations_local (create_table.sh:353-360).
RECOMMENDATIONS_SCHEMA: tuple = _cols(
    ("id", K.STRING),
    ("type", K.STRING),
    ("timeCreated", K.DATETIME),
    ("policy", K.STRING),
    ("kind", K.STRING),
)

# Result table for abnormal traffic-drop detection — the capability the
# reference ships only on its Snowflake backend (UDTF result row at
# snowflake/udfs/udfs/drop_detection/drop_detection_udf.py:6-19; query
# shape snowflake/cmd/dropDetection.go:36-175).
DROPDETECTION_SCHEMA: tuple = _cols(
    ("jobType", K.STRING),
    ("id", K.STRING),
    ("timeCreated", K.DATETIME),
    ("endpoint", K.STRING),
    ("direction", K.STRING),
    ("avgDrop", K.F64),
    ("stdevDrop", K.F64),
    ("anomalyDropDate", K.DATETIME),
    ("anomalyDropNumber", K.U64),
)

# Result table for frequent flow-pattern mining (analytics/itemsets.py;
# the BASELINE north-star FP-Growth config). `items` is the itemset as
# "column=value|column=value" (the #/| delimiter convention the NPR
# peer strings use). No reference table: the reference has no itemset
# mining.
FLOWPATTERNS_SCHEMA: tuple = _cols(
    ("id", K.STRING),
    ("timeCreated", K.DATETIME),
    ("items", K.STRING),
    ("itemsetLength", K.U8),
    ("support", K.U64),
)

# Result table for spatial DBSCAN anomaly detection
# (analytics/spatial.py; BASELINE north-star config 3): one row per
# noise flow — a flow outside every recurring traffic pattern.
SPATIALNOISE_SCHEMA: tuple = _cols(
    ("id", K.STRING),
    ("timeCreated", K.DATETIME),
    ("sourceIP", K.STRING),
    ("destinationIP", K.STRING),
    ("destinationTransportPort", K.U16),
    ("octetDeltaCount", K.U64),
)

# Durable (cold) tier of the detector's flow-state working-set store
# (ingest/state_tier.py): one row per spilled connection series, the
# StreamState fields plus a restart-stable identity. The connection
# 6-tuple is stored with STRING IPs — dictionary codes are not stable
# across restarts — and `keyHash` (64-bit BLAKE2b of the resolved
# tuple) is the recovery index key; `seq` disambiguates re-spills of
# the same key (latest wins on read, older rows are prunable).
DETSTATE_SCHEMA: tuple = _cols(
    ("sourceIP", K.STRING),
    ("destinationIP", K.STRING),
    ("sourceTransportPort", K.U16),
    ("destinationTransportPort", K.U16),
    ("protocolIdentifier", K.U16),
    ("flowStartSeconds", K.DATETIME),
    ("ewma", K.F64),
    ("mean", K.F64),
    ("m2", K.F64),
    ("count", K.U64),
    ("seq", K.U64),
    ("keyHash", K.U64),
    ("timeSpilled", K.DATETIME),
)

#: the one authoritative name of the self-scraped metrics history
#: table — the store registers it, the planner resolves it, and the
#: scrape loop writes it, all from this constant
METRICS_TABLE = "__metrics__"

#: scale factor for metric values stored in the `__metrics__` table:
#: the query plane aggregates in exact int64, so float samples
#: (histogram sums in seconds, fractional gauges) are stored as
#: micro-units — `round(value * 1e6)` — and consumers divide back.
METRICS_VALUE_SCALE = 1_000_000

# The `__metrics__` table: the process's own Prometheus registry as
# stored time series (the role Grafana-over-ClickHouse history plays
# in the reference — dashboards query the store, never live scrapes).
# One row per series sample per scrape tick, Prometheus exposition
# naming: counters under their declared name, histograms as
# `<name>_bucket` (le in `labels`) / `<name>_sum` / `<name>_count`.
# Rows at coarser `resolution` are the downsampler's rollups: `value`
# is the LAST sample in the bucket (cumulative counters stay exact),
# and valueMin/Max/Sum/Count fold the raw samples exactly, so
# min/max/sum/count aggregations over a window are bit-identical
# whether they scan raw 15s points or rollup parts.
METRICS_SCHEMA: tuple = _cols(
    ("timeInserted", K.DATETIME),   # sample (bucket-start) time
    ("metric", K.STRING),           # exposition series name
    ("labels", K.STRING),           # sorted `k=v,k=v` (incl. `le`)
    ("node", K.STRING),             # recording node id ('' standalone)
    ("kind", K.STRING),             # counter|gauge|sum|count|bucket
    ("resolution", K.U64),          # seconds per sample bucket
    ("value", K.U64),               # last sample, micro-units
    ("valueMin", K.U64),            # exact folds over the raw samples
    ("valueMax", K.U64),
    ("valueSum", K.U64),
    ("valueCount", K.U64),
)
