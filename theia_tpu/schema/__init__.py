from .flow_schema import (  # noqa: F401
    Column,
    ColumnKind,
    FLOW_SCHEMA,
    FLOW_COLUMNS,
    STRING_COLUMNS,
    NUMERIC_COLUMNS,
    TADETECTOR_SCHEMA,
    RECOMMENDATIONS_SCHEMA,
    DROPDETECTION_SCHEMA,
    FLOWPATTERNS_SCHEMA,
    SPATIALNOISE_SCHEMA,
    DETSTATE_SCHEMA,
    METRICS_SCHEMA,
    METRICS_TABLE,
    METRICS_VALUE_SCALE,
)
from .columnar import (  # noqa: F401
    ColumnarBatch,
    DictionaryMapper,
    StringDictionary,
)
