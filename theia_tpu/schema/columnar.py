"""Columnar batch representation: struct-of-arrays with dictionary-encoded
strings.

A `ColumnarBatch` is the unit of data movement through the framework: the
ingest path produces them, the store accumulates them, and the analytics jobs
slice/stack them into device tensors. All columns are fixed-width numpy
arrays of equal length, so a batch (or any column subset of it) can be
`jax.device_put` without copies or Python-object traversal.

The reference moves rows as ClickHouse result sets / Spark DataFrames; here
the equivalent contract is "int32 codes + per-column StringDictionary"
(reference behavior: string group-bys over e.g. sourcePodLabels in
plugins/anomaly-detection/anomaly_detection.py:118-137 and
plugins/policy-recommendation/policy_recommendation_job.py map steps).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Mapping, Optional, Sequence

import numpy as np
from ..analysis.lockdep import named_lock


class StringDictionary:
    """Append-only string↔int32 dictionary.

    Code 0 is always the empty string, matching ClickHouse's String default
    and the reference's pervasive `== ''` predicates (e.g. the unprotected
    flow filter in policy_recommendation_job.py:785-802).
    """

    __slots__ = ("_to_code", "_strings", "_lock")

    def __init__(self) -> None:
        self._to_code: Dict[str, int] = {"": 0}
        self._strings: List[str] = [""]
        self._lock = named_lock("schema.dict")

    def __len__(self) -> int:
        return len(self._strings)

    def encode_one(self, s: str) -> int:
        # Reads are lock-free (append-only tables); allocation of a new
        # code is locked so concurrent encoders can't mint two codes for
        # the same string (the tables share these dictionaries across
        # insert threads).
        code = self._to_code.get(s)
        if code is None:
            with self._lock:
                code = self._to_code.get(s)
                if code is None:
                    code = len(self._strings)
                    self._strings.append(s)
                    self._to_code[s] = code
        return code

    def encode(self, values: Sequence[str]) -> np.ndarray:
        """Vectorized encode: dedupe first so the Python loop only touches
        unique values (cheap for the low-cardinality k8s-identity columns)."""
        arr = np.asarray(values, dtype=object)
        uniques, inverse = np.unique(arr, return_inverse=True)
        codes_for_uniques = np.fromiter(
            (self.encode_one(u) for u in uniques), dtype=np.int32,
            count=len(uniques))
        return codes_for_uniques[inverse].astype(np.int32)

    def decode_one(self, code: int) -> str:
        return self._strings[int(code)]

    def decode(self, codes: np.ndarray) -> np.ndarray:
        table = np.asarray(self._strings, dtype=object)
        return table[np.asarray(codes, dtype=np.int64)]

    def lookup(self, s: str) -> Optional[int]:
        """Code for `s` if present, else None (never allocates)."""
        return self._to_code.get(s)

    def entries_since(self, start: int) -> List[str]:
        """Snapshot of entries [start:), in code order — for replaying
        deltas into a peer dictionary (native decoder, wire blocks)."""
        with self._lock:
            return list(self._strings[start:])

    def copy(self) -> "StringDictionary":
        """Independent copy (same codes for existing strings)."""
        out = StringDictionary()
        with self._lock:
            out._strings = list(self._strings)
            out._to_code = dict(self._to_code)
        return out


class DictionaryMapper:
    """Cached int32 code remap from source dictionaries onto one
    destination dictionary.

    The hot-path alternative to re-encoding strings row-by-row: per
    source dictionary, keep an int32 array mapping its codes to the
    destination's, extended only for entries minted since the last
    call — amortized O(new dictionary entries), zero string work for
    a steady population. Entries hold a strong reference to their
    source dictionary so an id() can never be recycled while its
    mapping is cached; a bounded LRU evicts mappings orphaned by
    producer resets. NOT thread-safe: callers serialize (the ingest
    detector lock, the table adoption lock).
    """

    def __init__(self, dst: StringDictionary,
                 max_entries: int = 128) -> None:
        self.dst = dst
        self.max_entries = max_entries
        self._maps: Dict[int, tuple] = {}   # id(src) → (src, mapping)

    def mapping(self, src: StringDictionary) -> np.ndarray:
        entry = self._maps.pop(id(src), None)
        if entry is None or entry[0] is not src:
            if len(self._maps) >= self.max_entries:
                # Every lookup re-inserts its key (pop above + insert
                # below), so insertion order IS recency order: the
                # front of the dict holds the coldest entries.
                for stale in list(self._maps)[:self.max_entries // 2]:
                    del self._maps[stale]
            entry = (src, np.zeros(0, np.int32))
        src_ref, mapping = entry
        if len(mapping) < len(src):
            new = np.fromiter(
                (self.dst.encode_one(s)
                 for s in src.entries_since(len(mapping))),
                dtype=np.int32)
            mapping = np.concatenate([mapping, new])
        self._maps[id(src)] = (src_ref, mapping)
        return mapping

    def remap(self, codes: np.ndarray,
              src: StringDictionary) -> np.ndarray:
        if src is self.dst:
            return np.asarray(codes, np.int32)
        return self.mapping(src)[np.asarray(codes, np.int64)]


class ColumnarBatch:
    """Equal-length struct-of-arrays with an associated dictionary set.

    `dicts` maps string-column name → StringDictionary used to encode that
    column. Dictionaries are shared by reference (typically owned by the
    FlowStore) so codes are comparable across batches.
    """

    def __init__(self, columns: Mapping[str, np.ndarray],
                 dicts: Optional[Mapping[str, StringDictionary]] = None):
        lengths = {len(v) for v in columns.values()}
        if len(lengths) > 1:
            raise ValueError(f"ragged columns: lengths {sorted(lengths)}")
        self.columns: Dict[str, np.ndarray] = dict(columns)
        self.dicts: Dict[str, StringDictionary] = dict(dicts or {})

    def __len__(self) -> int:
        if not self.columns:
            return 0
        return len(next(iter(self.columns.values())))

    def __getitem__(self, name: str) -> np.ndarray:
        return self.columns[name]

    def __contains__(self, name: str) -> bool:
        return name in self.columns

    @property
    def column_names(self) -> Iterable[str]:
        return self.columns.keys()

    def strings(self, name: str) -> np.ndarray:
        """Decode a dictionary-encoded column back to python strings."""
        return self.dicts[name].decode(self.columns[name])

    def take(self, indices: np.ndarray) -> "ColumnarBatch":
        return ColumnarBatch(
            {k: v[indices] for k, v in self.columns.items()}, self.dicts)

    def filter(self, mask: np.ndarray) -> "ColumnarBatch":
        return ColumnarBatch(
            {k: v[mask] for k, v in self.columns.items()}, self.dicts)

    def select(self, names: Sequence[str]) -> "ColumnarBatch":
        return ColumnarBatch({n: self.columns[n] for n in names},
                             {n: d for n, d in self.dicts.items()
                              if n in names})

    def column_selector(self, mask: np.ndarray, dtype=np.int64):
        """Narrow-column masked materializer: `col(name)` returns one
        column under `mask`, skipping the copy when the mask is all-true.
        The query paths use this instead of `filter(mask)` because
        masking all 52 columns costs more than the kernel the handful of
        surviving columns feed."""
        full = bool(mask.all())

        def col(name: str) -> np.ndarray:
            arr = np.asarray(self.columns[name], dtype)
            return arr if full else arr[mask]

        return col

    @staticmethod
    def concat(batches: Sequence["ColumnarBatch"]) -> "ColumnarBatch":
        """Concatenate batches. String columns encoded with *different*
        dictionaries are re-encoded against a merged dictionary (codes are
        only comparable when the dictionary object is shared)."""
        if not batches:
            return ColumnarBatch({})
        names = list(batches[0].column_names)
        dicts: Dict[str, StringDictionary] = {}
        cols: Dict[str, np.ndarray] = {}
        for n in names:
            parts = [b[n] for b in batches]
            col_dicts = [b.dicts.get(n) for b in batches]
            present = [d for d in col_dicts if d is not None]
            if present and any(d is not present[0] for d in present):
                # Mixed dictionaries: remap every batch's codes into a
                # fresh copy of the first batch's dictionary (codes it
                # already issued stay stable; the originals — possibly
                # store-owned — are left unmutated).
                merged = present[0].copy()
                remapped = []
                for part, d in zip(parts, col_dicts):
                    if d is None or d is merged:
                        remapped.append(part)
                        continue
                    mapping = np.fromiter(
                        (merged.encode_one(s) for s in d._strings),
                        dtype=np.int32, count=len(d))
                    remapped.append(mapping[np.asarray(part, np.int64)])
                parts = remapped
                dicts[n] = merged
            elif present:
                dicts[n] = present[0]
            cols[n] = np.concatenate(parts)
        return ColumnarBatch(cols, dicts)

    @staticmethod
    def from_rows(rows: Sequence[Mapping[str, object]], schema,
                  dicts: Optional[Mapping[str, StringDictionary]] = None
                  ) -> "ColumnarBatch":
        """Build a batch from row dicts against a schema (tuple of Column).

        Missing values take the column default (0 / empty string)."""
        dicts = dict(dicts or {})
        cols: Dict[str, np.ndarray] = {}
        for col in schema:
            if col.is_string:
                d = dicts.setdefault(col.name, StringDictionary())
                values = [str(r.get(col.name, "")) for r in rows]
                cols[col.name] = d.encode(values) if rows else np.zeros(
                    0, np.int32)
            else:
                cols[col.name] = np.asarray(
                    [r.get(col.name, 0) for r in rows], dtype=col.host_dtype)
        return ColumnarBatch(cols, dicts)

    def to_rows(self, schema=None) -> List[Dict[str, object]]:
        """Materialize python row dicts (decoding strings). Test/CLI helper —
        not a hot path."""
        names = list(self.column_names)
        decoded = {
            n: (self.strings(n) if n in self.dicts else self.columns[n])
            for n in names}
        out = []
        for i in range(len(self)):
            out.append({n: (decoded[n][i].item()
                            if isinstance(decoded[n][i], np.generic)
                            else decoded[n][i]) for n in names})
        return out
