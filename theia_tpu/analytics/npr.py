"""NetworkPolicy Recommendation (NPR) job.

Re-provides plugins/policy-recommendation/policy_recommendation_job.py:
read distinct unprotected (or trusted-denied) flow 9-tuples from the
store, classify them (pod_to_pod / pod_to_svc / pod_to_external,
get_flow_type :83-91), aggregate ingress/egress network peers per
appliedTo group (the reference's RDD map/reduceByKey pipeline :621-712),
and emit policy YAML for the three isolation options
(recommend_policies_for_unprotected_flows :714-726):

  1 — allow ANP/ACNP + per-group baseline reject ACNPs
  2 — allow ANP/ACNP + one cluster-wide reject ACNP
  3 — K8s NetworkPolicies, no deny rules

TPU-first note: the numeric kernel here is the DISTINCT over the 9-tuple
— executed on device for large windows via `npr_device.device_distinct`
(lax.sort multi-key dedupe; sharded variant merges per-chip distincts
with an all_gather + segment-sum, the collective replacing the Spark
shuffle); everything after operates on the (small) deduplicated set and
is host-side string/YAML work, as in the reference.
"""

from __future__ import annotations

import datetime
import uuid
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..schema import ColumnarBatch
from ..store import FlowDatabase
from . import policy_gen
from .npr_device import device_distinct
from .policy_gen import (
    KIND_ACG,
    KIND_ACNP,
    KIND_ANP,
    KIND_KNP,
    ROW_DELIMITER,
)
from .series import remove_meaningless_labels

NAMESPACE_ALLOW_LIST = ["kube-system", "flow-aggregator", "flow-visibility"]

FLOW_TABLE_COLUMNS = (
    "sourcePodNamespace", "sourcePodLabels", "destinationIP",
    "destinationPodNamespace", "destinationPodLabels",
    "destinationServicePortName", "destinationTransportPort",
    "protocolIdentifier", "flowType",
)


def get_protocol_string(protocol: int) -> str:
    return {6: "TCP", 17: "UDP"}.get(int(protocol), "UNKNOWN")


def get_flow_type(flow_type: int, svc_port_name: str,
                  dst_pod_labels: str) -> str:
    if flow_type == 3:
        return "pod_to_external"
    if svc_port_name != "":
        return "pod_to_svc"
    if dst_pod_labels != "":
        return "pod_to_pod"
    return "pod_to_external"


def read_distinct_flows(flows: ColumnarBatch,
                        limit: int = 0,
                        start_time: Optional[int] = None,
                        end_time: Optional[int] = None,
                        unprotected: bool = True,
                        rm_labels: bool = True,
                        mesh=None,
                        use_device=None) -> List[Dict[str, object]]:
    """SELECT DISTINCT 9 columns with the job's WHERE clause
    (generate_sql_query :785-802). The distinct runs vectorized over
    dictionary codes; decode happens only for the surviving rows."""
    mask = np.ones(len(flows), dtype=bool)
    if unprotected:
        # '' is always dictionary code 0.
        mask &= np.asarray(flows["ingressNetworkPolicyName"]) == 0
        mask &= np.asarray(flows["egressNetworkPolicyName"]) == 0
    else:
        mask &= np.asarray(flows["trusted"]) == 1
    if start_time is not None:
        mask &= np.asarray(flows["flowStartSeconds"]) >= start_time
    if end_time is not None:
        mask &= np.asarray(flows["flowEndSeconds"]) < end_time
    # Materialize only the 9 queried columns (same narrow-column rule
    # as the series tensorize: filtering all 52 costs more than the
    # distinct kernel it feeds).
    col = flows.column_selector(mask)
    keys = np.stack([col(c) for c in FLOW_TABLE_COLUMNS], axis=1)
    uniq, _counts = device_distinct(keys, use_device=use_device,
                                    mesh=mesh)

    rows: List[Dict[str, object]] = []
    for r in uniq:
        row: Dict[str, object] = {}
        for i, c in enumerate(FLOW_TABLE_COLUMNS):
            if c in flows.dicts:
                row[c] = flows.dicts[c].decode_one(int(r[i]))
            else:
                row[c] = int(r[i])
        rows.append(row)

    if rm_labels:
        # The reference rewrites labels then dropDuplicates on the two
        # label columns ONLY (read_flow_df :815-830) — a quirk we keep.
        seen = set()
        deduped = []
        for row in rows:
            row["sourcePodLabels"] = remove_meaningless_labels(
                str(row["sourcePodLabels"]))
            row["destinationPodLabels"] = remove_meaningless_labels(
                str(row["destinationPodLabels"]))
            key = (row["sourcePodLabels"], row["destinationPodLabels"])
            if key not in seen:
                seen.add(key)
                deduped.append(row)
        rows = deduped

    for row in rows:
        row["flowType"] = get_flow_type(
            int(row["flowType"]), str(row["destinationServicePortName"]),
            str(row["destinationPodLabels"]))
    if limit:
        rows = rows[:limit]
    return rows


# -- peer mapping (reference map_flow_to_* :119-171) ---------------------

def map_flow_to_egress(flow: Dict[str, object], k8s: bool = False) -> tuple:
    src = ROW_DELIMITER.join([str(flow["sourcePodNamespace"]),
                              str(flow["sourcePodLabels"])])
    if flow["flowType"] == "pod_to_external":
        dst = ROW_DELIMITER.join([
            str(flow["destinationIP"]),
            str(flow["destinationTransportPort"]),
            get_protocol_string(int(flow["protocolIdentifier"]))])
    elif flow["flowType"] == "pod_to_svc" and not k8s:
        svc_ns, svc_name = str(
            flow["destinationServicePortName"]).partition(":")[0].split("/")
        dst = ROW_DELIMITER.join([svc_ns, svc_name])
    else:
        dst = ROW_DELIMITER.join([
            str(flow["destinationPodNamespace"]),
            str(flow["destinationPodLabels"]),
            str(flow["destinationTransportPort"]),
            get_protocol_string(int(flow["protocolIdentifier"]))])
    return src, dst


def map_flow_to_egress_svc(flow: Dict[str, object]) -> tuple:
    src = ROW_DELIMITER.join([str(flow["sourcePodNamespace"]),
                              str(flow["sourcePodLabels"])])
    dst = ROW_DELIMITER.join([
        str(flow["destinationServicePortName"]),
        str(flow["destinationTransportPort"]),
        get_protocol_string(int(flow["protocolIdentifier"]))])
    return src, dst


def map_flow_to_ingress(flow: Dict[str, object]) -> tuple:
    src = ROW_DELIMITER.join([
        str(flow["sourcePodNamespace"]), str(flow["sourcePodLabels"]),
        str(flow["destinationTransportPort"]),
        get_protocol_string(int(flow["protocolIdentifier"]))])
    dst = ROW_DELIMITER.join([str(flow["destinationPodNamespace"]),
                              str(flow["destinationPodLabels"])])
    return dst, src


def aggregate_peers(flows: Sequence[Dict[str, object]], k8s: bool,
                    to_services: bool):
    """The reduceByKey stage: appliedTo group → (ingress set, egress set).

    Returns (network_peers, svc_egress) where network_peers maps
    applied_to → {"ingress": [...], "egress": [...]}, and svc_egress maps
    applied_to → [svc egress tuples] (populated only when to_services is
    False and k8s is False, reference :662-679)."""
    peers: Dict[str, Dict[str, List[str]]] = {}
    svc_egress: Dict[str, List[str]] = {}

    def entry(key: str) -> Dict[str, List[str]]:
        return peers.setdefault(key, {"ingress": [], "egress": []})

    for flow in flows:
        if flow["flowType"] != "pod_to_external":
            dst, src = map_flow_to_ingress(flow)
            entry(dst)["ingress"].append(src)
        if not k8s and not to_services and flow["flowType"] == "pod_to_svc":
            src, dst = map_flow_to_egress_svc(flow)
            svc_egress.setdefault(src, []).append(dst)
        else:
            src, dst = map_flow_to_egress(flow, k8s=k8s)
            entry(src)["egress"].append(dst)
    return peers, svc_egress


# -- recommendation passes (reference :621-734) --------------------------

def _allowed(applied_to: str, ns_allow_list: Sequence[str]) -> bool:
    ns = applied_to.split(ROW_DELIMITER)[0]
    return ns in ns_allow_list


def recommend_k8s_policies(flows, ns_allow_list) -> Dict[str, List[str]]:
    peers, _ = aggregate_peers(flows, k8s=True, to_services=True)
    knps = []
    for applied_to, io in sorted(peers.items()):
        if _allowed(applied_to, ns_allow_list):
            continue
        p = policy_gen.generate_k8s_np(
            applied_to, io["ingress"], io["egress"])
        if p:
            knps.append(p)
    return {KIND_KNP: knps}


def recommend_antrea_policies(flows, ns_allow_list, option: int = 1,
                              deny_rules: bool = True,
                              to_services: bool = True
                              ) -> Dict[str, List[str]]:
    peers, svc_egress = aggregate_peers(flows, k8s=False,
                                        to_services=to_services)
    anps, cgs, acnps = [], [], []
    for applied_to, io in sorted(peers.items()):
        if _allowed(applied_to, ns_allow_list):
            continue
        p = policy_gen.generate_anp(
            applied_to, io["ingress"], io["egress"])
        if p:
            anps.append(p)

    if not to_services:
        svc_names = sorted({
            str(f["destinationServicePortName"]) for f in flows
            if f["flowType"] == "pod_to_svc"})
        for svc in svc_names:
            svc_ns = svc.partition(":")[0].split("/")[0]
            if svc_ns in ns_allow_list:
                continue
            cgs.append(policy_gen.generate_svc_cg(svc))
        for applied_to, egresses in sorted(svc_egress.items()):
            if _allowed(applied_to, ns_allow_list):
                continue
            p = policy_gen.generate_svc_acnp(applied_to, egresses)
            if p:
                acnps.append(p)

    if deny_rules:
        if option == 1:
            groups = sorted(set(peers) | set(svc_egress))
            for applied_to in groups:
                if _allowed(applied_to, ns_allow_list):
                    continue
                p = policy_gen.generate_reject_acnp(applied_to)
                if p:
                    acnps.append(p)
        else:
            acnps.append(policy_gen.generate_reject_acnp(""))
    return {KIND_ANP: anps, KIND_ACG: cgs, KIND_ACNP: acnps}


def recommend_policies_for_unprotected_flows(
        flows, ns_allow_list, option: int = 1,
        to_services: bool = True) -> Dict[str, List[str]]:
    if option not in (1, 2, 3):
        raise ValueError(f"option must be 1, 2 or 3, got {option}")
    if option == 3:
        return recommend_k8s_policies(flows, ns_allow_list)
    return recommend_antrea_policies(
        flows, ns_allow_list, option, deny_rules=True,
        to_services=to_services)


def recommend_policies_for_ns_allow_list(ns_allow_list
                                         ) -> Dict[str, List[str]]:
    return {KIND_ACNP: [policy_gen.generate_ns_allow_acnp(ns)
                        for ns in ns_allow_list]}


def merge_policy_dict(a: Dict[str, List[str]],
                      b: Dict[str, List[str]]) -> Dict[str, List[str]]:
    for k, v in b.items():
        a[k] = a.get(k, []) + v
    return a


# -- job entry points (reference :880-1017) ------------------------------

def run_npr(db: FlowDatabase,
            recommendation_type: str = "initial",
            limit: int = 0,
            option: int = 1,
            start_time: Optional[int] = None,
            end_time: Optional[int] = None,
            ns_allow_list: Optional[Sequence[str]] = None,
            rm_labels: bool = True,
            to_services: bool = True,
            recommendation_id: Optional[str] = None,
            now: Optional[datetime.datetime] = None,
            progress=None, mesh="auto") -> str:
    """Run a full NPR job against the database; returns the job id.

    `mesh`: "auto" shards the DISTINCT kernel over every visible device
    (parallel.job_mesh; single-device hosts keep the plain path), None
    forces single-device, or pass an explicit mesh. Any mesh is
    flattened onto a rows axis for the distinct shuffle.
    """
    if recommendation_type not in ("initial", "subsequent"):
        raise ValueError(
            f"type must be initial|subsequent, got {recommendation_type}")
    use_device = None
    if isinstance(mesh, str):
        if mesh != "auto":
            raise ValueError(
                f"mesh must be 'auto', None or a Mesh, got {mesh!r} "
                f"(use THEIA_MESH=off to disable sharding)")
        from ..parallel import job_mesh
        mesh = job_mesh()
    elif mesh is not None:
        # An explicitly passed mesh is an opt-in to the device
        # distinct — don't gate it behind the auto size threshold.
        use_device = True
    if mesh is not None:
        from ..parallel import make_rows_mesh
        mesh = make_rows_mesh(devices=mesh.devices.flatten())
    ns_allow_list = list(ns_allow_list if ns_allow_list is not None
                         else NAMESPACE_ALLOW_LIST)
    recommendation_id = recommendation_id or str(uuid.uuid4())

    if progress:
        progress.stage("read")
    flows = db.flows.scan()
    unprotected = read_distinct_flows(
        flows, limit, start_time, end_time, unprotected=True,
        rm_labels=rm_labels, mesh=mesh, use_device=use_device)

    if progress:
        progress.stage("recommend")
    if recommendation_type == "initial":
        result = merge_policy_dict(
            recommend_policies_for_ns_allow_list(ns_allow_list),
            recommend_policies_for_unprotected_flows(
                unprotected, ns_allow_list, option, to_services))
    else:
        result = recommend_policies_for_unprotected_flows(
            unprotected, ns_allow_list, option, to_services)
        if option in (1, 2):
            trusted = read_distinct_flows(
                flows, limit, start_time, end_time, unprotected=False,
                rm_labels=rm_labels, mesh=mesh, use_device=use_device)
            result = merge_policy_dict(
                result,
                recommend_antrea_policies(
                    trusted, ns_allow_list, option, deny_rules=False,
                    to_services=to_services))

    if progress:
        progress.stage("write")
    time_created = (now or datetime.datetime.now(datetime.timezone.utc))
    rows = [{
        "id": recommendation_id,
        "type": recommendation_type,
        "timeCreated": int(time_created.timestamp()),
        "policy": policy,
        "kind": kind,
    } for kind, policies in result.items() for policy in policies if policy]
    db.recommendations.insert_rows(rows)
    if progress:
        progress.done()
    return recommendation_id
