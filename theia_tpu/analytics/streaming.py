"""Streaming anomaly detection: micro-batch updates, sub-second alerts.

The reference's TAD is a batch job — minutes from `theia tad run` to a
result row (Spark submit + full table scan + per-row UDFs). This module
is the TPU-native streaming upgrade the BASELINE north star asks for
(sub-second p50 alert latency): per-connection detector state lives
device-resident and every ingest micro-batch advances it with one tiny
fused XLA step — no rescans, no job submission.

Semantics: the EWMA recurrence is exactly the batch kernel's
(ops/ewma.py, reference anomaly_detection.py:146-165); the stddev band
uses Welford's running *sample* stddev over the points seen so far,
where the batch job uses the whole window's stddev — the streaming
detector can't see the future. Alerts therefore fire with the
information available at arrival time (documented difference; the batch
path remains available for parity).

Slot model: a fixed-capacity state table indexed by slot; the host maps
connection keys (tuples of dictionary codes) to slots on first sight.
Capacity overflow evicts nothing — new series beyond capacity are
dropped and counted, mirroring how a fixed-size flow cache degrades.
"""

from __future__ import annotations

import time
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.ewma import DEFAULT_ALPHA
from ..schema import ColumnarBatch

CONNECTION_KEY_COLUMNS = (
    "sourceIP", "sourceTransportPort", "destinationIP",
    "destinationTransportPort", "protocolIdentifier", "flowStartSeconds")


class StreamState(NamedTuple):
    ewma: jnp.ndarray    # [S]
    count: jnp.ndarray   # [S] int32  points seen
    mean: jnp.ndarray    # [S]       running mean (Welford)
    m2: jnp.ndarray      # [S]       running sum of squared deviations


def init_state(capacity: int, dtype=jnp.float32) -> StreamState:
    z = jnp.zeros(capacity, dtype)
    return StreamState(ewma=z, count=jnp.zeros(capacity, jnp.int32),
                       mean=z, m2=z)


@jax.jit
def stream_update(state: StreamState, x: jnp.ndarray,
                  active: jnp.ndarray,
                  alpha: float = DEFAULT_ALPHA
                  ) -> Tuple[StreamState, jnp.ndarray]:
    """One micro-batch step: x [S] new values, active [S] validity.

    Returns (new state, anomaly [S]): anomaly iff the slot is active,
    has seen ≥2 points, and |x − ewma| exceeds the running sample
    stddev (the streaming analogue of calculate_ewma_anomaly).
    """
    xa = jnp.where(active, x, 0.0)
    count = state.count + active.astype(jnp.int32)
    delta = xa - state.mean
    mean = jnp.where(active,
                     state.mean + delta / jnp.maximum(count, 1),
                     state.mean)
    m2 = jnp.where(active, state.m2 + delta * (xa - mean), state.m2)
    ewma = jnp.where(active,
                     (1.0 - alpha) * state.ewma + alpha * xa,
                     state.ewma)
    std = jnp.sqrt(m2 / jnp.maximum(count - 1, 1))
    anomaly = active & (count >= 2) & (jnp.abs(xa - ewma) > std)
    return StreamState(ewma, count, mean, m2), anomaly


class StreamingDetector:
    """Host-side driver: key→slot mapping + device-resident state."""

    def __init__(self, capacity: int = 65536,
                 alpha: float = DEFAULT_ALPHA,
                 value_column: str = "throughput") -> None:
        self.capacity = capacity
        self.alpha = alpha
        self.value_column = value_column
        self.state = init_state(capacity)
        # key → slot; dropped keys are remembered with slot -1 so a
        # series is only counted dropped once, however many rows it
        # keeps sending.
        self._slots: Dict[Tuple[int, ...], int] = {}
        self._slot_keys: List[Optional[Tuple[int, ...]]] = []
        self._n_alloc = 0
        self.dropped_series = 0

    @property
    def n_series(self) -> int:
        return self._n_alloc

    def _slot_for(self, key: Tuple[int, ...]) -> int:
        slot = self._slots.get(key)
        if slot is None:
            if self._n_alloc >= self.capacity:
                self._slots[key] = -1
                self.dropped_series += 1
                return -1
            slot = self._n_alloc
            self._n_alloc += 1
            self._slots[key] = slot
            self._slot_keys.append(key)
        return slot

    def ingest(self, batch: ColumnarBatch) -> List[Dict[str, object]]:
        """Advance state with one micro-batch; returns alert records.

        Rows are keyed by the 6-tuple connection columns; if a batch
        carries several points for one connection, each lands in a
        successive tick so the recurrence sees them in order.
        """
        if len(batch) == 0:
            return []
        t_arrival = time.perf_counter()
        keys = np.stack([np.asarray(batch[c], np.int64)
                         for c in CONNECTION_KEY_COLUMNS], axis=1)
        values = np.asarray(batch[self.value_column], np.float64)
        times = np.asarray(batch["flowEndSeconds"], np.int64)

        slots = np.fromiter(
            (self._slot_for(tuple(k)) for k in keys),
            dtype=np.int64, count=keys.shape[0])
        ok = slots >= 0

        # Bucket duplicate slots into successive ticks (stable order).
        order = np.argsort(slots[ok], kind="stable")
        s_sorted = slots[ok][order]
        v_sorted = values[ok][order]
        t_sorted = times[ok][order]
        idx_sorted = np.flatnonzero(ok)[order]
        # tick index = occurrence number of this slot within the batch,
        # computed vectorized (hot path): position minus the start index
        # of the slot's run.
        n = len(s_sorted)
        if n == 0:
            tick = np.zeros(0, np.int64)
        else:
            same = np.empty(n, bool)
            same[0] = False
            same[1:] = s_sorted[1:] == s_sorted[:-1]
            if not same.any():   # common case: one point per series
                tick = np.zeros(n, np.int64)
            else:
                idx = np.arange(n)
                run_start = np.maximum.accumulate(
                    np.where(same, 0, idx))
                tick = idx - run_start
        n_ticks = int(tick.max()) + 1 if n else 0

        alerts: List[Dict[str, object]] = []
        for t in range(n_ticks):
            sel = tick == t
            x = np.zeros(self.capacity, np.float32)
            active = np.zeros(self.capacity, bool)
            x[s_sorted[sel]] = v_sorted[sel]
            active[s_sorted[sel]] = True
            self.state, anomaly = stream_update(
                self.state, jnp.asarray(x), jnp.asarray(active),
                self.alpha)
            hit_slots = np.flatnonzero(np.asarray(anomaly))
            if hit_slots.size:
                latency = time.perf_counter() - t_arrival
                row_for_slot = {int(s): int(i) for s, i in zip(
                    s_sorted[sel], idx_sorted[sel])}
                for slot in hit_slots:
                    i = row_for_slot[int(slot)]
                    alerts.append({
                        "slot": int(slot),
                        "row": i,
                        "flowEndSeconds": int(times[i]),
                        "throughput": float(values[i]),
                        "latency_s": latency,
                    })
        return alerts

    def describe_alert(self, batch: ColumnarBatch,
                       alert: Dict[str, object]) -> Dict[str, object]:
        """Decode an alert's connection identity from its source row."""
        i = alert["row"]
        out = dict(alert)
        for c in CONNECTION_KEY_COLUMNS:
            out[c] = (batch.strings(c)[i] if c in batch.dicts
                      else int(batch[c][i]))
        return out
