"""Streaming anomaly detection: micro-batch updates, sub-second alerts.

The reference's TAD is a batch job — minutes from `theia tad run` to a
result row (Spark submit + full table scan + per-row UDFs). This module
is the TPU-native streaming upgrade the BASELINE north star asks for
(sub-second p50 alert latency): per-connection detector state lives
device-resident and every ingest micro-batch advances it with one tiny
fused XLA step — no rescans, no job submission.

Semantics: the EWMA recurrence is exactly the batch kernel's
(ops/ewma.py, reference anomaly_detection.py:146-165); the stddev band
uses Welford's running *sample* stddev over the points seen so far,
where the batch job uses the whole window's stddev — the streaming
detector can't see the future. Alerts therefore fire with the
information available at arrival time (documented difference; the batch
path remains available for parity).

Slot model: a fixed-capacity state table indexed by slot; the host maps
connection keys (packed 6-tuples of dictionary codes) to slots on first
sight. Capacity overflow evicts nothing — new series beyond capacity
are dropped and counted, mirroring how a fixed-size flow cache degrades.

Sharding: a StreamingDetector is deliberately single-writer (callers
serialize updates). The manager's ingest path scales it by running N
independent instances, one per destination-hash shard, each behind its
own lock (manager/ingest.py) — the per-slot recurrence only ever reads
its own slot's state, so partitioning the key space partitions the
state with no cross-shard coupling.

Hot-path shape: one micro-batch is ONE jitted device step however many
rows it carries. The step gathers only the U slots present in the batch,
scans the (usually 1-2) ticks of duplicate points per connection over a
[T, U] tile, and scatters the updated state back — O(T·U) device work
instead of O(T·capacity) dense dispatches, with U ≤ rows.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import metrics as _metrics
from ..ops.ewma import DEFAULT_ALPHA
from ..schema import ColumnarBatch

CONNECTION_KEY_COLUMNS = (
    "sourceIP", "sourceTransportPort", "destinationIP",
    "destinationTransportPort", "protocolIdentifier", "flowStartSeconds")

# Capacity overflow is silent at the data plane (new series simply stop
# being scored) — this counter is the operator's only line-rate signal
# that alerts are going missing before they do.
_M_DROPPED = _metrics.counter(
    "theia_detector_series_dropped_total",
    "New connection series dropped because every streaming-detector "
    "slot was taken (the series is never scored)")


class StreamState(NamedTuple):
    ewma: jnp.ndarray    # [S]
    count: jnp.ndarray   # [S] int32  points seen
    mean: jnp.ndarray    # [S]       running mean (Welford)
    m2: jnp.ndarray      # [S]       running sum of squared deviations


def init_state(capacity: int, dtype=jnp.float32) -> StreamState:
    z = jnp.zeros(capacity, dtype)
    return StreamState(ewma=z, count=jnp.zeros(capacity, jnp.int32),
                       mean=z, m2=z)


def _update(state: StreamState, x: jnp.ndarray, active: jnp.ndarray,
            alpha) -> Tuple[StreamState, jnp.ndarray]:
    """Elementwise detector recurrence (any shape): anomaly iff the
    slot is active, has seen ≥2 points, and |x − ewma| exceeds the
    running sample stddev (the streaming analogue of
    calculate_ewma_anomaly)."""
    xa = jnp.where(active, x, 0.0)
    count = state.count + active.astype(jnp.int32)
    delta = xa - state.mean
    mean = jnp.where(active,
                     state.mean + delta / jnp.maximum(count, 1),
                     state.mean)
    m2 = jnp.where(active, state.m2 + delta * (xa - mean), state.m2)
    ewma = jnp.where(active,
                     (1.0 - alpha) * state.ewma + alpha * xa,
                     state.ewma)
    std = jnp.sqrt(m2 / jnp.maximum(count - 1, 1))
    anomaly = active & (count >= 2) & (jnp.abs(xa - ewma) > std)
    return StreamState(ewma, count, mean, m2), anomaly


@jax.jit
def stream_update(state: StreamState, x: jnp.ndarray,
                  active: jnp.ndarray,
                  alpha: float = DEFAULT_ALPHA
                  ) -> Tuple[StreamState, jnp.ndarray]:
    """Dense one-tick step: x [S] new values, active [S] validity."""
    return _update(state, x, active, alpha)


@jax.jit
def stream_update_sparse(state: StreamState, slots: jnp.ndarray,
                         x: jnp.ndarray, active: jnp.ndarray,
                         alpha: float = DEFAULT_ALPHA
                         ) -> Tuple[StreamState, jnp.ndarray]:
    """Gather-scan-scatter step for one micro-batch.

    slots [U] int32: the distinct state slots present in the batch;
    padding entries hold `capacity` (out of bounds), so the gather
    clamps harmlessly and the scatter DROPS them (XLA's documented
    OOB semantics) — padded columns never touch real state.
    x, active [T, U]: tick-major values; tick t carries each
    connection's t-th point in this batch, so the recurrence sees
    duplicate points in arrival order.

    Returns (new state, anomaly [T, U]).
    """
    sub = StreamState(*(a[slots] for a in state))

    def step(carry, inp):
        x_t, act_t = inp
        new, anomaly = _update(carry, x_t, act_t, alpha)
        return new, anomaly

    sub, anomalies = jax.lax.scan(step, sub, (x, active))
    new_state = StreamState(*(
        full.at[slots].set(part, mode="drop")
        for full, part in zip(state, sub)))
    return new_state, anomalies


def _pad_pow2(n: int, minimum: int) -> int:
    """Next power-of-two dispatch bucket so the jitted step compiles
    once per bucket, not once per distinct micro-batch shape."""
    size = minimum
    while size < n:
        size <<= 1
    return size


class StreamPlan(NamedTuple):
    """Host half of one micro-batch: the [T, U] tick tile plus the slot
    gather/scatter vector, ready for the jitted device step. Built by
    `StreamingDetector.build_plan` and consumed either by this module's
    `stream_update_sparse` (sharded engine) or by the fused engine's
    single cross-shard dispatch (ops/fused_detector.py)."""
    slots: np.ndarray     # [U_pad] int32; padding holds `capacity`
    x: np.ndarray         # [T_pad, U_pad] float32 values
    active: np.ndarray    # [T_pad, U_pad] bool validity
    row_idx: np.ndarray   # [T_pad, U_pad] int64 source row (-1 padding)
    present: np.ndarray   # [U] slot id per live column


def alert_record(slot: int, flow_end: int, value: float,
                 latency: float) -> Dict[str, object]:
    """The connection-anomaly alert record — ONE builder for both
    engines (this module's ingest path and the fused engine's
    device_path._finish) so the published shape cannot drift."""
    return {
        "slot": int(slot),
        "flowEndSeconds": int(flow_end),
        "throughput": float(value),
        "latency_s": latency,
    }


def plan_alerts(plan: StreamPlan, hits: np.ndarray, times: np.ndarray,
                values: np.ndarray,
                latency: float) -> List[Dict[str, object]]:
    """Alert records for the anomaly hits of one plan's device step
    (sharded engine; `row` is batch-local and popped before
    publication by describe_alert's caller)."""
    alerts: List[Dict[str, object]] = []
    for t, c in hits:
        i = int(plan.row_idx[t, c])
        rec = alert_record(plan.present[c], times[i], values[i],
                           latency)
        rec["row"] = i
        alerts.append(rec)
    return alerts


class StreamingDetector:
    """Host-side driver: key→slot mapping + device-resident state."""

    def __init__(self, capacity: int = 65536,
                 alpha: float = DEFAULT_ALPHA,
                 value_column: str = "throughput",
                 clock=time.perf_counter, tier=None) -> None:
        self.capacity = capacity
        self.alpha = alpha
        self.value_column = value_column
        #: injectable for deterministic latency_s in tests (the alert
        #: latency is a measurement, not detector state)
        self.clock = clock
        self.state = init_state(capacity)
        # packed key bytes → slot; dropped keys are remembered with
        # slot -1 so a series is only counted dropped once, however
        # many rows it keeps sending.
        self._slots: Dict[bytes, int] = {}
        self._slot_keys: List[Optional[bytes]] = []
        self._n_alloc = 0
        self.dropped_series = 0
        #: optional working-set tier (ingest/state_tier.WorkingSetTier):
        #: when attached, slot assignment goes through the tier —
        #: capacity overflow spills LRU state instead of dropping new
        #: series, and spilled state is restored exactly on re-arrival
        self.tier = tier
        if tier is not None:
            tier.attach(self)

    @property
    def n_series(self) -> int:
        return self._n_alloc

    def _slot_for(self, key: bytes) -> int:
        slot = self._slots.get(key)
        if slot is None:
            if self._n_alloc >= self.capacity:
                self._slots[key] = -1
                self.dropped_series += 1
                _M_DROPPED.inc()
                return -1
            slot = self._n_alloc
            self._n_alloc += 1
            self._slots[key] = slot
            self._slot_keys.append(key)
        return slot

    def build_plan(self, keys: np.ndarray, values: np.ndarray,
                   staging: Optional[Callable] = None
                   ) -> Optional[StreamPlan]:
        """Host half of `ingest`: key→slot mapping plus the [T, U]
        tick tile for one micro-batch, no device work.

        `keys` is the [N, 6] int64 connection-key matrix (in
        CONNECTION_KEY_COLUMNS order), `values` the [N] metric column.
        `staging(tag, shape, dtype)` returns a reusable array to fill
        — the fused engine's pinned ring; None allocates fresh arrays
        (this class's own path). Returns None when no row maps to a
        live slot.

        Python work is O(distinct NEW connections), not O(rows): keys
        are packed into 48-byte rows and deduplicated vectorized, and
        the Python dict is touched once per distinct key.
        """
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        packed = keys.view(np.dtype((np.void, keys.itemsize *
                                     keys.shape[1]))).ravel()
        uniq, inverse = np.unique(packed, return_inverse=True)
        if self.tier is not None:
            slots_u = self.tier.assign(self, uniq)
        else:
            slots_u = np.fromiter(
                (self._slot_for(k.tobytes()) for k in uniq),
                dtype=np.int64, count=len(uniq))
        slots = slots_u[inverse]
        ok = slots >= 0

        # Bucket duplicate slots into successive ticks (stable order).
        order = np.argsort(slots[ok], kind="stable")
        s_sorted = slots[ok][order]
        v_sorted = values[ok][order]
        idx_sorted = np.flatnonzero(ok)[order]
        # tick index = occurrence number of this slot within the batch:
        # position minus the start index of the slot's run.
        n = len(s_sorted)
        if n == 0:
            return None
        same = np.empty(n, bool)
        same[0] = False
        same[1:] = s_sorted[1:] == s_sorted[:-1]
        if not same.any():   # common case: one point per series
            tick = np.zeros(n, np.int64)
        else:
            idx = np.arange(n)
            run_start = np.maximum.accumulate(np.where(same, 0, idx))
            tick = idx - run_start
        n_ticks = int(tick.max()) + 1

        # [T, U] tile over the distinct slots present in this batch.
        present, col = np.unique(s_sorted, return_inverse=True)
        u = len(present)
        u_pad = _pad_pow2(u, 64)
        t_pad = _pad_pow2(n_ticks, 1)

        def _alloc(tag, shape, dtype, fill):
            if staging is None:
                return np.full(shape, fill, dtype)
            a = staging(tag, shape, dtype)
            a[...] = fill
            return a

        x = _alloc("x", (t_pad, u_pad), np.float32, 0)
        active = _alloc("active", (t_pad, u_pad), bool, False)
        row_idx = _alloc("row_idx", (t_pad, u_pad), np.int64, -1)
        x[tick, col] = v_sorted
        active[tick, col] = True
        row_idx[tick, col] = idx_sorted
        slots_pad = _alloc("slots", (u_pad,), np.int32, self.capacity)
        slots_pad[:u] = present
        return StreamPlan(slots_pad, x, active, row_idx, present)

    def ingest(self, batch: ColumnarBatch) -> List[Dict[str, object]]:
        """Advance state with one micro-batch; returns alert records.

        Rows are keyed by the 6-tuple connection columns; if a batch
        carries several points for one connection, each lands in a
        successive tick so the recurrence sees them in order. The
        whole batch is one jitted gather-scan-scatter device step.
        """
        if len(batch) == 0:
            return []
        t_arrival = self.clock()
        keys = np.stack(
            [np.asarray(batch[c], np.int64)
             for c in CONNECTION_KEY_COLUMNS], axis=1)
        values = np.asarray(batch[self.value_column], np.float64)
        times = np.asarray(batch["flowEndSeconds"], np.int64)
        plan = self.build_plan(keys, values)
        if plan is None:
            return []
        self.state, anomaly = stream_update_sparse(
            self.state, jnp.asarray(plan.slots), jnp.asarray(plan.x),
            jnp.asarray(plan.active), self.alpha)

        hits = np.argwhere(np.asarray(anomaly))
        if not hits.size:
            return []
        latency = self.clock() - t_arrival
        return plan_alerts(plan, hits, times, values, latency)

    def describe_alert(self, batch: ColumnarBatch,
                       alert: Dict[str, object]) -> Dict[str, object]:
        """Decode an alert's connection identity from its source row.
        Per-cell decode_one, NOT a whole-column decode — an alert
        burst would otherwise pay O(rows) string work per alert."""
        i = int(alert["row"])
        out = dict(alert)
        for c in CONNECTION_KEY_COLUMNS:
            d = batch.dicts.get(c)
            out[c] = (d.decode_one(int(batch[c][i])) if d is not None
                      else int(batch[c][i]))
        return out
