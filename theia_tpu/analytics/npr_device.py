"""On-device DISTINCT + support counting for the NPR job.

The reference's NPR compute is a Spark `SELECT DISTINCT` over the flow
9-tuple followed by RDD reduceByKey shuffles
(policy_recommendation_job.py:785-802,621-712). Here the same kernel is
expressed TPU-natively:

  * single chip — `lax.sort` over the key columns (XLA's lexicographic
    multi-operand sort), boundary detection, and segment scatter/add to
    produce the unique rows and their multiplicities ("support counts")
    in one jitted computation with static shapes;
  * multi chip — `shard_map` over a row-sharded mesh: each device
    dedupes its block locally, the padded local distincts ride one
    `all_gather` over ICI, and a second sort + segment-sum merges them
    into a replicated global distinct — the collective pattern that
    replaces the reference's executor shuffle (SURVEY §2.7).

Outputs are padded to the input length with a validity mask (static
shapes for XLA); hosts slice by `n_unique`. Dictionary codes are int32
(dictionaries are far smaller than 2^31; INT32_MAX is reserved as the
cross-shard padding sentinel).
"""

from __future__ import annotations

import os
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import ROWS_AXIS, shard_map

_SENTINEL = np.iinfo(np.int32).max

# Host-side switch: "auto" uses the device path for large inputs only
# (the host numpy lexsort wins under ~64k rows once transfer overhead is
# counted), "1"/"0" force it on/off.
_AUTO_THRESHOLD = 65536


def _boundaries(sk: jnp.ndarray) -> jnp.ndarray:
    """is_new[i] = row i differs from row i-1 (sorted input)."""
    head = jnp.ones((1,), bool)
    return jnp.concatenate(
        [head, jnp.any(sk[1:] != sk[:-1], axis=1)]) if sk.shape[0] > 1 \
        else jnp.ones((sk.shape[0],), bool)


def _dedupe_sorted(sk: jnp.ndarray, weights: jnp.ndarray):
    """Segment-reduce a sorted key matrix: unique rows scattered to the
    front, weights summed per segment. Returns (uniq, counts, n_unique)
    padded to len(sk)."""
    n = sk.shape[0]
    is_new = _boundaries(sk)
    seg = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    n_unique = seg[-1] + 1
    counts = jnp.zeros((n,), weights.dtype).at[seg].add(weights)
    uniq = jnp.zeros_like(sk).at[seg].set(sk)
    return uniq, counts, n_unique


@jax.jit
def distinct_rows(keys: jnp.ndarray
                  ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """DISTINCT over [N, K] int32 rows with multiplicities.

    Returns (uniq [N, K], counts [N] int32, n_unique []): the first
    n_unique rows of `uniq` are the distinct key rows in lexicographic
    order; `counts[i]` is how many input rows equal `uniq[i]`.
    """
    n, k = keys.shape
    ops = tuple(keys[:, i] for i in range(k))
    sorted_cols = jax.lax.sort(ops, num_keys=k)
    sk = jnp.stack(sorted_cols, axis=1)
    # int32 counts: a single padded block never exceeds 2^31 rows
    # (hosts widen to int64); avoids the x64-disabled truncation
    # warning on TPU.
    return _dedupe_sorted(sk, jnp.ones((n,), jnp.int32))


def _sharded_distinct_step(keys: jnp.ndarray):
    """Per-shard body: local dedupe → all_gather → global dedupe.

    keys: the local [N_loc, K] block. Output is replicated (identical
    on every shard): (uniq [N, K], counts [N], n_unique) with
    N = N_loc * n_shards (the shard count is implicit in the
    all_gather output shape).
    """
    n_loc, k = keys.shape
    uniq, counts, n_unique = distinct_rows(keys)
    valid = jnp.arange(n_loc) < n_unique
    # Pad invalid slots with the sentinel so they sort to the end and
    # carry zero weight through the merge.
    uniq = jnp.where(valid[:, None], uniq, _SENTINEL)
    counts = jnp.where(valid, counts, 0)

    uniq_all = jax.lax.all_gather(uniq, ROWS_AXIS)       # [S, N_loc, K]
    counts_all = jax.lax.all_gather(counts, ROWS_AXIS)   # [S, N_loc]
    flat_keys = uniq_all.reshape(-1, k)
    flat_counts = counts_all.reshape(-1)

    ops = tuple(flat_keys[:, i] for i in range(k)) + (flat_counts,)
    sorted_ = jax.lax.sort(ops, num_keys=k)
    sk = jnp.stack(sorted_[:k], axis=1)
    merged, total, n_uniq = _dedupe_sorted(sk, sorted_[k])
    # Drop the sentinel segment (present iff any shard had padding):
    # padding rows are _SENTINEL in EVERY column, so a genuine row can
    # only be misidentified if all K of its codes equal INT32_MAX —
    # excluded by the module precondition (codes < INT32_MAX).
    has_pad = jnp.all(merged[jnp.maximum(n_uniq - 1, 0)] == _SENTINEL)
    n_uniq = jnp.where(has_pad, n_uniq - 1, n_uniq)
    return merged, total, n_uniq


def make_sharded_distinct(mesh: jax.sharding.Mesh):
    """Jitted multi-chip DISTINCT over a mesh with a `rows` axis.

    fn(keys [N, K]) with N divisible by the axis size; returns
    replicated (uniq, counts, n_unique) padded to N.

    Preconditions: key codes < INT32_MAX (the padding sentinel), and
    no single distinct key's GLOBAL multiplicity reaches 2^31 (counts
    merge in int32 because x64 is disabled on TPU; callers needing
    exact counts beyond that must sum per-shard results host-side).
    """
    from jax.sharding import PartitionSpec as P

    mapped = shard_map(
        _sharded_distinct_step, mesh=mesh,
        in_specs=(P(ROWS_AXIS, None),),
        out_specs=(P(), P(), P()),
        check_vma=False)
    return jax.jit(mapped)


def device_distinct(keys: np.ndarray,
                    use_device: str | bool | None = None,
                    mesh: jax.sharding.Mesh | None = None
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Host wrapper: DISTINCT + counts for an [N, K] int code matrix.

    Returns (uniq [U, K] int64, counts [U] int64) in lexicographic row
    order — bit-identical to the numpy group_reduce path. `use_device`
    defaults to the THEIA_NPR_DEVICE env switch ("auto"/"1"/"0").
    With `mesh` (a rows-axis mesh with >1 device), the device path
    shards input rows over the mesh and merges per-chip distincts with
    the all_gather + segment-sum collective (production scale-out of
    the Spark shuffle, SURVEY §2.7).
    """
    n = keys.shape[0]
    if n == 0:
        return (keys.astype(np.int64),
                np.zeros((0,), np.int64))
    if use_device is None:
        use_device = os.environ.get("THEIA_NPR_DEVICE", "auto")
    if use_device in ("0", False, "off", "false"):
        on_device = False
    elif use_device in ("1", True, "on", "true"):
        on_device = True
    else:
        on_device = n >= _AUTO_THRESHOLD
    if not on_device:
        from ..store.views import group_reduce

        uniq, counts = group_reduce(
            keys.astype(np.int64),
            np.ones((n, 1), np.int64))
        return uniq, counts[:, 0]

    if keys.max(initial=0) >= _SENTINEL:
        raise ValueError("dictionary code collides with the sentinel")
    if mesh is not None and mesh.size > 1 and n >= mesh.size:
        from ..parallel import cached_kernel
        from ..parallel.mesh import pad_to_multiple

        # Pad rows to the shard multiple with the sentinel; padding
        # rows sort to the end of the merge and the step drops the
        # trailing all-sentinel segment.
        padded, _ = pad_to_multiple(keys.astype(np.int32), mesh.size,
                                    axis=0, fill=_SENTINEL)
        fn = cached_kernel(("npr_distinct", mesh),
                           lambda: make_sharded_distinct(mesh))
        uniq, counts, n_unique = fn(padded)
    else:
        uniq, counts, n_unique = distinct_rows(keys.astype(np.int32))
    u = int(n_unique)
    return (np.asarray(uniq[:u]).astype(np.int64),
            np.asarray(counts[:u]).astype(np.int64))
