"""Frequent flow-pattern mining with on-device support counting.

The BASELINE north-star NPR config: "FP-Growth frequent-itemset mining
on 1B (src,dst,port) tuples, allreduce support counts over chips".

TPU-first formulation: FP-Growth's tree is pointer-chasing — hostile to
XLA's static-shape compilation — but its OUTPUT (all itemsets with
support >= min_support) is what matters. This module produces the same
output with staged, batched support counting (Apriori staging):

  level 1: per-item support = one `bincount` over the whole tuple
           stream;
  level 2: frequent items remapped to a dense [0, F) id space; every
           transaction's C(k,2) slot pairs encode to pair ids
           fa*F + fb; support = one bincount of size F^2;
  level 3: frequent pairs remapped to [0, P); triples encode to
           pair_id*F + fc; support = one bincount of size P*F.

Every count is a single scatter-add per level — MXU/VPU-friendly, no
data-dependent control flow — and the multi-chip version shard_maps the
transaction axis over the mesh with a `psum` allreduce of the count
vectors (the collective the config names; it replaces FP-Growth's
shared tree).

Transactions here are flow tuples: each row contributes one item per
selected column (e.g. sourcePodNamespace, destinationPodNamespace,
destinationTransportPort, protocolIdentifier) so a frequent itemset is
a recurring traffic pattern — the raw material for policy-rule
generalization.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import shard_map
from ..schema import ColumnarBatch

DEFAULT_COLUMNS = (
    "sourcePodNamespace", "destinationPodNamespace",
    "destinationTransportPort", "protocolIdentifier")

# Dense count-buffer budget (int32 entries): 64M entries = 256 MiB.
_MAX_DENSE_COUNTS = 64 * 1024 * 1024


@partial(jax.jit, static_argnames=("n_items",))
def _support_1(items: jnp.ndarray, *, n_items: int) -> jnp.ndarray:
    """items [n, k] int32 global item ids → per-item counts [n_items].
    Each transaction counts an item at most once (set semantics)."""
    return jnp.zeros(n_items, jnp.int32).at[items.reshape(-1)].add(1)


@partial(jax.jit, static_argnames=("f",))
def _support_2(dense: jnp.ndarray, *, f: int) -> jnp.ndarray:
    """dense [n, k] ids in [0, f) or -1 → pair counts [f*f] over all
    slot pairs a < b (invalid members drop out via id -1)."""
    n, k = dense.shape
    counts = jnp.zeros(f * f, jnp.int32)
    for a in range(k):
        for b in range(a + 1, k):
            ia, ib = dense[:, a], dense[:, b]
            lo = jnp.minimum(ia, ib)
            hi = jnp.maximum(ia, ib)
            valid = (lo >= 0)
            pid = jnp.where(valid, lo * f + hi, 0)
            counts = counts.at[pid].add(valid.astype(jnp.int32))
    return counts


@partial(jax.jit, static_argnames=("p", "f"))
def _support_3(dense: jnp.ndarray, pair_id: jnp.ndarray,
               *, p: int, f: int) -> jnp.ndarray:
    """Triple counts [p*f]: for each transaction, each frequent pair
    (dense pair id in [0,p) via `pair_id` lookup, -1 if not frequent)
    x each third member c > the pair's slots."""
    n, k = dense.shape
    counts = jnp.zeros(p * f, jnp.int32)
    for a in range(k):
        for b in range(a + 1, k):
            ia, ib = dense[:, a], dense[:, b]
            lo, hi = jnp.minimum(ia, ib), jnp.maximum(ia, ib)
            pair_ok = lo >= 0
            pid = jnp.where(pair_ok, pair_id[lo * f + hi], -1)
            for c in range(b + 1, k):
                ic = dense[:, c]
                valid = (pid >= 0) & (ic >= 0)
                tid = jnp.where(valid, pid * f + ic, 0)
                counts = counts.at[tid].add(valid.astype(jnp.int32))
    return counts


def _encode_items(flows: ColumnarBatch, columns: Sequence[str]
                  ) -> Tuple[np.ndarray, List[Tuple[str, int]]]:
    """Rows → [n, k] global item ids; item = (column, code). Returns the
    id→(column, code) table for decoding."""
    mats, table = [], []
    base = 0
    for col in columns:
        codes = np.asarray(flows[col], np.int64)
        if len(codes) and int(codes.min()) < 0:
            # A negative sentinel would alias into the previous column's
            # item-id range and corrupt support counts on decode.
            raise ValueError(
                f"column {col!r} contains negative codes; itemset "
                f"columns must be non-negative dictionary codes")
        n_codes = int(codes.max()) + 1 if len(codes) else 1
        mats.append(codes + base)
        table.extend((col, c) for c in range(n_codes))
        base += n_codes
    return np.stack(mats, axis=1).astype(np.int32), table


def mine_frequent_patterns(
        flows: ColumnarBatch,
        min_support: int,
        columns: Sequence[str] = DEFAULT_COLUMNS,
        max_len: int = 3,
        mesh: Optional[jax.sharding.Mesh] = None,
        ) -> List[Tuple[Tuple[Tuple[str, str], ...], int]]:
    """All itemsets (as ((column, value), ...) tuples) with support >=
    min_support, FP-Growth-equivalent output. With `mesh`, transactions
    shard over the mesh's first axis and each level's counts allreduce
    with psum."""
    n = len(flows)
    if n == 0:
        return []
    items, table = _encode_items(flows, columns)
    n_items = len(table)
    count_1 = _counts_over(items, mesh,
                           partial(_support_1, n_items=n_items))

    def decode(item_id: int) -> Tuple[str, str]:
        col, code = table[item_id]
        d = flows.dicts.get(col)
        return (col, d.decode_one(code) if d else str(code))

    out: List[Tuple[Tuple[Tuple[str, str], ...], int]] = []
    frequent_1 = np.nonzero(count_1 >= min_support)[0]
    for item in frequent_1:
        out.append(((decode(int(item)),), int(count_1[item])))
    if max_len < 2 or len(frequent_1) == 0:
        return out

    # Level 2: dense remap of frequent items. Counting is dense
    # (f^2 / p*f buffers) — exact but memory-quadratic, so refuse
    # clearly rather than OOM the device.
    f = len(frequent_1)
    if f * f > _MAX_DENSE_COUNTS:
        raise ValueError(
            f"{f} frequent items -> {f * f:,} pair counters exceeds "
            f"the dense-counting budget ({_MAX_DENSE_COUNTS:,}); "
            f"raise min_support or mine fewer columns")
    remap = np.full(n_items, -1, np.int32)
    remap[frequent_1] = np.arange(f, dtype=np.int32)
    dense = remap[items]
    count_2 = _counts_over(dense, mesh, partial(_support_2, f=f))
    freq_pairs = np.nonzero(count_2 >= min_support)[0]
    for pid in freq_pairs:
        lo, hi = divmod(int(pid), f)
        out.append(((decode(int(frequent_1[lo])),
                     decode(int(frequent_1[hi]))), int(count_2[pid])))
    if max_len < 3 or len(freq_pairs) == 0:
        return out

    # Level 3: dense remap of frequent pairs.
    p = len(freq_pairs)
    if p * f > _MAX_DENSE_COUNTS:
        raise ValueError(
            f"{p} frequent pairs x {f} items -> {p * f:,} triple "
            f"counters exceeds the dense-counting budget "
            f"({_MAX_DENSE_COUNTS:,}); raise min_support")
    pair_remap = np.full(f * f, -1, np.int32)
    pair_remap[freq_pairs] = np.arange(p, dtype=np.int32)
    count_3 = _counts_over(
        dense, mesh,
        partial(_support_3, p=p, f=f),
        extra=jnp.asarray(pair_remap))
    for tid in np.nonzero(count_3 >= min_support)[0]:
        pid, c = divmod(int(tid), f)
        lo, hi = divmod(int(freq_pairs[pid]), f)
        out.append(((decode(int(frequent_1[lo])),
                     decode(int(frequent_1[hi])),
                     decode(int(frequent_1[c]))), int(count_3[tid])))
    return out


def run_pattern_mining(db,
                       min_support: int = 0,
                       columns: Sequence[str] = DEFAULT_COLUMNS,
                       max_len: int = 3,
                       start_time: Optional[int] = None,
                       end_time: Optional[int] = None,
                       mining_id: Optional[str] = None,
                       mesh="auto",
                       now: Optional[int] = None,
                       progress=None) -> str:
    """Execute a pattern-mining job over the flow store; writes one
    row per frequent itemset to the `flowpatterns` table and returns
    the mining id.

    The user-facing form of the north-star FP-Growth config — a job
    kind beside TAD/NPR (the reference has no itemset mining at all).
    min_support=0 auto-scales to 1% of the window's rows (floor 2).
    mesh="auto" shards transactions over every visible device with
    psum-allreduced support counts (parallel.job_mesh).
    """
    import time as _time
    import uuid as _uuid

    mining_id = mining_id or str(_uuid.uuid4())
    if mesh == "auto":
        from ..parallel import job_mesh
        mesh = job_mesh()

    if progress:
        progress.stage("read")
    flows = db.flows.select(start_time, end_time)
    if len(flows) == 0:
        if progress:
            progress.done()
        return mining_id
    support = int(min_support) if min_support else max(
        2, len(flows) // 100)

    if progress:
        progress.stage("mine")
    patterns = mine_frequent_patterns(
        flows, support, columns=columns, max_len=max_len, mesh=mesh)

    if progress:
        progress.stage("write")
    created = int(now if now is not None else _time.time())
    rows = [{
        "id": mining_id,
        "timeCreated": created,
        # column=value pairs |-joined: the same delimiter convention
        # the NPR peer strings use (reference
        # policy_recommendation_job.py peer-string protocol)
        "items": "|".join(f"{col}={val}" for col, val in itemset),
        "itemsetLength": len(itemset),
        "support": support_count,
    } for itemset, support_count in patterns]
    if rows:
        db.flowpatterns.insert_rows(rows)
    if progress:
        progress.done()
    return mining_id


def _counts_over(rows: np.ndarray, mesh: Optional[jax.sharding.Mesh],
                 fn, extra: Optional[jnp.ndarray] = None) -> np.ndarray:
    """Run a support-count kernel over all rows: single device, or
    shard_map over the mesh's first axis + psum allreduce of counts."""
    if mesh is None:
        args = (jnp.asarray(rows),) + ((extra,) if extra is not None
                                       else ())
        return np.asarray(fn(*args))
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    n_dev = mesh.shape[axis]
    pad = (-len(rows)) % n_dev
    if pad:
        # Padding rows use item id 0; subtract their contribution after.
        rows = np.concatenate(
            [rows, np.zeros((pad, rows.shape[1]), rows.dtype)])

    in_specs = (P(axis),) + ((P(),) if extra is not None else ())

    def worker(shard, *rest):
        return jax.lax.psum(fn(shard, *rest), axis)

    counts = shard_map(worker, mesh=mesh, in_specs=in_specs,
                           out_specs=P())(
        jnp.asarray(rows), *((extra,) if extra is not None else ()))
    counts = np.asarray(counts).copy()
    if pad:
        # Remove the padded rows' counts (they all landed on id 0's
        # buckets — recompute their exact contribution host-side).
        pad_rows = np.zeros((pad, rows.shape[1]), rows.dtype)
        args = (jnp.asarray(pad_rows),) + ((extra,) if extra is not None
                                           else ())
        counts -= np.asarray(fn(*args))
    return counts
