"""Series construction: flow table → padded per-connection tensors.

Re-provides the reference TAD job's SQL + groupby pipeline
(plugins/anomaly-detection/anomaly_detection.py:507-710): filter flows,
aggregate throughput per (group key, flowEndSeconds) — max() for raw
connections, sum() for pod/external/svc aggregations — then collect each
key's time series. The reference materializes ragged `collect_list` rows
and maps Python UDFs over them; here every series lands in one padded
[S, T] tensor + mask, time-sorted, ready for the batched kernels.

Group-key modes (generate_tad_sql_query, :507-614):
  * None       — 6-tuple connection key, max(throughput)
  * "pod"      — (podNamespace, podLabels|podName, direction), inbound ∪
                 outbound, sum(throughput); start/end time filters do NOT
                 apply in this mode (reference behavior)
  * "external" — destinationIP with flowType == 3, sum(throughput)
  * "svc"      — destinationServicePortName, sum(throughput)

Ordering note: the reference's collect_list order is whatever the shuffle
produced (nondeterministic); we sort by flowEndSeconds, which is the only
semantically meaningful order for the time-series detectors.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..schema import ColumnarBatch
from ..store.views import group_reduce

MEANINGLESS_LABELS = (
    "pod-template-hash",
    "controller-revision-hash",
    "pod-template-generation",
)


@dataclasses.dataclass
class TadQuerySpec:
    """Mirror of the reference job's query arguments
    (anomaly_detection.py main:744-778)."""
    start_time: Optional[int] = None
    end_time: Optional[int] = None
    ns_ignore_list: Sequence[str] = ()
    agg_flow: str = ""          # "", "pod", "external", "svc"
    pod_label: str = ""
    pod_name: str = ""
    pod_namespace: str = ""
    external_ip: str = ""
    svc_port_name: str = ""
    # Scope the query to one cluster's rows in a multicluster store
    # (rows carry the emitting cluster's UUID, test/e2e_mc). Empty =
    # all clusters, like the reference job's unfiltered SQL.
    cluster_uuid: str = ""
    # ARIMA refit cadence: 1 = the reference's exact refit-per-step
    # (anomaly_detection.py:246-253), k>1 = grouped refits (fit reused
    # for k consecutive steps, a k× compute cut on long series), 0 =
    # auto (max(1, T // 2048), sized so 24h@1s series stay feasible).
    # Ignored by EWMA/DBSCAN. The effective value is emitted in every
    # ARIMA result row as `refitEvery`.
    refit_every: int = 1

    @property
    def agg_type(self) -> str:
        return self.agg_flow if self.agg_flow else "None"


@dataclasses.dataclass
class SeriesBatch:
    """Padded series: values/times [S, T], mask [S, T]; one key row per
    series in `keys` (decoded strings for string keys)."""
    key_names: Tuple[str, ...]
    keys: Dict[str, np.ndarray]
    values: np.ndarray
    times: np.ndarray
    mask: np.ndarray
    agg_type: str

    @property
    def n_series(self) -> int:
        return self.values.shape[0]


def _codes_for_strings(batch: ColumnarBatch, name: str,
                       values: Sequence[str]) -> List[int]:
    """Codes of `values` in the batch's dictionary (missing → -1 which
    matches nothing)."""
    d = batch.dicts[name]
    out = []
    for v in values:
        code = d.lookup(v)
        out.append(-1 if code is None else code)
    return out


def _ns_ignore_mask(batch: ColumnarBatch,
                    ns_ignore_list: Sequence[str]) -> np.ndarray:
    """sourcePodNamespace NOT IN (...) AND destinationPodNamespace NOT IN
    (...) (reference :549-553, :576-580)."""
    mask = np.ones(len(batch), dtype=bool)
    if not ns_ignore_list:
        return mask
    for col in ("sourcePodNamespace", "destinationPodNamespace"):
        codes = np.asarray(
            _codes_for_strings(batch, col, ns_ignore_list), np.int64)
        mask &= ~np.isin(np.asarray(batch[col], np.int64), codes)
    return mask


def _label_substring_codes(batch: ColumnarBatch, col: str,
                           needle: str) -> np.ndarray:
    """Codes whose decoded string contains `needle` case-insensitively
    (the reference's ilike '%needle%')."""
    d = batch.dicts[col]
    low = needle.lower()
    return np.asarray(
        [i for i, s in enumerate(d._strings) if low in s.lower()],
        np.int64)


def _pack_and_pad(key_mat: np.ndarray, t: np.ndarray, v: np.ndarray,
                  dtype=np.float64):
    """Group rows by key, sort each group by time, pad to [S, T_max]."""
    n = key_mat.shape[0]
    if n == 0:
        return (np.zeros((0, key_mat.shape[1]), np.int64),
                np.zeros((0, 0), dtype),
                np.zeros((0, 0), np.int64), np.zeros((0, 0), bool))
    order = np.lexsort((t,) + tuple(key_mat.T[::-1]))
    sk, st, sv = key_mat[order], t[order], v[order]
    boundary = np.empty(n, dtype=bool)
    boundary[0] = True
    boundary[1:] = np.any(sk[1:] != sk[:-1], axis=1)
    starts = np.flatnonzero(boundary)
    group_id = np.cumsum(boundary) - 1
    lengths = np.diff(np.append(starts, n))
    S, T = len(starts), int(lengths.max())
    pos = np.arange(n) - starts[group_id]
    values = np.zeros((S, T), dtype)
    times = np.zeros((S, T), np.int64)
    mask = np.zeros((S, T), bool)
    values[group_id, pos] = sv.astype(dtype)
    times[group_id, pos] = st
    mask[group_id, pos] = True
    return sk[starts], values, times, mask


def _group_and_pad(key_mat: np.ndarray, t: np.ndarray, v: np.ndarray,
                   op: str, dtype):
    """Stage-1 (key,time) reduction + ragged→padded packing.

    One seam with two equivalent implementations: the native C++
    builder (native/seriesbuild.cc — one hash-group pass; the host
    tensorize hot path) and the numpy lexsort pipeline. Selected by
    THEIA_NATIVE_SERIES=auto/1/0 (auto = native when available)."""
    flag = os.environ.get("THEIA_NATIVE_SERIES", "auto").lower()
    if flag not in ("0", "off", "false"):
        from ..ingest.native import build_padded_series

        res = build_padded_series(key_mat, t, v, op, dtype)
        if res is not None:
            return res
        if flag in ("1", "on", "true"):
            raise RuntimeError("THEIA_NATIVE_SERIES=1 but the native "
                               "library is unavailable")
    stage1 = np.concatenate([key_mat, t[:, None]], axis=1)
    gk, gv = group_reduce(stage1, v[:, None], op)
    return _pack_and_pad(gk[:, :-1], gk[:, -1], gv[:, 0], dtype)


def remove_meaningless_labels(labels_json: str) -> str:
    """Drop autogenerated label keys (reference :631-644); non-JSON
    input → empty string."""
    try:
        d = json.loads(labels_json)
        if not isinstance(d, dict):
            return ""
    except Exception:
        return ""
    return json.dumps(
        {k: v for k, v in d.items() if k not in MEANINGLESS_LABELS},
        sort_keys=True)


def build_series(flows: ColumnarBatch, spec: TadQuerySpec,
                 dtype=np.float64) -> SeriesBatch:
    """Build the padded series batch for one TAD query."""
    base = _ns_ignore_mask(flows, spec.ns_ignore_list)
    if spec.cluster_uuid:
        code = flows.dicts["clusterUUID"].lookup(spec.cluster_uuid)
        base &= (np.asarray(flows["clusterUUID"])
                 == (-1 if code is None else code))
    if spec.agg_flow == "pod":
        return _build_pod_series(flows, spec, base, dtype)

    if spec.start_time is not None:
        base &= np.asarray(flows["flowStartSeconds"]) >= spec.start_time
    if spec.end_time is not None:
        base &= np.asarray(flows["flowEndSeconds"]) < spec.end_time

    if spec.agg_flow == "external":
        base &= np.asarray(flows["flowType"]) == 3
        if spec.external_ip:
            code = flows.dicts["destinationIP"].lookup(spec.external_ip)
            base &= (np.asarray(flows["destinationIP"])
                     == (-1 if code is None else code))
        key_names: Tuple[str, ...] = ("destinationIP",)
        op = "sum"
    elif spec.agg_flow == "svc":
        if spec.svc_port_name:
            code = flows.dicts["destinationServicePortName"].lookup(
                spec.svc_port_name)
            base &= (np.asarray(flows["destinationServicePortName"])
                     == (-1 if code is None else code))
        else:
            base &= np.asarray(flows["destinationServicePortName"]) != 0
        key_names = ("destinationServicePortName",)
        op = "sum"
    else:
        key_names = ("sourceIP", "sourceTransportPort", "destinationIP",
                     "destinationTransportPort", "protocolIdentifier",
                     "flowStartSeconds")
        op = "max"

    # Materialize only the columns this query touches (masking all 52
    # through ColumnarBatch.filter costs more than the grouping itself
    # on the tensorize hot path).
    col = flows.column_selector(base)

    key_cols = np.stack([col(c) for c in key_names], axis=1)
    key_mat, values, times, mask = _group_and_pad(
        key_cols, col("flowEndSeconds"), col("throughput"), op, dtype)
    keys = _decode_keys(flows, key_names, key_mat)
    return SeriesBatch(key_names, keys, values, times, mask, spec.agg_type)


def _build_pod_series(flows: ColumnarBatch, spec: TadQuerySpec,
                      base: np.ndarray, dtype) -> SeriesBatch:
    """Inbound ∪ outbound pod aggregation (reference :511-565)."""
    by_name = bool(spec.pod_name)
    parts = []  # (keys [n,2], time, thr, direction_id)
    for direction, ns_col, id_col in (
            ("inbound", "destinationPodNamespace",
             "destinationPodName" if by_name else "destinationPodLabels"),
            ("outbound", "sourcePodNamespace",
             "sourcePodName" if by_name else "sourcePodLabels")):
        m = base.copy()
        if by_name:
            code = flows.dicts[id_col].lookup(spec.pod_name)
            m &= np.asarray(flows[id_col]) == (
                -1 if code is None else code)
        elif spec.pod_label:
            codes = _label_substring_codes(flows, id_col, spec.pod_label)
            m &= np.isin(np.asarray(flows[id_col], np.int64), codes)
        else:
            m &= np.asarray(flows[id_col]) != 0  # labels <> ''
        if spec.pod_namespace:
            code = flows.dicts[ns_col].lookup(spec.pod_namespace)
            m &= np.asarray(flows[ns_col]) == (
                -1 if code is None else code)
        col = flows.column_selector(m)

        keys = np.stack([col(ns_col), col(id_col)], axis=1)
        parts.append((keys, col("flowEndSeconds"), col("throughput"),
                      direction))

    id_name = "podName" if by_name else "podLabels"
    key_names = ("podNamespace", id_name, "direction")
    dir_code = {"inbound": 0, "outbound": 1}
    all_keys = np.concatenate(
        [np.concatenate(
            [k, np.full((k.shape[0], 1), dir_code[d], np.int64)], axis=1)
         for k, _, _, d in parts], axis=0)
    all_t = np.concatenate([t for _, t, _, _ in parts])
    all_v = np.concatenate([v for _, _, v, _ in parts])

    key_mat, values, times, mask = _group_and_pad(
        all_keys, all_t, all_v, "sum", dtype)

    ns_dict = flows.dicts["destinationPodNamespace"]
    id_dict = flows.dicts[
        ("destinationPodName" if by_name else "destinationPodLabels")]
    # Source- and destination-side columns share string values but not
    # dictionaries; decode via the side each row came from is impossible
    # after the union, so decode against a merged lookup.
    src_ns = flows.dicts["sourcePodNamespace"]
    src_id = flows.dicts[
        "sourcePodName" if by_name else "sourcePodLabels"]

    def dual_decode(codes, primary, secondary, is_outbound):
        out = np.empty(len(codes), dtype=object)
        for i, (c, ob) in enumerate(zip(codes, is_outbound)):
            d = secondary if ob else primary
            out[i] = d.decode_one(int(c))
        return out

    is_outbound = key_mat[:, 2] == 1
    ns_strings = dual_decode(key_mat[:, 0], ns_dict, src_ns, is_outbound)
    id_strings = dual_decode(key_mat[:, 1], id_dict, src_id, is_outbound)
    if not by_name:
        # remove_meaningless_labels UDF applies in the label mode
        # (reference :687-695)
        id_strings = np.asarray(
            [remove_meaningless_labels(s) for s in id_strings],
            dtype=object)
    keys = {
        "podNamespace": ns_strings,
        id_name: id_strings,
        "direction": np.where(is_outbound, "outbound", "inbound").astype(
            object),
    }
    return SeriesBatch(key_names, keys, values, times, mask, "pod")


def _decode_keys(flows: ColumnarBatch, key_names, key_mat) -> Dict[
        str, np.ndarray]:
    keys: Dict[str, np.ndarray] = {}
    for i, name in enumerate(key_names):
        col = key_mat[:, i] if key_mat.size else np.zeros(
            key_mat.shape[0], np.int64)
        if name in flows.dicts:
            keys[name] = flows.dicts[name].decode(col)
        else:
            keys[name] = col
    return keys
