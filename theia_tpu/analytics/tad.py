"""Throughput Anomaly Detection job — the framework's flagship compute path.

Re-provides plugins/anomaly-detection/anomaly_detection.py end to end:
read a flow window from the store, build per-connection (or aggregated)
throughput series, score them with EWMA / ARIMA / DBSCAN, and write
anomalous points to the `tadetector` table (schema create_table.sh:363-384),
including the reference's "NO ANOMALY DETECTED" filler row when nothing
fires (:395-420).

The scoring step is one jitted XLA computation over the padded [S, T]
batch (kernels in theia_tpu.ops); the reference's per-row Python UDFs
(`plot_anomaly` :424-504) are replaced by `vmap`-batched scans.
"""

from __future__ import annotations

import time
import uuid
from typing import Dict, Optional

import numpy as np

from ..ops import arima_scores, dbscan_scores, ewma_scores
from ..store import FlowDatabase
from ..utils import get_logger
from .series import SeriesBatch, TadQuerySpec, build_series

logger = get_logger("tad")

#: series length at which an under-populated mesh (fewer series than
#: devices) re-shards EWMA over TIME instead of running local — below
#: this the blockwise scan's collective overhead beats its win
LONG_SERIES_T = 4096

ALGORITHMS = ("EWMA", "ARIMA", "DBSCAN")


def effective_refit(algo: str, refit_every: int, n_steps: int) -> int:
    """Resolve the ARIMA refit cadence a job will actually run with.

    refit_every=1 is the reference's exact refit-per-step
    (anomaly_detection.py:246-253); 0 selects the auto heuristic
    max(1, T // 2048) that keeps 24h@1s series feasible. Non-ARIMA
    algorithms have no refit concept → 0."""
    if algo != "ARIMA":
        return 0
    if refit_every < 0:
        raise ValueError(f"refitEvery must be >= 0, got {refit_every}")
    return refit_every if refit_every else max(1, n_steps // 2048)


def _score_series_sharded(values, mask, algo, refit_every, mesh):
    """Score over a device mesh: data-parallel over series (plus
    sequence-parallel over time for EWMA). The sharded kernels run the
    same per-series computation as the single-device path, so result
    rows are identical — this is the reference's `executorInstances`
    scale-out applied to the production job (SURVEY §2.7 row 1)."""
    from ..parallel import (cached_kernel, make_sharded_arima,
                            make_sharded_dbscan, make_sharded_ewma,
                            pad_to_multiple, shard_arrays)
    from ..parallel.mesh import SERIES_AXIS, TIME_AXIS
    from ..parallel.tad_sharded import make_series_sharded

    S, T = values.shape
    values, _ = pad_to_multiple(values, mesh.shape[SERIES_AXIS], axis=0)
    mask, _ = pad_to_multiple(mask, mesh.shape[SERIES_AXIS], axis=0)
    if algo == "EWMA" and mesh.shape.get(TIME_AXIS, 1) > 1:
        # Sequence-parallel scan over the mesh's time axis (its stddev
        # psum may differ from the local kernel in the last float bit;
        # the job path uses time_shards=1 meshes, which are exact).
        values, _ = pad_to_multiple(values, mesh.shape[TIME_AXIS],
                                    axis=1)
        mask, _ = pad_to_multiple(mask, mesh.shape[TIME_AXIS], axis=1)
        fn = cached_kernel(("ewma_time", mesh),
                           lambda: make_sharded_ewma(mesh))
        calc, std, anom, _count = fn(*shard_arrays(mesh, values, mask))
    elif algo == "EWMA":
        fn = cached_kernel(
            ("ewma", mesh),
            lambda: make_series_sharded(mesh, ewma_scores))
        calc, std, anom = fn(*shard_arrays(mesh, values, mask))
    elif algo == "ARIMA":
        refit = effective_refit(algo, refit_every, T)
        fn = cached_kernel(
            ("arima", mesh, refit),
            lambda: make_sharded_arima(mesh, refit_every=refit))
        calc, std, anom = fn(*shard_arrays(mesh, values, mask))
    else:
        from ..ops.dbscan import DEFAULT_EPS, DEFAULT_MIN_SAMPLES
        fn = cached_kernel(
            ("dbscan", mesh),
            lambda: make_sharded_dbscan(
                mesh, eps=DEFAULT_EPS,
                min_samples=DEFAULT_MIN_SAMPLES))
        calc, std, anom = fn(*shard_arrays(mesh, values, mask))
    return (np.asarray(calc)[:S, :T], np.asarray(std)[:S],
            np.asarray(anom)[:S, :T])


def score_series(values: np.ndarray, mask: np.ndarray, algo: str,
                 refit_every: int = 1, mesh=None):
    """Run one algorithm over a padded [S, T] batch.

    Returns (algo_calc [S,T], stddev [S], anomaly [S,T]) as numpy.
    `refit_every` applies to ARIMA only (see `effective_refit`).
    With `mesh` (a jax.sharding.Mesh with >1 device), scoring shards
    over the mesh; results are identical to the local path for
    series-sharded meshes (time_shards=1 — the job_mesh() default).
    Time sharding engages in two cases, both bit-approximate in the
    psum-reduced stddev (anomaly flags exactly ON the threshold can
    differ): an explicitly time-sharded mesh, or automatically for
    EWMA when the batch has fewer series than devices and T ≥
    LONG_SERIES_T (sequence parallelism instead of idle devices).
    """
    if algo not in ALGORITHMS:
        raise ValueError(
            f"algo must be one of {ALGORITHMS}, got {algo!r}")
    if mesh is not None and mesh.size > 1:
        if values.shape[0] >= mesh.size:
            return _score_series_sharded(values, mask, algo,
                                         refit_every, mesh)
        if algo == "EWMA" and values.shape[1] >= LONG_SERIES_T:
            # Few series, long T: series-DP would idle most devices,
            # so re-mesh the same devices sequence-parallel and scan
            # the TIME axis cooperatively (the long-time-series role
            # SURVEY §5 assigns to sequence sharding). The psum'd
            # stddev is bit-approximate vs the local kernel — anomaly
            # flags exactly ON the threshold can flip; worth it only
            # when T is long enough for the blockwise scan to pay.
            from ..parallel.mesh import make_mesh
            tmesh = make_mesh(devices=mesh.devices.flatten(),
                              time_shards=mesh.devices.size)
            logger.info(
                "EWMA over %d series x %d steps: sequence-parallel "
                "time sharding over %d devices (series-DP would idle "
                "%d of them)", values.shape[0], values.shape[1],
                tmesh.devices.size,
                mesh.devices.size - values.shape[0])
            return _score_series_sharded(values, mask, algo,
                                         refit_every, tmesh)
    if algo == "EWMA":
        calc, std, anom = ewma_scores(values, mask)
    elif algo == "ARIMA":
        refit = effective_refit(algo, refit_every, values.shape[1])
        if refit > 1:
            logger.info(
                "ARIMA grouped-refit approximation active: refitting "
                "every %d steps over T=%d (reference-exact is "
                "refitEvery=1)", refit, values.shape[1])
        elif values.shape[1] > 8192:
            logger.warning(
                "ARIMA exact refit-per-step over T=%d steps is "
                "O(T^2) — expect a long job; pass refitEvery=0 "
                "(auto) or k>1 for grouped refits", values.shape[1])
        calc, std, anom = arima_scores(values, mask,
                                       refit_every=refit)
    else:
        calc, std, anom = dbscan_scores(values, mask)
    return np.asarray(calc), np.asarray(std), np.asarray(anom)


def run_tad(db: FlowDatabase, algo: str, spec: TadQuerySpec,
            tad_id: Optional[str] = None,
            now: Optional[int] = None,
            progress=None, mesh="auto") -> str:
    """Execute a full TAD job against the database; returns the job id.

    `mesh`: "auto" scores over every visible device (parallel.job_mesh;
    single-device hosts and THEIA_MESH=off keep the plain path), None
    forces single-device, or pass an explicit jax.sharding.Mesh.
    """
    if algo not in ALGORITHMS:
        raise ValueError(f"algo must be one of {ALGORITHMS}, got {algo!r}")
    if isinstance(mesh, str):
        if mesh != "auto":
            raise ValueError(
                f"mesh must be 'auto', None or a Mesh, got {mesh!r} "
                f"(use THEIA_MESH=off to disable sharding)")
        from ..parallel import job_mesh
        mesh = job_mesh()
    tad_id = tad_id or str(uuid.uuid4())

    if progress:
        progress.stage("read")
    flows = db.flows.scan()

    if progress:
        progress.stage("tensorize")
    batch = build_series(flows, spec)

    if progress:
        progress.stage("score")
    rows = detect_anomalies(batch, algo, tad_id, now=now,
                            refit_every=spec.refit_every, mesh=mesh)

    if progress:
        progress.stage("write")
    db.tadetector.insert_rows(rows)
    if progress:
        progress.done()
    return tad_id


def detect_anomalies(batch: SeriesBatch, algo: str, tad_id: str,
                     now: Optional[int] = None, refit_every: int = 1,
                     mesh=None):
    """Score a series batch and materialize tadetector result rows."""
    refit = effective_refit(
        algo, refit_every,
        batch.values.shape[1] if batch.n_series else 0)
    if batch.n_series == 0:
        return [_no_anomaly_row(batch.agg_type, algo, tad_id, now,
                                refit)]

    # Pass the resolved cadence so the emitted refitEvery and the one
    # actually executed cannot drift (effective_refit is idempotent).
    calc, std, anom = score_series(batch.values, batch.mask, algo,
                                   refit_every=refit if refit else 1,
                                   mesh=mesh)
    sidx, tidx = np.nonzero(anom)
    if sidx.size == 0:
        return [_no_anomaly_row(batch.agg_type, algo, tad_id, now,
                                refit)]

    # stddev_samp is NULL (NaN) for 1-point series; those can't be
    # anomalous, but guard the cast anyway.
    std = np.nan_to_num(std, nan=0.0)
    rows = []
    for s, t in zip(sidx, tidx):
        row: Dict[str, object] = {
            "aggType": batch.agg_type,
            "algoType": algo,
            "flowEndSeconds": int(batch.times[s, t]),
            "throughputStandardDeviation": float(std[s]),
            "algoCalc": float(calc[s, t]),
            "throughput": float(batch.values[s, t]),
            "anomaly": "true",
            "refitEvery": refit,
            "id": tad_id,
        }
        # Series key names coincide with tadetector column names; keys
        # not present for this agg mode default to ''/0 in the schema
        # (the reference emits a mode-specific column subset,
        # filter_df_with_true_anomalies :352-394).
        for key_name in batch.key_names:
            v = batch.keys[key_name][s]
            row[key_name] = v.item() if isinstance(v, np.generic) else v
        rows.append(row)
    return rows


def _no_anomaly_row(agg_type: str, algo: str, tad_id: str,
                    now: Optional[int],
                    refit: int = 0) -> Dict[str, object]:
    """The reference's filler row (:401-419): string identity columns get
    'None', flowStartSeconds gets the wall clock, anomaly gets the
    sentinel text."""
    return {
        "sourceIP": "None",
        "sourceTransportPort": 0,
        "destinationIP": "None",
        "destinationTransportPort": 0,
        "protocolIdentifier": 0,
        "flowStartSeconds": int(now if now is not None else time.time()),
        "podNamespace": "None",
        "podLabels": "None",
        "podName": "None",
        "destinationServicePortName": "None",
        "direction": "None",
        "flowEndSeconds": 0,
        "throughputStandardDeviation": 0.0,
        "aggType": agg_type,
        "algoType": algo,
        "algoCalc": 0.0,
        "throughput": 0.0,
        "anomaly": "NO ANOMALY DETECTED",
        "refitEvery": refit,
        "id": tad_id,
    }
