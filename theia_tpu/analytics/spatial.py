"""Spatial anomaly detection over flow embeddings.

The BASELINE north-star config 3: "DBSCAN spatial anomaly on
(srcIP, dstIP, dstPort, bytes) embeddings". Flows embed into a 4-D
feature space — categorical identities (source, destination, port)
hash to pseudo-random coordinates so distance means same/different,
volume contributes a log-scaled continuous axis — and the blocked
spatial DBSCAN kernel (ops/dbscan.py dbscan_points_noise) marks the
flows that belong to no recurring traffic pattern as noise.

A clustered flow = a pattern seen many times (same endpoints/port,
similar volume); noise = one-off combinations — exfiltration probes,
scans, misconfigurations. The reference has DBSCAN only over per-
connection 1-D throughput series; this is the cross-flow spatial
variant its benchmark config names.
"""

from __future__ import annotations

from typing import Dict, List

import jax.numpy as jnp
import numpy as np

from ..ops.dbscan import dbscan_points_noise
from ..schema import ColumnarBatch

# Categorical axes are scaled so ANY identity mismatch dominates a
# volume difference: hash01 in [0, SCALE) with SCALE >> eps.
CATEGORICAL_SCALE = 100.0
DEFAULT_EPS = 1.0
DEFAULT_MIN_SAMPLES = 4


def _hash01(codes: np.ndarray) -> np.ndarray:
    """Integer codes → deterministic pseudo-random floats in [0, 1)."""
    h = codes.astype(np.uint32)
    h ^= h >> 16
    h = (h * np.uint32(0x85EBCA6B)) & np.uint32(0xFFFFFFFF)
    h ^= h >> 13
    h = (h * np.uint32(0xC2B2AE35)) & np.uint32(0xFFFFFFFF)
    h ^= h >> 16
    return h.astype(np.float64) / 4294967296.0


def flow_embeddings(flows: ColumnarBatch) -> np.ndarray:
    """[n, 4] float32 (src, dst, port, log-bytes) embedding."""
    src = _hash01(np.asarray(flows["sourceIP"], np.int64))
    dst = _hash01(np.asarray(flows["destinationIP"], np.int64))
    port = _hash01(np.asarray(flows["destinationTransportPort"],
                              np.int64))
    vol = np.log1p(np.asarray(flows["octetDeltaCount"], np.float64))
    return np.stack([src * CATEGORICAL_SCALE, dst * CATEGORICAL_SCALE,
                     port * CATEGORICAL_SCALE, vol],
                    axis=1).astype(np.float32)


def spatial_outliers(flows: ColumnarBatch,
                     eps: float = DEFAULT_EPS,
                     min_samples: int = DEFAULT_MIN_SAMPLES,
                     block: int = 1024) -> List[Dict[str, object]]:
    """Flows outside every recurring traffic pattern. Returns one dict
    per noise flow: decoded source/destination/port/bytes."""
    n = len(flows)
    if n == 0:
        return []
    emb = flow_embeddings(flows)
    noise = np.asarray(dbscan_points_noise(
        jnp.asarray(emb), jnp.ones(n, bool), eps=eps,
        min_samples=min_samples, block=block))
    idx = np.nonzero(noise)[0]
    src = flows.strings("sourceIP")
    dst = flows.strings("destinationIP")
    port = np.asarray(flows["destinationTransportPort"])
    octets = np.asarray(flows["octetDeltaCount"])
    return [{"sourceIP": str(src[i]), "destinationIP": str(dst[i]),
             "destinationTransportPort": int(port[i]),
             "octetDeltaCount": int(octets[i])} for i in idx]
