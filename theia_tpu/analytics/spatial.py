"""Spatial anomaly detection over flow embeddings.

The BASELINE north-star config 3: "DBSCAN spatial anomaly on
(srcIP, dstIP, dstPort, bytes) embeddings". Flows embed into a 4-D
feature space — categorical identities (source, destination, port)
hash to pseudo-random coordinates so distance means same/different,
volume contributes a log-scaled continuous axis — and the blocked
spatial DBSCAN kernel (ops/dbscan.py dbscan_points_noise) marks the
flows that belong to no recurring traffic pattern as noise.

A clustered flow = a pattern seen many times (same endpoints/port,
similar volume); noise = one-off combinations — exfiltration probes,
scans, misconfigurations. The reference has DBSCAN only over per-
connection 1-D throughput series; this is the cross-flow spatial
variant its benchmark config names.
"""

from __future__ import annotations

from typing import Dict, List

import jax.numpy as jnp
import numpy as np

from ..ops.dbscan import dbscan_points_noise
from ..schema import ColumnarBatch

# Categorical axes are scaled so ANY identity mismatch dominates a
# volume difference: hash coordinates in [0, SCALE) with SCALE >> eps.
# Each identity gets TWO independent hash coordinates: a single axis
# collides two distinct identities with probability ~2·eps/SCALE (~2%),
# which would silently merge clusters; two axes square that to ~1e-4.
# (f32 d² cancellation caps SCALE itself at ~1e2 for eps=1.)
CATEGORICAL_SCALE = 100.0
DEFAULT_EPS = 1.0
DEFAULT_MIN_SAMPLES = 4

EMBED_DIM = 7   # 2 src + 2 dst + 2 port + volume


def _hash01(codes: np.ndarray, seed: int) -> np.ndarray:
    """Integer codes → deterministic pseudo-random floats in [0, 1)."""
    h = codes.astype(np.uint32) ^ np.uint32(seed)
    h ^= h >> 16
    h = (h * np.uint32(0x85EBCA6B)) & np.uint32(0xFFFFFFFF)
    h ^= h >> 13
    h = (h * np.uint32(0xC2B2AE35)) & np.uint32(0xFFFFFFFF)
    h ^= h >> 16
    return h.astype(np.float64) / 4294967296.0


def flow_embeddings(flows: ColumnarBatch) -> np.ndarray:
    """[n, 7] float32 (src×2, dst×2, port×2, log-bytes) embedding."""
    axes = []
    for col in ("sourceIP", "destinationIP",
                "destinationTransportPort"):
        codes = np.asarray(flows[col], np.int64)
        for seed in (0x1234ABCD, 0x9E3779B9):
            axes.append(_hash01(codes, seed) * CATEGORICAL_SCALE)
    axes.append(np.log1p(
        np.asarray(flows["octetDeltaCount"], np.float64)))
    return np.stack(axes, axis=1).astype(np.float32)


def spatial_outliers(flows: ColumnarBatch,
                     eps: float = DEFAULT_EPS,
                     min_samples: int = DEFAULT_MIN_SAMPLES,
                     block: int = 1024) -> List[Dict[str, object]]:
    """Flows outside every recurring traffic pattern. Returns one dict
    per noise flow: decoded source/destination/port/bytes."""
    n = len(flows)
    if n == 0:
        return []
    emb = flow_embeddings(flows)
    noise = np.asarray(dbscan_points_noise(
        jnp.asarray(emb), jnp.ones(n, bool), eps=eps,
        min_samples=min_samples, block=block))
    idx = np.nonzero(noise)[0]
    src = flows.strings("sourceIP")
    dst = flows.strings("destinationIP")
    port = np.asarray(flows["destinationTransportPort"])
    octets = np.asarray(flows["octetDeltaCount"])
    return [{"sourceIP": str(src[i]), "destinationIP": str(dst[i]),
             "destinationTransportPort": int(port[i]),
             "octetDeltaCount": int(octets[i])} for i in idx]
