"""Spatial anomaly detection over flow embeddings.

The BASELINE north-star config 3: "DBSCAN spatial anomaly on
(srcIP, dstIP, dstPort, bytes) embeddings". Flows embed into a 4-D
feature space — categorical identities (source, destination, port)
hash to pseudo-random coordinates so distance means same/different,
volume contributes a log-scaled continuous axis — and the blocked
spatial DBSCAN kernel (ops/dbscan.py dbscan_points_noise) marks the
flows that belong to no recurring traffic pattern as noise.

A clustered flow = a pattern seen many times (same endpoints/port,
similar volume); noise = one-off combinations — exfiltration probes,
scans, misconfigurations. The reference has DBSCAN only over per-
connection 1-D throughput series; this is the cross-flow spatial
variant its benchmark config names.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..ops.dbscan import dbscan_points_noise
from ..schema import ColumnarBatch

# Categorical axes are scaled so ANY identity mismatch dominates a
# volume difference: hash coordinates in [0, SCALE) with SCALE >> eps.
# Each identity gets TWO independent hash coordinates: a single axis
# collides two distinct identities with probability ~2·eps/SCALE (~2%),
# which would silently merge clusters; two axes square that to ~1e-4.
# (f32 d² cancellation caps SCALE itself at ~1e2 for eps=1.)
CATEGORICAL_SCALE = 100.0
DEFAULT_EPS = 1.0
DEFAULT_MIN_SAMPLES = 4

EMBED_DIM = 7   # 2 src + 2 dst + 2 port + volume


def _hash01(codes: np.ndarray, seed: int) -> np.ndarray:
    """Integer codes → deterministic pseudo-random floats in [0, 1)."""
    h = codes.astype(np.uint32) ^ np.uint32(seed)
    h ^= h >> 16
    h = (h * np.uint32(0x85EBCA6B)) & np.uint32(0xFFFFFFFF)
    h ^= h >> 13
    h = (h * np.uint32(0xC2B2AE35)) & np.uint32(0xFFFFFFFF)
    h ^= h >> 16
    return h.astype(np.float64) / 4294967296.0


def flow_embeddings(flows: ColumnarBatch) -> np.ndarray:
    """[n, 7] float32 (src×2, dst×2, port×2, log-bytes) embedding."""
    axes = []
    for col in ("sourceIP", "destinationIP",
                "destinationTransportPort"):
        codes = np.asarray(flows[col], np.int64)
        for seed in (0x1234ABCD, 0x9E3779B9):
            axes.append(_hash01(codes, seed) * CATEGORICAL_SCALE)
    axes.append(np.log1p(
        np.asarray(flows["octetDeltaCount"], np.float64)))
    return np.stack(axes, axis=1).astype(np.float32)


def spatial_outliers(flows: ColumnarBatch,
                     eps: float = DEFAULT_EPS,
                     min_samples: int = DEFAULT_MIN_SAMPLES,
                     block: int = 1024,
                     mesh=None,
                     embeddings: Optional[np.ndarray] = None
                     ) -> List[Dict[str, object]]:
    """Flows outside every recurring traffic pattern. Returns one dict
    per noise flow: decoded source/destination/port/bytes. With
    `mesh`, the pairwise pass shards rows over the mesh with
    all_gathered points/core-flags (parallel.make_sharded_points_
    dbscan). `embeddings` lets a caller that already embedded the
    flows (run_spatial's staged progress) skip recomputation."""
    n = len(flows)
    if n == 0:
        return []
    emb = embeddings if embeddings is not None \
        else flow_embeddings(flows)
    if mesh is not None:
        from ..parallel import make_sharded_points_dbscan, \
            pad_to_multiple
        from ..parallel.mesh import ROWS_AXIS, make_rows_mesh
        if ROWS_AXIS not in mesh.axis_names:
            # job_mesh() hands out the (series x time) job mesh; the
            # points kernel shards tile ROWS — rebuild over the same
            # devices with the rows axis.
            mesh = make_rows_mesh(devices=mesh.devices.flatten())
        n_dev = mesh.devices.size
        padded, _ = pad_to_multiple(emb, n_dev, axis=0)
        valid = np.zeros(len(padded), bool)
        valid[:n] = True
        noise = np.asarray(make_sharded_points_dbscan(
            mesh, eps=eps, min_samples=min_samples)(
            jnp.asarray(padded), jnp.asarray(valid)))[:n]
    else:
        noise = np.asarray(dbscan_points_noise(
            jnp.asarray(emb), jnp.ones(n, bool), eps=eps,
            min_samples=min_samples, block=block))
    idx = np.nonzero(noise)[0]
    src = flows.strings("sourceIP")
    dst = flows.strings("destinationIP")
    port = np.asarray(flows["destinationTransportPort"])
    octets = np.asarray(flows["octetDeltaCount"])
    return [{"sourceIP": str(src[i]), "destinationIP": str(dst[i]),
             "destinationTransportPort": int(port[i]),
             "octetDeltaCount": int(octets[i])} for i in idx]


def run_spatial(db,
                eps: float = DEFAULT_EPS,
                min_samples: int = DEFAULT_MIN_SAMPLES,
                start_time=None,
                end_time=None,
                spatial_id=None,
                mesh="auto",
                now=None,
                progress=None) -> str:
    """Execute a spatial anomaly-detection job over the flow store;
    writes one row per noise flow to the `spatialnoise` table and
    returns the detection id.

    The user-facing form of the north-star spatial-DBSCAN config — a
    job kind beside TAD/NPR (the reference's DBSCAN is per-connection
    1-D throughput only, plugins/anomaly-detection/
    anomaly_detection.py:325-349). mesh="auto" shards the pairwise
    pass over every visible device (parallel.job_mesh).
    """
    import time as _time
    import uuid as _uuid

    spatial_id = spatial_id or str(_uuid.uuid4())
    if mesh == "auto":
        from ..parallel import job_mesh
        mesh = job_mesh()

    if progress:
        progress.stage("read")
    flows = db.flows.select(start_time, end_time)
    if len(flows) == 0:
        if progress:
            progress.done()
        return spatial_id

    if progress:
        progress.stage("embed")
    emb = flow_embeddings(flows)

    if progress:
        progress.stage("score")
    outliers = spatial_outliers(flows, eps=eps,
                                min_samples=min_samples, mesh=mesh,
                                embeddings=emb)

    if progress:
        progress.stage("write")
    created = int(now if now is not None else _time.time())
    rows = [{**o, "id": spatial_id, "timeCreated": created}
            for o in outliers]
    if rows:
        db.spatialnoise.insert_rows(rows)
    if progress:
        progress.done()
    return spatial_id
