"""NetworkPolicy YAML generation for the recommendation job.

Builds the same policy documents the reference emits via kubernetes-client
/ antrea_crd dataclasses + camelCase conversion (reference:
plugins/policy-recommendation/policy_recommendation_job.py:188-618 and
policy_recommendation_utils.py camel_dict/dict_to_yaml). Here the dicts
are written in camelCase directly — no dataclass detour — and dumped with
pyyaml. Policy kinds match the reference's result-table values
(antrea_crd.py:789-793: anp/knp/acnp/acg).

Name suffixes: the reference appends 5 random lowercase/digit chars
(generate_policy_name :244-250); we derive a deterministic 5-char hash of
the policy's identity instead, so runs are reproducible and golden tests
don't need to stub the RNG.
"""

from __future__ import annotations

import hashlib
import ipaddress
import json
from typing import Dict, List, Optional

import yaml

ROW_DELIMITER = "#"
PEER_DELIMITER = "|"
DEFAULT_POLICY_PRIORITY = 5

KIND_ANP = "anp"
KIND_KNP = "knp"
KIND_ACNP = "acnp"
KIND_ACG = "acg"


def policy_name(info: str, identity: str) -> str:
    suffix = hashlib.sha1(identity.encode()).hexdigest()[:5]
    return f"{info}-{suffix}"


def _cidr(ip: str) -> str:
    version = ipaddress.ip_address(ip).version
    return f"{ip}/32" if version == 4 else f"{ip}/128"


def dump_yaml(doc: Dict) -> str:
    return yaml.dump(doc)


# -- K8s NetworkPolicy (option 3; reference generate_k8s_np :253-296) ----

def k8s_egress_rule(egress: str) -> Dict:
    parts = egress.split(ROW_DELIMITER)
    if len(parts) == 4:
        ns, labels, port, protocol = parts
        peer = {"namespaceSelector": {"matchLabels": {"name": ns}},
                "podSelector": {"matchLabels": json.loads(labels)}}
    elif len(parts) == 3:
        ip, port, protocol = parts
        peer = {"ipBlock": {"cidr": _cidr(ip)}}
    else:
        raise ValueError(f"egress tuple {egress!r} has wrong format")
    return {"to": [peer],
            "ports": [{"port": int(port), "protocol": protocol}]}


def k8s_ingress_rule(ingress: str) -> Dict:
    parts = ingress.split(ROW_DELIMITER)
    if len(parts) != 4:
        raise ValueError(f"ingress tuple {ingress!r} has wrong format")
    ns, labels, port, protocol = parts
    peer = {"namespaceSelector": {"matchLabels": {"name": ns}},
            "podSelector": {"matchLabels": json.loads(labels)}}
    return {"from": [peer],
            "ports": [{"port": int(port), "protocol": protocol}]}


def generate_k8s_np(applied_to: str, ingresses: List[str],
                    egresses: List[str]) -> Optional[str]:
    ns, labels = applied_to.split(ROW_DELIMITER)
    egress_rules = [k8s_egress_rule(e) for e in sorted(set(egresses))
                    if ROW_DELIMITER in e]
    ingress_rules = [k8s_ingress_rule(i) for i in sorted(set(ingresses))
                     if ROW_DELIMITER in i]
    if not egress_rules and not ingress_rules:
        return None
    policy_types = ([] + (["Egress"] if egress_rules else [])
                    + (["Ingress"] if ingress_rules else []))
    doc = {
        "apiVersion": "networking.k8s.io/v1",
        "kind": "NetworkPolicy",
        "metadata": {"name": policy_name("recommend-k8s-np", applied_to),
                     "namespace": ns},
        "spec": {
            "egress": egress_rules,
            "ingress": ingress_rules,
            "podSelector": {"matchLabels": json.loads(labels)},
            "policyTypes": policy_types,
        },
    }
    return dump_yaml(doc)


# -- Antrea NetworkPolicy (options 1/2; reference generate_anp :391-448) -

def anp_egress_rule(egress: str) -> Optional[Dict]:
    parts = egress.split(ROW_DELIMITER)
    if len(parts) == 4:           # pod-to-pod
        ns, labels, port, protocol = parts
        try:
            labels_dict = json.loads(labels)
        except Exception:
            return None
        peer = {"namespaceSelector":
                {"matchLabels": {"kubernetes.io/metadata.name": ns}},
                "podSelector": {"matchLabels": labels_dict}}
        return {"action": "Allow", "to": [peer],
                "ports": [{"protocol": protocol, "port": int(port)}]}
    if len(parts) == 3:           # pod-to-external
        ip, port, protocol = parts
        return {"action": "Allow",
                "to": [{"ipBlock": {"cidr": _cidr(ip)}}],
                "ports": [{"protocol": protocol, "port": int(port)}]}
    if len(parts) == 2:           # pod-to-svc (toServices)
        svc_ns, svc_name = parts
        return {"action": "Allow",
                "toServices": [{"namespace": svc_ns, "name": svc_name}]}
    raise ValueError(f"egress tuple {egress!r} has wrong format")


def anp_ingress_rule(ingress: str) -> Optional[Dict]:
    parts = ingress.split(ROW_DELIMITER)
    if len(parts) != 4:
        raise ValueError(f"ingress tuple {ingress!r} has wrong format")
    ns, labels, port, protocol = parts
    try:
        labels_dict = json.loads(labels)
    except Exception:
        return None
    peer = {"namespaceSelector":
            {"matchLabels": {"kubernetes.io/metadata.name": ns}},
            "podSelector": {"matchLabels": labels_dict}}
    return {"action": "Allow", "from": [peer],
            "ports": [{"protocol": protocol, "port": int(port)}]}


def generate_anp(applied_to: str, ingresses: List[str],
                 egresses: List[str]) -> Optional[str]:
    ns, labels = applied_to.split(ROW_DELIMITER)
    try:
        labels_dict = json.loads(labels)
    except Exception:
        return None
    egress_rules = [r for e in sorted(set(egresses)) if ROW_DELIMITER in e
                    for r in [anp_egress_rule(e)] if r]
    ingress_rules = [r for i in sorted(set(ingresses)) if ROW_DELIMITER in i
                     for r in [anp_ingress_rule(i)] if r]
    if not egress_rules and not ingress_rules:
        return None
    doc = {
        "apiVersion": "crd.antrea.io/v1alpha1",
        "kind": "NetworkPolicy",
        "metadata": {"name": policy_name("recommend-allow-anp", applied_to),
                     "namespace": ns},
        "spec": {
            "tier": "Application",
            "priority": DEFAULT_POLICY_PRIORITY,
            "appliedTo": [{"podSelector": {"matchLabels": labels_dict}}],
            "egress": egress_rules,
            "ingress": ingress_rules,
        },
    }
    return dump_yaml(doc)


# -- Service ClusterGroup + ACNP (reference :451-549) --------------------

def svc_cg_name(namespace: str, name: str) -> str:
    return "-".join(["cg", namespace, name])


def generate_svc_cg(service_port_name: str) -> str:
    namespace, name = service_port_name.partition(":")[0].split("/")
    doc = {
        "apiVersion": "crd.antrea.io/v1alpha2",
        "kind": "ClusterGroup",
        "metadata": {"name": svc_cg_name(namespace, name)},
        "spec": {"serviceReference": {"name": name,
                                      "namespace": namespace}},
    }
    return dump_yaml(doc)


def acnp_svc_egress_rule(egress: str) -> Dict:
    svc_port_name, port, protocol = egress.split(ROW_DELIMITER)
    ns, svc = svc_port_name.partition(":")[0].split("/")
    return {"action": "Allow",
            "to": [{"group": svc_cg_name(ns, svc)}],
            "ports": [{"protocol": protocol, "port": int(port)}]}


def generate_svc_acnp(applied_to: str,
                      egresses: List[str]) -> Optional[str]:
    ns, labels = applied_to.split(ROW_DELIMITER)
    try:
        labels_dict = json.loads(labels)
    except Exception:
        return None
    egress_rules = [acnp_svc_egress_rule(e) for e in sorted(set(egresses))]
    if not egress_rules:
        return None
    doc = {
        "apiVersion": "crd.antrea.io/v1alpha1",
        "kind": "ClusterNetworkPolicy",
        "metadata": {
            "name": policy_name("recommend-svc-allow-acnp", applied_to)},
        "spec": {
            "tier": "Application",
            "priority": DEFAULT_POLICY_PRIORITY,
            "appliedTo": [{
                "podSelector": {"matchLabels": labels_dict},
                "namespaceSelector":
                    {"matchLabels": {"kubernetes.io/metadata.name": ns}},
            }],
            "egress": egress_rules,
        },
    }
    return dump_yaml(doc)


# -- Baseline reject ACNPs (reference generate_reject_acnp :552-618) -----

def generate_reject_acnp(applied_to: str = "") -> Optional[str]:
    if not applied_to:
        name = "recommend-reject-all-acnp"
        applied = {"podSelector": {}, "namespaceSelector": {}}
    else:
        name = policy_name("recommend-reject-acnp", applied_to)
        ns, labels = applied_to.split(ROW_DELIMITER)
        try:
            labels_dict = json.loads(labels)
        except Exception:
            return None
        applied = {
            "podSelector": {"matchLabels": labels_dict},
            "namespaceSelector":
                {"matchLabels": {"kubernetes.io/metadata.name": ns}},
        }
    doc = {
        "apiVersion": "crd.antrea.io/v1alpha1",
        "kind": "ClusterNetworkPolicy",
        "metadata": {"name": name},
        "spec": {
            "tier": "Baseline",
            "priority": DEFAULT_POLICY_PRIORITY,
            "appliedTo": [applied],
            "egress": [{"action": "Reject",
                        "to": [{"podSelector": {}}]}],
            "ingress": [{"action": "Reject",
                         "from": [{"podSelector": {}}]}],
        },
    }
    return dump_yaml(doc)


# -- Namespace allow-list ACNPs (reference :737-782) ---------------------

def generate_ns_allow_acnp(ns: str) -> str:
    doc = {
        "apiVersion": "crd.antrea.io/v1alpha1",
        "kind": "ClusterNetworkPolicy",
        "metadata": {"name": policy_name(
            f"recommend-allow-acnp-{ns}", ns)},
        "spec": {
            "tier": "Platform",
            "priority": DEFAULT_POLICY_PRIORITY,
            "appliedTo": [{"namespaceSelector":
                           {"matchLabels":
                            {"kubernetes.io/metadata.name": ns}}}],
            "egress": [{"action": "Allow", "to": [{"podSelector": {}}]}],
            "ingress": [{"action": "Allow",
                         "from": [{"podSelector": {}}]}],
        },
    }
    return dump_yaml(doc)
