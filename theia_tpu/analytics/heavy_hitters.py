"""Streaming heavy-hitter / DDoS detection at ingest line rate.

The BASELINE north-star config the reference has no equivalent for:
"Streaming Count-Min-Sketch + online k-means heavy-hitter / DDoS
detection at line rate from live Antrea FlowExporter". Per ingest
micro-batch, one fused device step:

  1. CMS update: per-destination traffic volume sketched into a
     [depth, width] counter array (ops/sketch.py) — sub-linear memory
     however many distinct destinations the cluster sees.
  2. Heavy hitters: destinations whose sketched share of total volume
     exceeds `hh_fraction` (the classic phi-heavy-hitter definition).
  3. Online k-means over per-flow feature vectors
     (log bytes, log packets, log mean packet size, log peer fan-in):
     flows assigned far from every centroid (distance > `ddos_sigma`
     x the running distance scale) are traffic-shape outliers — the
     DDoS signal that volume alone misses (many small flows from many
     sources map to a fan-in-heavy corner of feature space).

Keys are integer dictionary codes straight from the columnar batch —
no string work on the hot path. Alerts carry decoded names.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.sketch import (
    CmsState,
    KMeansState,
    cms_init,
    cms_query,
    cms_update,
    kmeans_init,
    kmeans_step,
)
from ..schema import ColumnarBatch

FEATURES = 4


@jax.jit
def _fused_step(cms: CmsState, km: KMeansState, keys: jnp.ndarray,
                volumes: jnp.ndarray, q: jnp.ndarray,
                feats: jnp.ndarray, valid: jnp.ndarray
                ) -> Tuple[CmsState, KMeansState, jnp.ndarray,
                           jnp.ndarray]:
    """The whole per-batch device step as ONE dispatch: sketch update,
    heavy-hitter query, k-means step. Per-dispatch overhead (host→
    device puts + sync round trips) dominates the actual compute on
    weak ingest hosts, so three separate kernel calls per block would
    triple the fixed cost."""
    cms = cms_update(cms, keys, volumes)
    est = cms_query(cms, q)
    km, _, dist = kmeans_step(km, feats, valid)
    return cms, km, est, dist


@dataclasses.dataclass
class HeavyHitterAlert:
    kind: str              # "heavy_hitter" | "ddos_shape"
    destination: str
    estimate: float        # sketched volume (hh) or outlier distance
    share: float           # fraction of total volume (hh) / sigma (ddos)


class HHPlan(NamedTuple):
    """Padded device inputs for one micro-batch's heavy-hitter step,
    built host-side by `build_hh_plan` and consumed either by this
    class's own `_fused_step` or by the cross-shard fused engine
    (ops/fused_detector.py) — one builder so the two engines cannot
    drift."""
    keys: np.ndarray        # [size] uint32 CMS keys (dst codes, padded)
    vols: np.ndarray        # [size] float32 volumes (zero padding)
    q: np.ndarray           # [q_size] uint32 distinct-dst query keys
    feats: np.ndarray       # [size, FEATURES] float32
    valid: np.ndarray       # [size] bool (False on padding)
    uniq_codes: np.ndarray  # distinct destination codes, unpadded
    dst_codes: np.ndarray   # [n] int64 per-row destination codes
    n: int                  # live rows


def pad_bucket(n: int, minimum: int = 256) -> int:
    """Fixed dispatch buckets (next power of two, min 256) so the
    jitted kernels compile once per bucket instead of once per
    distinct micro-batch size."""
    size = minimum
    while size < n:
        size <<= 1
    return size


def _features_cols(octets: np.ndarray, packets: np.ndarray,
                   dst: np.ndarray, src: np.ndarray) -> np.ndarray:
    """Traffic-shape feature matrix from raw columns (vectorized,
    host side). octets/packets float64, dst/src int64 codes."""
    # peer fan-in: DISTINCT sources per destination in this batch —
    # a 64-source flood and one chatty source sending 64 flows must
    # score differently. One 1-D unique over a packed 64-bit
    # (dst, src) key instead of np.unique(axis=0)'s row-structured
    # sort (codes are int32, so the pack is lossless).
    pairs = np.unique((dst << np.int64(32)) | src)
    per_dst_dsts, per_dst_counts = np.unique(
        pairs >> np.int64(32), return_counts=True)
    fan_in = per_dst_counts[
        np.searchsorted(per_dst_dsts, dst)].astype(np.float64)
    mean_pkt = octets / np.maximum(packets, 1.0)
    return np.stack([np.log1p(octets), np.log1p(packets),
                     np.log1p(mean_pkt), np.log1p(fan_in)], axis=1)


def build_hh_plan(dst_codes: np.ndarray, src_codes: np.ndarray,
                  octets: np.ndarray, packets: np.ndarray,
                  staging: Optional[Callable] = None) -> HHPlan:
    """Padded device inputs for one micro-batch. `staging(tag, shape,
    dtype)` returns a reusable buffer to fill (the fused engine's
    pinned ring); None allocates fresh arrays. Padded rows carry zero
    volume (sketch-neutral) and are masked out of the centroid
    update."""
    n = len(dst_codes)
    size = pad_bucket(n)

    def _alloc(tag, shape, dtype):
        if staging is None:
            return np.zeros(shape, dtype)
        a = staging(tag, shape, dtype)
        a[...] = 0
        return a

    keys = _alloc("hh_keys", (size,), np.uint32)
    keys[:n] = dst_codes.astype(np.uint32)
    vols = _alloc("hh_vols", (size,), np.float32)
    vols[:n] = octets

    # Heavy-hitter query keys: this batch's distinct destinations.
    uniq_codes = np.unique(dst_codes)
    q = _alloc("hh_q", (pad_bucket(len(uniq_codes)),), np.uint32)
    q[:len(uniq_codes)] = uniq_codes.astype(np.uint32)

    feats = _alloc("hh_feats", (size, FEATURES), np.float32)
    feats[:n] = _features_cols(octets, packets, dst_codes, src_codes)
    valid = _alloc("hh_valid", (size,), bool)
    valid[:n] = True
    return HHPlan(keys, vols, q, feats, valid, uniq_codes,
                  np.asarray(dst_codes), n)


class HeavyHitterDetector:
    """Device-resident CMS + online k-means over ingest micro-batches."""

    def __init__(self, depth: int = 4, width: int = 8192,
                 k: int = 8, hh_fraction: float = 0.10,
                 ddos_sigma: float = 4.0, seed: int = 0) -> None:
        self.cms: CmsState = cms_init(depth, width)
        rng = np.random.default_rng(seed)
        self.kmeans: KMeansState = kmeans_init(
            rng.normal(0.0, 1.0, size=(k, FEATURES)))
        self.hh_fraction = hh_fraction
        self.ddos_sigma = ddos_sigma
        # Running mean distance scale (EW average) for the outlier band.
        self._dist_scale = 1.0
        self.batches = 0
        #: total sketched volume after the last update (host float) —
        #: peers in a sharded ensemble read this to evaluate shares
        #: against the cluster total, not just this shard's.
        self.total_volume = 0.0

    # -- one micro-batch -------------------------------------------------

    def update(self, batch: ColumnarBatch,
               extra_total: float = 0.0) -> List[HeavyHitterAlert]:
        """Advance the sketch/centroids with one micro-batch.

        `extra_total` is volume held by OTHER detector shards in a
        sharded ensemble: the phi-heavy-hitter share is evaluated
        against (this shard's total + extra_total), so a destination's
        share still means its fraction of the whole cluster's traffic
        when the key space is partitioned."""
        if len(batch) == 0:
            return []
        plan = build_hh_plan(
            np.asarray(batch["destinationIP"], np.int64),
            np.asarray(batch["sourceIP"], np.int64),
            np.asarray(batch["octetDeltaCount"], np.float64),
            np.asarray(batch["packetDeltaCount"], np.float64))

        # One dispatch, one fetch. Host arrays go in raw: jit batches
        # the transfers into the call instead of one device_put round
        # trip per array.
        self.cms, self.kmeans, est_d, dist_d = _fused_step(
            self.cms, self.kmeans, plan.keys, plan.vols, plan.q,
            plan.feats, plan.valid)
        est, total, dist = jax.device_get(
            (est_d, self.cms.total, dist_d))
        hits = self.threshold(plan, est, total, dist, extra_total,
                              batch.dicts.get("destinationIP"))
        return [alert for alert, _, _ in hits]

    def threshold(self, plan: HHPlan, est, total, dist,
                  extra_total: float = 0.0, dst_dict=None
                  ) -> List[Tuple[HeavyHitterAlert, int, int]]:
        """Host half of `update`: advance the running statistics and
        threshold the fetched estimates. Returns (alert, source_row,
        dst_code) triples — source_row is the plan-local row for
        ddos_shape alerts and -1 for heavy_hitter alerts (whose
        subject is the whole micro-batch, not one row); the fused
        engine uses the extras to attribute alerts back to the
        coalesced blocks they came from."""
        est = np.asarray(est)[:len(plan.uniq_codes)]
        total = float(total)
        self.total_volume = total
        dist = np.asarray(dist)[:plan.n]
        self.batches += 1

        hits: List[Tuple[HeavyHitterAlert, int, int]] = []
        grand_total = total + max(float(extra_total), 0.0)
        if grand_total > 0:
            share = est / grand_total
            for code, e, s in zip(plan.uniq_codes, est, share):
                if s >= self.hh_fraction:
                    name = (dst_dict.decode_one(int(code))
                            if dst_dict else str(int(code)))
                    hits.append((HeavyHitterAlert(
                        "heavy_hitter", name, float(e), float(s)),
                        -1, int(code)))
        scale = float(np.mean(dist)) if len(dist) else 0.0
        # Warmup: let centroids settle before alerting on distance.
        if self.batches > 3 and self._dist_scale > 0:
            outliers = dist > self.ddos_sigma * self._dist_scale
            for i in np.nonzero(outliers)[0]:
                code = int(plan.dst_codes[i])
                name = (dst_dict.decode_one(code)
                        if dst_dict else str(code))
                hits.append((HeavyHitterAlert(
                    "ddos_shape", name, float(dist[i]),
                    float(dist[i] / self._dist_scale)),
                    int(i), code))
        self._dist_scale = 0.7 * self._dist_scale + 0.3 * scale
        return hits

    def volume_estimate(self, destination_code: int) -> float:
        return float(np.asarray(cms_query(
            self.cms,
            jnp.asarray(np.asarray([destination_code],
                                   np.uint32))))[0])
