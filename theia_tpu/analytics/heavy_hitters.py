"""Streaming heavy-hitter / DDoS detection at ingest line rate.

The BASELINE north-star config the reference has no equivalent for:
"Streaming Count-Min-Sketch + online k-means heavy-hitter / DDoS
detection at line rate from live Antrea FlowExporter". Per ingest
micro-batch, one fused device step:

  1. CMS update: per-destination traffic volume sketched into a
     [depth, width] counter array (ops/sketch.py) — sub-linear memory
     however many distinct destinations the cluster sees.
  2. Heavy hitters: destinations whose sketched share of total volume
     exceeds `hh_fraction` (the classic phi-heavy-hitter definition).
  3. Online k-means over per-flow feature vectors
     (log bytes, log packets, log mean packet size, log peer fan-in):
     flows assigned far from every centroid (distance > `ddos_sigma`
     x the running distance scale) are traffic-shape outliers — the
     DDoS signal that volume alone misses (many small flows from many
     sources map to a fan-in-heavy corner of feature space).

Keys are integer dictionary codes straight from the columnar batch —
no string work on the hot path. Alerts carry decoded names.
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.sketch import (
    CmsState,
    KMeansState,
    cms_init,
    cms_query,
    cms_update,
    kmeans_init,
    kmeans_step,
)
from ..schema import ColumnarBatch

FEATURES = 4


@jax.jit
def _fused_step(cms: CmsState, km: KMeansState, keys: jnp.ndarray,
                volumes: jnp.ndarray, q: jnp.ndarray,
                feats: jnp.ndarray, valid: jnp.ndarray
                ) -> Tuple[CmsState, KMeansState, jnp.ndarray,
                           jnp.ndarray]:
    """The whole per-batch device step as ONE dispatch: sketch update,
    heavy-hitter query, k-means step. Per-dispatch overhead (host→
    device puts + sync round trips) dominates the actual compute on
    weak ingest hosts, so three separate kernel calls per block would
    triple the fixed cost."""
    cms = cms_update(cms, keys, volumes)
    est = cms_query(cms, q)
    km, _, dist = kmeans_step(km, feats, valid)
    return cms, km, est, dist


@dataclasses.dataclass
class HeavyHitterAlert:
    kind: str              # "heavy_hitter" | "ddos_shape"
    destination: str
    estimate: float        # sketched volume (hh) or outlier distance
    share: float           # fraction of total volume (hh) / sigma (ddos)


class HeavyHitterDetector:
    """Device-resident CMS + online k-means over ingest micro-batches."""

    def __init__(self, depth: int = 4, width: int = 8192,
                 k: int = 8, hh_fraction: float = 0.10,
                 ddos_sigma: float = 4.0, seed: int = 0) -> None:
        self.cms: CmsState = cms_init(depth, width)
        rng = np.random.default_rng(seed)
        self.kmeans: KMeansState = kmeans_init(
            rng.normal(0.0, 1.0, size=(k, FEATURES)))
        self.hh_fraction = hh_fraction
        self.ddos_sigma = ddos_sigma
        # Running mean distance scale (EW average) for the outlier band.
        self._dist_scale = 1.0
        self.batches = 0
        #: total sketched volume after the last update (host float) —
        #: peers in a sharded ensemble read this to evaluate shares
        #: against the cluster total, not just this shard's.
        self.total_volume = 0.0

    # -- feature engineering (vectorized, host side) ---------------------

    @staticmethod
    def _features(batch: ColumnarBatch) -> np.ndarray:
        octets = np.asarray(batch["octetDeltaCount"], np.float64)
        packets = np.asarray(batch["packetDeltaCount"], np.float64)
        dst = np.asarray(batch["destinationIP"], np.int64)
        src = np.asarray(batch["sourceIP"], np.int64)
        # peer fan-in: DISTINCT sources per destination in this batch —
        # a 64-source flood and one chatty source sending 64 flows must
        # score differently. One 1-D unique over a packed 64-bit
        # (dst, src) key instead of np.unique(axis=0)'s row-structured
        # sort (codes are int32, so the pack is lossless).
        pairs = np.unique((dst << np.int64(32)) | src)
        per_dst_dsts, per_dst_counts = np.unique(
            pairs >> np.int64(32), return_counts=True)
        fan_in = per_dst_counts[
            np.searchsorted(per_dst_dsts, dst)].astype(np.float64)
        mean_pkt = octets / np.maximum(packets, 1.0)
        feats = np.stack([np.log1p(octets), np.log1p(packets),
                          np.log1p(mean_pkt), np.log1p(fan_in)], axis=1)
        return feats

    @staticmethod
    def _pad(n: int) -> int:
        """Fixed dispatch buckets (next power of two, min 256) so the
        jitted kernels compile once per bucket instead of once per
        distinct micro-batch size."""
        size = 256
        while size < n:
            size <<= 1
        return size

    # -- one micro-batch -------------------------------------------------

    def update(self, batch: ColumnarBatch,
               extra_total: float = 0.0) -> List[HeavyHitterAlert]:
        """Advance the sketch/centroids with one micro-batch.

        `extra_total` is volume held by OTHER detector shards in a
        sharded ensemble: the phi-heavy-hitter share is evaluated
        against (this shard's total + extra_total), so a destination's
        share still means its fraction of the whole cluster's traffic
        when the key space is partitioned."""
        if len(batch) == 0:
            return []
        n = len(batch)
        size = self._pad(n)
        dst_codes = np.asarray(batch["destinationIP"], np.int64)
        # Pad to the bucket size: padded rows carry zero volume, so the
        # sketch is unaffected; queries are sliced back to n.
        keys = np.zeros(size, np.uint32)
        keys[:n] = dst_codes.astype(np.uint32)
        vols = np.zeros(size, np.float32)
        vols[:n] = np.asarray(batch["octetDeltaCount"], np.float32)

        # Heavy-hitter query keys: this batch's distinct destinations.
        uniq_codes = np.unique(dst_codes)
        q = np.zeros(self._pad(len(uniq_codes)), np.uint32)
        q[:len(uniq_codes)] = uniq_codes.astype(np.uint32)

        # Traffic-shape features (padded rows are masked out of the
        # centroid update).
        feats = np.zeros((size, FEATURES), np.float32)
        feats[:n] = self._features(batch)
        valid = np.zeros(size, bool)
        valid[:n] = True

        # One dispatch, one fetch. Host arrays go in raw: jit batches
        # the transfers into the call instead of one device_put round
        # trip per array.
        self.cms, self.kmeans, est_d, dist_d = _fused_step(
            self.cms, self.kmeans, keys, vols, q, feats, valid)
        est, total, dist = jax.device_get(
            (est_d, self.cms.total, dist_d))
        est = est[:len(uniq_codes)]
        total = float(total)
        self.total_volume = total
        dist = dist[:n]
        self.batches += 1

        alerts: List[HeavyHitterAlert] = []
        dst_dict = batch.dicts.get("destinationIP")
        grand_total = total + max(float(extra_total), 0.0)
        if grand_total > 0:
            share = est / grand_total
            for code, e, s in zip(uniq_codes, est, share):
                if s >= self.hh_fraction:
                    name = (dst_dict.decode_one(int(code))
                            if dst_dict else str(int(code)))
                    alerts.append(HeavyHitterAlert(
                        "heavy_hitter", name, float(e), float(s)))
        scale = float(np.mean(dist)) if len(dist) else 0.0
        # Warmup: let centroids settle before alerting on distance.
        if self.batches > 3 and self._dist_scale > 0:
            outliers = dist > self.ddos_sigma * self._dist_scale
            for i in np.nonzero(outliers)[0]:
                name = (dst_dict.decode_one(int(dst_codes[i]))
                        if dst_dict else str(int(dst_codes[i])))
                alerts.append(HeavyHitterAlert(
                    "ddos_shape", name, float(dist[i]),
                    float(dist[i] / self._dist_scale)))
        self._dist_scale = 0.7 * self._dist_scale + 0.3 * scale
        return alerts

    def volume_estimate(self, destination_code: int) -> float:
        return float(np.asarray(cms_query(
            self.cms,
            jnp.asarray(np.asarray([destination_code],
                                   np.uint32))))[0])
