"""Analytics jobs: throughput anomaly detection, policy recommendation,
and abnormal traffic-drop detection."""

from .drop_detection import run_drop_detection
from .heavy_hitters import HeavyHitterAlert, HeavyHitterDetector
from .itemsets import mine_frequent_patterns, run_pattern_mining
from .npr import (NAMESPACE_ALLOW_LIST, read_distinct_flows, run_npr)
from .series import SeriesBatch, TadQuerySpec, build_series
from .spatial import flow_embeddings, run_spatial, spatial_outliers
from .streaming import StreamingDetector, stream_update
from .tad import ALGORITHMS, detect_anomalies, run_tad, score_series

__all__ = [
    "SeriesBatch", "TadQuerySpec", "build_series",
    "ALGORITHMS", "detect_anomalies", "run_tad", "score_series",
    "NAMESPACE_ALLOW_LIST", "read_distinct_flows", "run_npr",
    "StreamingDetector", "stream_update",
    "run_drop_detection",
    "HeavyHitterAlert", "HeavyHitterDetector",
    "mine_frequent_patterns", "run_pattern_mining",
    "flow_embeddings", "run_spatial", "spatial_outliers",
]
