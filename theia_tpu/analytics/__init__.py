"""Analytics jobs: throughput anomaly detection + policy recommendation."""

from .series import SeriesBatch, TadQuerySpec, build_series
from .tad import ALGORITHMS, detect_anomalies, run_tad, score_series

__all__ = [
    "SeriesBatch", "TadQuerySpec", "build_series",
    "ALGORITHMS", "detect_anomalies", "run_tad", "score_series",
]
