"""Abnormal traffic-drop detection job.

Re-provides the capability the reference ships only on its deprecated
Snowflake backend (`theia-sf drop-detection`): find endpoints whose
daily count of NetworkPolicy-dropped flows is anomalous.

Reference semantics (snowflake/cmd/dropDetection.go:36-175 builds the
query; snowflake/udfs/udfs/drop_detection/drop_detection_udf.py scores):

  1. Keep flows whose ingress OR egress NetworkPolicy rule action is
     Drop (2) or Reject (3), optionally time-windowed and filtered by
     clusterUUID.
  2. Attribute each flow to a victim endpoint: ingress-dropped traffic
     belongs to the destination (`ns/pod`, falling back to the IP),
     otherwise to the source; direction is "ingress"/"egress".
  3. Count dropped flows per (endpoint, direction, day).
  4. Per (endpoint, direction) partition with >= 3 observed days:
     anomaly iff the daily count is outside mean +/- 3*stddev_samp.

TPU-first: steps 1-3 are one vectorized pass over dictionary codes (no
string materialization until result rows), and step 4 is a single
jitted [S, D] kernel (`theia_tpu.ops.drops.drop_scores`) instead of a
per-partition pandas loop.
"""

from __future__ import annotations

import time
import uuid
from typing import Dict, List, Optional

import numpy as np

from ..ops.drops import drop_scores
from ..store import FlowDatabase

SECONDS_PER_DAY = 86400

ACTION_DROP = 2
ACTION_REJECT = 3


def _dropped_partitions(flows, start_time, end_time, cluster_uuid):
    """Steps 1-2: masks + integer partition keys.

    Returns (endpoint_key [N,3], direction [N] uint8 0=ingress/1=egress,
    date [N]) for the dropped rows, all as integer codes."""
    ingress = np.asarray(flows["ingressNetworkPolicyRuleAction"])
    egress = np.asarray(flows["egressNetworkPolicyRuleAction"])
    ing_drop = (ingress == ACTION_DROP) | (ingress == ACTION_REJECT)
    egr_drop = (egress == ACTION_DROP) | (egress == ACTION_REJECT)
    mask = ing_drop | egr_drop
    starts = np.asarray(flows["flowStartSeconds"])
    if start_time is not None:
        mask &= starts >= start_time
    if end_time is not None:
        mask &= np.asarray(flows["flowEndSeconds"]) < end_time
    if cluster_uuid:
        code = flows.dicts["clusterUUID"].lookup(cluster_uuid)
        mask &= np.asarray(flows["clusterUUID"]) == (
            -1 if code is None else code)

    col = flows.column_selector(mask)
    ing_drop = ing_drop[mask]
    # Victim endpoint: destination for ingress-dropped flows (the CASE
    # in dropDetection.go:131-143 prefers ingress when both dropped),
    # else source. Key = (pod_name_code, ns_code, ip_code); decode
    # happens only for anomalous rows.
    dst_name, dst_ns = col("destinationPodName"), \
        col("destinationPodNamespace")
    src_name, src_ns = col("sourcePodName"), col("sourcePodNamespace")
    dst_ip, src_ip = col("destinationIP"), col("sourceIP")
    name = np.where(ing_drop, dst_name, src_name)
    ns = np.where(ing_drop, dst_ns, src_ns)
    ip = np.where(ing_drop, dst_ip, src_ip)
    # Partition on the derived endpoint exactly as the reference derives
    # it (dropDetection.go:131-143): when the pod name is set the
    # endpoint is "ns/pod" (IP ignored — a pod restart that changes the
    # IP must not split the partition); otherwise it is the bare IP
    # (namespace ignored). Code 0 is the empty string.
    has_pod = name != 0
    ns = np.where(has_pod, ns, 0)
    ip = np.where(has_pod, 0, ip)
    direction = np.where(ing_drop, 0, 1).astype(np.int64)
    date = col("flowStartSeconds") // SECONDS_PER_DAY
    key = np.stack([name, ns, ip, direction], axis=1)
    return key, date


def _count_matrix(key: np.ndarray, date: np.ndarray):
    """Step 3: dropped-flow count per (partition, day), packed into a
    padded [S, D] matrix + mask (dates are dense-ranked per partition,
    real calendar value kept alongside)."""
    # Group identical (key, date) pairs → counts.
    full = np.concatenate([key, date[:, None]], axis=1)
    uniq, counts = np.unique(full, axis=0, return_counts=True)
    part_keys, part_idx = np.unique(uniq[:, :-1], axis=0,
                                    return_inverse=True)
    days = uniq[:, -1]
    n_parts = len(part_keys)
    # Rank each partition's dates (uniq rows are lex-sorted, so dates
    # ascend within a partition).
    order = np.argsort(part_idx, kind="stable")
    pos_in_part = np.arange(len(uniq)) - np.searchsorted(
        part_idx[order], part_idx[order])
    width = int(pos_in_part.max()) + 1 if len(uniq) else 0
    mat = np.zeros((n_parts, width), np.float64)
    dates = np.zeros((n_parts, width), np.int64)
    mask = np.zeros((n_parts, width), bool)
    rows = part_idx[order]
    mat[rows, pos_in_part] = counts[order]
    dates[rows, pos_in_part] = days[order]
    mask[rows, pos_in_part] = True
    return part_keys, mat, dates, mask


def run_drop_detection(db: FlowDatabase,
                       job_type: str = "initial",
                       detection_id: Optional[str] = None,
                       start_time: Optional[int] = None,
                       end_time: Optional[int] = None,
                       cluster_uuid: str = "",
                       now: Optional[int] = None,
                       progress=None) -> str:
    """Execute a drop-detection job; writes anomalies to the
    `dropdetection` table and returns the detection id."""
    if job_type != "initial":
        # Reference: "we only support initial jobType for now"
        # (dropDetection.go:282).
        raise ValueError(f"unsupported drop-detection jobType "
                         f"{job_type!r} (only 'initial')")
    detection_id = detection_id or str(uuid.uuid4())

    if progress:
        progress.stage("read")
    flows = db.flows.scan()
    if len(flows) == 0:
        if progress:
            progress.done()
        return detection_id
    key, date = _dropped_partitions(flows, start_time, end_time,
                                    cluster_uuid)

    if progress:
        progress.stage("tensorize")
    part_keys, mat, dates, mask = _count_matrix(key, date)
    if len(part_keys) == 0:
        if progress:
            progress.done()
        return detection_id

    if progress:
        progress.stage("score")
    anomaly, mean, std = (np.asarray(a) for a in drop_scores(mat, mask))

    if progress:
        progress.stage("write")
    rows = _result_rows(flows, part_keys, mat, dates, anomaly, mean,
                        std, job_type, detection_id, now)
    if rows:
        db.dropdetection.insert_rows(rows)
    if progress:
        progress.done()
    return detection_id


def _result_rows(flows, part_keys, mat, dates, anomaly, mean, std,
                 job_type, detection_id, now) -> List[Dict[str, object]]:
    """`flows` is the scanned batch the partition keys were built from —
    its dicts are the ONLY tables the codes are valid against (a sharded
    scan re-encodes into merged dictionaries distinct from any shard's)."""
    created = int(now if now is not None else time.time())
    name_dict = flows.dicts["sourcePodName"]
    ns_dict = flows.dicts["sourcePodNamespace"]
    ip_dict = flows.dicts["sourceIP"]
    # All pod-name/ns/IP columns have per-column dicts; endpoint codes
    # were taken from whichever side was the victim, so decode against
    # the matching dict per column pair.
    dst_name_dict = flows.dicts["destinationPodName"]
    dst_ns_dict = flows.dicts["destinationPodNamespace"]
    dst_ip_dict = flows.dicts["destinationIP"]

    rows: List[Dict[str, object]] = []
    sidx, didx = np.nonzero(anomaly)
    for s, d in zip(sidx, didx):
        name_c, ns_c, ip_c, direction = part_keys[s]
        if direction == 0:  # ingress → destination-side codes
            pod = dst_name_dict.decode_one(int(name_c))
            ns = dst_ns_dict.decode_one(int(ns_c))
            ip = dst_ip_dict.decode_one(int(ip_c))
        else:
            pod = name_dict.decode_one(int(name_c))
            ns = ns_dict.decode_one(int(ns_c))
            ip = ip_dict.decode_one(int(ip_c))
        endpoint = f"{ns}/{pod}" if pod else ip
        rows.append({
            "jobType": job_type,
            "id": detection_id,
            "timeCreated": created,
            "endpoint": endpoint,
            "direction": "ingress" if direction == 0 else "egress",
            "avgDrop": float(mean[s]),
            "stdevDrop": float(std[s]),
            "anomalyDropDate": int(dates[s, d]) * SECONDS_PER_DAY,
            "anomalyDropNumber": int(mat[s, d]),
        })
    return rows
