"""Dashboard read-path queries over the flow store.

Re-provides the data behind the reference's eight Grafana dashboards
(build/charts/theia/provisioning/dashboards/*.json, inventory at SURVEY
§2.5): homepage summary stats, raw flow records, pod-to-pod /
pod-to-service / pod-to-external / node-to-node sankey+timeseries,
networkpolicy chord, and the network-topology dependency graph. The
reference's panels run rawSql against the flows*_view ClickHouse tables
with $__timeFilter macros; here each function reads the equivalent
materialized view (store/views.py) and reduces over dictionary codes —
same data contract, no SQL engine in the path.

Every function returns plain-JSON data (lists/dicts), consumed by both
the HTML renderer (web.py) and the /dashboards/api endpoints.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..store import FlowDatabase
from ..store.views import group_reduce

FLOW_TYPE_TO_EXTERNAL = 3


def _view_scan(db, name: str):
    """One materialized view in the ViewTable.scan() shape, routed by
    THEIA_DASHBOARD_ROLLUP: unset/0 reads the legacy in-memory view
    table; `1` reads the rollup-backed `__rollup__:<view>` aggregate
    parts (query/rollup.py — the view must be declared, e.g. via
    THEIA_ROLLUP_DEFAULTS=1, else legacy serves); `assert` reads the
    rollup path AND verifies it group-for-group against the legacy
    table (the migration parity gate — raises on divergence)."""
    mode = os.environ.get("THEIA_DASHBOARD_ROLLUP",
                          "").strip().lower()
    if mode in ("", "0", "off", "false", "no"):
        return db.views[name].scan()
    from ..query import rollup as _rollup
    batch = _rollup.view_scan_batch(db, name)
    if batch is None:
        return db.views[name].scan()
    if mode == "assert":
        _rollup.assert_view_parity(batch, db.views[name].scan(), name)
    return batch

# NetworkPolicy rule-action codes (reference schema: 0 none, 1 allow,
# 2 drop, 3 reject) — single source for every dashboard consumer.
RULE_ACTION_LABELS = {0: "none", 1: "allow", 2: "drop", 3: "reject"}
DENY_RULE_ACTIONS = (2, 3)


def _top_links(keys: np.ndarray, values: np.ndarray, names_a, names_b,
               k: int) -> List[Dict[str, object]]:
    """Aggregate (a, b) → sum(value), return the top-k as sankey links."""
    gk, gv = group_reduce(keys, values[:, None])
    order = np.argsort(-gv[:, 0])[:k]
    return [{"source": str(names_a[gk[i, 0]]),
             "target": str(names_b[gk[i, 1]]),
             "value": int(gv[i, 0])} for i in order]


def _decode_table(dicts, name):
    return np.asarray(dicts[name]._strings, dtype=object)


def _time_window(col: np.ndarray, start: Optional[int],
                 end: Optional[int]) -> np.ndarray:
    mask = np.ones(len(col), bool)
    if start is not None:
        mask &= col >= start
    if end is not None:
        mask &= col < end
    return mask


def _throughput_series(times: np.ndarray, groups: np.ndarray,
                       values: np.ndarray, names, k: int
                       ) -> Dict[str, object]:
    """Per-group throughput over time for the top-k groups by volume.
    Fully vectorized (unique + bincount) — this runs on every dashboard
    render over the whole selected window."""
    if len(times) == 0:
        return {"times": [], "series": {}}
    values = np.asarray(values, np.float64)
    uniq_g, g_inv = np.unique(groups, return_inverse=True)
    totals = np.bincount(g_inv, weights=values)
    top = np.argsort(-totals)[:k]
    t_axis, t_inv = np.unique(times, return_inverse=True)
    series = {}
    for gi in top:
        sel = g_inv == gi
        ys = np.bincount(t_inv[sel], weights=values[sel],
                         minlength=len(t_axis))
        series[str(names[uniq_g[gi]])] = ys.astype(np.int64).tolist()
    return {"times": t_axis.tolist(), "series": series}


def homepage(db: FlowDatabase) -> Dict[str, object]:
    """Cluster summary (reference homepage.json: 12 stat panels +
    bargauge of top namespaces + cluster-throughput timeseries +
    dashlist — the dashlist is the nav bar on every page)."""
    flows = db.flows.scan()
    out: Dict[str, object] = {
        "flowCount": len(flows),
        "tadAnomalies": 0,
        "recommendations": 0,
        "droppedFlowCount": 0,
        "topNamespaces": [],
        "throughput": {"times": [], "series": {}},
    }
    if len(flows):
        for stat, col in (("podCount", "sourcePodName"),
                          ("namespaceCount", "sourcePodNamespace"),
                          ("nodeCount", "sourceNodeName"),
                          ("serviceCount", "destinationServicePortName"),
                          ("clusterCount", "clusterUUID")):
            codes = np.unique(np.asarray(flows[col]))
            out[stat] = int((codes != 0).sum())
        out["totalBytes"] = int(flows["octetDeltaCount"].sum())
        out["currentThroughput"] = int(
            flows["throughput"][flows["timeInserted"]
                                == flows["timeInserted"].max()].sum())
        ingress = np.asarray(flows["ingressNetworkPolicyRuleAction"])
        egress = np.asarray(flows["egressNetworkPolicyRuleAction"])
        out["droppedFlowCount"] = int(
            (np.isin(ingress, DENY_RULE_ACTIONS)
             | np.isin(egress, DENY_RULE_ACTIONS)).sum())
        # bargauge: top namespaces by traffic volume
        ns = np.asarray(flows["sourcePodNamespace"], np.int64)
        octets = np.asarray(flows["octetDeltaCount"], np.float64)
        names = flows.dicts["sourcePodNamespace"]
        totals = np.bincount(ns, weights=octets)
        if len(totals):
            totals[0] = 0              # code 0 == '' (no namespace)
        top = np.argsort(-totals)[:8]
        out["topNamespaces"] = [
            {"name": names.decode_one(int(g)), "value": int(totals[g])}
            for g in top if totals[g] > 0]
        # timeseries: cluster-wide throughput (one constant group)
        out["throughput"] = _throughput_series(
            np.asarray(flows["flowEndSeconds"], np.int64),
            np.zeros(len(flows), np.int64),
            np.asarray(flows["throughput"], np.int64),
            {0: "cluster"}, 1)
    tad = db.tadetector.scan()
    if len(tad):
        out["tadAnomalies"] = int(
            (tad.strings("anomaly") == "true").sum())
    out["dropAnomalies"] = len(db.dropdetection)
    out["recommendations"] = len(db.recommendations)
    return out


def flow_records(db: FlowDatabase, limit: int = 100,
                 start: Optional[int] = None,
                 end: Optional[int] = None) -> List[Dict[str, object]]:
    """Raw recent records (reference flow_records_dashboard.json:90)."""
    flows = db.flows.scan()
    mask = _time_window(np.asarray(flows["flowEndSeconds"]), start, end)
    sub = flows.filter(mask)
    order = np.argsort(-np.asarray(sub["flowEndSeconds"]))[:limit]
    cols = ("flowEndSeconds", "sourcePodNamespace", "sourcePodName",
            "destinationPodNamespace", "destinationPodName",
            "destinationIP", "destinationTransportPort",
            "destinationServicePortName", "protocolIdentifier",
            "throughput", "octetDeltaCount",
            "ingressNetworkPolicyName", "egressNetworkPolicyName")
    picked = sub.take(order).select(list(cols))
    return picked.to_rows()


def _pair_view(db: FlowDatabase, a_col: str, b_col: str,
               row_filter, k: int, start, end) -> Dict[str, object]:
    view = _view_scan(db, "flows_pod_view")
    mask = _time_window(np.asarray(view["flowEndSeconds"]), start, end)
    mask &= row_filter(view)
    a = np.asarray(view[a_col], np.int64)[mask]
    b = np.asarray(view[b_col], np.int64)[mask]
    thr = np.asarray(view["throughput"], np.int64)[mask]
    octets = np.asarray(view["octetDeltaCount"], np.int64)[mask]
    t = np.asarray(view["flowEndSeconds"], np.int64)[mask]
    names_a = _decode_table(view.dicts, a_col)
    names_b = _decode_table(view.dicts, b_col)

    links = _top_links(np.stack([a, b], axis=1), octets,
                       names_a, names_b, k)
    ts = _throughput_series(t, a, thr, names_a, k)
    totals_a: Dict[str, int] = {}
    for code, v in zip(a.tolist(), octets.tolist()):
        key = str(names_a[code])
        totals_a[key] = totals_a.get(key, 0) + v
    pie = sorted(totals_a.items(), key=lambda kv: -kv[1])[:k]
    return {"links": links, "throughput": ts,
            "topSources": [{"name": n, "value": v} for n, v in pie]}


def pod_to_pod(db: FlowDatabase, k: int = 10, start=None, end=None):
    return _pair_view(
        db, "sourcePodName", "destinationPodName",
        lambda v: (np.asarray(v["sourcePodName"]) != 0)
        & (np.asarray(v["destinationPodName"]) != 0), k, start, end)


def pod_to_service(db: FlowDatabase, k: int = 10, start=None, end=None):
    return _pair_view(
        db, "sourcePodName", "destinationServicePortName",
        lambda v: np.asarray(v["destinationServicePortName"]) != 0,
        k, start, end)


def pod_to_external(db: FlowDatabase, k: int = 10, start=None,
                    end=None):
    return _pair_view(
        db, "sourcePodName", "destinationIP",
        lambda v: np.asarray(v["flowType"]) == FLOW_TYPE_TO_EXTERNAL,
        k, start, end)


def node_to_node(db: FlowDatabase, k: int = 10, start=None, end=None):
    view = _view_scan(db, "flows_node_view")
    mask = _time_window(np.asarray(view["flowEndSeconds"]), start, end)
    mask &= (np.asarray(view["sourceNodeName"]) != 0) \
        & (np.asarray(view["destinationNodeName"]) != 0)
    a = np.asarray(view["sourceNodeName"], np.int64)[mask]
    b = np.asarray(view["destinationNodeName"], np.int64)[mask]
    octets = np.asarray(view["octetDeltaCount"], np.int64)[mask]
    thr = np.asarray(view["throughput"], np.int64)[mask]
    t = np.asarray(view["flowEndSeconds"], np.int64)[mask]
    names_a = _decode_table(view.dicts, "sourceNodeName")
    names_b = _decode_table(view.dicts, "destinationNodeName")
    return {"links": _top_links(np.stack([a, b], axis=1), octets,
                                names_a, names_b, k),
            "throughput": _throughput_series(t, a, thr, names_a, k)}


def networkpolicy(db: FlowDatabase, k: int = 10, start=None, end=None):
    """Policy traffic chord (reference networkpolicy_dashboard.json):
    bytes per (egress policy, ingress policy) pair + allow/deny split."""
    view = _view_scan(db, "flows_policy_view")
    mask = _time_window(np.asarray(view["flowEndSeconds"]), start, end)
    eg = np.asarray(view["egressNetworkPolicyName"], np.int64)[mask]
    ing = np.asarray(view["ingressNetworkPolicyName"], np.int64)[mask]
    octets = np.asarray(view["octetDeltaCount"], np.int64)[mask]
    eg_act = np.asarray(view["egressNetworkPolicyRuleAction"],
                        np.int64)[mask]
    names_e = _decode_table(view.dicts, "egressNetworkPolicyName")
    names_i = _decode_table(view.dicts, "ingressNetworkPolicyName")
    has_policy = (eg != 0) | (ing != 0)
    links = _top_links(np.stack([eg[has_policy], ing[has_policy]], axis=1), octets[has_policy],
                       names_e, names_i, k)
    by_action: Dict[str, int] = {}
    for act, v in zip(eg_act.tolist(), octets.tolist()):
        label = RULE_ACTION_LABELS.get(act, str(act))
        by_action[label] = by_action.get(label, 0) + v
    return {"chord": links,
            "byAction": [{"name": n, "value": v}
                         for n, v in sorted(by_action.items())]}


def network_topology(db: FlowDatabase, start=None, end=None):
    """Namespace-level dependency edges (reference
    network_topology_dashboard's mermaid graph, DependencyPanel.tsx)."""
    flows = db.flows.scan()
    mask = _time_window(np.asarray(flows["flowEndSeconds"]), start, end)
    src = np.asarray(flows["sourcePodNamespace"], np.int64)[mask]
    dst_ns = np.asarray(flows["destinationPodNamespace"],
                        np.int64)[mask]
    ftype = np.asarray(flows["flowType"])[mask]
    octets = np.asarray(flows["octetDeltaCount"], np.int64)[mask]
    names = _decode_table(flows.dicts, "sourcePodNamespace")
    dst_names = _decode_table(flows.dicts, "destinationPodNamespace")

    edges: Dict[Tuple[str, str], int] = {}
    for s, d, ft, v in zip(src.tolist(), dst_ns.tolist(),
                           ftype.tolist(), octets.tolist()):
        a = str(names[s]) or "(unknown)"
        b = ("external" if ft == FLOW_TYPE_TO_EXTERNAL
             else str(dst_names[d]) or "(unknown)")
        edges[(a, b)] = edges.get((a, b), 0) + v
    return {"edges": [{"source": a, "target": b, "value": v}
                      for (a, b), v in sorted(edges.items())]}


DASHBOARDS = {
    "homepage": homepage,
    "flow_records": flow_records,
    "pod_to_pod": pod_to_pod,
    "pod_to_service": pod_to_service,
    "pod_to_external": pod_to_external,
    "node_to_node": node_to_node,
    "networkpolicy": networkpolicy,
    "network_topology": network_topology,
}
