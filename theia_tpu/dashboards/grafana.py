"""Grafana dashboard JSON export.

The reference provisions eight Grafana dashboards as JSON
(build/charts/theia/provisioning/dashboards/*.json) with three custom
panel plugins (ids theia-grafana-{sankey,chord,dependency}-plugin).
This module emits dashboards in the same document shape — title, uid,
panels with gridPos and the reference's panel-type ids — so an
operator running a real Grafana (with the reference's panel plugins
and a JSON API datasource) can import the export and point it at this
manager's `/dashboards/api/<name>` endpoints, which serve the
underlying data.

Served as `GET /dashboards/api/<name>?format=grafana`.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List

from . import queries

#: dashboard name → list of (panel title, panel type, data field)
#: panel types: the reference's custom plugin ids + core Grafana types
_PANELS: Dict[str, List] = {
    "homepage": [
        ("Cluster summary", "stat", ""),
        ("Top namespaces by traffic", "bargauge", "topNamespaces"),
        ("Cluster throughput", "timeseries", "throughput"),
    ],
    "flow_records": [
        ("Flow records", "table", ""),
    ],
    "pod_to_pod": [
        ("Pod-to-pod traffic", "theia-grafana-sankey-plugin", "links"),
        ("Throughput", "timeseries", "throughput"),
        ("Top sources", "piechart", "topSources"),
    ],
    "pod_to_service": [
        ("Pod-to-service traffic", "theia-grafana-sankey-plugin",
         "links"),
        ("Throughput", "timeseries", "throughput"),
        ("Top sources", "piechart", "topSources"),
    ],
    "pod_to_external": [
        ("Pod-to-external traffic", "theia-grafana-sankey-plugin",
         "links"),
        ("Throughput", "timeseries", "throughput"),
        ("Top sources", "piechart", "topSources"),
    ],
    "node_to_node": [
        ("Node-to-node traffic", "theia-grafana-sankey-plugin",
         "links"),
        ("Throughput", "timeseries", "throughput"),
    ],
    "networkpolicy": [
        ("Cumulative bytes of flows with NetworkPolicy information",
         "theia-grafana-chord-plugin", "chord"),
        ("Bytes by rule action", "piechart", "byAction"),
    ],
    "network_topology": [
        ("Network topology", "theia-grafana-dependency-plugin",
         "edges"),
    ],
}


def _uid(name: str) -> str:
    return "theia-" + hashlib.sha1(name.encode()).hexdigest()[:8]


def grafana_dashboard(name: str) -> Dict[str, object]:
    """One dashboard as a Grafana-importable JSON document. A
    dashboard present in queries.DASHBOARDS but without a curated
    panel layout exports as a generic table panel over its data —
    new dashboards never 404 here just because this map lagged."""
    if name not in queries.DASHBOARDS:
        raise KeyError(name)
    layout = _PANELS.get(
        name, [(name.replace("_", " "), "table", "")])
    panels = []
    y = 0
    for i, (title, ptype, field) in enumerate(layout):
        h, w = (10, 12) if ptype != "table" else (16, 24)
        panels.append({
            "id": i + 1,
            "title": title,
            "type": ptype,
            "gridPos": {"h": h, "w": w,
                        "x": (i % 2) * 12, "y": y},
            "datasource": {"type": "marcusolsson-json-datasource",
                           "uid": "theia-manager"},
            "targets": [{
                "refId": "A",
                # the JSON API datasource fetches this path relative
                # to its configured base URL (the manager address)
                "urlPath": f"/dashboards/api/{name}",
                "fields": [{"jsonPath": f"$.data.{field}" if field
                            else "$.data"}],
            }],
        })
        if i % 2 == 1:
            y += h
    return {
        "title": f"theia-tpu {name.replace('_', ' ')}",
        "uid": _uid(name),
        "tags": ["theia", "flow-visibility"],
        "timezone": "browser",
        "schemaVersion": 39,
        "version": 1,
        "editable": True,
        "time": {"from": "now-12h", "to": "now"},
        "panels": panels,
    }


def grafana_dashboards() -> Dict[str, Dict[str, object]]:
    """Every dashboard (the provisioning-directory equivalent) —
    driven by queries.DASHBOARDS so additions export automatically."""
    return {name: grafana_dashboard(name)
            for name in queries.DASHBOARDS}
