"""Self-contained HTML/SVG dashboard renderer.

Replaces the reference's Grafana deployment + three custom TS panels
(plugins/grafana-custom-plugins: sankey via Google Charts, chord via
d3, dependency via mermaid) with dependency-free server-side SVG — the
manager serves these pages directly, so the observability UI works in
the zero-egress TPU environment with no Grafana, no JS CDNs.

Panels: sankey (two-column band diagram), line chart (timeseries),
bar list (pie-equivalent), dependency graph (layered left-to-right),
stat tiles, and raw tables. Pages map 1:1 to the reference dashboards
(queries.DASHBOARDS).
"""

from __future__ import annotations

import html
from typing import Dict, List

from . import queries

_PALETTE = ["#4e79a7", "#f28e2b", "#e15759", "#76b7b2", "#59a14f",
            "#edc948", "#b07aa1", "#ff9da7", "#9c755f", "#bab0ac"]


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024:
            return f"{n:,.1f} {unit}"
        n /= 1024
    return f"{n:,.1f} PiB"


def _esc(s: object) -> str:
    return html.escape(str(s))


# -- SVG panels ----------------------------------------------------------

def svg_sankey(links: List[Dict[str, object]], width=640,
               height=360) -> str:
    if not links:
        return "<p class='empty'>no data</p>"
    sources = list(dict.fromkeys(l["source"] for l in links))
    targets = list(dict.fromkeys(l["target"] for l in links))
    total = sum(l["value"] for l in links) or 1
    s_out: Dict[str, float] = {s: 0.0 for s in sources}
    t_in: Dict[str, float] = {t: 0.0 for t in targets}
    for l in links:
        s_out[l["source"]] += l["value"]
        t_in[l["target"]] += l["value"]

    usable = height - 10 * max(len(sources), len(targets))
    usable = max(usable, 100)

    def stack(nodes, totals):
        pos, y = {}, 5.0
        for n in nodes:
            h = usable * totals[n] / total
            pos[n] = [y, y, h]  # top, fill-cursor, height
            y += h + 10
        return pos

    s_pos = stack(sources, s_out)
    t_pos = stack(targets, t_in)
    parts = [f"<svg viewBox='0 0 {width} {height}' "
             f"class='sankey' xmlns='http://www.w3.org/2000/svg'>"]
    x0, x1 = 150, width - 150
    for i, l in enumerate(links):
        h = usable * l["value"] / total
        sy = s_pos[l["source"]][1]
        ty = t_pos[l["target"]][1]
        s_pos[l["source"]][1] += h
        t_pos[l["target"]][1] += h
        c = _PALETTE[i % len(_PALETTE)]
        mid = (x0 + x1) / 2
        parts.append(
            f"<path d='M{x0},{sy + h / 2} C{mid},{sy + h / 2} "
            f"{mid},{ty + h / 2} {x1},{ty + h / 2}' stroke='{c}' "
            f"stroke-width='{max(h, 1):.1f}' fill='none' "
            f"opacity='0.55'><title>{_esc(l['source'])} → "
            f"{_esc(l['target'])}: {_fmt_bytes(l['value'])}</title>"
            f"</path>")
    for n in sources:
        top, _, h = s_pos[n]
        parts.append(f"<rect x='{x0 - 8}' y='{top}' width='8' "
                     f"height='{max(h, 1):.1f}' fill='#555'/>")
        parts.append(f"<text x='{x0 - 12}' y='{top + h / 2 + 4}' "
                     f"text-anchor='end' class='lbl'>{_esc(n)}</text>")
    for n in targets:
        top, _, h = t_pos[n]
        parts.append(f"<rect x='{x1}' y='{top}' width='8' "
                     f"height='{max(h, 1):.1f}' fill='#555'/>")
        parts.append(f"<text x='{x1 + 12}' y='{top + h / 2 + 4}' "
                     f"class='lbl'>{_esc(n)}</text>")
    parts.append("</svg>")
    return "".join(parts)


def svg_chord(links: List[Dict[str, object]], size=520) -> str:
    """Circular chord diagram: every entity is an arc on one circle
    (span ∝ its total in+out traffic), every flow a ribbon between its
    endpoints' arcs — the same layout the reference's d3 chord panel
    draws (plugins/grafana-custom-plugins/grafana-chord-plugin/src/
    ChordPanel.tsx, d3.chord over an N×N flow matrix)."""
    import math

    if not links:
        return "<p class='empty'>no data</p>"
    nodes = list(dict.fromkeys(
        [l["source"] for l in links] + [l["target"] for l in links]))
    totals = {n: 0.0 for n in nodes}
    for l in links:
        v = float(l["value"])
        totals[l["source"]] += v
        totals[l["target"]] += v
    total = sum(totals.values()) or 1.0

    pad = 0.06   # radians between node arcs
    span = 2 * math.pi - pad * len(nodes)
    if span <= 0:
        pad, span = 0.0, 2 * math.pi
    r_out, r_in = size / 2 - 50, size / 2 - 62
    cx = cy = size / 2

    def pt(angle: float, r: float):
        return (cx + r * math.cos(angle - math.pi / 2),
                cy + r * math.sin(angle - math.pi / 2))

    # Node arc spans + a fill cursor for ribbon sub-arcs (a node's arc
    # is consumed by its flows in link order, out and in alike).
    arcs: Dict[str, List[float]] = {}
    theta = 0.0
    for n in nodes:
        width_n = span * totals[n] / total
        arcs[n] = [theta, theta, width_n]   # start, cursor, width
        theta += width_n + pad

    def sub_arc(n: str, value: float):
        a0 = arcs[n][1]
        a1 = a0 + span * value / total
        arcs[n][1] = a1
        return a0, a1

    parts = [f"<svg viewBox='0 0 {size} {size}' class='chord' "
             f"xmlns='http://www.w3.org/2000/svg'>"]
    # Ribbons first (under the node arcs).
    for i, l in enumerate(sorted(links, key=lambda x: -x["value"])):
        v = float(l["value"])
        s0, s1 = sub_arc(l["source"], v)
        t0, t1 = sub_arc(l["target"], v)
        sx0, sy0 = pt(s0, r_in)
        sx1, sy1 = pt(s1, r_in)
        tx0, ty0 = pt(t0, r_in)
        tx1, ty1 = pt(t1, r_in)
        large_s = 1 if (s1 - s0) > math.pi else 0
        large_t = 1 if (t1 - t0) > math.pi else 0
        c = _PALETTE[nodes.index(l["source"]) % len(_PALETTE)]
        parts.append(
            f"<path d='M{sx0:.1f},{sy0:.1f} "
            f"A{r_in:.1f},{r_in:.1f} 0 {large_s} 1 "
            f"{sx1:.1f},{sy1:.1f} "
            f"Q{cx:.1f},{cy:.1f} {tx0:.1f},{ty0:.1f} "
            f"A{r_in:.1f},{r_in:.1f} 0 {large_t} 1 "
            f"{tx1:.1f},{ty1:.1f} "
            f"Q{cx:.1f},{cy:.1f} {sx0:.1f},{sy0:.1f} Z' "
            f"fill='{c}' opacity='0.45'>"
            f"<title>{_esc(l['source'])} → {_esc(l['target'])}: "
            f"{_fmt_bytes(l['value'])}</title></path>")
    # Node arcs + labels.
    for n in nodes:
        a0, _, w = arcs[n]
        a1 = a0 + w
        x0, y0 = pt(a0, r_out)
        x1, y1 = pt(a1, r_out)
        xi1, yi1 = pt(a1, r_in)
        xi0, yi0 = pt(a0, r_in)
        large = 1 if w > math.pi else 0
        c = _PALETTE[nodes.index(n) % len(_PALETTE)]
        parts.append(
            f"<path d='M{x0:.1f},{y0:.1f} "
            f"A{r_out:.1f},{r_out:.1f} 0 {large} 1 {x1:.1f},{y1:.1f} "
            f"L{xi1:.1f},{yi1:.1f} "
            f"A{r_in:.1f},{r_in:.1f} 0 {large} 0 {xi0:.1f},{yi0:.1f} "
            f"Z' fill='{c}'>"
            f"<title>{_esc(n)}: {_fmt_bytes(totals[n])}</title></path>")
        mid = (a0 + a1) / 2
        lx, ly = pt(mid, r_out + 10)
        anchor = "start" if math.cos(mid - math.pi / 2) >= 0 else "end"
        parts.append(f"<text x='{lx:.1f}' y='{ly:.1f}' "
                     f"text-anchor='{anchor}' class='lbl'>"
                     f"{_esc(n)}</text>")
    parts.append("</svg>")
    return "".join(parts)


def svg_lines(ts: Dict[str, object], width=640, height=220) -> str:
    times = ts.get("times", [])
    series = ts.get("series", {})
    if not times or not series:
        return "<p class='empty'>no data</p>"
    t0, t1 = min(times), max(times)
    span = max(t1 - t0, 1)
    vmax = max((max(ys) for ys in series.values()), default=1) or 1
    parts = [f"<svg viewBox='0 0 {width} {height}' class='lines' "
             f"xmlns='http://www.w3.org/2000/svg'>"]
    plot_w, plot_h, pad = width - 60, height - 30, 10
    for i, (name, ys) in enumerate(series.items()):
        pts = " ".join(
            f"{pad + plot_w * (t - t0) / span:.1f},"
            f"{pad + plot_h * (1 - y / vmax):.1f}"
            for t, y in zip(times, ys))
        c = _PALETTE[i % len(_PALETTE)]
        parts.append(f"<polyline points='{pts}' fill='none' "
                     f"stroke='{c}' stroke-width='1.5'>"
                     f"<title>{_esc(name)}</title></polyline>")
    parts.append(f"<text x='{pad}' y='{height - 6}' class='lbl'>"
                 f"{_fmt_bytes(vmax)}/s peak · "
                 f"{len(series)} series · {span}s window</text>")
    parts.append("</svg>")
    return "".join(parts)


def svg_barlist(items: List[Dict[str, object]], width=640) -> str:
    if not items:
        return "<p class='empty'>no data</p>"
    vmax = max(i["value"] for i in items) or 1
    rows = []
    for i, item in enumerate(items):
        w = 380 * item["value"] / vmax
        c = _PALETTE[i % len(_PALETTE)]
        y = 4 + i * 22
        rows.append(
            f"<text x='0' y='{y + 12}' class='lbl'>"
            f"{_esc(item['name'])}</text>"
            f"<rect x='200' y='{y}' width='{w:.0f}' height='16' "
            f"fill='{c}'/>"
            f"<text x='{204 + w:.0f}' y='{y + 12}' class='lbl'>"
            f"{_fmt_bytes(item['value'])}</text>")
    h = 8 + 22 * len(items)
    return (f"<svg viewBox='0 0 {width} {h}' class='bars' "
            f"xmlns='http://www.w3.org/2000/svg'>{''.join(rows)}</svg>")


def svg_dependency(edges: List[Dict[str, object]], width=640,
                   height=320) -> str:
    if not edges:
        return "<p class='empty'>no data</p>"
    left = list(dict.fromkeys(e["source"] for e in edges))
    right = list(dict.fromkeys(e["target"] for e in edges))
    pos_l = {n: 40 + i * (height - 60) / max(len(left) - 1, 1)
             for i, n in enumerate(left)}
    pos_r = {n: 40 + i * (height - 60) / max(len(right) - 1, 1)
             for i, n in enumerate(right)}
    vmax = max(e["value"] for e in edges) or 1
    parts = [f"<svg viewBox='0 0 {width} {height}' class='dep' "
             f"xmlns='http://www.w3.org/2000/svg'>"]
    for e in edges:
        y1, y2 = pos_l[e["source"]], pos_r[e["target"]]
        w = 1 + 5 * e["value"] / vmax
        parts.append(
            f"<line x1='170' y1='{y1}' x2='{width - 170}' y2='{y2}' "
            f"stroke='#4e79a7' stroke-width='{w:.1f}' opacity='0.6'>"
            f"<title>{_esc(e['source'])} → {_esc(e['target'])}: "
            f"{_fmt_bytes(e['value'])}</title></line>")
    for n, y in pos_l.items():
        parts.append(f"<circle cx='170' cy='{y}' r='5' fill='#333'/>"
                     f"<text x='160' y='{y + 4}' text-anchor='end' "
                     f"class='lbl'>{_esc(n)}</text>")
    for n, y in pos_r.items():
        parts.append(
            f"<circle cx='{width - 170}' cy='{y}' r='5' fill='#333'/>"
            f"<text x='{width - 160}' y='{y + 4}' class='lbl'>"
            f"{_esc(n)}</text>")
    parts.append("</svg>")
    return "".join(parts)


def stat_tiles(stats: Dict[str, object]) -> str:
    tiles = []
    for name, value in stats.items():
        shown = (_fmt_bytes(value) if "Bytes" in name
                 else f"{_fmt_bytes(value)}/s" if "Throughput" in name
                 else f"{value:,}" if isinstance(value, int) else value)
        tiles.append(f"<div class='tile'><div class='v'>{_esc(shown)}"
                     f"</div><div class='k'>{_esc(name)}</div></div>")
    return f"<div class='tiles'>{''.join(tiles)}</div>"


def table(rows: List[Dict[str, object]]) -> str:
    if not rows:
        return "<p class='empty'>no data</p>"
    cols = list(rows[0].keys())
    head = "".join(f"<th>{_esc(c)}</th>" for c in cols)
    body = "".join(
        "<tr>" + "".join(f"<td>{_esc(r.get(c, ''))}</td>"
                         for c in cols) + "</tr>"
        for r in rows)
    return (f"<table><thead><tr>{head}</tr></thead>"
            f"<tbody>{body}</tbody></table>")


_STYLE = """
body{font:14px system-ui,sans-serif;margin:24px;color:#222}
h1{font-size:20px} h2{font-size:16px;margin-top:28px}
nav a{margin-right:14px}
.tiles{display:flex;flex-wrap:wrap;gap:12px}
.tile{border:1px solid #ddd;border-radius:6px;padding:10px 16px;
      min-width:130px;text-align:center}
.tile .v{font-size:22px;font-weight:600}
.tile .k{font-size:11px;color:#666}
svg{max-width:100%;border:1px solid #eee;border-radius:6px;
    margin:6px 0}
svg .lbl{font:11px sans-serif;fill:#333}
table{border-collapse:collapse;font-size:12px}
td,th{border:1px solid #ddd;padding:3px 8px;text-align:left}
.empty{color:#999}
"""

_NAV = "".join(
    f"<a href='/dashboards/{name}'>{name.replace('_', ' ')}</a>"
    for name in queries.DASHBOARDS)


def _page(title: str, body: str) -> str:
    return (f"<!doctype html><html><head><meta charset='utf-8'>"
            f"<title>theia-tpu · {_esc(title)}</title>"
            f"<style>{_STYLE}</style></head><body>"
            f"<nav><a href='/dashboards/'>⌂</a>{_NAV}</nav>"
            f"<h1>{_esc(title)}</h1>{body}</body></html>")


def render(name: str, db) -> str:
    """Render one dashboard page by name."""
    if name in ("", "index"):
        name = "homepage"
    if name not in queries.DASHBOARDS:
        raise KeyError(name)
    data = queries.DASHBOARDS[name](db)
    if name == "homepage":
        scalars = {k: v for k, v in data.items()
                   if not isinstance(v, (dict, list))}
        body = stat_tiles(scalars)
        if data.get("topNamespaces"):
            body += (f"<h2>top namespaces by traffic</h2>"
                     f"{svg_barlist(data['topNamespaces'])}")
        if data.get("throughput", {}).get("times"):
            body += (f"<h2>cluster throughput</h2>"
                     f"{svg_lines(data['throughput'])}")
    elif name == "flow_records":
        body = table(data)
    elif name in ("pod_to_pod", "pod_to_service", "pod_to_external"):
        body = (f"<h2>traffic (sankey)</h2>{svg_sankey(data['links'])}"
                f"<h2>throughput</h2>{svg_lines(data['throughput'])}"
                f"<h2>top sources</h2>"
                f"{svg_barlist(data.get('topSources', []))}")
    elif name == "node_to_node":
        body = (f"<h2>traffic (sankey)</h2>{svg_sankey(data['links'])}"
                f"<h2>throughput</h2>{svg_lines(data['throughput'])}")
    elif name == "networkpolicy":
        body = (f"<h2>policy traffic (chord)</h2>"
                f"{svg_chord(data['chord'])}"
                f"<h2>bytes by rule action</h2>"
                f"{svg_barlist(data['byAction'])}")
    else:  # network_topology
        body = (f"<h2>namespace dependencies</h2>"
                f"{svg_dependency(data['edges'])}")
    return _page(name.replace("_", " "), body)
