"""Flow-visibility dashboards: store-native queries + SVG web UI."""

from .grafana import grafana_dashboard, grafana_dashboards
from .queries import DASHBOARDS
from .web import render

__all__ = ["DASHBOARDS", "grafana_dashboard", "grafana_dashboards",
           "render"]
