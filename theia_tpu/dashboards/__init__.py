"""Flow-visibility dashboards: store-native queries + SVG web UI."""

from .queries import DASHBOARDS
from .web import render

__all__ = ["DASHBOARDS", "render"]
