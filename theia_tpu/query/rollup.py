"""Streaming materialized rollup views — incremental aggregate parts
with a transparent planner rewrite.

The reference maintains three ClickHouse SummingMergeTree materialized
views precisely so Grafana never scans raw flows (create_table.sh:
92-351); our PR-7 port of those views (`store/views.py` ViewTable) is
an in-memory side table invisible to the `/query` plane, so a
month-window dashboard group-by still streams every cold part through
the decode buffer on each cache miss. This module is the ROADMAP
item-5 arc: declarative rollup views whose definition IS a normalized
QueryPlan shape, maintained incrementally as first-class aggregate
parts, and a planner rewrite that answers subsumed windowed plans from
the coarsest rollup tier with raw-scan edges stitched bit-identically.

Three cooperating pieces:

  * **Declaration** (`RollupView`, `THEIA_ROLLUP_VIEWS`): a view is a
    groupBy column list + lowered count/sum/min/max aggregates (mean
    lowers to sum+count exactly like the query plane) + optional
    AND-ed filters + a base time bucket over `timeInserted` + an
    optional cascade of coarser tiers (each resolution a multiple of
    the previous — the divisibility chain is what makes window
    alignment provable). The JSON file hot-reloads on mtime change
    with the THEIA_ALERT_RULES discipline: a torn/malformed file keeps
    the previous set evaluating and surfaces `loadError`. The
    reference's pod/node/policy views ship as built-in defaults
    (`THEIA_ROLLUP_DEFAULTS=1`).
  * **Maintenance** (`RollupManager`, one per physical FlowDatabase):
    every flows insert block folds through each view (hash-run
    grouping, the `group_sum_fast` trick generalized to mixed
    count/sum/min/max — partial rows may split on a hash collision,
    which is exactly SummingMergeTree part semantics: the read path
    re-merges exactly) and appends to a parts-backed
    `__rollup__:<view>` table sorted by (bucketStart, group key) with
    `resolution` in the per-part min/max, so rollup reads prune like
    `__metrics__` history does. Rollup writes are deliberately
    WAL-INVISIBLE (the PR-13 contract): raw flow inserts are
    journaled, recovery replays them through the same insert path and
    re-derives identical rollups — journaling both would double-count
    the window on replay. Parts-aware snapshots persist the aggregate
    state (stamped with the view definition, so a definition change
    rebuilds instead of restoring a stale shape); cluster replication
    ships flows frames and each copy re-derives deterministically;
    resync truncates and rebuilds through `insert_flows`. Cascaded
    downsampling folds aged parts 1m→1h by the PR-13 atomic
    part-surgery swap, through the SAME shared fold helper the
    `__metrics__` downsampler now uses (`fold_rows_to_buckets` +
    `downsample_parts` — one implementation, two callers). TTL /
    retention trims drop every bucket below the tier-aligned horizon
    and advance a LOW WATERMARK; the planner serves the sub-watermark
    remainder (< one coarse bucket of surviving raw rows) from the
    raw edge — so rollup answers track deletes exactly without
    re-derivation, race-free against concurrent block applies.
  * **Planner rewrite** (`match_view` + `try_rollup_partial`): a
    windowed plan whose groupBy ⊆ view groupBy, whose lowered
    aggregates all exist in the view, whose window rides the view's
    time column, and whose filters are the view's filters plus
    residuals on group columns, is transparently answered from the
    rollup table: the window aligns to the coarsest resolution
    PRESENT in the captured part set (every finer resolution divides
    it, so any bucket inside the aligned middle is provably contained
    by it), the aligned middle reads O(groups·buckets) aggregate rows
    via the normal part-native engine, and the unaligned head/tail
    edges scan raw flows — all partials merging exactly in int64, so
    the result is bit-identical to the raw path. `execute_partial`
    applies the same rewrite per peer, so PR-10 coordinators get
    O(groups) partials even on cold month-scale history; EXPLAIN and
    the result doc name the view, the alignment, and the stitched
    edge spans.

Env knobs (documented in docs/queries.md):

    THEIA_ROLLUP_VIEWS      JSON view-definition file (hot-reloaded)
    THEIA_ROLLUP_DEFAULTS   1 = include the reference's three MVs as
                            built-in views (default 0)
    THEIA_ROLLUP_QUERY      0 = disable the planner rewrite (forced
                            raw scans; the bench A/B uses the per-
                            request `rollup=0` flag instead)
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import metrics as _metrics
from ..schema import FLOW_SCHEMA, Column, ColumnKind, ColumnarBatch
from ..store.views import MATERIALIZED_VIEWS
from ..utils.logging import get_logger
from .plan import (Aggregate, Filter, PlanError, QueryPlan,
                   _parse_aggregate, _parse_filter)
from .reference import filter_mask, materialize_keys
from .result import lower_specs
from ..analysis.lockdep import named_lock

logger = get_logger("rollup")

#: result-table namespace of one view's aggregate parts
ROLLUP_TABLE_PREFIX = "__rollup__:"
#: bucket-start column of every rollup table (deliberately NOT
#: `timeInserted`: the view's time column may itself be a group key —
#: the reference MVs key on raw timeInserted — and the two must not
#: collide)
BUCKET_COLUMN = "bucketStart"
RESOLUTION_COLUMN = "resolution"
DEFAULT_BUCKET_SECONDS = 60

#: partial-merge op per lowered aggregate op (mirrors kernels.MERGE_OP
#: without importing the kernels at module load)
_MERGE_OP = {"count": "sum", "sum": "sum", "min": "min", "max": "max"}

_NAME_RE = re.compile(r"^[A-Za-z0-9_.\-]+$")

#: rollup memtables force-seal on this cadence so aggregate rows
#: become prunable, foldable parts (the obs/history SEAL_SPAN
#: discipline — size-based sealing would hold low-cardinality views
#: in the memtable for hours)
SEAL_SPAN_SECONDS = 60

_M_VIEWS = _metrics.gauge(
    "theia_rollup_views",
    "Declared active rollup views on this node (built-in defaults + "
    "THEIA_ROLLUP_VIEWS), after the last successful config load")
_M_APPLIED = _metrics.counter(
    "theia_rollup_applied_rows_total",
    "Flow rows folded into rollup views on the insert path (counted "
    "once per view per physical store)")
_M_AGG_ROWS = _metrics.counter(
    "theia_rollup_aggregate_rows_total",
    "Aggregate partial rows appended to __rollup__ tables by insert-"
    "block maintenance")
_M_APPLY_SECONDS = _metrics.histogram(
    "theia_rollup_apply_seconds",
    "Rollup maintenance time per flows insert block (all views)")
_M_FOLDS = _metrics.counter(
    "theia_rollup_folds_total",
    "Rollup parts replaced by cascaded tier downsampling (atomic "
    "part-surgery folds), by target resolution",
    labelnames=("resolution",))
_M_REWRITES = _metrics.counter(
    "theia_rollup_query_rewrites_total",
    "Queries transparently answered from rollup tiers by the planner "
    "rewrite (stitched raw edges included)")


class RollupConfigError(ValueError):
    """A rollup view document is malformed — a config error surfaced
    in /debug/views `loadError`, never an engine crash."""


def config_path() -> str:
    return os.environ.get("THEIA_ROLLUP_VIEWS", "")


def defaults_enabled() -> bool:
    return os.environ.get("THEIA_ROLLUP_DEFAULTS", "").strip().lower() \
        in ("1", "true", "yes", "on")


def rewrite_enabled() -> bool:
    """THEIA_ROLLUP_QUERY: the planner-rewrite kill switch (default
    on; maintenance is unaffected — only answering from rollups)."""
    return os.environ.get("THEIA_ROLLUP_QUERY", "").strip().lower() \
        not in ("0", "false", "off", "no")


# -- view definitions ------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RollupView:
    """One declared view: a normalized QueryPlan shape (groupBy +
    lowered aggregates + filters + a time bucket) plus the tier
    cascade. Immutable; config reloads replace the object."""

    name: str
    group_by: Tuple[str, ...]
    #: lowered aggregate specs (label, op, column) — op in
    #: count/sum/min/max only (mean lowered at parse)
    specs: Tuple[Tuple[str, str, Optional[str]], ...]
    filters: Tuple[Filter, ...]
    bucket: int
    #: (resolution seconds, fold after seconds), ascending; every
    #: resolution is a multiple of its predecessor (bucket first)
    tiers: Tuple[Tuple[int, int], ...]
    time_column: str = "timeInserted"

    @staticmethod
    def agg_column(op: str, column: Optional[str]) -> str:
        """Storage column of one lowered aggregate."""
        return "agg_count" if op == "count" else f"agg_{op}_{column}"

    def agg_columns(self) -> Dict[str, str]:
        """{storage column: merge op} over the view's specs."""
        return {self.agg_column(op, col): _MERGE_OP[op]
                for _, op, col in self.specs}

    def schema(self) -> tuple:
        """The `__rollup__:<name>` table schema: bucket + resolution +
        the group columns (flow kinds preserved — strings stay
        dictionary-coded) + one exact-int64 column per aggregate."""
        by_name = {c.name: c for c in FLOW_SCHEMA}
        cols: List[Column] = [
            Column(BUCKET_COLUMN, ColumnKind.DATETIME),
            Column(RESOLUTION_COLUMN, ColumnKind.U64),
        ]
        for g in self.group_by:
            cols.append(by_name[g])
        for _, op, col in self.specs:
            cols.append(Column(self.agg_column(op, col),
                               ColumnKind.U64))
        return tuple(cols)

    def max_resolution(self) -> int:
        return self.tiers[-1][0] if self.tiers else self.bucket

    def to_doc(self) -> Dict[str, object]:
        return {
            "name": self.name,
            "groupBy": list(self.group_by),
            "aggregates": [{"op": op, "column": col}
                           for _, op, col in self.specs],
            "filters": sorted((f.to_doc() for f in self.filters),
                              key=lambda d: json.dumps(
                                  d, sort_keys=True)),
            "bucketSeconds": self.bucket,
            "tiers": [{"resolutionSeconds": r, "afterSeconds": a}
                      for r, a in self.tiers],
            "timeColumn": self.time_column,
        }

    def normalized(self) -> str:
        return json.dumps(self.to_doc(), sort_keys=True,
                          separators=(",", ":"))


def parse_view(doc: Dict[str, object]) -> RollupView:
    """Validate one view document against the flow schema. Raises
    RollupConfigError on anything malformed — the whole file is
    rejected (the parse_rules discipline), so a typo cannot silently
    drop one view while keeping its neighbors."""
    if not isinstance(doc, dict):
        raise RollupConfigError(f"view must be an object, got {doc!r}")
    name = str(doc.get("name") or "").strip()
    if not name or not _NAME_RE.match(name):
        raise RollupConfigError(
            f"view needs a [A-Za-z0-9_.-]+ `name`, got {name!r}")
    by_name = {c.name: c for c in FLOW_SCHEMA}
    group_by = doc.get("groupBy") or []
    if isinstance(group_by, str):
        group_by = [g for g in group_by.split(",") if g]
    groups: List[str] = []
    for g in group_by:
        g = str(g)
        if g not in by_name:
            raise RollupConfigError(
                f"view {name}: unknown groupBy column {g!r}")
        if g in groups:
            raise RollupConfigError(
                f"view {name}: duplicate groupBy column {g!r}")
        groups.append(g)
    aggs_doc = doc.get("aggregates") or ["count"]
    if isinstance(aggs_doc, (str, dict)):
        aggs_doc = [aggs_doc]
    specs: List[Tuple[str, str, Optional[str]]] = []

    def add(label: str, op: str, column: Optional[str]) -> None:
        if all(s[0] != label for s in specs):
            specs.append((label, op, column))

    try:
        for a in aggs_doc:
            agg = _parse_aggregate(a, FLOW_SCHEMA)
            if agg.op == "mean":
                # the query plane's exact lowering: a view declaring
                # mean stores the (sum, count) partials it needs
                add(f"sum({agg.column})", "sum", agg.column)
                add("count", "count", None)
            else:
                add(agg.label, agg.op, agg.column)
        filters = tuple(_parse_filter(f, FLOW_SCHEMA)
                        for f in (doc.get("filters") or []))
    except PlanError as e:
        raise RollupConfigError(f"view {name}: {e}")
    time_column = str(doc.get("timeColumn") or "timeInserted")
    if time_column != "timeInserted":
        # TTL / retention trims delete flows by timeInserted; a view
        # bucketing any other column could not track those deletes
        # exactly (a trim would touch arbitrary buckets)
        raise RollupConfigError(
            f"view {name}: timeColumn must be timeInserted "
            f"(got {time_column!r}) — the TTL/retention contract")
    bucket = int(doc.get("bucketSeconds", DEFAULT_BUCKET_SECONDS))
    if bucket <= 0:
        raise RollupConfigError(
            f"view {name}: bucketSeconds must be positive")
    tiers: List[Tuple[int, int]] = []
    prev = bucket
    for t in (doc.get("tiers") or []):
        if not isinstance(t, dict):
            raise RollupConfigError(
                f"view {name}: tier must be an object, got {t!r}")
        try:
            res = int(t["resolutionSeconds"])
            after = int(t["afterSeconds"])
        except (KeyError, TypeError, ValueError):
            raise RollupConfigError(
                f"view {name}: tiers need integer resolutionSeconds "
                f"and afterSeconds")
        if res <= prev or res % prev != 0:
            # the divisibility chain is what makes planner window
            # alignment provable (any finer bucket inside an aligned
            # window is contained by it)
            raise RollupConfigError(
                f"view {name}: tier resolution {res} must be an "
                f"ascending multiple of the previous ({prev})")
        if after <= 0:
            raise RollupConfigError(
                f"view {name}: afterSeconds must be positive")
        tiers.append((res, after))
        prev = res
    return RollupView(name=name, group_by=tuple(groups),
                      specs=tuple(specs), filters=filters,
                      bucket=bucket, tiers=tuple(tiers),
                      time_column=time_column)


def default_views() -> List[RollupView]:
    """The reference's three MVs (store/views.py MATERIALIZED_VIEWS)
    re-declared as rollup views: full MV key set as the group key,
    summed metric columns, base bucket, no coarser tiers (the raw MV
    keys include raw timestamps, so coarser tiers would only compact
    partial rows, never change an answer)."""
    out: List[RollupView] = []
    for name, spec in MATERIALIZED_VIEWS.items():
        specs = tuple((f"sum({c})", "sum", c)
                      for c in spec.sum_columns)
        out.append(RollupView(
            name=name, group_by=tuple(spec.key_columns), specs=specs,
            filters=(), bucket=DEFAULT_BUCKET_SECONDS, tiers=()))
    return out


def parse_views(raw: str) -> List[Dict[str, object]]:
    """THEIA_ROLLUP_VIEWS file → raw view documents (a JSON list, or
    `{"views": [...]}`). Validation happens per entry in the merge
    (entries may be `{"name": ..., "disabled": true}` overrides)."""
    try:
        doc = json.loads(raw)
    except ValueError as e:
        raise RollupConfigError(f"views file is not valid JSON: {e}")
    if isinstance(doc, dict):
        doc = doc.get("views")
    if not isinstance(doc, list):
        raise RollupConfigError(
            "views file must be a JSON list (or {\"views\": [...]})")
    return doc


def merge_view_docs(defaults: Sequence[RollupView],
                    docs: Sequence[Dict[str, object]]
                    ) -> Dict[str, RollupView]:
    """Built-in defaults + file entries, merged by name (file wins;
    `disabled: true` removes a default)."""
    merged: Dict[str, RollupView] = {v.name: v for v in defaults}
    for d in docs:
        if isinstance(d, dict) and d.get("disabled"):
            name = str(d.get("name") or "")
            merged.pop(name, None)
            continue
        v = parse_view(d)
        merged[v.name] = v
    names = list(merged)
    if len(set(names)) != len(names):   # pragma: no cover - dict keys
        raise RollupConfigError(f"duplicate view names: {names}")
    return merged


# -- shared bucket-fold helpers (metrics downsampler + rollup tiers) -------

def fold_rows_to_buckets(batch: ColumnarBatch, resolution: int,
                         key_columns: Sequence[str],
                         merge_ops: Dict[str, str],
                         time_column: str = "timeInserted",
                         resolution_column: str = RESOLUTION_COLUMN,
                         last_columns: Sequence[str] = ()
                         ) -> List[Dict[str, object]]:
    """Fold decoded rows into `resolution`-second buckets — THE shared
    aligned-window fold (one implementation behind both the
    `__metrics__` downsampler and the rollup tier cascade). Rows
    already at or above the target resolution pass through unchanged
    (recovery can reseal mixed-resolution parts); finer rows fold per
    (key columns, bucket): `merge_ops` columns merge exactly
    (min/max/sum), `last_columns` keep the latest-time sample in the
    bucket (the cumulative-counter-exact `value` semantic)."""
    out: List[Dict[str, object]] = []
    acc: Dict[tuple, Dict[str, object]] = {}
    t = np.asarray(batch[time_column], np.int64)
    res = np.asarray(batch[resolution_column], np.int64)
    keys = {c: (batch.strings(c) if c in batch.dicts
                else np.asarray(batch[c], np.int64))
            for c in key_columns}
    cols = {c: np.asarray(batch[c], np.int64)
            for c in (*merge_ops, *last_columns)}
    for i in range(len(batch)):
        kvals = tuple(
            (str(keys[c][i]) if c in batch.dicts else int(keys[c][i]))
            for c in key_columns)
        if res[i] >= resolution:
            out.append({
                time_column: int(t[i]),
                resolution_column: int(res[i]),
                **dict(zip(key_columns, kvals)),
                **{c: int(cols[c][i]) for c in cols}})
            continue
        bucket = int(t[i]) // resolution * resolution
        key = (*kvals, bucket)
        row = acc.get(key)
        if row is None:
            acc[key] = {
                time_column: bucket,
                resolution_column: resolution,
                **dict(zip(key_columns, kvals)),
                **{c: int(cols[c][i]) for c in cols},
                "_last_t": int(t[i])}
            continue
        if last_columns and int(t[i]) >= row["_last_t"]:
            row["_last_t"] = int(t[i])
            for c in last_columns:
                row[c] = int(cols[c][i])
        for c, op in merge_ops.items():
            v = int(cols[c][i])
            if op == "sum":
                row[c] += v
            elif op == "min":
                row[c] = min(row[c], v)
            else:
                row[c] = max(row[c], v)
    for row in acc.values():
        row.pop("_last_t")
        out.append(row)
    return out


def downsample_parts(table, now: int,
                     tiers: Sequence[Tuple[int, int]],
                     fold: Callable[[ColumnarBatch, int],
                                    List[Dict[str, object]]],
                     time_column: str = "timeInserted",
                     resolution_column: str = RESOLUTION_COLUMN
                     ) -> Dict[int, int]:
    """One cascade pass over one concrete PartTable — the shared
    part-surgery loop (extracted from obs/history.py): for each
    (resolution, age) tier, decode the sealed parts whose rows are all
    older than `now - age` and not yet at that resolution, fold via
    the callback, and atomically swap old parts for one rollup part
    through the PartTable surgery contract (`sealed_parts` +
    `replace_parts`). Readers see the old parts or the new one, never
    neither. Returns {resolution: parts replaced}; a swap that loses
    to a concurrent merge/demote aborts for this tier and the next
    pass retries against fresh state."""
    out: Dict[int, int] = {}
    if not callable(getattr(table, "sealed_parts", None)):
        return out   # flat Table (no parts engine) — nothing to do
    for resolution, age in tiers:
        cutoff = int(now) - int(age)
        eligible = [
            p for p in table.sealed_parts()
            if p.minmax.get(time_column) is not None
            and p.minmax[time_column][1] < cutoff
            and p.minmax.get(resolution_column) is not None
            and p.minmax[resolution_column][0] < resolution]
        if not eligible:
            continue
        batch = ColumnarBatch.concat(
            [table._decode_part(p) for p in eligible])
        folded = fold(batch, resolution)
        if not table.replace_parts(eligible, folded):
            continue
        out[resolution] = out.get(resolution, 0) + len(eligible)
    return out


# -- insert-block fold (the maintenance hot path) --------------------------

def _hash_runs(keys: np.ndarray
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(order, run starts, sorted keys) grouping rows by full key via
    a 64-bit row hash sort — the group_sum_fast trick generalized:
    ~20x less sort work than lexsorting 15-20 key columns. A hash
    collision between distinct keys may split one group across runs;
    every run is still key-uniform (full-row boundary compare), so the
    emitted partial rows stay exactly mergeable — the read path
    re-groups, which is where SummingMergeTree collapses rows too."""
    n = keys.shape[0]
    h = np.full(n, 0xcbf29ce484222325, np.uint64)
    for i in range(keys.shape[1]):
        x = keys[:, i].astype(np.uint64)
        x *= np.uint64(0xff51afd7ed558ccd)
        x ^= x >> np.uint64(33)
        h ^= x
        h *= np.uint64(0x100000001b3)
    order = np.argsort(h, kind="stable")
    sk = keys[order]
    boundary = np.empty(n, dtype=bool)
    boundary[0] = True
    boundary[1:] = np.any(sk[1:] != sk[:-1], axis=1)
    return order, np.flatnonzero(boundary), sk


_FOLD_UFUNC = {"sum": np.add, "min": np.minimum, "max": np.maximum}

#: packed-key fold ceiling: the product of the block's per-column
#: key ranges must fit this for the O(n) bincount path (the
#: occupancy scoreboard and its cumsum are O(cap))
_PACK_CAP = 1 << 21
#: bincount's float64 weights hold integer partial sums EXACTLY only
#: below 2^53; splitting int64 values into 32-bit halves bounds each
#: half's sum by n * 2^32, so n must stay under 2^21
_PACK_MAX_ROWS = 1 << 21


def _packed_fold(keycols: List[np.ndarray],
                 specs: Sequence[Tuple[str, str, Optional[str]]],
                 values: Dict[str, np.ndarray]
                 ) -> Optional[Tuple[np.ndarray,
                                     Dict[str, np.ndarray]]]:
    """O(n) insert-block fold for SMALL key spaces: pack the key
    columns into one narrow integer (per-column block min/range
    strides), scoreboard the occupied slots, and reduce each sum
    column with two bincounts (32-bit halves — each half's float64
    partial sums stay integer-exact below 2^53, recombined in int64,
    so the result is bit-identical to the sort paths). Returns None
    when the shape disqualifies it: a min/max spec, a negative
    value, or a key-range product over _PACK_CAP — callers fall back
    to the native/hash-sort folds."""
    n = len(keycols[0])
    if n == 0 or n > _PACK_MAX_ROWS:
        return None
    if any(op not in ("count", "sum") for _, op, _ in specs):
        return None
    packed = None
    mins: List[int] = []
    strides: List[int] = []
    total = 1
    for col in keycols:
        mn = int(col.min())
        rng = int(col.max()) - mn + 1
        mins.append(mn)
        strides.append(total)
        total *= rng
        if total > _PACK_CAP:
            return None
    packed = np.zeros(n, np.int64)
    for col, mn, stride in zip(keycols, mins, strides):
        packed += (col - mn) * stride
    mask = np.zeros(total, bool)
    mask[packed] = True
    uniq_packed = np.flatnonzero(mask)
    remap = np.cumsum(mask, dtype=np.int32) - 1
    gids = remap[packed]
    g = len(uniq_packed)
    uniq = np.empty((g, len(keycols)), np.int64)
    rem = uniq_packed
    for j in range(len(keycols) - 1, -1, -1):
        uniq[:, j] = rem // strides[j] + mins[j]
        rem = rem % strides[j]
    counts = None
    out: Dict[str, np.ndarray] = {}
    for label, op, col in specs:
        if op == "count":
            if counts is None:
                counts = np.bincount(gids, minlength=g).astype(
                    np.int64)
            out[label] = counts
            continue
        v = values[col]
        if int(v.min()) < 0:
            return None   # the 32-bit split assumes non-negative
        lo = np.bincount(gids, weights=(v & 0xFFFFFFFF),
                         minlength=g)
        hi = np.bincount(gids, weights=(v >> 32), minlength=g)
        out[label] = (lo.astype(np.int64)
                      + (hi.astype(np.int64) << 32))
    return uniq, out


# -- the per-store manager -------------------------------------------------

class RollupManager:
    """Owns one physical FlowDatabase's rollup state: the view set
    (hot-reloaded), one parts-backed `__rollup__:<view>` table per
    view, insert-block application, the tier cascade, delete
    tracking, and snapshot persistence. Constructed by FlowDatabase;
    sharded/replicated topologies hold one manager per physical
    store, each maintaining deterministically identical state from
    its own row stream."""

    def __init__(self, db, path: Optional[str] = None,
                 include_defaults: Optional[bool] = None) -> None:
        self.db = db
        self.path = config_path() if path is None else path
        self.include_defaults = (defaults_enabled()
                                 if include_defaults is None
                                 else bool(include_defaults))
        self.views: Dict[str, RollupView] = {}
        self.tables: Dict[str, object] = {}
        self._plans: Dict[str, QueryPlan] = {}
        self.load_error: Optional[str] = None
        self.loaded_at: Optional[float] = None
        self._mtime: Optional[float] = None
        self._lock = named_lock("rollup.manager")
        #: per-view LOW WATERMARK (a bucket-aligned timestamp): a
        #: TTL/retention trim drops every rollup bucket below it and
        #: advances it; the planner serves [watermark, ...) from the
        #: rollup tiers and routes everything below it to the raw
        #: edge. This is what makes trims race-free against
        #: concurrent block applies WITHOUT re-derivation: a late
        #: apply that re-creates sub-watermark partial rows leaves
        #: dead weight the planner ignores (and the next trim
        #: drops), never a wrong answer.
        self._watermarks: Dict[str, int] = {}
        self.rows_applied = 0
        self.agg_rows = 0
        self.folds = 0
        self.rebuilds = 0
        self._last_seal = 0
        self.reload(rebuild=False)

    @property
    def active(self) -> bool:
        return bool(self.views)

    def table(self, name: str):
        return self.tables[name]

    def views_snapshot(self) -> Dict[str, RollupView]:
        """Point-in-time copy of the view set — what the query-path
        readers iterate (the hot-reload thread mutates self.views
        under the lock; iterating the live dict from an HTTP thread
        would race a reload into RuntimeError)."""
        with self._lock:
            return dict(self.views)

    def table_for(self, name: str):
        """The named view's table, or None (race-safe against a
        concurrent reload removing the view)."""
        with self._lock:
            return self.tables.get(name)

    def watermark_for(self, name: str) -> int:
        """The view's trim low watermark: rollup buckets below it
        are dropped (or dead weight) — the planner must serve that
        region from the raw edge."""
        with self._lock:
            return self._watermarks.get(name, 0)

    # -- config loading ----------------------------------------------------

    def _maintenance_plan(self, view: RollupView) -> QueryPlan:
        """Filter template for the insert-block fold (filter_mask only
        reads filters/start/end/time columns)."""
        return QueryPlan(
            group_by=(), aggregates=(Aggregate("count", None),),
            filters=view.filters, start=None, end=None,
            time_column=view.time_column,
            end_column=view.time_column, k=0, order_by="count")

    def _make_table(self, view: RollupView):
        from ..store.parts import PartTable
        return PartTable(
            ROLLUP_TABLE_PREFIX + view.name, view.schema(),
            sort_key=(BUCKET_COLUMN, *view.group_by),
            time_column=BUCKET_COLUMN,
            prune_columns=(BUCKET_COLUMN, RESOLUTION_COLUMN))

    def reload(self, force: bool = False, rebuild: bool = True) -> bool:
        """(Re)load the view set: built-in defaults merged with the
        THEIA_ROLLUP_VIEWS file (re-read when its mtime moved, or
        `force`). A parse error KEEPS the previous set maintaining and
        records `loadError`. New or redefined views rebuild their
        aggregates from the raw flows currently in the store (under
        the ingest latch where one exists, so a racing insert can
        neither be missed nor double-counted); removed views drop
        their tables. Returns True when the active set changed."""
        docs: List[Dict[str, object]] = []
        unreadable = False
        if self.path:
            try:
                mtime = os.stat(self.path).st_mtime
            except OSError as e:
                self.load_error = f"views file unreadable: {e}"
                if self.views:
                    return False   # keep the previous set evaluating
                # nothing loaded yet: fall through so the built-in
                # defaults (explicitly enabled) still activate; the
                # recorded loadError keeps every later maintain pass
                # re-probing the path until the file appears
                logger.error(
                    "rollup views file unreadable (%s) — activating "
                    "built-in defaults only until it appears", e)
                unreadable = True
            if not unreadable:
                if not force and mtime == self._mtime and \
                        self.load_error is None:
                    return False
                self._mtime = mtime
        if self.path and not unreadable:
            try:
                with open(self.path) as f:
                    docs = parse_views(f.read())
            except (OSError, RollupConfigError) as e:
                self.load_error = str(e)
                logger.error(
                    "rollup views reload failed (keeping %d previous "
                    "views): %s", len(self.views), e)
                return False
        defaults = default_views() if self.include_defaults else []
        try:
            merged = merge_view_docs(defaults, docs)
        except RollupConfigError as e:
            self.load_error = str(e)
            logger.error(
                "rollup views reload failed (keeping %d previous "
                "views): %s", len(self.views), e)
            return False
        if not unreadable:
            self.load_error = None
        self.loaded_at = time.time()
        with self._lock:
            changed = False
            for name in list(self.views):
                if name not in merged:
                    del self.views[name]
                    del self.tables[name]
                    self._plans.pop(name, None)
                    self._watermarks.pop(name, None)
                    changed = True
            staged: List[Tuple[str, RollupView, object]] = []
            for name, view in merged.items():
                old = self.views.get(name)
                if old is not None and \
                        old.normalized() == view.normalized():
                    continue
                staged.append((name, view, self._make_table(view)))
                changed = True
        if staged:
            if rebuild:
                # derive the staged tables' content BEFORE installing
                # them: a query racing the reload keeps answering from
                # the previous view (or raw) instead of from an empty
                # table missing the whole middle of history. ALWAYS
                # through the latch path, even on an apparently-empty
                # store — a first insert racing the length check
                # would otherwise apply to the old view set and then
                # be missing from the freshly-installed empty table
                # forever. _rebuild_staged acquires the ingest latch
                # first and the manager lock second — the same order
                # as the insert path — and installs the finished
                # tables while the latch still excludes inserts, so
                # no block can slip between the derivation scan and
                # visibility (on an empty store it is a no-op scan).
                self._rebuild_staged(staged)
            else:
                # constructor path only (rebuild=False): nothing is
                # serving yet, install directly
                with self._lock:
                    for name, view, table in staged:
                        self.views[name] = view
                        self.tables[name] = table
                        self._plans[name] = \
                            self._maintenance_plan(view)
                        self._watermarks.pop(name, None)
        _M_VIEWS.set(len(self.views))
        if changed:
            logger.info("rollup views loaded: %d active (%s)",
                        len(self.views),
                        ",".join(sorted(self.views)) or "-")
        return changed

    # -- insert-path maintenance -------------------------------------------

    def apply_insert_block(self, block: ColumnarBatch) -> None:
        """Fold one adopted flows insert block into every view — the
        MV SELECT ... GROUP BY per inserted block, emitting exactly-
        mergeable aggregate partial rows into the view's parts-backed
        table. WAL-invisible by design: the flows record is journaled,
        so crash replay re-runs this hook and re-derives identical
        state (journaling the rollup insert too would double-count the
        block on replay)."""
        with self._lock:
            items = [(v, self.tables[n], self._plans[n])
                     for n, v in self.views.items()]
        if not items or not len(block):
            return
        t0 = time.perf_counter()
        for view, table, tplan in items:
            self._apply_one(view, table, tplan, block)
        _M_APPLY_SECONDS.observe(time.perf_counter() - t0)

    def _apply_one(self, view: RollupView, table, tplan: QueryPlan,
                   block: ColumnarBatch) -> None:
        sel = block
        if view.filters:
            mask = filter_mask(tplan, block, self.db.flows.dicts)
            if not mask.any():
                return
            if not mask.all():
                sel = block.filter(mask)
        n = len(sel)
        if n == 0:
            return
        t = np.asarray(sel[view.time_column], np.int64)
        bucket = (t // view.bucket) * view.bucket
        keycols = [bucket] + [np.asarray(sel[c], np.int64)
                              for c in view.group_by]
        uniq: Optional[np.ndarray] = None
        agg_out: Dict[str, np.ndarray] = {}
        vals_by_col = {col: np.asarray(sel[col], np.int64)
                       for _, op, col in view.specs
                       if col is not None}
        packed = _packed_fold(keycols, view.specs, vals_by_col)
        if packed is not None:
            uniq, by_label = packed
            for label, op, col in view.specs:
                agg_out[view.agg_column(op, col)] = by_label[label]
        if uniq is None and all(
                op in ("count", "sum") for _, op, _ in view.specs):
            # sum/count-only views take the MV hot path: one native
            # single-pass hash group-sum (ingest/native.py — the
            # GIL-releasing kernel the legacy ViewTable fan-out uses;
            # count rides as a summed ones column)
            from ..ingest.native import native_group_sum
            vals = [(np.ones(n, np.int64) if op == "count"
                     else np.asarray(sel[col], np.int64))
                    for _, op, col in view.specs]
            out = native_group_sum(keycols, vals)
            if out is not None:
                uniq, reduced = out
                for j, (_, op, col) in enumerate(view.specs):
                    agg_out[view.agg_column(op, col)] = reduced[:, j]
        if uniq is None:
            # mixed min/max (or no native kernel): hash-run grouping
            # + one reduceat per aggregate — still exact partials
            keys = np.stack(keycols, axis=1)
            order, starts, sk = _hash_runs(keys)
            uniq = sk[starts]
            src: Dict[str, np.ndarray] = {}
            for _, op, col in view.specs:
                if col is not None and col not in src:
                    src[col] = np.asarray(sel[col], np.int64)[order]
            for _, op, col in view.specs:
                name = view.agg_column(op, col)
                if op == "count":
                    agg_out[name] = np.diff(
                        np.append(starts, n)).astype(np.int64)
                else:
                    agg_out[name] = _FOLD_UFUNC[op].reduceat(
                        src[col], starts)
        g = uniq.shape[0]
        cols: Dict[str, np.ndarray] = {
            BUCKET_COLUMN: np.asarray(uniq[:, 0], np.int64),
            RESOLUTION_COLUMN: np.full(g, view.bucket, np.int64),
            **agg_out,
        }
        flows_dicts = self.db.flows.dicts
        dicts = {}
        by_name = {c.name: c for c in FLOW_SCHEMA}
        for i, gcol in enumerate(view.group_by):
            arr = uniq[:, 1 + i]
            col = by_name[gcol]
            cols[gcol] = arr.astype(col.host_dtype)
            if col.is_string:
                dicts[gcol] = flows_dicts[gcol]
        table.insert(ColumnarBatch(cols, dicts))
        self.rows_applied += n
        self.agg_rows += g
        _M_APPLIED.inc(n)
        _M_AGG_ROWS.inc(g)

    # -- background maintenance --------------------------------------------

    def maintain(self, now: Optional[int] = None) -> int:
        """One pass: hot-reload the config, run the tier cascade
        (shared part-surgery fold) and part compaction over every view
        table. Returns folds + merges performed (keeps the maintenance
        loop's cadence honest). Driven by PartMaintenanceLoop via
        FlowDatabase.maintenance_tick."""
        now = int(time.time()) if now is None else int(now)
        self.reload()
        with self._lock:
            items = [(v, self.tables[n])
                     for n, v in self.views.items()]
        work = 0
        if items and now - self._last_seal >= SEAL_SPAN_SECONDS:
            # force-seal on a time cadence so aggregate rows become
            # sorted, prunable parts the tier cascade can fold
            for _, table in items:
                seal = getattr(table, "seal", None)
                if callable(seal):
                    seal()
            self._last_seal = now
        for view, table in items:
            if view.tiers:
                merges = view.agg_columns()
                per = downsample_parts(
                    table, now, view.tiers,
                    lambda batch, res, _m=merges, _v=view:
                        fold_rows_to_buckets(
                            batch, res, _v.group_by, _m,
                            time_column=BUCKET_COLUMN),
                    time_column=BUCKET_COLUMN)
                for res, cnt in per.items():
                    _M_FOLDS.labels(resolution=str(res)).inc(cnt)
                    self.folds += cnt
                    work += cnt
            maintain = getattr(table, "maintain", None)
            if callable(maintain):
                work += int(maintain())
        return work

    # -- delete tracking ---------------------------------------------------

    def apply_delete(self, boundary: int) -> None:
        """Track a `timeInserted < boundary` flows trim (TTL /
        retention): every rollup bucket below H — the boundary
        rounded up to the view's coarsest tier — is dropped (whole
        parts below H drop without decoding; one straddling part
        pays a rewrite) and the view's LOW WATERMARK advances to H.
        Buckets at or above H hold only surviving rows, and the
        planner answers [watermark, ...) from rollups with the
        sub-watermark remainder (< one coarse bucket of surviving
        raw rows) stitched from the raw scan — so rollup answers
        track the trim exactly without re-deriving anything, and a
        concurrent insert whose apply lands after the drop merely
        leaves ignored dead weight below the watermark."""
        with self._lock:
            items = [(v, self.tables[n])
                     for n, v in self.views.items()]
        for view, table in items:
            R = view.max_resolution()
            H = -(-int(boundary) // R) * R
            mn = table.min_value(BUCKET_COLUMN)
            if mn is None or mn >= H:
                continue   # nothing below H → nothing to drop/cover
            # watermark BEFORE the drop: a query captures part refs
            # first and reads the watermark second, so any reader
            # that can observe the post-drop part set must also
            # observe the advanced watermark (the reverse order
            # could serve a middle whose trimmed region is covered
            # by neither rollup buckets nor the raw edge)
            with self._lock:
                if self._watermarks.get(view.name, 0) < H:
                    self._watermarks[view.name] = H
            table.delete_older_than(H, column=BUCKET_COLUMN)

    # -- rebuild / persistence / resync ------------------------------------

    def truncate_all(self) -> None:
        with self._lock:
            for t in self.tables.values():
                t.truncate()
            self._watermarks.clear()   # resync re-derives exactly

    def _flows_batches(self):
        flows = self.db.flows
        if hasattr(flows, "_snapshot_refs"):
            parts, mem = flows._snapshot_refs()
            for p in parts:
                yield flows._decode_part(p)
            for b in mem:
                yield b
        else:
            yield flows.scan()

    def _rebuild(self, names: Sequence[str]) -> None:
        """Re-derive ALREADY-INSTALLED views from the raw flows in
        the store (snapshot restore with definition drift — load
        time, before the store serves queries). Lock ORDER matters:
        the ingest latch (where the store has one) is taken FIRST —
        excluding in-flight insert_flows, so a block is counted
        exactly once (by the rebuild scan or by its own apply, never
        both) — and self._lock second, the same order as the insert
        path (which holds latch.read while apply takes the manager
        lock); taking them the other way around deadlocks against
        concurrent ingest."""
        latch = getattr(self.db, "_ingest_latch", None)
        import contextlib
        with (latch.write() if latch is not None
              else contextlib.nullcontext()):
            with self._lock:
                items = [(self.views[n], self.tables[n],
                          self._plans[n])
                         for n in names if n in self.views]
                for _, table, _ in items:
                    table.truncate()
                for batch in self._flows_batches():
                    if not len(batch):
                        continue
                    for view, table, tplan in items:
                        self._apply_one(view, table, tplan, batch)
                for n in names:
                    self._watermarks.pop(n, None)
                self.rebuilds += len(items)

    def _rebuild_staged(self, staged) -> None:
        """Hot-reload half of the rebuild: derive STAGED (not yet
        visible) tables from the flows rows, then install them —
        all while the ingest latch excludes in-flight inserts, so a
        block is either in the derivation scan (its insert finished
        first) or applies after installation, never lost and never
        double-counted; queries meanwhile keep resolving the
        previous table. Same latch-before-manager-lock order as
        _rebuild."""
        latch = getattr(self.db, "_ingest_latch", None)
        import contextlib
        with (latch.write() if latch is not None
              else contextlib.nullcontext()):
            plans = {name: self._maintenance_plan(view)
                     for name, view, _ in staged}
            for batch in self._flows_batches():
                if not len(batch):
                    continue
                for name, view, table in staged:
                    self._apply_one(view, table, plans[name], batch)
            with self._lock:
                for name, view, table in staged:
                    self.views[name] = view
                    self.tables[name] = table
                    self._plans[name] = plans[name]
                    self._watermarks.pop(name, None)
                self.rebuilds += len(staged)

    def snapshot_payload(self) -> Dict[str, np.ndarray]:
        """Parts-aware snapshot leg: every view's aggregate state +
        dictionaries, stamped with the view definition so load can
        detect drift and rebuild instead of restoring a stale shape.
        Captured under the caller's ingest latch / WAL quiesce (the
        flow_store.save discipline)."""
        with self._lock:
            items = [(v, self.tables[n],
                      self._watermarks.get(n, 0))
                     for n, v in self.views.items()]
        out: Dict[str, np.ndarray] = {}
        for view, table, wm in items:
            base = f"__rollup__/{view.name}"
            out[f"{base}/__def__"] = np.asarray(view.normalized(),
                                                dtype=object)
            if wm:
                # the trim watermark must survive restarts: without
                # it a stale sub-watermark partial row (the benign
                # dead weight a concurrent apply can leave) would be
                # served as real data after a reload
                out[f"{base}/__watermark__"] = np.asarray(wm,
                                                          np.int64)
            data = table.scan()
            for col in table.schema:
                out[f"{base}/{col.name}"] = data[col.name]
            for cname, d in table.dicts.items():
                out[f"{base}/__dict__/{cname}"] = np.asarray(
                    d._strings, dtype=object)
        return out

    def restore_or_rebuild(self, payload: Dict[str, np.ndarray]
                           ) -> int:
        """Load-side counterpart: views whose persisted definition
        matches restore their aggregate rows wholesale; the rest
        (absent from the payload, or redefined since the snapshot)
        rebuild from the loaded flows. Returns views restored."""
        restored = 0
        missing: List[str] = []
        with self._lock:
            items = [(v, self.tables[n])
                     for n, v in self.views.items()]
        for view, table in items:
            base = f"__rollup__/{view.name}"
            key = f"{base}/__def__"
            ok = key in payload and str(
                np.asarray(payload[key]).item()) == view.normalized()
            if ok:
                for cname, d in table.dicts.items():
                    dk = f"{base}/__dict__/{cname}"
                    if dk in payload:
                        for s in payload[dk]:
                            d.encode_one(str(s))
                cols: Dict[str, np.ndarray] = {}
                for col in table.schema:
                    ck = f"{base}/{col.name}"
                    if ck not in payload:
                        ok = False
                        break
                    cols[col.name] = np.asarray(payload[ck],
                                                col.host_dtype)
                if ok:
                    n = len(next(iter(cols.values()))) if cols else 0
                    if n:
                        table.insert(ColumnarBatch(cols, table.dicts))
                    wk = f"{base}/__watermark__"
                    if wk in payload:
                        with self._lock:
                            self._watermarks[view.name] = int(
                                np.asarray(payload[wk]))
                    restored += 1
                    continue
            missing.append(view.name)
        if missing and len(self.db.flows):
            logger.info(
                "rollup views %s not restorable from snapshot "
                "(new or redefined) — rebuilding from %d flow rows",
                ",".join(missing), len(self.db.flows))
            self._rebuild(missing)
        return restored

    # -- observability -----------------------------------------------------

    def stats(self) -> Dict[str, object]:
        return {
            "views": len(self.views),
            "rowsApplied": self.rows_applied,
            "aggregateRows": self.agg_rows,
            "folds": self.folds,
            "rebuilds": self.rebuilds,
            "configPath": self.path or None,
            "loadError": self.load_error,
        }

    def doc(self) -> Dict[str, object]:
        """Inspection doc for GET /debug/views (one manager's half —
        views_doc() aggregates across shards)."""
        with self._lock:
            items = [(v, self.tables[n],
                      self._watermarks.get(n, 0))
                     for n, v in self.views.items()]
        views = []
        for view, table, wm in items:
            vdoc: Dict[str, object] = {
                "definition": view.to_doc(),
                "rows": len(table),
                "bytes": table.nbytes,
            }
            if wm:
                vdoc["watermark"] = wm
            ps = getattr(table, "parts_stats", None)
            if callable(ps):
                s = ps()
                vdoc["parts"] = s["count"]
                vdoc["memtableRows"] = s["memtableRows"]
                resolutions = sorted({
                    int(p.minmax[RESOLUTION_COLUMN][0])
                    for p in table.sealed_parts()
                    if p.minmax.get(RESOLUTION_COLUMN) is not None})
                vdoc["partResolutions"] = resolutions
            views.append(vdoc)
        out = self.stats()
        out["views"] = views   # stats() counts them; doc lists them
        return out


# -- topology resolution ---------------------------------------------------

def rollup_managers(db) -> List[RollupManager]:
    """Every RollupManager behind a store topology (all replicas, all
    shards) — the maintenance/inspection view."""
    reps = getattr(db, "replicas", None)
    if reps:
        return [m for r in reps for m in rollup_managers(r)]
    shards = getattr(db, "shards", None)
    if shards:
        return [m for s in shards for m in rollup_managers(s)]
    m = getattr(db, "rollups", None)
    return [m] if isinstance(m, RollupManager) else []


def _read_db(db):
    """The store a READ should hit: the active replica of a
    replicated topology, the facade itself otherwise."""
    if getattr(db, "replicas", None):
        return db.active
    return db


def query_managers(db) -> List[RollupManager]:
    """The managers one query's rollup read resolves against: per
    shard on a sharded store, the active replica's on a replicated
    one."""
    return rollup_managers(_read_db(db))


def rollup_active(db) -> bool:
    try:
        return any(m.active for m in rollup_managers(db))
    except Exception:
        return False


def rollup_configured(db) -> bool:
    """True when ANY rollup config source exists (a views file path
    or defaults enabled) — the maintenance-loop gate. Deliberately
    broader than rollup_active: a file that is torn/empty/missing at
    boot must still get the hot-reload cadence that will pick up its
    repair, which active-view gating would never start."""
    try:
        return any(m.path or m.include_defaults
                   for m in rollup_managers(db))
    except Exception:
        return False


def truncate_rollups(db) -> None:
    for m in rollup_managers(db):
        m.truncate_all()


def views_doc(db) -> Dict[str, object]:
    """GET /debug/views: declared views, tiers, per-store part/row
    counts, maintenance stats, loadError — the /debug/parts shape."""
    mgrs = rollup_managers(db)
    if not mgrs:
        return {"enabled": False, "views": []}
    by_name: Dict[str, Dict[str, object]] = {}
    load_error = None
    for i, m in enumerate(mgrs):
        mdoc = m.doc()
        load_error = load_error or mdoc.get("loadError")
        for vdoc in mdoc["views"]:
            name = vdoc["definition"]["name"]
            agg = by_name.setdefault(name, {
                "name": name,
                "definition": vdoc["definition"],
                "rows": 0, "parts": 0, "bytes": 0,
                "memtableRows": 0, "partResolutions": [],
            })
            agg["rows"] += vdoc.get("rows", 0)
            agg["bytes"] += vdoc.get("bytes", 0)
            agg["parts"] += vdoc.get("parts", 0)
            agg["memtableRows"] += vdoc.get("memtableRows", 0)
            agg["partResolutions"] = sorted(
                set(agg["partResolutions"])
                | set(vdoc.get("partResolutions") or []))
    totals = [m.stats() for m in mgrs]
    return {
        "enabled": any(m.active for m in mgrs),
        "stores": len(mgrs),
        "configPath": mgrs[0].path or None,
        "loadError": load_error,
        "rowsApplied": sum(t["rowsApplied"] for t in totals),
        "aggregateRows": sum(t["aggregateRows"] for t in totals),
        "folds": sum(t["folds"] for t in totals),
        "rebuilds": sum(t["rebuilds"] for t in totals),
        "views": sorted(by_name.values(),
                        key=lambda v: str(v["name"])),
    }


# -- the planner rewrite ---------------------------------------------------

def match_view(db, plan: QueryPlan) -> Optional[RollupView]:
    """The first declared view (declaration order) that SUBSUMES the
    plan, or None. Subsumption: the plan targets `flows`; its groupBy
    is a subset of the view's; each of its lowered aggregates exists
    in the view; any window rides the view's time column; the view's
    own filters all appear in the plan (they are pre-applied at
    maintenance time) and every residual plan filter names a view
    group column (group keys are stored exactly, so residual
    predicates evaluate on the aggregate rows)."""
    if plan.table != "flows" or not rewrite_enabled():
        return None
    mgrs = query_managers(db)
    if not mgrs:
        return None
    snaps = [m.views_snapshot() for m in mgrs]
    best = None
    for view in snaps[0].values():
        if all(view.name in s
               and s[view.name].normalized() == view.normalized()
               for s in snaps) and _subsumes(view, plan):
            # most SELECTIVE subsuming view wins: fewest group
            # columns (fewest aggregate rows per bucket), then the
            # coarsest tier cascade — a plan both a full-key default
            # MV and a narrow tiered view subsume must take the
            # narrow one or the speedup is quietly forfeited; ties
            # fall back to declaration order
            key = (len(view.group_by), -view.max_resolution())
            if best is None or key < best[0]:
                best = (key, view)
    return best[1] if best else None


def _subsumes(view: RollupView, plan: QueryPlan) -> bool:
    gset = set(view.group_by)
    if not set(plan.group_by) <= gset:
        return False
    if plan.start is not None and plan.time_column != view.time_column:
        return False
    if plan.end is not None and plan.end_column != view.time_column:
        return False
    have = {(op, col) for _, op, col in view.specs}
    for _, op, col in lower_specs(plan):
        if (op, col) not in have:
            return False
    vf = set(view.filters)
    pf = set(plan.filters)
    if not vf <= pf:
        return False
    return all(f.column in gset for f in pf - vf)


def _internal_plan(view: RollupView, plan: QueryPlan,
                   lo: Optional[int], hi: Optional[int]
                   ) -> Tuple[QueryPlan, Dict[str, str]]:
    """The plan the engine executes over the `__rollup__:<view>`
    table, plus the internal-label → user-label rename map. User
    aggregates become their partial-merge op over the storage column
    (count → sum(agg_count), min(c) → min(agg_min_c), ...)."""
    internal: List[Aggregate] = []
    label_map: Dict[str, str] = {}
    for label, op, col in lower_specs(plan):
        a = Aggregate(_MERGE_OP[op], view.agg_column(op, col))
        if a.label not in label_map:
            internal.append(a)
        label_map[a.label] = label
    vf = set(view.filters)
    residual = tuple(f for f in plan.filters if f not in vf)
    iplan = QueryPlan(
        group_by=plan.group_by, aggregates=tuple(internal),
        filters=residual, start=lo, end=hi,
        time_column=BUCKET_COLUMN, end_column=BUCKET_COLUMN,
        k=0, order_by=internal[0].label,
        table=ROLLUP_TABLE_PREFIX + view.name)
    return iplan, label_map


def _align_boundary(refs, value: int, base: int,
                    ceil: bool) -> Optional[Tuple[int, int]]:
    """(aligned boundary, alignment used), or None: iterate
    alignment up the tier chain until NO captured bucket straddles
    the candidate (a bucket (t, r) straddles B iff t < B < t+r;
    per-part the check is conservative from resident bucketStart /
    resolution min-max). Per-boundary alignment is what keeps a
    ragged RECENT window edge at base-bucket width even when months
    of old history have folded coarse — a global coarsest-tier
    alignment would force raw-scan edges up to a whole coarse bucket
    wide on both sides. Returns None when a part lacks the metadata
    to prove anything (caller declines the rewrite)."""
    a = int(base)
    for _ in range(16):   # tier chains are short; a only grows
        bnd = (-(-int(value) // a) * a) if ceil else \
            (int(value) // a * a)
        need = int(base)
        for parts, mem in refs:
            for p in parts:
                mt = p.minmax.get(BUCKET_COLUMN)
                mr = p.minmax.get(RESOLUTION_COLUMN)
                if mt is None or mr is None:
                    return None
                if mt[0] < bnd and mt[1] + mr[1] > bnd:
                    need = max(need, int(mr[1]))
            for b in mem:
                if not len(b):
                    continue
                t = np.asarray(b[BUCKET_COLUMN], np.int64)
                r = np.asarray(b[RESOLUTION_COLUMN], np.int64)
                straddle = (t < bnd) & (t + r > bnd)
                if straddle.any():
                    need = max(need, int(r[straddle].max()))
        if need <= a:
            return bnd, a
        a = need
    return None   # pragma: no cover - chain validation bounds this


def try_rollup_partial(engine, plan: QueryPlan, stats: Dict[str, int],
                       prof, view: RollupView):
    """Answer `plan` from the view's rollup tiers: capture each
    rollup table's part set ONCE, align each window edge to the
    coarsest bucket actually straddling it (per-boundary — the tier
    divisibility chain plus the straddle check prove every bucket
    inside the aligned middle is contained by it), read the middle
    from the aggregate parts through the normal part-native engine,
    scan the unaligned head/tail edges from raw flows, and merge all
    partials exactly in materialized key space. Returns (keys, aggs,
    info) or None when the rewrite cannot serve this plan against
    current state (caller falls back to the raw path)."""
    from .engine import merge_materialized
    db = engine.db
    mgrs = query_managers(db)
    tables = []
    for m in mgrs:
        t = m.table_for(view.name)
        if t is None:
            return None
        tables.append(t)
    if not tables:
        return None
    refs = [t._snapshot_refs() for t in tables]
    wm = max((m.watermark_for(view.name) for m in mgrs), default=0)
    lo = plan.start
    hi = plan.end
    align = view.bucket
    head_at_watermark = False
    if wm:
        # TTL/retention trims dropped every bucket below the
        # watermark (any late-apply leftovers there are dead weight):
        # the middle may only start at wm — aligned by construction,
        # nothing straddles it — with the sub-watermark survivors
        # stitched from the raw edge
        if hi is not None and int(hi) <= wm:
            return None   # whole window below the watermark → raw
        if lo is None or int(lo) < wm:
            lo = wm
            head_at_watermark = True
    if lo is not None and not head_at_watermark:
        got = _align_boundary(refs, int(lo), view.bucket, ceil=True)
        if got is None:
            return None
        lo, a_lo = got
        align = max(align, a_lo)
    if hi is not None:
        got = _align_boundary(refs, int(hi), view.bucket, ceil=False)
        if got is None:
            return None
        hi, a_hi = got
        align = max(align, a_hi)
    if lo is not None and hi is not None and lo >= hi:
        return None   # window narrower than one aligned bucket
    iplan, label_map = _internal_plan(view, plan, lo, hi)
    results = []
    for t, r in zip(tables, refs):
        keys, aggs = engine._execute_table(iplan, t, stats, prof,
                                           refs=r)
        if aggs is not None:
            results.append((keys, {label_map[k]: v
                                   for k, v in aggs.items()}))
    edges: List[List[Optional[int]]] = []
    if lo is not None and (
            (plan.start is None and head_at_watermark)
            or (plan.start is not None and plan.start < lo)):
        # a None head means "everything below lo" (open-start plan
        # clamped at the trim watermark — raw holds only survivors)
        edges.append([None if plan.start is None
                      else int(plan.start), int(lo)])
    if plan.end is not None and hi is not None and hi < plan.end:
        edges.append([int(hi), int(plan.end)])
    flows_tables = engine._tables("flows")
    for s, e in edges:
        eplan = dataclasses.replace(
            plan, start=s, end=e, time_column=view.time_column,
            end_column=view.time_column, k=0)
        keys, aggs = engine._partial_for_tables(eplan, flows_tables,
                                                stats, prof)
        if aggs is not None:
            results.append((keys, aggs))
    info = {
        "view": view.name,
        "alignment": align,
        "middle": [lo, hi],
        "edges": edges,
    }
    if wm:
        info["watermark"] = wm
    _M_REWRITES.inc()
    if not results:
        return None, None, info
    if len(results) == 1:
        keys, aggs = results[0]
        return keys, aggs, info
    keys, aggs = merge_materialized(plan, results)
    return keys, aggs, info


# -- dashboard view reads (the legacy ViewTable.scan shape) ----------------

_SCAN_ENGINES: "weakref.WeakKeyDictionary" = None


def _scan_engine(db):
    """One cached QueryEngine per store for the dashboard view
    reads — constructing an engine (cache, env parsing) per panel
    render would do the same setup work on every HTTP request."""
    global _SCAN_ENGINES
    import weakref
    if _SCAN_ENGINES is None:
        _SCAN_ENGINES = weakref.WeakKeyDictionary()
    eng = _SCAN_ENGINES.get(db)
    if eng is None:
        from .engine import QueryEngine
        eng = QueryEngine(db)
        _SCAN_ENGINES[db] = eng
    return eng


def view_scan_batch(db, name: str) -> Optional[ColumnarBatch]:
    """One view's aggregate state in the legacy ViewTable.scan shape
    (group-key columns + summed metric columns, one row per group) —
    the rollup-backed read path dashboards/queries.py routes through
    behind THEIA_DASHBOARD_ROLLUP. Returns None when the view is not
    declared on this store (caller falls back to the legacy table).
    Bucket partial rows collapse across buckets here, so the result
    is group-for-group identical to ViewTable.scan()."""
    mgrs = query_managers(db)
    snaps = [m.views_snapshot() for m in mgrs]
    if not mgrs or any(name not in s for s in snaps):
        return None
    view = snaps[0][name]
    uplan = QueryPlan(
        group_by=view.group_by,
        aggregates=tuple(Aggregate(op, col)
                         for _, op, col in view.specs),
        filters=(), start=None, end=None,
        time_column=view.time_column, end_column=view.time_column,
        k=0, order_by=view.specs[0][0])
    iplan, label_map = _internal_plan(view, uplan, None, None)
    value_col = {label: (col if op != "count" else "count")
                 for label, op, col in view.specs}
    if len(set(value_col.values())) != len(value_col):
        # two ops over one column (a redefined built-in): fall back
        # to the unambiguous aggregate labels as output column names
        value_col = {label: label for label, _, _ in view.specs}
    by_name = {c.name: c for c in FLOW_SCHEMA}
    out_schema = tuple(
        [by_name[g] for g in view.group_by]
        + [Column(value_col[label], ColumnKind.U64)
           for label, _, _ in view.specs])
    tables = [m.table_for(name) for m in mgrs]
    if any(t is None for t in tables):
        return None
    # the PART-NATIVE engine path (encoded-space predicates, granule
    # pruning, no whole-table decode — cold aggregate parts stream
    # their column subset), not the reference oracle: a dashboard
    # render over a big default view must not decode every part
    eng = _scan_engine(db)
    stats = {"rowsScanned": 0, "partsScanned": 0, "partsPruned": 0,
             "granulesScanned": 0, "granulesSkipped": 0}
    if len(tables) == 1:
        # single store: stay in the table's code space (no decode)
        t = tables[0]
        partial = eng._parts_partials(iplan, t, stats)
        if partial is None:
            return ColumnarBatch(
                {c.name: np.zeros(0, c.host_dtype)
                 for c in out_schema}, {})
        uniq, aggs = partial
        cols: Dict[str, np.ndarray] = {}
        dicts = {}
        for j, g in enumerate(view.group_by):
            col = by_name[g]
            cols[g] = uniq[:, j].astype(col.host_dtype)
            if col.is_string:
                dicts[g] = t.dicts[g]
        for label, _, _ in view.specs:
            internal = next(il for il, ul in label_map.items()
                            if ul == label)
            cols[value_col[label]] = aggs[internal]
        return ColumnarBatch(cols, dicts)
    # sharded: materialize per shard (own dictionaries), merge, and
    # re-encode into one batch with fresh dictionaries
    from .engine import merge_materialized
    results = []
    for t in tables:
        partial = eng._parts_partials(iplan, t, stats)
        if partial is None:
            continue
        uniq, aggs = partial
        keys = materialize_keys(iplan, uniq, t.dicts, t.schema)
        results.append((keys, {label_map[k]: v
                               for k, v in aggs.items()}))
    keys, aggs = merge_materialized(uplan, results)
    rows: List[Dict[str, object]] = []
    if aggs is not None:
        g = len(next(iter(aggs.values())))
        for i in range(g):
            row: Dict[str, object] = {}
            for j, gcol in enumerate(view.group_by):
                v = keys[j][i]
                row[gcol] = v.item() if isinstance(v, np.generic) \
                    else v
            for label, _, _ in view.specs:
                row[value_col[label]] = int(aggs[label][i])
            rows.append(row)
    return ColumnarBatch.from_rows(rows, out_schema)


def assert_view_parity(rollup_batch: ColumnarBatch,
                       legacy_batch: ColumnarBatch,
                       name: str) -> None:
    """Group-for-group equality between the rollup-backed view read
    and the legacy ViewTable.scan() — the dashboard routing flag's
    parity gate. Decodes both sides to value space (codes differ by
    dictionary) and compares as mappings."""
    def as_map(batch: ColumnarBatch) -> Dict[tuple, tuple]:
        names = list(batch.column_names)
        decoded = {n: (batch.strings(n) if n in batch.dicts
                       else np.asarray(batch[n], np.int64))
                   for n in names}
        spec = MATERIALIZED_VIEWS.get(name)
        key_names = [n for n in names
                     if spec is None or n in spec.key_columns]
        val_names = [n for n in names if n not in key_names]
        out: Dict[tuple, tuple] = {}
        for i in range(len(batch)):
            k = tuple(str(decoded[n][i]) for n in key_names)
            v = tuple(int(decoded[n][i]) for n in val_names)
            out[k] = v
        return out
    a, b = as_map(rollup_batch), as_map(legacy_batch)
    if a != b:
        only_a = len(set(a) - set(b))
        only_b = len(set(b) - set(a))
        diff = sum(1 for k in set(a) & set(b) if a[k] != b[k])
        raise RuntimeError(
            f"rollup view {name} diverges from the legacy view: "
            f"{only_a} groups only in rollup, {only_b} only in "
            f"legacy, {diff} with different sums")
