"""Query execution profiles (EXPLAIN) and the slow-query capture ring.

The engine's prune/scan/cache/merge decisions were previously visible
only as aggregate counters; this module makes them first-class per
query:

  * **QueryProfiler** — collected alongside a normal execution (never
    a second run, so the profiled rows are bit-identical to the
    unprofiled result): per-part scanned/pruned with the prune
    *reason* (time window, numeric range, dictionary-code miss), rows
    scanned vs matched, kernel used, cache disposition, and on a
    cluster coordinator per-peer timings/bytes/degraded reasons plus
    merge and top-K time. Attached to the result doc under
    `"profile"` when the caller asked (`GET /query?...&explain=1`,
    POST `"explain": true`).
  * **SlowQueryLog** — any query slower than `THEIA_QUERY_SLOW_MS`
    (default 1000 ms; <= 0 disables) is captured WITH its full
    profile into a bounded ring (`THEIA_QUERY_SLOW_RING`, default 64)
    served at `GET /debug/slow_queries` (token-gated — plans carry
    flow identities). Because a slow query must be profiled before it
    is known to be slow, profile collection runs whenever capture is
    enabled; the collection cost is a few dict appends per PART,
    invisible next to the scans that make a query slow.

Profilers are cheap but not free, so `QueryProfiler.maybe(explain)`
returns None when neither explain nor slow capture wants one — the
engine threads `None` through and pays nothing.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Deque, Dict, List, Optional

from ..obs import metrics as _metrics
from ..utils.env import env_int
from ..analysis.lockdep import named_lock

_M_SLOW = _metrics.counter(
    "theia_query_slow_queries_total",
    "Queries slower than THEIA_QUERY_SLOW_MS captured (with their "
    "full execution profile) into the /debug/slow_queries ring")

#: per-part detail entries kept per profile (a 10k-part scan still
#: profiles — the list just truncates, with the drop counted)
MAX_PROFILE_PARTS = 128


def slow_threshold_ms() -> float:
    """THEIA_QUERY_SLOW_MS (default 1000; <= 0 disables capture)."""
    raw = os.environ.get("THEIA_QUERY_SLOW_MS", "")
    try:
        return float(raw) if raw else 1000.0
    except ValueError:
        return 1000.0


class QueryProfiler:
    """One query's execution profile, filled in by the engine as it
    runs. Thread-safe where the engine is parallel (matched-row counts
    come from the worker pool); the per-part prune/scan log is
    appended on the planning thread only."""

    def __init__(self, detail: bool = True) -> None:
        #: detail=False (slow-capture-only) skips collection that
        #: costs real work (e.g. the flat engine's extra mask pass);
        #: cheap per-part bookkeeping is collected either way
        self.detail = detail
        self.parts: List[Dict[str, object]] = []
        self.parts_truncated = 0
        self.rows_matched = 0
        self.memtable_rows = 0
        self.phases: Dict[str, float] = {}
        self.peers: List[Dict[str, object]] = []
        self._lock = named_lock("query.profiler")

    @staticmethod
    def maybe(explain: bool) -> Optional["QueryProfiler"]:
        """A profiler when someone will read it (explain requested, or
        slow-query capture armed), else None — the engine's signal to
        skip collection entirely."""
        if explain or slow_threshold_ms() > 0:
            return QueryProfiler(detail=explain)
        return None

    def add_part(self, uid: object, tier: str, rows: int,
                 pruned: Optional[str] = None,
                 granules: Optional[Dict[str, object]] = None,
                 resolution=None) -> None:
        """One part's fate: scanned, or pruned with the reason
        (`time_window`, `range:<col>`, `codes:<col>`, or `granules`
        when every index granule proved empty). `granules` carries the
        intra-part skip-index story for a sorted part — {"scanned",
        "skipped", "reasons": {"pk:<col>"|"skip_minmax:<col>"|
        "skip_set:<col>": granule count}} — exactly as the engine
        decided it (engine._granule_prune). `resolution` is the
        part's (min, max) `resolution` metadata when the table tracks
        one (`__metrics__`): a 6h window answered from downsampled
        history shows rollup-tier parts (e.g. 3600) here, not raw
        scrape points."""
        if len(self.parts) >= MAX_PROFILE_PARTS:
            self.parts_truncated += 1
            return
        entry: Dict[str, object] = {"part": uid, "tier": tier,
                                    "rows": int(rows)}
        if pruned is not None:
            entry["pruned"] = pruned
        else:
            entry["scanned"] = True
        if granules is not None:
            entry["granules"] = granules
        if resolution is not None:
            lo, hi = int(resolution[0]), int(resolution[1])
            entry["resolution"] = lo if lo == hi else [lo, hi]
        self.parts.append(entry)

    def add_matched(self, n: int) -> None:
        """Rows surviving the filter mask (worker threads)."""
        with self._lock:
            self.rows_matched += int(n)

    def phase(self, name: str, seconds: float) -> None:
        self.phases[name] = self.phases.get(name, 0.0) + seconds

    def add_peer(self, peer: str, status: str, **extra: object) -> None:
        """Coordinator-side per-peer outcome: `queried` (with timing/
        bytes/scan stats), `pruned`, `down`, or `failed` (with the
        degraded reason)."""
        self.peers.append({"peer": peer, "status": status, **extra})

    def doc(self, **extra: object) -> Dict[str, object]:
        out: Dict[str, object] = dict(extra)
        if self.detail:
            # matched counts are collected only under explicit
            # explain (they cost an extra reduction per part)
            out["rowsMatched"] = self.rows_matched
        if self.memtable_rows:
            out["memtableRows"] = self.memtable_rows
        if self.parts:
            out["parts"] = self.parts
        if self.parts_truncated:
            out["partsListTruncated"] = self.parts_truncated
        if self.peers:
            out["peers"] = sorted(self.peers,
                                  key=lambda p: str(p.get("peer")))
        if self.phases:
            out["phases"] = {k: round(v * 1000, 3)
                             for k, v in sorted(self.phases.items())}
        return out


class SlowQueryLog:
    """Bounded, process-wide ring of slow-query captures (newest first
    on read). Entries carry the plan, timing, scan stats, trace id,
    and the full profile — NOT the result rows (the ring must stay
    small and the rows add nothing to "why was it slow")."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        cap = (env_int("THEIA_QUERY_SLOW_RING", 64)
               if capacity is None else int(capacity))
        self._ring: Deque[Dict[str, object]] = collections.deque(
            maxlen=max(0, cap))
        self._lock = named_lock("query.slowlog")
        self.captured = 0

    def capture(self, plan, doc: Dict[str, object],
                profile: Dict[str, object]) -> None:
        if not self._ring.maxlen:
            return
        entry: Dict[str, object] = {
            "time": time.time(),
            "tookMs": doc.get("tookMs"),
            "engine": doc.get("engine"),
            "plan": plan.to_doc(),
            "groupCount": doc.get("groupCount"),
            "rowsScanned": doc.get("rowsScanned"),
            "partsScanned": doc.get("partsScanned"),
            "partsPruned": doc.get("partsPruned"),
            # the PR-12 granule skip-index story rides every capture:
            # "slow despite skipping?" / "slow because nothing
            # skipped?" is the first question a profile answers
            "granulesScanned": doc.get("granulesScanned"),
            "granulesSkipped": doc.get("granulesSkipped"),
            "profile": profile,
        }
        if doc.get("traceId"):
            entry["traceId"] = doc["traceId"]
        if doc.get("partial"):
            entry["partial"] = True
        with self._lock:
            self._ring.append(entry)
            self.captured += 1
        _M_SLOW.inc()

    def observe(self, plan, doc: Dict[str, object],
                profiler: Optional[QueryProfiler],
                profile_doc: Optional[Dict[str, object]]) -> None:
        """Capture `doc` iff it crossed the threshold and a profile was
        collected (the engine's single call site per query)."""
        threshold = slow_threshold_ms()
        if threshold <= 0 or profiler is None:
            return
        took = float(doc.get("tookMs") or 0.0)
        if took >= threshold:
            self.capture(plan, doc, profile_doc or profiler.doc())

    def snapshot(self) -> List[Dict[str, object]]:
        with self._lock:
            out = list(self._ring)
        out.reverse()
        return out

    def doc(self) -> Dict[str, object]:
        """The GET /debug/slow_queries payload."""
        return {
            "thresholdMs": slow_threshold_ms(),
            "captured": self.captured,
            "capacity": self._ring.maxlen,
            "queries": self.snapshot(),
        }

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self.captured = 0


#: the process-wide slow-query ring every engine captures into (one
#: manager process = one ring, exactly like the trace ring)
SLOW_QUERIES = SlowQueryLog()
