"""Vectorized query engine over the part-based column store.

`plan.py` parses/normalizes queries, `engine.py` executes them
part-natively (pruned, encoded-space filters, late-materializing
group-by, bounded-pool parallelism, cold streaming, result cache),
`kernels.py` holds the aggregation kernels (numpy reduceat / jitted
jnp segment reductions), and `reference.py` is the slow-but-correct
oracle the whole path is gated against.
"""

from .engine import QueryCache, QueryEngine, QueryError
from .kernels import kernel_mode
from .plan import (AGG_OPS, Aggregate, Filter, PlanError, QueryPlan,
                   parse_plan, plan_from_params)
from .reference import reference_execute

__all__ = [
    "AGG_OPS", "Aggregate", "Filter", "PlanError", "QueryCache",
    "QueryEngine", "QueryError", "QueryPlan", "kernel_mode",
    "parse_plan", "plan_from_params", "reference_execute",
]
