"""Vectorized query engine over the part-based column store.

`plan.py` parses/normalizes queries, `engine.py` executes them
part-natively (pruned, encoded-space filters, late-materializing
group-by, bounded-pool parallelism, cold streaming, result cache),
`kernels.py` holds the aggregation kernels (numpy reduceat / jitted
jnp segment reductions), `reference.py` is the slow-but-correct
oracle the whole path is gated against, `distributed.py` is the
cluster scatter-gather tier (coordinator fan-out over
`/query/partial`, mergeable TQPF partial frames, peer pruning,
cluster-fingerprint caching), and `rollup.py` is the streaming
materialized rollup-view subsystem (declarative aggregate views
maintained incrementally as first-class parts, cascaded tier
downsampling, and the transparent planner rewrite that answers
subsumed windowed plans from the coarsest covering tier with
raw-scan edges stitched bit-identically).
"""

from .distributed import ClusterQueryCoordinator, IncompleteResultError
from .engine import (QueryCache, QueryEngine, QueryError,
                     merge_materialized)
from .kernels import kernel_mode
from .plan import (AGG_OPS, Aggregate, Filter, PlanError, QueryPlan,
                   parse_plan, plan_from_params)
from .reference import reference_execute

__all__ = [
    "AGG_OPS", "Aggregate", "ClusterQueryCoordinator", "Filter",
    "IncompleteResultError", "PlanError", "QueryCache", "QueryEngine",
    "QueryError", "QueryPlan", "kernel_mode", "merge_materialized",
    "parse_plan", "plan_from_params", "reference_execute",
]
