"""Shared query-result machinery: aggregate lowering and the
finalization step both executors use.

The engine (vectorized, part-native) and the reference executor (slow,
obviously correct) must answer BIT-IDENTICALLY — that parity is the
gate the whole read path stands on (the PR-6/7 playbook). The safest
way to make the *presentation* identical is to share it: both sides
produce the same intermediate shape — materialized group-key columns +
int64 aggregate arrays — and this module turns that into ordered,
top-K-limited result rows. `mean` is never aggregated directly; it is
LOWERED to (sum, count) partials (which merge exactly) and divided
here, once, in float64 — so a mean computed from two part partials
equals the mean computed from one flat scan, bitwise.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .plan import QueryPlan

#: lowered spec: (label, op, column) with op in count/sum/min/max
Spec = Tuple[str, str, Optional[str]]


def lower_specs(plan: QueryPlan) -> List[Spec]:
    """Physical aggregates the kernels must compute: user aggregates
    minus `mean`, which lowers to sum + count (deduplicated — a plan
    asking for mean(x), sum(x) and count computes each once)."""
    specs: List[Spec] = []

    def add(label: str, op: str, column: Optional[str]) -> None:
        if all(s[0] != label for s in specs):
            specs.append((label, op, column))

    for a in plan.aggregates:
        if a.op == "mean":
            add(f"sum({a.column})", "sum", a.column)
            add("count", "count", None)
        else:
            add(a.label, a.op, a.column)
    return specs


def value_columns(specs: Sequence[Spec]) -> Tuple[str, ...]:
    """Distinct value columns the lowered specs read."""
    out: List[str] = []
    for _, op, column in specs:
        if column is not None and column not in out:
            out.append(column)
    return tuple(out)


def empty_result(plan: QueryPlan
                 ) -> Tuple[List[Dict[str, object]], int]:
    """Zero surviving rows: a grouped query has no groups; a GLOBAL
    aggregate still answers one row (count 0, every aggregate 0 —
    the convention both executors share so parity holds on empty
    windows)."""
    if plan.group_by:
        return [], 0
    row: Dict[str, object] = {}
    for a in plan.aggregates:
        row[a.label] = 0.0 if a.op == "mean" else 0
    return [row], 1


def finalize(plan: QueryPlan,
             key_columns: Sequence[np.ndarray],
             aggs: Dict[str, np.ndarray]
             ) -> Tuple[List[Dict[str, object]], int]:
    """Materialized groups → ordered result rows.

    `key_columns` are per-group arrays aligned with `plan.group_by`
    (strings already decoded); `aggs` carries one int64 array per
    LOWERED spec label. Rows are ordered by the `order_by` aggregate
    descending, ties broken by the group key ascending (decoded
    values, so the order is stable across engines, shards, and
    dictionary states), then truncated to `k` (0 = all). Returns
    (rows, total group count before the top-K cut)."""
    n_groups = len(aggs["count"]) if "count" in aggs else (
        len(key_columns[0]) if key_columns
        else len(next(iter(aggs.values()))))

    out_vals: Dict[str, np.ndarray] = {}
    for a in plan.aggregates:
        if a.op == "mean":
            s = aggs[f"sum({a.column})"].astype(np.float64)
            c = aggs["count"].astype(np.float64)
            with np.errstate(invalid="ignore", divide="ignore"):
                out_vals[a.label] = np.where(c > 0, s / c, 0.0)
        else:
            out_vals[a.label] = aggs[a.label]

    keys = [np.asarray(k) for k in key_columns]

    # fully vectorized ordering (a group-by can yield 10^5+ groups and
    # the top-K cut happens after the sort): lexsort the key columns
    # ascending (object/string columns widen to numpy unicode, whose
    # comparison matches Python's code-point order), then a STABLE
    # descending argsort on the order_by aggregate — value desc, ties
    # by group key asc, identical to the old per-tuple Python sort
    if keys:
        sort_cols = tuple(
            (k.astype(str) if k.dtype == object else k)
            for k in reversed(keys))
        order = np.lexsort(sort_cols)
    else:
        order = np.arange(n_groups)
    order_vals = np.asarray(out_vals[plan.order_by])
    order = order[np.argsort(-order_vals[order], kind="stable")]
    limited = order[:plan.k] if plan.k > 0 else order

    rows: List[Dict[str, object]] = []
    for i in limited:
        row: Dict[str, object] = {}
        for name, col in zip(plan.group_by, keys):
            v = col[i]
            row[name] = v.item() if isinstance(v, np.generic) else v
        for a in plan.aggregates:
            v = out_vals[a.label][i]
            row[a.label] = (float(v) if a.op == "mean" else int(v))
        rows.append(row)
    return rows, n_groups
