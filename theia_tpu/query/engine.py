"""Vectorized query engine over the part-based column store.

The read-side twin of the PR-6 fused detector: where PR 7 made the
flows table a set of immutable, width-reduced, dictionary-coded column
parts, this module runs filtered aggregations DIRECTLY over that
encoding — the ARIMA_PLUS "push analytics into the store" pattern —
instead of decoding parts back to table code space and aggregating a
materialized copy:

  1. **Plan → prune.** Part min/max metadata (the PR-7 pruning
     substrate) drops parts that cannot overlap the time window or a
     numeric filter's range before any column is touched. Inside the
     surviving SORTED parts (store/parts.py format v2), the same
     decision repeats at GRANULE granularity from the resident index
     metadata: the sparse primary index (zone map of the sort-key
     prefix, ascending because the part is sorted), per-granule
     min/max zone maps on every column, and bounded set indexes of
     distinct dictionary codes on string columns. Predicates decide
     granules BEFORE any row is gathered; only surviving granule row
     ranges are evaluated (`pk:`/`skip_minmax:`/`skip_set:` reasons
     in EXPLAIN, theia_query_granules_{scanned,skipped}_total).
  2. **Filters in encoded space.** On a hot part, a numeric predicate
     compares the WIDTH-REDUCED stored array against the rebased
     threshold (`v - base`, clamped: an out-of-range threshold decides
     the whole part without widening a single row); a string predicate
     resolves to table-global dictionary codes ONCE per query, then
     per part intersects the part's unique-code set — a miss skips the
     part entirely, a hit turns into a boolean gather over the narrow
     local indices. No strings, no widening, no row materialization.
  3. **Late-materializing group-by.** Group keys aggregate in the
     part's LOCAL code space (u1/u2 indices); only the SURVIVING
     groups map local → global codes (strings) or `+ base`
     (numerics). Aggregation itself is query/kernels.py — lexsort +
     reduceat, or one jitted `jnp` segment-reduction dispatch
     (`THEIA_QUERY_JAX`, the THEIA_FUSED_PALLAS auto/fallback
     discipline). When the plan's groupBy is a PREFIX of the part's
     sort key, the part's rows are already key-clustered (local
     indices and width-reduced ints are monotone in the decoded
     values) and the kernel skips its lexsort entirely — group
     boundaries come from one adjacent-row comparison over
     contiguous runs, bit-identical output.
  4. **Parallel per-part execution.** Live parts are striped across a
     bounded pool (`THEIA_QUERY_WORKERS`); each worker folds its
     parts into ONE per-worker partial accumulator, and the partials
     merge exactly (count via sum, min via min, ...).
  5. **Cold tier stays cold.** A demoted part streams through a
     bounded decode buffer (`THEIA_QUERY_COLD_BUFFER` concurrent
     decodes), decoding ONLY the columns the plan touches
     (column-subset part-file decode), and is never promoted back to
     RAM — the hot/cold working-set split of arXiv:1902.04143 holds
     under scans.
  6. **Result cache.** Finalized results cache under (normalized
     plan, store-state fingerprint); any seal/merge/demote/delete/
     insert changes the fingerprint, so invalidation is structural,
     not timed (`THEIA_QUERY_CACHE_BYTES`).

The flat engine and the parts memtable take the slow-but-correct
reference executor path (query/reference.py); the randomized oracle
suite (tests/test_query.py) holds every path bit-identical.
"""

from __future__ import annotations

import collections
import hashlib
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..schema import ColumnarBatch
from ..utils.env import env_int
from ..utils.logging import get_logger
from ..utils.pool import get_pool
from . import kernels
from .explain import SLOW_QUERIES, QueryProfiler
from .plan import QUERYABLE_TABLES, QueryPlan
from .reference import filter_mask, materialize_keys, reference_partial
from .result import empty_result, finalize, lower_specs, value_columns
from ..analysis.lockdep import named_lock

logger = get_logger("query")

DEFAULT_WORKERS = min(8, os.cpu_count() or 1)
DEFAULT_CACHE_BYTES = 16 << 20
DEFAULT_COLD_BUFFER = 2

_M_SECONDS = _metrics.histogram(
    "theia_query_seconds",
    "End-to-end query-engine execution time (cache misses; hits are "
    "counted separately)")
_M_ROWS_SCANNED = _metrics.counter(
    "theia_query_rows_scanned_total",
    "Rows evaluated by the query engine (part rows after pruning + "
    "memtable rows)")
_M_PARTS_SCANNED = _metrics.counter(
    "theia_query_parts_scanned_total",
    "Parts evaluated by queries after pruning")
_M_PARTS_PRUNED = _metrics.counter(
    "theia_query_parts_pruned_total",
    "Parts skipped by query min/max + dictionary-code pruning (read "
    "with theia_query_parts_scanned_total for the prune ratio)")
_M_GRANULES_SCANNED = _metrics.counter(
    "theia_query_granules_scanned_total",
    "Index granules evaluated inside sorted parts after granule-level "
    "skip-index pruning (sorted format-v2 parts only)")
_M_GRANULES_SKIPPED = _metrics.counter(
    "theia_query_granules_skipped_total",
    "Index granules skipped inside sorted parts by the sparse primary "
    "index and per-granule zone-map/set skip indexes (read with "
    "theia_query_granules_scanned_total for the intra-part prune "
    "ratio)")
_M_CACHE_HITS = _metrics.counter(
    "theia_query_cache_hits_total",
    "Queries answered from the result cache (same normalized plan, "
    "unchanged store fingerprint)")
_M_CACHE_MISSES = _metrics.counter(
    "theia_query_cache_misses_total",
    "Queries that had to execute (cold cache, or the store fingerprint "
    "moved under seal/merge/demote/insert/delete)")


class QueryError(Exception):
    """The engine could not execute a valid plan (store-side issue)."""


# -- compiled predicates ---------------------------------------------------

class _CompiledFilter:
    """One plan filter resolved against a concrete table: string
    values → sorted global dictionary codes (resolved once per query,
    not per part)."""

    __slots__ = ("column", "op", "value", "codes", "is_string")

    def __init__(self, f, table) -> None:
        self.column = f.column
        self.op = f.op
        self.value = f.value
        d = table.dicts.get(f.column)
        self.is_string = d is not None
        self.codes: Optional[np.ndarray] = None
        if self.is_string:
            values = (f.value if isinstance(f.value, tuple)
                      else (f.value,))
            # unique, not just sorted: isin(assume_unique=True)
            # downstream requires it, and `in` values may repeat.
            # int32 — the dictionaries' native code dtype — so the
            # per-part intersections below need no conversions.
            self.codes = np.unique(np.asarray(
                [c for c in (d.lookup(str(v)) for v in values)
                 if c is not None], np.int32))

    def excludes_part(self, part) -> bool:
        """True when this predicate PROVABLY matches no row of a hot
        part, from resident metadata alone: eq/in whose resolved code
        set misses the part's unique-code set (or resolved to nothing
        at all). The dictionary-code half of part pruning."""
        if not self.is_string or self.op == "ne":
            return False
        if not len(self.codes):
            return True        # value(s) not in the table dictionary
        chunks = part.chunks
        chunk = chunks.get(self.column) if chunks is not None else None
        if chunk is None or not hasattr(chunk, "uniq"):
            return False       # cold/lazy: no resident code set
        return not _sorted_intersects(self.codes, chunk.uniq)


def _minmax_excludes(mm: Tuple[int, int], op: str, value) -> bool:
    """True when part min/max PROVES no row can match a numeric
    predicate (the filter-level analogue of window pruning)."""
    lo, hi = mm
    if op == "ge":
        return hi < value
    if op == "gt":
        return hi <= value
    if op == "le":
        return lo > value
    if op == "lt":
        return lo >= value
    if op == "eq":
        return value < lo or value > hi
    if op == "in":
        return all(v < lo or v > hi for v in value)
    return False   # ne: metadata can't exclude


def _zone_excludes(mins: np.ndarray, maxs: np.ndarray, op: str,
                   value) -> np.ndarray:
    """Vectorized `_minmax_excludes` over per-granule zone maps: a
    bool array, True where granule g PROVABLY holds no matching row.
    `ne` proves nothing (a granule whose zone equals the value could
    still be all-equal — but so could any other)."""
    if op == "ge":
        return maxs < value
    if op == "gt":
        return maxs <= value
    if op == "le":
        return mins > value
    if op == "lt":
        return mins >= value
    if op == "eq":
        return (value < mins) | (value > maxs)
    if op == "in":
        drop = np.ones(len(mins), bool)
        for v in value:
            drop &= (v < mins) | (v > maxs)
        return drop
    return np.zeros(len(mins), bool)


def _sorted_intersects(a: np.ndarray, b: np.ndarray) -> bool:
    """Any common element between two SORTED unique integer arrays.
    This runs once per (surviving granule, string filter) — np.isin's
    dispatch overhead (dtype logic, zeros_like, min/max probing) is
    ~50us per call at that grain and was the dominant cost of a fully
    index-pruned query; two searchsorted-style ops are ~2us."""
    if not len(a) or not len(b):
        return False
    if len(a) > len(b):
        a, b = b, a
    pos = np.searchsorted(b, a)
    pos[pos == len(b)] = len(b) - 1
    return bool((b[pos] == a).any())


def _ranges_to_rows(starts: np.ndarray, ends: np.ndarray) -> np.ndarray:
    """Concatenated `arange(s, e)` for every surviving granule range,
    in one cumsum pass (no per-granule allocations): an all-ones array
    with each range's first element patched to jump from the previous
    range's end."""
    lens = (ends - starts).astype(np.int64)
    total = int(lens.sum())
    out = np.ones(total, np.int64)
    out[0] = starts[0]
    cuts = np.cumsum(lens)[:-1]
    out[cuts] = starts[1:] - ends[:-1] + 1
    return np.cumsum(out)


def _cmp_encoded(chunk, op: str, value: int,
                 rows: Optional[np.ndarray] = None) -> object:
    """Evaluate `col <op> value` on a width-reduced numeric chunk
    WITHOUT widening: compare the narrow stored array against the
    rebased threshold. Returns a bool array, or True/False when the
    rebased threshold falls outside the stored dtype's range (the
    whole part decides at once). `rows` restricts the comparison to
    that row selection (the granule-surviving rows)."""
    s = chunk.stored if rows is None else chunk.stored[rows]
    if op == "in":
        vals = np.asarray(value, np.int64) - chunk.base
        lo, hi = (np.iinfo(s.dtype).min, np.iinfo(s.dtype).max) \
            if s.dtype.kind in "iu" else (-np.inf, np.inf)
        vals = vals[(vals >= lo) & (vals <= hi)]
        if not len(vals):
            return False
        return np.isin(s, vals.astype(s.dtype))
    t = value - chunk.base
    if s.dtype.kind in "iu":
        info = np.iinfo(s.dtype)
        if t < info.min:     # every stored value is above t
            return {"ge": True, "gt": True, "le": False,
                    "lt": False, "eq": False, "ne": True}[op]
        if t > info.max:     # every stored value is below t
            return {"ge": False, "gt": False, "le": True,
                    "lt": True, "eq": False, "ne": True}[op]
        t = s.dtype.type(t)
    return {"eq": s == t, "ne": s != t, "ge": s >= t,
            "gt": s > t, "le": s <= t, "lt": s < t}[op]


def _and_mask(mask, m) -> object:
    """AND-combine masks where True means all rows / False means no
    rows (short-circuit forms the encoded comparisons return)."""
    if m is True or mask is False:
        return mask
    if mask is True or m is False:
        return m
    mask &= m
    return mask


# -- result cache ----------------------------------------------------------

class QueryCache:
    """LRU-by-bytes cache of finalized result docs keyed by
    (normalized plan, store-state fingerprint). Invalidation is the
    fingerprint moving — every seal, merge, demote, delete, and insert
    changes it — so a stale hit is structurally impossible."""

    def __init__(self, max_bytes: Optional[int] = None) -> None:
        self.max_bytes = (
            env_int("THEIA_QUERY_CACHE_BYTES", DEFAULT_CACHE_BYTES)
            if max_bytes is None else int(max_bytes))
        self._entries: "collections.OrderedDict[tuple, Tuple[dict, int]]" = (
            collections.OrderedDict())
        self._bytes = 0
        self._lock = named_lock("query.cache")
        self.hits = 0
        self.misses = 0

    def lookup(self, key: tuple) -> Optional[dict]:
        if self.max_bytes <= 0:
            return None
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return entry[0]

    @staticmethod
    def _estimate_bytes(doc: dict) -> int:
        """Cheap structural size estimate for the LRU byte charge —
        a full json.dumps here would serialize every result doc a
        second time (the HTTP layer already pays one) just to weigh
        it, which is worst exactly on the large results the cache
        exists to help. String values are charged at their REAL
        length (sampled from the first row): pod-label group keys run
        to kilobytes, and a flat per-value charge would let the
        configured byte budget retain 10x its size."""
        rows = doc.get("rows") or ()
        if not rows:
            return 512
        per_row = 24 + sum(
            (len(k) + len(v) + 49) if isinstance(v, str)
            else (len(k) + 40)
            for k, v in rows[0].items())
        return 512 + len(rows) * per_row

    def store(self, key: tuple, doc: dict) -> None:
        if self.max_bytes <= 0:
            return
        nbytes = self._estimate_bytes(doc)
        if nbytes > self.max_bytes:
            return
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (doc, nbytes)
            self._bytes += nbytes
            while self._bytes > self.max_bytes and self._entries:
                _, (_, n) = self._entries.popitem(last=False)
                self._bytes -= n

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {"entries": len(self._entries),
                    "bytes": self._bytes,
                    "maxBytes": self.max_bytes,
                    "hits": self.hits, "misses": self.misses}


# -- the engine ------------------------------------------------------------

Partial = Optional[Tuple[np.ndarray, Dict[str, np.ndarray]]]


class QueryEngine:
    """Executes QueryPlans over a FlowDatabase (plain, sharded, or
    replicated; parts or flat engine). Thread-safe; one instance per
    manager."""

    def __init__(self, db,
                 workers: Optional[int] = None,
                 cache_bytes: Optional[int] = None,
                 cold_buffer: Optional[int] = None) -> None:
        self.db = db
        self.workers = max(1, (
            env_int("THEIA_QUERY_WORKERS", DEFAULT_WORKERS)
            if workers is None else int(workers)))
        self.cold_buffer = max(1, (
            env_int("THEIA_QUERY_COLD_BUFFER", DEFAULT_COLD_BUFFER)
            if cold_buffer is None else int(cold_buffer)))
        self._cold_sem = threading.Semaphore(self.cold_buffer)
        self.cache = QueryCache(cache_bytes)
        self.queries = 0
        self._lock = named_lock("query.engine")

    # -- store resolution --------------------------------------------------

    def _tables(self, table: str = "flows") -> List[object]:
        """Concrete tables to query for one plan's target: one for
        plain/replicated (the active replica resolves through
        __getattr__ — all replicas down raises, surfacing as 503),
        every shard for a sharded store. `flows` is the data plane;
        any other name resolves through the store's result-table
        registry (the `__metrics__` history table queries through
        the same engine)."""
        if table == "flows":
            root = self.db.flows
        else:
            try:
                root = self.db.result_tables[table]
            except (KeyError, AttributeError):
                raise QueryError(
                    f"table {table!r} is not present in this store")
        if hasattr(root, "tables"):
            return list(root.tables)
        return [root]

    @staticmethod
    def _table_state(table) -> tuple:
        """Cache-fingerprint component for one table: covers inserts/
        deletes (generation), seals (memtable length + part set),
        merges (part uids), and demotions (tiers)."""
        parts = getattr(table, "_parts", None)
        if parts is not None:
            with table._lock:
                return (table.generation, table._memtable_len,
                        tuple((p.uid, p.tier) for p in table._parts))
        return (table.generation, len(table))

    def fingerprint(self, tables: Optional[List[object]] = None
                    ) -> tuple:
        """Cache-key component covering one table set's state; pass
        `tables` to fingerprint an already-resolved snapshot (execute
        does — key and execution must cover the same table set). The
        default covers the FLOWS tables only — the `__metrics__`
        history mutates every scrape tick, so folding it in here
        would invalidate every flows cache (and re-trigger heartbeat
        bounds scans) each tick; per-table digests come from
        `table_fingerprints()`."""
        if tables is None:
            tables = self._tables()
        return tuple(self._table_state(t) for t in tables)

    def table_fingerprints(self) -> Dict[str, str]:
        """{table: digest} for every queryable table present in this
        store — what cluster heartbeats piggyback, so a coordinator
        keys its cache PER PLAN TABLE: a peer's scrape tick moves its
        `__metrics__` digest (invalidating metrics-history results
        within one heartbeat) without touching the flows digest that
        keys everything else."""
        out: Dict[str, str] = {}
        for name in QUERYABLE_TABLES:
            try:
                tables = self._tables(name)
            except Exception:
                continue   # a store predating the table
            out[name] = self.fingerprint_hash(self.fingerprint(tables))
        return out

    def fingerprint_hash(self, fingerprint: Optional[tuple] = None
                         ) -> str:
        """Compact digest of `fingerprint()` — what cluster heartbeats
        piggyback so a query coordinator can key its cluster-wide
        result cache on per-peer store states (any seal/merge/demote/
        insert/delete on any node moves its digest). Pass an
        already-computed fingerprint to digest the exact state an
        execution keyed on (EXPLAIN profiles do)."""
        if fingerprint is None:
            fingerprint = self.fingerprint()
        return hashlib.sha1(
            repr(fingerprint).encode()).hexdigest()[:16]

    # -- public API --------------------------------------------------------

    def execute(self, plan: QueryPlan,
                use_cache: bool = True,
                explain: bool = False,
                traceparent: Optional[str] = None,
                use_rollup: bool = True
                ) -> Dict[str, object]:
        """Run one plan; returns the result doc. Raises PlanError
        (from parsing, upstream), QueryError, or the store's
        availability errors. `explain=True` attaches the execution
        profile (query/explain.py) WITHOUT re-running anything — the
        result rows are bit-identical either way; `traceparent`
        adopts a caller's trace context (this is a trace ingress);
        `use_rollup=False` (the request's `rollup=0` flag) forces the
        raw-scan path even when a declared rollup view subsumes the
        plan — the bench's A/B lever and the parity tests' oracle
        side."""
        with _trace.ingress_span("query.request",
                                 traceparent=traceparent) as sp:
            doc = self._execute_traced(plan, use_cache, explain,
                                       use_rollup)
            sp.attrs["groups"] = doc.get("groupCount")
            sp.attrs["cache"] = doc.get("cache")
            return doc

    @staticmethod
    def _stamp_trace(doc: Dict[str, object]) -> None:
        """Attach the current sampled trace id to a result doc (the
        caller's handle into `theia trace <id>`)."""
        ctx = _trace.current_context()
        if ctx is not None:
            doc["traceId"] = ctx.trace_id

    def _execute_traced(self, plan: QueryPlan, use_cache: bool,
                        explain: bool,
                        use_rollup: bool = True) -> Dict[str, object]:
        with self._lock:
            self.queries += 1
        t0 = time.perf_counter()
        tables = self._tables(plan.table)
        fp = self.fingerprint(tables)
        # a disabled cache (THEIA_QUERY_CACHE_BYTES=0) reports "off",
        # not a permanent 0% hit ratio that reads as a broken cache —
        # and an uncached execution (every /query/partial, every
        # cache=0 probe) skips the key's plan-JSON normalization
        # entirely
        caching = use_cache and self.cache.max_bytes > 0
        if caching:
            # the rollup flag joins the key: the ROWS are identical
            # either way (the parity gate), but the doc's rollup/scan
            # accounting differs and must not leak across flags
            key = (plan.normalized(), fp, bool(use_rollup))
            hit = self.cache.lookup(key)
            if hit is not None:
                _M_CACHE_HITS.inc()
                doc = dict(hit)
                doc["cache"] = "hit"
                # THIS answer's latency, not the cached miss's —
                # anyone debugging from the footer would otherwise
                # read the slow path for a microsecond hit
                doc["tookMs"] = round(
                    (time.perf_counter() - t0) * 1000, 3)
                self._stamp_trace(doc)
                if explain:
                    # a hit has no per-part story to tell — the honest
                    # profile is "served from cache under this state"
                    doc["profile"] = {
                        "engine": doc.get("engine"),
                        "cache": "hit",
                        "fingerprint": self.fingerprint_hash(fp),
                    }
                return doc
            _M_CACHE_MISSES.inc()
        prof = QueryProfiler.maybe(explain)
        stats = {"rowsScanned": 0, "partsScanned": 0, "partsPruned": 0,
                 "granulesScanned": 0, "granulesSkipped": 0}
        t_exec = time.perf_counter()
        keys, aggs, rollup_info = self._partial_with_rollup(
            plan, tables, stats, prof, use_rollup)
        t_fin = time.perf_counter()
        if aggs is None or _n_groups(aggs) == 0:
            rows, groups = empty_result(plan)
        else:
            rows, groups = finalize(plan, keys, aggs)
        took = time.perf_counter() - t0
        _M_SECONDS.observe(took)
        _M_ROWS_SCANNED.inc(stats["rowsScanned"])
        _M_PARTS_SCANNED.inc(stats["partsScanned"])
        _M_PARTS_PRUNED.inc(stats["partsPruned"])
        _M_GRANULES_SCANNED.inc(stats["granulesScanned"])
        _M_GRANULES_SKIPPED.inc(stats["granulesSkipped"])
        doc = {
            "plan": plan.to_doc(),
            "rows": rows,
            "groupCount": groups,
            "rowsScanned": stats["rowsScanned"],
            "partsScanned": stats["partsScanned"],
            "partsPruned": stats["partsPruned"],
            "granulesScanned": stats["granulesScanned"],
            "granulesSkipped": stats["granulesSkipped"],
            "engine": ("parts" if any(
                getattr(t, "_parts", None) is not None
                for t in tables) else "flat"),
            "tookMs": round(took * 1000, 3),
            "cache": "miss" if caching else "off",
        }
        if rollup_info is not None:
            # the planner-rewrite story rides the result doc: which
            # view answered, the alignment tier, and the stitched
            # raw-scan edge spans — the rows are bit-identical to the
            # raw path either way
            doc["rollup"] = rollup_info
        if caching:
            # the cached doc carries no profile or trace id: a later
            # hit under the same key would serve a stale one
            self.cache.store(key, doc)
            doc = dict(doc)
        self._stamp_trace(doc)   # BEFORE slow capture: entries link
        profile = None           # back via theia trace <id>
        if prof is not None:
            prof.phase("execute", t_fin - t_exec)
            prof.phase("finalize", time.perf_counter() - t_fin)
            extra: Dict[str, object] = {}
            if rollup_info is not None:
                extra["rollup"] = rollup_info
            profile = prof.doc(
                engine=doc["engine"],
                kernel=kernels.kernel_mode(),
                cache=doc["cache"],
                fingerprint=self.fingerprint_hash(fp),
                rowsScanned=stats["rowsScanned"],
                partsScanned=stats["partsScanned"],
                partsPruned=stats["partsPruned"],
                granulesScanned=stats["granulesScanned"],
                granulesSkipped=stats["granulesSkipped"],
                **extra,
            )
            SLOW_QUERIES.observe(plan, doc, prof, profile)
        if explain and profile is not None:
            doc["profile"] = profile
        return doc

    def stats(self) -> Dict[str, object]:
        """Operator doc for /healthz `query`."""
        return {
            "queries": self.queries,
            "workers": self.workers,
            "coldBuffer": self.cold_buffer,
            "kernel": kernels.kernel_mode(),
            "cache": self.cache.stats(),
        }

    def execute_partial(self, plan: QueryPlan,
                        stats: Optional[Dict[str, int]] = None,
                        prof: Optional[QueryProfiler] = None,
                        use_rollup: bool = True
                        ) -> Tuple[Optional[List[np.ndarray]],
                                   Optional[Dict[str, np.ndarray]]]:
        """One node's share of a distributed query: (materialized
        group-key columns, merged LOWERED aggregates) over the local
        store only — the `/query/partial` server half. No finalize, no
        top-K, no cache: partials must merge exactly on the
        coordinator, and the top-K cut is only correct after that
        merge (query/distributed.py). The rollup planner rewrite
        applies HERE too, so a coordinator gets O(groups) partials
        even when this peer's window is cold month-scale history."""
        if stats is None:
            stats = {"rowsScanned": 0, "partsScanned": 0,
                     "partsPruned": 0, "granulesScanned": 0,
                     "granulesSkipped": 0}
        for k in ("granulesScanned", "granulesSkipped"):
            stats.setdefault(k, 0)
        keys, aggs, _ = self._partial_with_rollup(
            plan, self._tables(plan.table), stats, prof, use_rollup)
        return keys, aggs

    def _partial_with_rollup(self, plan: QueryPlan, tables, stats,
                             prof: Optional[QueryProfiler],
                             use_rollup: bool
                             ) -> Tuple[Optional[List[np.ndarray]],
                                        Optional[Dict[str,
                                                      np.ndarray]],
                                        Optional[Dict[str, object]]]:
        """(keys, aggs, rollup-info): the rollup planner rewrite when
        a declared view subsumes the plan (query/rollup.py — aligned
        middle from aggregate parts, raw-scan edges stitched), else
        the normal raw path with info=None."""
        if use_rollup:
            from . import rollup as _rollup
            view = _rollup.match_view(self.db, plan)
            if view is not None:
                res = _rollup.try_rollup_partial(self, plan, stats,
                                                 prof, view)
                if res is not None:
                    return res
        keys, aggs = self._partial_for_tables(plan, tables, stats,
                                              prof)
        return keys, aggs, None

    # -- per-table execution -----------------------------------------------

    def _partial_for_tables(self, plan: QueryPlan, tables, stats,
                            prof: Optional[QueryProfiler] = None
                            ) -> Tuple[Optional[List[np.ndarray]],
                                       Optional[Dict[str, np.ndarray]]]:
        table_results = [self._execute_table(plan, t, stats, prof)
                         for t in tables]
        if len(table_results) == 1:
            return table_results[0]
        return merge_materialized(plan, table_results)

    def _execute_table(self, plan: QueryPlan, table, stats,
                       prof: Optional[QueryProfiler] = None,
                       refs=None
                       ) -> Tuple[Optional[List[np.ndarray]],
                                  Optional[Dict[str, np.ndarray]]]:
        """One table → (materialized key columns, merged aggregates)
        or (None, None) when nothing survives. `refs` pins a caller's
        pre-captured (parts, memtable) snapshot — the rollup rewrite
        computes its window alignment from one capture and must
        evaluate exactly that capture."""
        if getattr(table, "_parts", None) is None:
            partial, scanned = self._flat_partial(plan, table, prof)
            stats["rowsScanned"] += scanned
        else:
            partial = self._parts_partials(plan, table, stats, prof,
                                           refs=refs)
        if partial is None:
            return None, None
        uniq, aggs = partial
        keys = materialize_keys(plan, uniq, table.dicts, table.schema)
        return keys, aggs

    def _flat_partial(self, plan, table,
                      prof: Optional[QueryProfiler] = None
                      ) -> Tuple[Partial, int]:
        """Flat engine: the reference executor over a (column-subset)
        scan — slow but correct, and the parity anchor."""
        cols = plan.columns_touched()
        batch = table.select(columns=cols) if cols else table.scan()
        if prof is not None and prof.detail and len(batch):
            # an extra mask evaluation — paid only under an explicit
            # explain=1, never on the always-on slow-capture profiler
            prof.add_matched(int(filter_mask(plan, batch,
                                             table.dicts).sum()))
        return reference_partial(plan, batch, table.dicts), len(batch)

    def _granule_prune(self, plan: QueryPlan, filters, part
                       ) -> Optional[Tuple[np.ndarray,
                                           Dict[str, int]]]:
        """Granule-level skip decisions for one SORTED part from its
        RESIDENT index metadata only — no chunk or file is touched.
        Returns (keep bool array over granules, {reason: granules
        skipped}) or None when the part carries no indexes (format
        v1, or a lazily-adopted v2 part whose indexes rebuild on
        promotion — scanned whole, exactly as pre-PR-12).

        Reasons mirror the part-level ones one tier down:
        `pk:<col>` — the sparse primary index (the zone map of the
        part's FIRST sort-key column, ascending because the part is
        sorted, so this is the binary-searchable MergeTree index);
        `skip_minmax:<col>` — any other column's zone map;
        `skip_set:<col>` — a string column's per-granule distinct-
        code set missed every resolved filter code."""
        idx = part.indexes
        if idx is None:
            return None
        keep = np.ones(idx.n_granules, bool)
        reasons: Dict[str, int] = {}
        pk = part.sort_key[0] if part.sort_key else None

        def drop(col: str, excluded: np.ndarray, kind: str) -> None:
            newly = int((excluded & keep).sum())
            if newly:
                label = (f"pk:{col}" if col == pk
                         else f"{kind}:{col}")
                reasons[label] = reasons.get(label, 0) + newly
                np.logical_and(keep, ~excluded, out=keep)

        if plan.start is not None:
            zm = idx.zones.get(plan.time_column)
            if zm is not None:
                drop(plan.time_column, zm[1] < plan.start,
                     "skip_minmax")
        if plan.end is not None and keep.any():
            zm = idx.zones.get(plan.end_column)
            if zm is not None:
                drop(plan.end_column, zm[0] >= plan.end,
                     "skip_minmax")
        for f in filters:
            if not keep.any():
                break
            if f.op == "ne":
                continue   # proves nothing at any granularity
            if f.is_string:
                if not len(f.codes):
                    # value(s) absent from the dictionary: no granule
                    # anywhere can match (cold parts reach here — the
                    # part-level code check needs resident chunks)
                    drop(f.column, np.ones(len(keep), bool),
                         "skip_set")
                    break
                zm = idx.zones.get(f.column)
                if zm is not None:
                    # zone maps over dictionary codes: f.codes is
                    # sorted unique, so "any code in [min, max]" is
                    # two searchsorteds, vectorized over granules
                    lo = np.searchsorted(f.codes, zm[0], side="left")
                    hi = np.searchsorted(f.codes, zm[1], side="right")
                    drop(f.column, hi == lo, "skip_minmax")
                sets = idx.sets.get(f.column)
                if sets is not None:
                    excluded = np.zeros(len(keep), bool)
                    for g in np.flatnonzero(keep):
                        s = sets[g]
                        if s is not None and not _sorted_intersects(
                                f.codes, s):
                            excluded[g] = True
                    drop(f.column, excluded, "skip_set")
            else:
                zm = idx.zones.get(f.column)
                if zm is not None:
                    drop(f.column, _zone_excludes(zm[0], zm[1],
                                                  f.op, f.value),
                         "skip_minmax")
        return keep, reasons

    def _parts_partials(self, plan: QueryPlan, table, stats,
                        prof: Optional[QueryProfiler] = None,
                        refs=None) -> Partial:
        """Parts engine: prune (whole parts from min/max + code sets,
        then GRANULES inside surviving sorted parts from their skip
        indexes) → stripe live parts across the worker pool (each
        worker folds its stripe into one partial accumulator) →
        evaluate the memtable via the reference path → merge
        everything exactly. `prof` (the EXPLAIN profiler) records each
        part's fate, the prune REASON, and the per-part granule
        scanned/skipped counts with reasons — the decisions are
        computed here regardless, so profiling adds bookkeeping,
        never work."""
        specs = lower_specs(plan)
        filters = [_CompiledFilter(f, table) for f in plan.filters]
        parts, mem = table._snapshot_refs() if refs is None else refs
        #: (part, surviving-row selection or None for all rows)
        live: List[Tuple[object, Optional[np.ndarray]]] = []
        pruned = 0
        for p in parts:
            reason = None
            if not p.overlaps(plan.start, plan.end, plan.time_column,
                              plan.end_column):
                reason = "time_window"
            else:
                for f in filters:
                    if f.is_string:
                        # dictionary-code pruning (hot parts: the
                        # unique code set is resident metadata)
                        if f.excludes_part(p):
                            reason = f"codes:{f.column}"
                            break
                        continue
                    if f.op == "ne":
                        continue
                    mm = p.minmax.get(f.column)
                    if mm is not None and _minmax_excludes(
                            mm, f.op, f.value):
                        reason = f"range:{f.column}"
                        break
            rows_sel = None
            gdetail = None
            if reason is None:
                gp = self._granule_prune(plan, filters, p)
                if gp is not None:
                    keep, greasons = gp
                    kept = int(keep.sum())
                    skipped = len(keep) - kept
                    stats["granulesScanned"] += kept
                    stats["granulesSkipped"] += skipped
                    gdetail = {"scanned": kept, "skipped": skipped}
                    if greasons:
                        gdetail["reasons"] = greasons
                    if kept == 0:
                        # every granule provably empty — the part
                        # prunes wholesale, one tier late
                        reason = "granules"
                    elif skipped:
                        idx = p.indexes
                        rows_sel = _ranges_to_rows(
                            idx.starts[keep],
                            idx.granule_ends()[keep])
            if reason is not None:
                pruned += 1
            else:
                live.append((p, rows_sel))
                stats["rowsScanned"] += (
                    len(rows_sel) if rows_sel is not None else p.rows)
            if prof is not None:
                prof.add_part(p.uid, p.tier, p.rows, pruned=reason,
                              granules=gdetail,
                              resolution=p.minmax.get("resolution"))
        partials: List[Partial] = []
        if live:
            stripes = [live[i::self.workers]
                       for i in range(min(self.workers, len(live)))]
            if len(stripes) == 1:
                partials.append(self._fold_stripe(
                    plan, table, specs, filters, stripes[0], prof))
            else:
                pool = get_pool("query", self.workers)
                futs = [pool.submit(self._fold_stripe, plan, table,
                                    specs, filters, s, prof)
                        for s in stripes]
                partials.extend(f.result() for f in futs)
        for b in mem:
            if len(b):
                partials.append(self._decoded_partial(plan, table,
                                                      specs, b, prof))
                stats["rowsScanned"] += len(b)
                if prof is not None:
                    prof.memtable_rows += len(b)
        stats["partsScanned"] += len(live)
        stats["partsPruned"] += pruned
        merged = kernels.merge_partials(
            [p for p in partials if p is not None], specs)
        return merged if len(merged[0]) else None

    def _fold_stripe(self, plan, table, specs, filters,
                     parts: Sequence,
                     prof: Optional[QueryProfiler] = None) -> Partial:
        """One worker's stripe of (part, row-selection) pairs:
        evaluate each part over its granule-surviving rows, fold the
        partials into a single per-worker accumulator."""
        partials = [self._part_partial(plan, table, specs, filters, p,
                                       rows_sel, prof)
                    for p, rows_sel in parts]
        partials = [p for p in partials if p is not None]
        if not partials:
            return None
        return kernels.merge_partials(partials, specs)

    # -- per-part evaluation -----------------------------------------------

    def _part_partial(self, plan, table, specs, filters, part,
                      rows_sel: Optional[np.ndarray] = None,
                      prof: Optional[QueryProfiler] = None
                      ) -> Partial:
        chunks = part.chunks
        if chunks is None:
            if part.tier == "cold":
                return self._cold_partial(plan, table, specs, part,
                                          rows_sel, prof)
            # lazy-recovery hot part: decode (and promote) once, then
            # evaluate in decoded space. rows_sel is normally None
            # here (a lazy part has no resident indexes when the
            # selection is computed), but a promotion racing the
            # planning loop can hand us one — honor it through the
            # freshly-promoted rowid so the rowsScanned accounting
            # stays truthful (the decoded batch is insertion-order;
            # rowid maps the sort-order selection back onto it).
            batch = table._decode_part(part)
            if rows_sel is not None:
                rid = part.rowid
                if rid is not None:
                    batch = batch.take(
                        np.asarray(rid, np.int64)[rows_sel])
            return self._decoded_partial(plan, table, specs, batch,
                                         prof)
        return self._encoded_partial(plan, table, specs, filters,
                                     part, chunks, rows_sel, prof)

    def _encoded_partial(self, plan, table, specs, filters,
                         part, chunks,
                         rows_sel: Optional[np.ndarray] = None,
                         prof: Optional[QueryProfiler] = None
                         ) -> Partial:
        """Hot part, no decode: predicates on width-reduced ints and
        local dictionary indices; group keys aggregate in local code
        space; only surviving groups widen to global codes. A non-None
        `rows_sel` (granule pruning) restricts every column touch to
        the surviving granules' rows — skipped granules cost nothing,
        not even the predicate comparison."""
        n_rows = part.rows if rows_sel is None else len(rows_sel)

        def take(arr: np.ndarray) -> np.ndarray:
            return arr if rows_sel is None else arr[rows_sel]

        mask: object = True
        if plan.start is not None:
            mask = _and_mask(mask, _cmp_encoded(
                chunks[plan.time_column], "ge", plan.start, rows_sel))
        if mask is not False and plan.end is not None:
            mask = _and_mask(mask, _cmp_encoded(
                chunks[plan.end_column], "lt", plan.end, rows_sel))
        for f in filters:
            if mask is False:
                return None
            chunk = chunks[f.column]
            if f.is_string:
                # global code set → positions in the part's unique
                # codes (both sorted unique: searchsorted, not a
                # linear isin over the part's whole code set); an
                # empty intersection decides the part
                sel = np.zeros(len(chunk.uniq), bool)
                if len(f.codes):
                    pos = np.searchsorted(chunk.uniq, f.codes)
                    ok = pos < len(chunk.uniq)
                    pos = pos[ok]
                    sel[pos[chunk.uniq[pos] == f.codes[ok]]] = True
                if f.op == "ne":
                    if not sel.any():
                        continue   # nothing excluded
                    m = ~sel[take(chunk.local)]
                else:
                    if not sel.any():
                        return None   # eq/in can never match here
                    m = sel[take(chunk.local)]
                mask = _and_mask(mask, m)
            else:
                mask = _and_mask(mask, _cmp_encoded(
                    chunk, f.op, f.value, rows_sel))
        if mask is False:
            return None
        full = mask is True
        if not full and not mask.any():
            return None
        if prof is not None and prof.detail:
            # explain-only: the always-on slow-capture profiler must
            # not tax every query with an extra reduction
            prof.add_matched(int(n_rows if full else mask.sum()))

        def masked(arr: np.ndarray) -> np.ndarray:
            rows = take(arr)
            return rows if full else rows[mask]

        # group keys in LOCAL narrow space; remember how to widen the
        # survivors. When the groupBy is a PREFIX of the part's sort
        # key the rows are already key-clustered (local indices and
        # width-reduced ints are monotone in the decoded values, and
        # granule selection/masking preserve row order), so the kernel
        # can skip its lexsort — boundaries from one adjacent-row
        # comparison over the contiguous runs.
        presorted = bool(plan.group_by) and part.sort_key and \
            tuple(plan.group_by) == \
            tuple(part.sort_key[:len(plan.group_by)])
        key_cols: List[np.ndarray] = []
        widen: List[Tuple[str, object]] = []
        for name in plan.group_by:
            chunk = chunks[name]
            if hasattr(chunk, "uniq"):      # string column
                key_cols.append(masked(chunk.local).astype(np.int64))
                widen.append(("uniq", chunk.uniq))
            else:
                key_cols.append(masked(chunk.stored).astype(np.int64))
                widen.append(("base", chunk.base))
        n_masked = int(n_rows if full else mask.sum())
        keys = (np.stack(key_cols, axis=1) if key_cols
                else np.zeros((n_masked, 0), np.int64))
        values: Dict[str, np.ndarray] = {}
        for column in value_columns(specs):
            chunk = chunks[column]
            arr = masked(chunk.stored).astype(np.int64)
            if chunk.base:
                arr += chunk.base
            values[column] = arr
        uniq, aggs = kernels.aggregate(keys, values, specs,
                                       presorted=bool(presorted))
        # late materialization: widen only surviving group keys
        for j, (kind, aux) in enumerate(widen):
            if kind == "uniq":
                uniq[:, j] = aux[uniq[:, j]].astype(np.int64)
            elif aux:
                uniq[:, j] += aux
        return uniq, aggs

    def _cold_partial(self, plan, table, specs, part,
                      rows_sel: Optional[np.ndarray] = None,
                      prof: Optional[QueryProfiler] = None) -> Partial:
        """Cold part: stream through the bounded decode buffer,
        decoding ONLY the plan's columns from the self-contained part
        file, adopt the subset into table code space, evaluate, drop —
        the part is never promoted (chunks stay None, tier stays
        cold). The decode is in FILE (sort) order — aggregation is
        row-order-insensitive in exact int64, and for a sorted part
        this skips reading the rowid column and the un-permute
        entirely; `rows_sel` (granule indexes survive demotion) then
        slices the surviving granules' rows before evaluation."""
        # a plan touching NO columns (global count, no filters/window)
        # still needs the row count — carry one cheap numeric column
        cols = plan.columns_touched() or (table.schema[0].name,)
        with self._cold_sem:
            batch = table._decode_part_sorted(part, columns=cols)
            if rows_sel is not None:
                batch = batch.take(rows_sel)
            return self._decoded_partial(plan, table, specs, batch,
                                         prof)

    def _decoded_partial(self, plan, table, specs,
                         batch: ColumnarBatch,
                         prof: Optional[QueryProfiler] = None
                         ) -> Partial:
        """Table-coded batch (memtable, cold subset, lazy part):
        reference-style mask, kernel aggregation — global code space
        throughout, so the partial merges directly with the encoded
        ones."""
        mask = filter_mask(plan, batch, table.dicts)
        if prof is not None and prof.detail:
            prof.add_matched(int(mask.sum()))
        if not mask.any():
            return None
        if plan.group_by:
            keys = np.stack(
                [np.asarray(batch[g], np.int64)[mask]
                 for g in plan.group_by], axis=1)
        else:
            keys = np.zeros((int(mask.sum()), 0), np.int64)
        values = {c: np.asarray(batch[c], np.int64)[mask]
                  for c in value_columns(specs)}
        return kernels.aggregate(keys, values, specs)


# -- cross-store merge (sharded stores, cluster partials) ------------------

def merge_materialized(plan, table_results
                       ) -> Tuple[Optional[List[np.ndarray]],
                                  Optional[Dict[str, np.ndarray]]]:
    """Shards — and cluster peers — own independent dictionaries, so
    cross-store merging happens in MATERIALIZED key space: fold each
    partial's (decoded keys, lowered aggregates) into one dict keyed
    by the group tuple. Count/sum partials merge via sum, min via min,
    max via max — exactly, in int64 — so the merged result is
    bit-identical to a single-store execution over the union of the
    rows."""
    specs = lower_specs(plan)
    acc: Dict[tuple, List[int]] = {}
    for keys, aggs in table_results:
        if aggs is None:
            continue
        g = _n_groups(aggs)
        for i in range(g):
            kt = tuple(
                (k[i].item() if isinstance(k[i], np.generic)
                 else k[i]) for k in keys) if keys else ()
            vals = acc.get(kt)
            if vals is None:
                acc[kt] = [int(aggs[label][i])
                           for label, _, _ in specs]
                continue
            for j, (label, op, _) in enumerate(specs):
                v = int(aggs[label][i])
                if kernels.MERGE_OP[op] == "sum":
                    vals[j] += v
                elif kernels.MERGE_OP[op] == "min":
                    vals[j] = min(vals[j], v)
                else:
                    vals[j] = max(vals[j], v)
    if not acc:
        return None, None
    keys_out: List[np.ndarray] = []
    ordered = list(acc.keys())
    for j in range(len(plan.group_by)):
        vals = [kt[j] for kt in ordered]
        # numeric group keys must stay int64 — an object array
        # would make finalize's tie-break compare them as STRINGS
        # ('80' < '9'), diverging from the single-table engines
        if all(isinstance(v, (int, np.integer)) for v in vals):
            keys_out.append(np.asarray(vals, np.int64))
        else:
            keys_out.append(np.asarray(vals, dtype=object))
    aggs_out = {
        label: np.asarray([acc[kt][j] for kt in ordered], np.int64)
        for j, (label, _, _) in enumerate(specs)}
    return keys_out, aggs_out


def _n_groups(aggs: Dict[str, np.ndarray]) -> int:
    return len(next(iter(aggs.values()))) if aggs else 0
