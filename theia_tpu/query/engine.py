"""Vectorized query engine over the part-based column store.

The read-side twin of the PR-6 fused detector: where PR 7 made the
flows table a set of immutable, width-reduced, dictionary-coded column
parts, this module runs filtered aggregations DIRECTLY over that
encoding — the ARIMA_PLUS "push analytics into the store" pattern —
instead of decoding parts back to table code space and aggregating a
materialized copy:

  1. **Plan → prune.** Part min/max metadata (the PR-7 pruning
     substrate) drops parts that cannot overlap the time window or a
     numeric filter's range before any column is touched.
  2. **Filters in encoded space.** On a hot part, a numeric predicate
     compares the WIDTH-REDUCED stored array against the rebased
     threshold (`v - base`, clamped: an out-of-range threshold decides
     the whole part without widening a single row); a string predicate
     resolves to table-global dictionary codes ONCE per query, then
     per part intersects the part's unique-code set — a miss skips the
     part entirely, a hit turns into a boolean gather over the narrow
     local indices. No strings, no widening, no row materialization.
  3. **Late-materializing group-by.** Group keys aggregate in the
     part's LOCAL code space (u1/u2 indices); only the SURVIVING
     groups map local → global codes (strings) or `+ base`
     (numerics). Aggregation itself is query/kernels.py — lexsort +
     reduceat, or one jitted `jnp` segment-reduction dispatch
     (`THEIA_QUERY_JAX`, the THEIA_FUSED_PALLAS auto/fallback
     discipline).
  4. **Parallel per-part execution.** Live parts are striped across a
     bounded pool (`THEIA_QUERY_WORKERS`); each worker folds its
     parts into ONE per-worker partial accumulator, and the partials
     merge exactly (count via sum, min via min, ...).
  5. **Cold tier stays cold.** A demoted part streams through a
     bounded decode buffer (`THEIA_QUERY_COLD_BUFFER` concurrent
     decodes), decoding ONLY the columns the plan touches
     (column-subset part-file decode), and is never promoted back to
     RAM — the hot/cold working-set split of arXiv:1902.04143 holds
     under scans.
  6. **Result cache.** Finalized results cache under (normalized
     plan, store-state fingerprint); any seal/merge/demote/delete/
     insert changes the fingerprint, so invalidation is structural,
     not timed (`THEIA_QUERY_CACHE_BYTES`).

The flat engine and the parts memtable take the slow-but-correct
reference executor path (query/reference.py); the randomized oracle
suite (tests/test_query.py) holds every path bit-identical.
"""

from __future__ import annotations

import collections
import hashlib
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..schema import ColumnarBatch
from ..utils.env import env_int
from ..utils.logging import get_logger
from ..utils.pool import get_pool
from . import kernels
from .explain import SLOW_QUERIES, QueryProfiler
from .plan import QueryPlan
from .reference import filter_mask, materialize_keys, reference_partial
from .result import empty_result, finalize, lower_specs, value_columns

logger = get_logger("query")

DEFAULT_WORKERS = min(8, os.cpu_count() or 1)
DEFAULT_CACHE_BYTES = 16 << 20
DEFAULT_COLD_BUFFER = 2

_M_SECONDS = _metrics.histogram(
    "theia_query_seconds",
    "End-to-end query-engine execution time (cache misses; hits are "
    "counted separately)")
_M_ROWS_SCANNED = _metrics.counter(
    "theia_query_rows_scanned_total",
    "Rows evaluated by the query engine (part rows after pruning + "
    "memtable rows)")
_M_PARTS_SCANNED = _metrics.counter(
    "theia_query_parts_scanned_total",
    "Parts evaluated by queries after pruning")
_M_PARTS_PRUNED = _metrics.counter(
    "theia_query_parts_pruned_total",
    "Parts skipped by query min/max + dictionary-code pruning (read "
    "with theia_query_parts_scanned_total for the prune ratio)")
_M_CACHE_HITS = _metrics.counter(
    "theia_query_cache_hits_total",
    "Queries answered from the result cache (same normalized plan, "
    "unchanged store fingerprint)")
_M_CACHE_MISSES = _metrics.counter(
    "theia_query_cache_misses_total",
    "Queries that had to execute (cold cache, or the store fingerprint "
    "moved under seal/merge/demote/insert/delete)")


class QueryError(Exception):
    """The engine could not execute a valid plan (store-side issue)."""


# -- compiled predicates ---------------------------------------------------

class _CompiledFilter:
    """One plan filter resolved against a concrete table: string
    values → sorted global dictionary codes (resolved once per query,
    not per part)."""

    __slots__ = ("column", "op", "value", "codes", "is_string")

    def __init__(self, f, table) -> None:
        self.column = f.column
        self.op = f.op
        self.value = f.value
        d = table.dicts.get(f.column)
        self.is_string = d is not None
        self.codes: Optional[np.ndarray] = None
        if self.is_string:
            values = (f.value if isinstance(f.value, tuple)
                      else (f.value,))
            # unique, not just sorted: isin(assume_unique=True)
            # downstream requires it, and `in` values may repeat.
            # int32 — the dictionaries' native code dtype — so the
            # per-part intersections below need no conversions.
            self.codes = np.unique(np.asarray(
                [c for c in (d.lookup(str(v)) for v in values)
                 if c is not None], np.int32))

    def excludes_part(self, part) -> bool:
        """True when this predicate PROVABLY matches no row of a hot
        part, from resident metadata alone: eq/in whose resolved code
        set misses the part's unique-code set (or resolved to nothing
        at all). The dictionary-code half of part pruning."""
        if not self.is_string or self.op == "ne":
            return False
        if not len(self.codes):
            return True        # value(s) not in the table dictionary
        chunks = part.chunks
        chunk = chunks.get(self.column) if chunks is not None else None
        if chunk is None or not hasattr(chunk, "uniq"):
            return False       # cold/lazy: no resident code set
        return not np.isin(chunk.uniq, self.codes,
                           assume_unique=True).any()


def _minmax_excludes(mm: Tuple[int, int], op: str, value) -> bool:
    """True when part min/max PROVES no row can match a numeric
    predicate (the filter-level analogue of window pruning)."""
    lo, hi = mm
    if op == "ge":
        return hi < value
    if op == "gt":
        return hi <= value
    if op == "le":
        return lo > value
    if op == "lt":
        return lo >= value
    if op == "eq":
        return value < lo or value > hi
    if op == "in":
        return all(v < lo or v > hi for v in value)
    return False   # ne: metadata can't exclude


def _cmp_encoded(chunk, op: str, value: int) -> object:
    """Evaluate `col <op> value` on a width-reduced numeric chunk
    WITHOUT widening: compare the narrow stored array against the
    rebased threshold. Returns a bool array, or True/False when the
    rebased threshold falls outside the stored dtype's range (the
    whole part decides at once)."""
    s = chunk.stored
    if op == "in":
        vals = np.asarray(value, np.int64) - chunk.base
        lo, hi = (np.iinfo(s.dtype).min, np.iinfo(s.dtype).max) \
            if s.dtype.kind in "iu" else (-np.inf, np.inf)
        vals = vals[(vals >= lo) & (vals <= hi)]
        if not len(vals):
            return False
        return np.isin(s, vals.astype(s.dtype))
    t = value - chunk.base
    if s.dtype.kind in "iu":
        info = np.iinfo(s.dtype)
        if t < info.min:     # every stored value is above t
            return {"ge": True, "gt": True, "le": False,
                    "lt": False, "eq": False, "ne": True}[op]
        if t > info.max:     # every stored value is below t
            return {"ge": False, "gt": False, "le": True,
                    "lt": True, "eq": False, "ne": True}[op]
        t = s.dtype.type(t)
    return {"eq": s == t, "ne": s != t, "ge": s >= t,
            "gt": s > t, "le": s <= t, "lt": s < t}[op]


def _and_mask(mask, m) -> object:
    """AND-combine masks where True means all rows / False means no
    rows (short-circuit forms the encoded comparisons return)."""
    if m is True or mask is False:
        return mask
    if mask is True or m is False:
        return m
    mask &= m
    return mask


# -- result cache ----------------------------------------------------------

class QueryCache:
    """LRU-by-bytes cache of finalized result docs keyed by
    (normalized plan, store-state fingerprint). Invalidation is the
    fingerprint moving — every seal, merge, demote, delete, and insert
    changes it — so a stale hit is structurally impossible."""

    def __init__(self, max_bytes: Optional[int] = None) -> None:
        self.max_bytes = (
            env_int("THEIA_QUERY_CACHE_BYTES", DEFAULT_CACHE_BYTES)
            if max_bytes is None else int(max_bytes))
        self._entries: "collections.OrderedDict[tuple, Tuple[dict, int]]" = (
            collections.OrderedDict())
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def lookup(self, key: tuple) -> Optional[dict]:
        if self.max_bytes <= 0:
            return None
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return entry[0]

    @staticmethod
    def _estimate_bytes(doc: dict) -> int:
        """Cheap structural size estimate for the LRU byte charge —
        a full json.dumps here would serialize every result doc a
        second time (the HTTP layer already pays one) just to weigh
        it, which is worst exactly on the large results the cache
        exists to help. String values are charged at their REAL
        length (sampled from the first row): pod-label group keys run
        to kilobytes, and a flat per-value charge would let the
        configured byte budget retain 10x its size."""
        rows = doc.get("rows") or ()
        if not rows:
            return 512
        per_row = 24 + sum(
            (len(k) + len(v) + 49) if isinstance(v, str)
            else (len(k) + 40)
            for k, v in rows[0].items())
        return 512 + len(rows) * per_row

    def store(self, key: tuple, doc: dict) -> None:
        if self.max_bytes <= 0:
            return
        nbytes = self._estimate_bytes(doc)
        if nbytes > self.max_bytes:
            return
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (doc, nbytes)
            self._bytes += nbytes
            while self._bytes > self.max_bytes and self._entries:
                _, (_, n) = self._entries.popitem(last=False)
                self._bytes -= n

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {"entries": len(self._entries),
                    "bytes": self._bytes,
                    "maxBytes": self.max_bytes,
                    "hits": self.hits, "misses": self.misses}


# -- the engine ------------------------------------------------------------

Partial = Optional[Tuple[np.ndarray, Dict[str, np.ndarray]]]


class QueryEngine:
    """Executes QueryPlans over a FlowDatabase (plain, sharded, or
    replicated; parts or flat engine). Thread-safe; one instance per
    manager."""

    def __init__(self, db,
                 workers: Optional[int] = None,
                 cache_bytes: Optional[int] = None,
                 cold_buffer: Optional[int] = None) -> None:
        self.db = db
        self.workers = max(1, (
            env_int("THEIA_QUERY_WORKERS", DEFAULT_WORKERS)
            if workers is None else int(workers)))
        self.cold_buffer = max(1, (
            env_int("THEIA_QUERY_COLD_BUFFER", DEFAULT_COLD_BUFFER)
            if cold_buffer is None else int(cold_buffer)))
        self._cold_sem = threading.Semaphore(self.cold_buffer)
        self.cache = QueryCache(cache_bytes)
        self.queries = 0
        self._lock = threading.Lock()

    # -- store resolution --------------------------------------------------

    def _tables(self) -> List[object]:
        """Concrete flow tables to query: one for plain/replicated
        (the active replica resolves through __getattr__ — all
        replicas down raises, surfacing as 503), every shard for a
        sharded store."""
        flows = self.db.flows
        if hasattr(flows, "tables"):
            return list(flows.tables)
        return [flows]

    @staticmethod
    def _table_state(table) -> tuple:
        """Cache-fingerprint component for one table: covers inserts/
        deletes (generation), seals (memtable length + part set),
        merges (part uids), and demotions (tiers)."""
        parts = getattr(table, "_parts", None)
        if parts is not None:
            with table._lock:
                return (table.generation, table._memtable_len,
                        tuple((p.uid, p.tier) for p in table._parts))
        return (table.generation, len(table))

    def fingerprint(self, tables: Optional[List[object]] = None
                    ) -> tuple:
        """Cache-key component covering the whole store state; pass
        `tables` to fingerprint an already-resolved snapshot (execute
        does — key and execution must cover the same table set)."""
        if tables is None:
            tables = self._tables()
        return tuple(self._table_state(t) for t in tables)

    def fingerprint_hash(self, fingerprint: Optional[tuple] = None
                         ) -> str:
        """Compact digest of `fingerprint()` — what cluster heartbeats
        piggyback so a query coordinator can key its cluster-wide
        result cache on per-peer store states (any seal/merge/demote/
        insert/delete on any node moves its digest). Pass an
        already-computed fingerprint to digest the exact state an
        execution keyed on (EXPLAIN profiles do)."""
        if fingerprint is None:
            fingerprint = self.fingerprint()
        return hashlib.sha1(
            repr(fingerprint).encode()).hexdigest()[:16]

    # -- public API --------------------------------------------------------

    def execute(self, plan: QueryPlan,
                use_cache: bool = True,
                explain: bool = False,
                traceparent: Optional[str] = None
                ) -> Dict[str, object]:
        """Run one plan; returns the result doc. Raises PlanError
        (from parsing, upstream), QueryError, or the store's
        availability errors. `explain=True` attaches the execution
        profile (query/explain.py) WITHOUT re-running anything — the
        result rows are bit-identical either way; `traceparent`
        adopts a caller's trace context (this is a trace ingress)."""
        with _trace.ingress_span("query.request",
                                 traceparent=traceparent) as sp:
            doc = self._execute_traced(plan, use_cache, explain)
            sp.attrs["groups"] = doc.get("groupCount")
            sp.attrs["cache"] = doc.get("cache")
            return doc

    @staticmethod
    def _stamp_trace(doc: Dict[str, object]) -> None:
        """Attach the current sampled trace id to a result doc (the
        caller's handle into `theia trace <id>`)."""
        ctx = _trace.current_context()
        if ctx is not None:
            doc["traceId"] = ctx.trace_id

    def _execute_traced(self, plan: QueryPlan, use_cache: bool,
                        explain: bool) -> Dict[str, object]:
        with self._lock:
            self.queries += 1
        t0 = time.perf_counter()
        tables = self._tables()
        fp = self.fingerprint(tables)
        key = (plan.normalized(), fp)
        # a disabled cache (THEIA_QUERY_CACHE_BYTES=0) reports "off",
        # not a permanent 0% hit ratio that reads as a broken cache
        caching = use_cache and self.cache.max_bytes > 0
        if caching:
            hit = self.cache.lookup(key)
            if hit is not None:
                _M_CACHE_HITS.inc()
                doc = dict(hit)
                doc["cache"] = "hit"
                # THIS answer's latency, not the cached miss's —
                # anyone debugging from the footer would otherwise
                # read the slow path for a microsecond hit
                doc["tookMs"] = round(
                    (time.perf_counter() - t0) * 1000, 3)
                self._stamp_trace(doc)
                if explain:
                    # a hit has no per-part story to tell — the honest
                    # profile is "served from cache under this state"
                    doc["profile"] = {
                        "engine": doc.get("engine"),
                        "cache": "hit",
                        "fingerprint": self.fingerprint_hash(fp),
                    }
                return doc
            _M_CACHE_MISSES.inc()
        prof = QueryProfiler.maybe(explain)
        stats = {"rowsScanned": 0, "partsScanned": 0, "partsPruned": 0}
        t_exec = time.perf_counter()
        keys, aggs = self._partial_for_tables(plan, tables, stats,
                                              prof)
        t_fin = time.perf_counter()
        if aggs is None or _n_groups(aggs) == 0:
            rows, groups = empty_result(plan)
        else:
            rows, groups = finalize(plan, keys, aggs)
        took = time.perf_counter() - t0
        _M_SECONDS.observe(took)
        _M_ROWS_SCANNED.inc(stats["rowsScanned"])
        _M_PARTS_SCANNED.inc(stats["partsScanned"])
        _M_PARTS_PRUNED.inc(stats["partsPruned"])
        doc = {
            "plan": plan.to_doc(),
            "rows": rows,
            "groupCount": groups,
            "rowsScanned": stats["rowsScanned"],
            "partsScanned": stats["partsScanned"],
            "partsPruned": stats["partsPruned"],
            "engine": ("parts" if any(
                getattr(t, "_parts", None) is not None
                for t in tables) else "flat"),
            "tookMs": round(took * 1000, 3),
            "cache": "miss" if caching else "off",
        }
        if caching:
            # the cached doc carries no profile or trace id: a later
            # hit under the same key would serve a stale one
            self.cache.store(key, doc)
            doc = dict(doc)
        self._stamp_trace(doc)   # BEFORE slow capture: entries link
        profile = None           # back via theia trace <id>
        if prof is not None:
            prof.phase("execute", t_fin - t_exec)
            prof.phase("finalize", time.perf_counter() - t_fin)
            profile = prof.doc(
                engine=doc["engine"],
                kernel=kernels.kernel_mode(),
                cache=doc["cache"],
                fingerprint=self.fingerprint_hash(fp),
                rowsScanned=stats["rowsScanned"],
                partsScanned=stats["partsScanned"],
                partsPruned=stats["partsPruned"],
            )
            SLOW_QUERIES.observe(plan, doc, prof, profile)
        if explain and profile is not None:
            doc["profile"] = profile
        return doc

    def stats(self) -> Dict[str, object]:
        """Operator doc for /healthz `query`."""
        return {
            "queries": self.queries,
            "workers": self.workers,
            "coldBuffer": self.cold_buffer,
            "kernel": kernels.kernel_mode(),
            "cache": self.cache.stats(),
        }

    def execute_partial(self, plan: QueryPlan,
                        stats: Optional[Dict[str, int]] = None,
                        prof: Optional[QueryProfiler] = None
                        ) -> Tuple[Optional[List[np.ndarray]],
                                   Optional[Dict[str, np.ndarray]]]:
        """One node's share of a distributed query: (materialized
        group-key columns, merged LOWERED aggregates) over the local
        store only — the `/query/partial` server half. No finalize, no
        top-K, no cache: partials must merge exactly on the
        coordinator, and the top-K cut is only correct after that
        merge (query/distributed.py)."""
        if stats is None:
            stats = {"rowsScanned": 0, "partsScanned": 0,
                     "partsPruned": 0}
        return self._partial_for_tables(plan, self._tables(), stats,
                                        prof)

    # -- per-table execution -----------------------------------------------

    def _partial_for_tables(self, plan: QueryPlan, tables, stats,
                            prof: Optional[QueryProfiler] = None
                            ) -> Tuple[Optional[List[np.ndarray]],
                                       Optional[Dict[str, np.ndarray]]]:
        table_results = [self._execute_table(plan, t, stats, prof)
                         for t in tables]
        if len(table_results) == 1:
            return table_results[0]
        return merge_materialized(plan, table_results)

    def _execute_table(self, plan: QueryPlan, table, stats,
                       prof: Optional[QueryProfiler] = None
                       ) -> Tuple[Optional[List[np.ndarray]],
                                  Optional[Dict[str, np.ndarray]]]:
        """One table → (materialized key columns, merged aggregates)
        or (None, None) when nothing survives."""
        if getattr(table, "_parts", None) is None:
            partial, scanned = self._flat_partial(plan, table, prof)
            stats["rowsScanned"] += scanned
        else:
            partial = self._parts_partials(plan, table, stats, prof)
        if partial is None:
            return None, None
        uniq, aggs = partial
        keys = materialize_keys(plan, uniq, table.dicts, table.schema)
        return keys, aggs

    def _flat_partial(self, plan, table,
                      prof: Optional[QueryProfiler] = None
                      ) -> Tuple[Partial, int]:
        """Flat engine: the reference executor over a (column-subset)
        scan — slow but correct, and the parity anchor."""
        cols = plan.columns_touched()
        batch = table.select(columns=cols) if cols else table.scan()
        if prof is not None and prof.detail and len(batch):
            # an extra mask evaluation — paid only under an explicit
            # explain=1, never on the always-on slow-capture profiler
            prof.add_matched(int(filter_mask(plan, batch,
                                             table.dicts).sum()))
        return reference_partial(plan, batch, table.dicts), len(batch)

    def _parts_partials(self, plan: QueryPlan, table, stats,
                        prof: Optional[QueryProfiler] = None
                        ) -> Partial:
        """Parts engine: prune → stripe live parts across the worker
        pool (each worker folds its stripe into one partial
        accumulator) → evaluate the memtable via the reference path →
        merge everything exactly. `prof` (the EXPLAIN profiler)
        records each part's fate and the prune REASON — the decisions
        are computed here regardless, so profiling adds bookkeeping,
        never work."""
        specs = lower_specs(plan)
        filters = [_CompiledFilter(f, table) for f in plan.filters]
        parts, mem = table._snapshot_refs()
        live = []
        pruned = 0
        for p in parts:
            reason = None
            if not p.overlaps(plan.start, plan.end, plan.time_column,
                              plan.end_column):
                reason = "time_window"
            else:
                for f in filters:
                    if f.is_string:
                        # dictionary-code pruning (hot parts: the
                        # unique code set is resident metadata)
                        if f.excludes_part(p):
                            reason = f"codes:{f.column}"
                            break
                        continue
                    if f.op == "ne":
                        continue
                    mm = p.minmax.get(f.column)
                    if mm is not None and _minmax_excludes(
                            mm, f.op, f.value):
                        reason = f"range:{f.column}"
                        break
            if reason is not None:
                pruned += 1
            else:
                live.append(p)
            if prof is not None:
                prof.add_part(p.uid, p.tier, p.rows, pruned=reason)
        partials: List[Partial] = []
        if live:
            stripes = [live[i::self.workers]
                       for i in range(min(self.workers, len(live)))]
            if len(stripes) == 1:
                partials.append(self._fold_stripe(
                    plan, table, specs, filters, stripes[0], prof))
            else:
                pool = get_pool("query", self.workers)
                futs = [pool.submit(self._fold_stripe, plan, table,
                                    specs, filters, s, prof)
                        for s in stripes]
                partials.extend(f.result() for f in futs)
        for b in mem:
            if len(b):
                partials.append(self._decoded_partial(plan, table,
                                                      specs, b, prof))
                stats["rowsScanned"] += len(b)
                if prof is not None:
                    prof.memtable_rows += len(b)
        stats["partsScanned"] += len(live)
        stats["partsPruned"] += pruned
        stats["rowsScanned"] += sum(p.rows for p in live)
        merged = kernels.merge_partials(
            [p for p in partials if p is not None], specs)
        return merged if len(merged[0]) else None

    def _fold_stripe(self, plan, table, specs, filters,
                     parts: Sequence,
                     prof: Optional[QueryProfiler] = None) -> Partial:
        """One worker's stripe: evaluate each part, fold the partials
        into a single per-worker accumulator."""
        partials = [self._part_partial(plan, table, specs, filters, p,
                                       prof)
                    for p in parts]
        partials = [p for p in partials if p is not None]
        if not partials:
            return None
        return kernels.merge_partials(partials, specs)

    # -- per-part evaluation -----------------------------------------------

    def _part_partial(self, plan, table, specs, filters, part,
                      prof: Optional[QueryProfiler] = None
                      ) -> Partial:
        chunks = part.chunks
        if chunks is None:
            if part.tier == "cold":
                return self._cold_partial(plan, table, specs, part,
                                          prof)
            # lazy-recovery hot part: decode (and promote) once, then
            # evaluate in decoded space
            batch = table._decode_part(part)
            return self._decoded_partial(plan, table, specs, batch,
                                         prof)
        return self._encoded_partial(plan, table, specs, filters,
                                     chunks, part.rows, prof)

    def _encoded_partial(self, plan, table, specs, filters,
                         chunks, n_rows: int,
                         prof: Optional[QueryProfiler] = None
                         ) -> Partial:
        """Hot part, no decode: predicates on width-reduced ints and
        local dictionary indices; group keys aggregate in local code
        space; only surviving groups widen to global codes."""
        mask: object = True
        if plan.start is not None:
            mask = _and_mask(mask, _cmp_encoded(
                chunks[plan.time_column], "ge", plan.start))
        if mask is not False and plan.end is not None:
            mask = _and_mask(mask, _cmp_encoded(
                chunks[plan.end_column], "lt", plan.end))
        for f in filters:
            if mask is False:
                return None
            chunk = chunks[f.column]
            if f.is_string:
                # global code set → positions in the part's unique
                # codes; an empty intersection decides the part
                sel = np.zeros(len(chunk.uniq), bool)
                if len(f.codes):
                    sel[np.isin(chunk.uniq, f.codes,
                                assume_unique=True)] = True
                if f.op == "ne":
                    if not sel.any():
                        continue   # nothing excluded
                    m = ~sel[chunk.local]
                else:
                    if not sel.any():
                        return None   # eq/in can never match here
                    m = sel[chunk.local]
                mask = _and_mask(mask, m)
            else:
                mask = _and_mask(mask,
                                 _cmp_encoded(chunk, f.op, f.value))
        if mask is False:
            return None
        full = mask is True
        if not full and not mask.any():
            return None
        if prof is not None and prof.detail:
            # explain-only: the always-on slow-capture profiler must
            # not tax every query with an extra reduction
            prof.add_matched(int(n_rows if full else mask.sum()))

        def masked(arr: np.ndarray) -> np.ndarray:
            return arr if full else arr[mask]

        # group keys in LOCAL narrow space; remember how to widen the
        # survivors
        key_cols: List[np.ndarray] = []
        widen: List[Tuple[str, object]] = []
        for name in plan.group_by:
            chunk = chunks[name]
            if hasattr(chunk, "uniq"):      # string column
                key_cols.append(masked(chunk.local).astype(np.int64))
                widen.append(("uniq", chunk.uniq))
            else:
                key_cols.append(masked(chunk.stored).astype(np.int64))
                widen.append(("base", chunk.base))
        n_masked = int(n_rows if full else mask.sum())
        keys = (np.stack(key_cols, axis=1) if key_cols
                else np.zeros((n_masked, 0), np.int64))
        values: Dict[str, np.ndarray] = {}
        for column in value_columns(specs):
            chunk = chunks[column]
            arr = masked(chunk.stored).astype(np.int64)
            if chunk.base:
                arr += chunk.base
            values[column] = arr
        uniq, aggs = kernels.aggregate(keys, values, specs)
        # late materialization: widen only surviving group keys
        for j, (kind, aux) in enumerate(widen):
            if kind == "uniq":
                uniq[:, j] = aux[uniq[:, j]].astype(np.int64)
            elif aux:
                uniq[:, j] += aux
        return uniq, aggs

    def _cold_partial(self, plan, table, specs, part,
                      prof: Optional[QueryProfiler] = None) -> Partial:
        """Cold part: stream through the bounded decode buffer,
        decoding ONLY the plan's columns from the self-contained part
        file, adopt the subset into table code space, evaluate, drop —
        the part is never promoted (chunks stay None, tier stays
        cold)."""
        # a plan touching NO columns (global count, no filters/window)
        # still needs the row count — carry one cheap numeric column
        cols = plan.columns_touched() or (table.schema[0].name,)
        with self._cold_sem:
            batch = table._decode_part(part, columns=cols)
            return self._decoded_partial(plan, table, specs, batch,
                                         prof)

    def _decoded_partial(self, plan, table, specs,
                         batch: ColumnarBatch,
                         prof: Optional[QueryProfiler] = None
                         ) -> Partial:
        """Table-coded batch (memtable, cold subset, lazy part):
        reference-style mask, kernel aggregation — global code space
        throughout, so the partial merges directly with the encoded
        ones."""
        mask = filter_mask(plan, batch, table.dicts)
        if prof is not None and prof.detail:
            prof.add_matched(int(mask.sum()))
        if not mask.any():
            return None
        if plan.group_by:
            keys = np.stack(
                [np.asarray(batch[g], np.int64)[mask]
                 for g in plan.group_by], axis=1)
        else:
            keys = np.zeros((int(mask.sum()), 0), np.int64)
        values = {c: np.asarray(batch[c], np.int64)[mask]
                  for c in value_columns(specs)}
        return kernels.aggregate(keys, values, specs)


# -- cross-store merge (sharded stores, cluster partials) ------------------

def merge_materialized(plan, table_results
                       ) -> Tuple[Optional[List[np.ndarray]],
                                  Optional[Dict[str, np.ndarray]]]:
    """Shards — and cluster peers — own independent dictionaries, so
    cross-store merging happens in MATERIALIZED key space: fold each
    partial's (decoded keys, lowered aggregates) into one dict keyed
    by the group tuple. Count/sum partials merge via sum, min via min,
    max via max — exactly, in int64 — so the merged result is
    bit-identical to a single-store execution over the union of the
    rows."""
    specs = lower_specs(plan)
    acc: Dict[tuple, List[int]] = {}
    for keys, aggs in table_results:
        if aggs is None:
            continue
        g = _n_groups(aggs)
        for i in range(g):
            kt = tuple(
                (k[i].item() if isinstance(k[i], np.generic)
                 else k[i]) for k in keys) if keys else ()
            vals = acc.get(kt)
            if vals is None:
                acc[kt] = [int(aggs[label][i])
                           for label, _, _ in specs]
                continue
            for j, (label, op, _) in enumerate(specs):
                v = int(aggs[label][i])
                if kernels.MERGE_OP[op] == "sum":
                    vals[j] += v
                elif kernels.MERGE_OP[op] == "min":
                    vals[j] = min(vals[j], v)
                else:
                    vals[j] = max(vals[j], v)
    if not acc:
        return None, None
    keys_out: List[np.ndarray] = []
    ordered = list(acc.keys())
    for j in range(len(plan.group_by)):
        vals = [kt[j] for kt in ordered]
        # numeric group keys must stay int64 — an object array
        # would make finalize's tie-break compare them as STRINGS
        # ('80' < '9'), diverging from the single-table engines
        if all(isinstance(v, (int, np.integer)) for v in vals):
            keys_out.append(np.asarray(vals, np.int64))
        else:
            keys_out.append(np.asarray(vals, dtype=object))
    aggs_out = {
        label: np.asarray([acc[kt][j] for kt in ordered], np.int64)
        for j, (label, _, _) in enumerate(specs)}
    return keys_out, aggs_out


def _n_groups(aggs: Dict[str, np.ndarray]) -> int:
    return len(next(iter(aggs.values()))) if aggs else 0
