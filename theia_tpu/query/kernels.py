"""Vectorized group-aggregation kernels for the query engine.

The per-part unit of work is always the same shape: a key matrix
[n, k] of int64 group keys (dictionary codes / narrow ints already
widened for the surviving rows) and a set of int64 value columns, in;
one row per distinct key with count/sum/min/max columns, out. Two
implementations share that contract:

  * numpy (always available, the canonical semantics): one lexsort
    over the key columns, group boundaries from adjacent-row
    comparison, then `ufunc.reduceat` per aggregate — exact int64
    arithmetic, no Python-object work.
  * jitted `jnp` segment reductions (`THEIA_QUERY_JAX=auto|1|0`, the
    THEIA_FUSED_PALLAS discipline): the host still computes the group
    ids (sorting is host work either way); the per-aggregate segment
    sums/mins/maxes run as ONE jitted dispatch, with the segment count
    padded to the next power of two so retrace count stays bounded.
    `auto` enables it only when JAX runs in x64 mode — without x64 the
    int64 sums would silently truncate to int32, and the engine's
    parity contract (bit-identical to the reference executor) is not
    negotiable. Any runtime failure falls back to numpy for the
    process, loudly, once.

Merging partials is the same operation: concat the per-part key
matrices + partial aggregates and re-reduce, with `count` partials
merged via sum and min/max via min/max.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..utils.logging import get_logger
from ..analysis.lockdep import named_lock

logger = get_logger("query.kernels")

#: reduction op per aggregate when MERGING partials (count becomes a
#: sum of partial counts; everything else merges with its own op)
MERGE_OP = {"count": "sum", "sum": "sum", "min": "min", "max": "max"}

_jax_state_lock = named_lock("query.jax_state")
_jax_disabled_reason: Optional[str] = None


def kernel_mode() -> str:
    """'jax' or 'numpy' — what `aggregate()` will use right now, per
    THEIA_QUERY_JAX (auto|1|0; auto = jax only under x64) and any
    recorded runtime failure."""
    raw = os.environ.get("THEIA_QUERY_JAX", "auto").strip().lower()
    if raw in ("0", "off", "false", "no"):
        return "numpy"
    if _jax_disabled_reason is not None:
        return "numpy"
    try:
        import jax
    except Exception:
        return "numpy"
    if raw in ("1", "force", "on", "yes"):
        return "jax"
    return "jax" if jax.config.jax_enable_x64 else "numpy"


def _disable_jax(reason: str) -> None:
    global _jax_disabled_reason
    with _jax_state_lock:
        if _jax_disabled_reason is None:
            _jax_disabled_reason = reason
            logger.error(
                "query jax kernel disabled for this process "
                "(falling back to numpy): %s", reason)


def group_ids(keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray, int]:
    """Factorize a key matrix: (order, sorted-group-start offsets,
    group count). `keys[order]` is lexicographically sorted; group g
    spans order[starts[g]:starts[g+1]]."""
    n = keys.shape[0]
    order = np.lexsort(keys.T[::-1])
    sk = keys[order]
    boundary = np.empty(n, dtype=bool)
    boundary[0] = True
    boundary[1:] = np.any(sk[1:] != sk[:-1], axis=1)
    starts = np.flatnonzero(boundary)
    return order, starts, len(starts)


def _reduce_numpy(sorted_vals: Dict[str, np.ndarray],
                  starts: np.ndarray, n: int,
                  specs: Sequence[Tuple[str, str, Optional[str]]]
                  ) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    counts: Optional[np.ndarray] = None
    for label, op, column in specs:
        if op == "count":
            if counts is None:
                counts = np.diff(starts, append=n).astype(np.int64)
            out[label] = counts
            continue
        sv = sorted_vals[column]
        ufunc = {"sum": np.add, "min": np.minimum,
                 "max": np.maximum}[op]
        out[label] = ufunc.reduceat(sv, starts)
    return out


def _reduce_jax(gids: np.ndarray, n_groups: int,
                sorted_vals: Dict[str, np.ndarray],
                specs: Sequence[Tuple[str, str, Optional[str]]]
                ) -> Dict[str, np.ndarray]:
    """One jitted dispatch covering every aggregate. Segment count is
    padded to the next power of two so the jit cache stays small; the
    pad groups are sliced off on the way out."""
    import jax

    padded = 1 << max(int(n_groups) - 1, 0).bit_length()
    ops = tuple((op, column) for _, op, column in specs)
    names = tuple(sorted({c for _, c in ops if c is not None}))
    vals = [sorted_vals[c] for c in names]
    results = _jax_segment_reduce(
        tuple(ops), names, jax.numpy.asarray(gids), padded, *vals)
    out: Dict[str, np.ndarray] = {}
    for (label, _, _), r in zip(specs, results):
        out[label] = np.asarray(r)[:n_groups]
    return out


_jax_fns: Dict[tuple, object] = {}


def _jax_segment_reduce(ops, names, gids, num_segments, *vals):
    """Dispatch through a per-(ops, names) jitted closure so
    `num_segments` stays a static arg (padded upstream)."""
    import jax
    import jax.numpy as jnp

    key = (ops, names)
    fn = _jax_fns.get(key)
    if fn is None:
        def body(gids, num_segments, *vals):
            cols = dict(zip(names, vals))
            outs = []
            for op, column in ops:
                if op == "count":
                    outs.append(jax.ops.segment_sum(
                        jnp.ones_like(gids), gids,
                        num_segments=num_segments))
                elif op == "sum":
                    outs.append(jax.ops.segment_sum(
                        cols[column], gids,
                        num_segments=num_segments))
                elif op == "min":
                    outs.append(jax.ops.segment_min(
                        cols[column], gids,
                        num_segments=num_segments))
                else:
                    outs.append(jax.ops.segment_max(
                        cols[column], gids,
                        num_segments=num_segments))
            return tuple(outs)

        fn = _jax_fns[key] = jax.jit(
            body, static_argnames=("num_segments",))
    return fn(gids, num_segments, *vals)


def aggregate(keys: np.ndarray, values: Dict[str, np.ndarray],
              specs: Sequence[Tuple[str, str, Optional[str]]],
              presorted: bool = False
              ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
    """GROUP BY `keys` ([n, k] int64) computing every spec
    (label, op, column) over int64 `values`. Returns (unique keys
    [g, k] in lexicographic order, {label: [g] int64}).

    `n == 0` returns empty outputs; `k == 0` (global aggregate)
    reduces everything into one group.

    `presorted=True` is the CONTIGUOUS-RUN fast path: the caller
    guarantees rows with equal keys are adjacent and keys are
    non-decreasing (a sorted part whose groupBy is a sort-key
    prefix — engine.py proves it from the part's sort key), so the
    lexsort is skipped entirely and group boundaries come from one
    adjacent-row comparison. Output is bit-identical to the sorted
    path: a stable lexsort of already-sorted keys is the identity
    permutation."""
    n = keys.shape[0]
    if n == 0:
        return (keys.reshape(0, keys.shape[1]),
                {label: np.zeros(0, np.int64) for label, _, _ in specs})
    order: Optional[np.ndarray] = None
    if keys.shape[1] == 0:
        starts = np.zeros(1, np.int64)
    elif presorted:
        boundary = np.empty(n, dtype=bool)
        boundary[0] = True
        boundary[1:] = np.any(keys[1:] != keys[:-1], axis=1)
        starts = np.flatnonzero(boundary)
    else:
        order, starts, _ = group_ids(keys)
    sorted_vals = {c: np.ascontiguousarray(
                       v if order is None else v[order])
                   for c, v in values.items()}
    uniq = (keys if order is None else keys[order])[starts]
    if kernel_mode() == "jax":
        try:
            gids = np.zeros(n, np.int64)
            gids[starts[1:]] = 1
            gids = np.cumsum(gids)
            return uniq, _reduce_jax(gids, len(starts), sorted_vals,
                                     specs)
        except Exception as e:   # pragma: no cover - env dependent
            _disable_jax(f"{type(e).__name__}: {e}")
    return uniq, _reduce_numpy(sorted_vals, starts, n, specs)


def merge_partials(partials: Sequence[
        Tuple[np.ndarray, Dict[str, np.ndarray]]],
        specs: Sequence[Tuple[str, str, Optional[str]]]
        ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
    """Combine per-part partial aggregates: concat their (keys, aggs)
    and re-reduce with each aggregate's MERGE op (partial counts sum;
    partial mins min; ...). Key spaces must be comparable (same table
    dictionary) — cross-table merges materialize first."""
    live = [p for p in partials if p is not None and len(p[0])]
    if not live:
        k = partials[0][0].shape[1] if partials else 0
        return (np.zeros((0, k), np.int64),
                {label: np.zeros(0, np.int64) for label, _, _ in specs})
    if len(live) == 1:
        return live[0]
    keys = np.concatenate([p[0] for p in live])
    merge_specs = [(label, MERGE_OP[op], label)
                   for label, op, _ in specs]
    values = {label: np.concatenate([p[1][label] for p in live])
              for label, _, _ in specs}
    return aggregate(keys, values, merge_specs)
