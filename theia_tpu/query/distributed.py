"""Cluster-wide scatter-gather query execution with partial-aggregate
pushdown.

PR 9 spreads ingested rows across the routing mesh by destination
hash; this module makes `/query` answer over ALL of them. The node
that receives a query becomes the **coordinator**: it fans the
normalized plan out to every live peer's `POST /query/partial`, each
peer executes the existing part-native engine locally and answers
**mergeable partials** — group keys plus count/sum/min/max columns
(`mean` stays lowered to sum+count, exactly like the sharded merge) —
and the coordinator merges them in materialized key space, applies
top-K ONCE, and serves the cluster-wide result. Per-group partials
ship, never rows: bytes on the wire are proportional to surviving
groups, so every node added multiplies query throughput instead of
multiplying transfer (the ARIMA_PLUS "push analytics into the store"
principle, applied across nodes; arXiv:1902.04143's in-DRAM
working-set argument says the hot data stays node-local, so
scatter-gather is the only shape that scales).

**Wire format (TQPF).** A partial response is a small envelope —
magic + version + JSON meta (node id, scan stats, store fingerprint) —
followed by ONE self-contained WAL record body (store/wal.py
`encode_record_body`): group-key columns (string keys ship their
unique strings + narrow local codes, numerics int64) plus one int64
column per lowered aggregate. The same encoding that ships WAL
frames and sealed parts ships query partials.

**Peer pruning.** Heartbeats piggyback each node's per-table time
min/max and row count (cluster/node.py `ping_doc`); a windowed query
skips peers whose data provably cannot overlap — before any fan-out
byte moves. Pruning decisions are as-of the peer's LAST HEARTBEAT
(bounded-staleness, like the cluster cache and follower reads): rows
a peer acked within the last heartbeat interval may be skipped by a
window that covers them. Two mitigations bound the exposure to that
one interval: a peer whose store is changing inside the bounds-scan
throttle window ships a bare fingerprint (no bounds) and is not
pruned at all, and the heartbeat cadence (THEIA_CLUSTER_HEARTBEAT,
default 1 s) is the hard ceiling on how stale a pruning decision can
be.

**Cluster result cache.** Complete results cache under (normalized
plan, local store fingerprint, membership epoch, per-peer store
fingerprints from the last heartbeat) — any peer's seal/merge/insert
moves its fingerprint and invalidates structurally within one
heartbeat; a peer going down or coming back bumps the membership
epoch. Partial results are never cached. Fingerprints are per PLAN
TABLE (heartbeats piggyback a per-table digest map): a scrape tick
moving a peer's `__metrics__` digest invalidates cached history
results without churning the flows caches.

**Degraded modes are first-class.** A down peer (no heartbeat inside
the liveness timeout) or a peer whose fan-out request fails/times out
yields `partial: true` with the missing peers named — or a 503 under
`THEIA_QUERY_STRICT=1`. Fan-out requests ride the per-peer
`net.send`/`peer.partition` fault sites, so partition drills sever
the read path with the data plane; `/query/partial` admits one rung
ahead of ingest on the PEER side too (a shed peer answers 429 and
degrades the coordinator to a partial result).
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..schema import FLOW_SCHEMA, ColumnarBatch, StringDictionary
from ..utils.env import env_float
from ..utils.logging import get_logger
from ..utils.pool import get_pool
from .engine import (
    _M_CACHE_HITS,
    _M_CACHE_MISSES,
    QueryCache,
    QueryEngine,
    QueryError,
    merge_materialized,
)
from .explain import SLOW_QUERIES, QueryProfiler
from .plan import QUERYABLE_TABLES, QueryPlan
from .result import empty_result, finalize, lower_specs
from ..analysis.lockdep import named_lock

logger = get_logger("query.distributed")

DEFAULT_FANOUT_TIMEOUT = 15.0

#: partial-frame envelope: magic, version, reserved, reserved,
#: JSON-meta length; the WAL record body follows the meta
_PF_MAGIC = b"TQPF"
_PF_HEADER = struct.Struct("<4sBBHI")

_M_FANOUT_SECONDS = _metrics.histogram(
    "theia_query_fanout_seconds",
    "End-to-end coordinator time for one distributed query (fan-out + "
    "local partial + merge + finalize; cache hits excluded)")
_M_FANOUT_BYTES = _metrics.counter(
    "theia_query_fanout_bytes_total",
    "Partial-frame bytes received from peers by this coordinator "
    "(proportional to surviving groups, never rows)")
_M_PEERS_QUERIED = _metrics.counter(
    "theia_query_peers_queried_total",
    "Peers that contributed a partial to a distributed query")
_M_PEERS_PRUNED = _metrics.counter(
    "theia_query_peers_pruned_total",
    "Peers skipped before fan-out because their heartbeat-reported "
    "time bounds (or empty store) provably cannot overlap the query")
_M_PEERS_FAILED = _metrics.counter(
    "theia_query_peers_failed_total",
    "Peers that were down or failed/timed out during fan-out "
    "(the query degraded to partial:true, or 503 under "
    "THEIA_QUERY_STRICT=1)")
_M_PARTIALS_SERVED = _metrics.counter(
    "theia_query_partials_served_total",
    "Partial-aggregate executions this node served to coordinators "
    "(POST /query/partial)")


class IncompleteResultError(Exception):
    """THEIA_QUERY_STRICT=1 and one or more peers could not contribute
    to a distributed query — HTTP 503: retry when the cluster heals
    (the default mode answers partial:true instead)."""


def strict_mode() -> bool:
    return os.environ.get("THEIA_QUERY_STRICT", "").strip().lower() \
        in ("1", "true", "yes", "on")


# -- the TQPF partial frame ------------------------------------------------

def pack_partial(meta: Dict[str, object], plan: QueryPlan,
                 keys: Optional[List[np.ndarray]],
                 aggs: Optional[Dict[str, np.ndarray]],
                 schema=None) -> bytes:
    """Serialize one node's partial: envelope meta + a WAL record body
    carrying the materialized group-key columns and one int64 column
    per LOWERED aggregate label. Self-contained — string keys ship
    their unique strings, so the coordinator decodes without any
    shared dictionary state. The schema defaults to the PLAN table's
    (a `__metrics__` plan groups by metric/labels/node/kind — string
    columns the flows schema doesn't know)."""
    from ..store.wal import encode_record_body
    if schema is None:
        schema = QUERYABLE_TABLES.get(plan.table,
                                      (FLOW_SCHEMA,))[0]
    specs = lower_specs(plan)
    string_cols = {c.name for c in schema if c.is_string}
    cols: Dict[str, np.ndarray] = {}
    dicts: Dict[str, StringDictionary] = {}
    for j, name in enumerate(plan.group_by):
        vals = (keys[j] if keys is not None
                else np.zeros(0, np.int64))
        if name in string_cols:
            d = StringDictionary()
            cols[name] = (d.encode([str(v) for v in vals])
                          if len(vals) else np.zeros(0, np.int32))
            dicts[name] = d
        else:
            cols[name] = np.asarray(vals, np.int64)
    for label, _, _ in specs:
        vals = (aggs[label] if aggs is not None
                else np.zeros(0, np.int64))
        cols[label] = np.asarray(vals, np.int64)
    body = encode_record_body("partial", ColumnarBatch(cols, dicts))
    header = json.dumps(meta).encode()
    return (_PF_HEADER.pack(_PF_MAGIC, 1, 0, 0, len(header))
            + header + body)


def unpack_partial(data: bytes
                   ) -> Tuple[Dict[str, object], ColumnarBatch]:
    """(meta, decoded partial batch). Raises QueryError on a frame
    that is not a TQPF partial (version skew, truncation, non-binary
    error body)."""
    from ..store.wal import WalCorruption, decode_record_body
    if len(data) < _PF_HEADER.size:
        raise QueryError("short partial frame")
    magic, ver, _, _, hlen = _PF_HEADER.unpack_from(data, 0)
    if magic != _PF_MAGIC or ver != 1:
        raise QueryError(
            f"bad partial frame magic/version ({magic!r} v{ver})")
    off = _PF_HEADER.size
    try:
        meta = json.loads(bytes(data[off:off + hlen]))
        _, batch = decode_record_body(bytes(data[off + hlen:]))
    except (ValueError, WalCorruption) as e:
        raise QueryError(f"undecodable partial frame: {e}")
    return meta, batch


def partial_from_batch(plan: QueryPlan, batch: ColumnarBatch
                       ) -> Tuple[Optional[List[np.ndarray]],
                                  Optional[Dict[str, np.ndarray]]]:
    """Decoded TQPF batch → the (keys, aggs) shape
    `merge_materialized` folds (string keys back to materialized
    strings, aggregates int64)."""
    specs = lower_specs(plan)
    if len(batch) == 0:
        return None, None
    keys = [(batch.strings(g) if g in batch.dicts
             else np.asarray(batch[g], np.int64))
            for g in plan.group_by]
    aggs = {label: np.asarray(batch[label], np.int64)
            for label, _, _ in specs}
    return keys, aggs


# -- peer pruning ----------------------------------------------------------

def _peer_table_fp(store_doc: Dict[str, object],
                   table: str) -> Optional[str]:
    """The digest a coordinator keys one peer's state on for a plan
    over `table`: the heartbeat's per-table digest when the peer
    ships one, else the legacy whole-store (flows) fingerprint —
    'maybe stale' beats 'never invalidates', and a peer reporting
    neither keeps the result uncacheable (the store guard)."""
    tables = store_doc.get("tables")
    if isinstance(tables, dict) and tables.get(table):
        return tables[table]
    return store_doc.get("fingerprint")


def peer_excluded(plan: QueryPlan,
                  store_doc: Optional[Dict[str, object]]) -> bool:
    """True when a peer's heartbeat-reported store state PROVES it can
    contribute nothing: zero rows, or time bounds that cannot overlap
    the plan's half-open window. Missing/partial state means 'maybe'
    — the peer is queried, never wrongly skipped. Heartbeat bounds
    and row counts describe the FLOWS tables only, so plans over any
    other table (`__metrics__`) never prune a peer here."""
    if plan.table != "flows":
        return False
    if not store_doc:
        return False
    if store_doc.get("rows") == 0:
        return True
    bounds = store_doc.get("bounds") or {}
    if plan.start is not None:
        mm = bounds.get(plan.time_column)
        if mm is not None and int(mm[1]) < plan.start:
            return True
    if plan.end is not None:
        mm = bounds.get(plan.end_column)
        if mm is not None and int(mm[0]) >= plan.end:
            return True
    return False


# -- the coordinator -------------------------------------------------------

class ClusterQueryCoordinator:
    """Scatter-gather executor for one node of the routing mesh: local
    partial + fan-out partials → exact merge → one finalize. Wired by
    TheiaManagerServer when the cluster role is `peer` (leader/
    follower topologies replicate the whole store, so their local
    engine already answers cluster-wide)."""

    def __init__(self, node, engine,
                 timeout: Optional[float] = None,
                 cache_bytes: Optional[int] = None) -> None:
        self.node = node
        self.engine = engine
        self.cmap = node.cmap
        self.transport = node.transport
        self.timeout = (
            env_float("THEIA_QUERY_FANOUT_TIMEOUT",
                      DEFAULT_FANOUT_TIMEOUT)
            if timeout is None else float(timeout))
        self.cache = QueryCache(cache_bytes)
        self.workers = max(2, len(self.cmap.order) - 1)
        self.fanouts = 0
        self.partial_results = 0
        self._lock = named_lock("query.coordinator")

    # -- execution ---------------------------------------------------------

    def execute(self, plan: QueryPlan,
                use_cache: bool = True,
                explain: bool = False,
                traceparent: Optional[str] = None,
                use_rollup: bool = True
                ) -> Dict[str, object]:
        """Coordinate one cluster-wide query. This is a trace ingress:
        the fan-out's `/query/partial` requests carry the minted (or
        adopted) context, so every peer's partial-execution spans join
        ONE cross-node trace. `explain=True` attaches the coordinator
        profile (per-peer timings/bytes/degraded reasons, merge and
        top-K time) without changing the result rows."""
        with _trace.ingress_span("query.request", engine="cluster",
                                 traceparent=traceparent) as sp:
            doc = self._execute_traced(plan, use_cache, explain,
                                       use_rollup)
            sp.attrs["groups"] = doc.get("groupCount")
            sp.attrs["cache"] = doc.get("cache")
            return doc

    def _execute_traced(self, plan: QueryPlan, use_cache: bool,
                        explain: bool,
                        use_rollup: bool = True) -> Dict[str, object]:
        t0 = time.perf_counter()
        others = self.cmap.others()
        epoch = self.cmap.membership_epoch()
        peer_store = {p: (self.cmap.peer_info(p).get("store") or {})
                      for p in others}
        pruned = [p for p in others
                  if peer_excluded(plan, peer_store[p])]
        candidates = [p for p in others if p not in pruned]
        live = [p for p in candidates if self.cmap.is_alive(p)]
        down = [p for p in candidates if p not in live]
        # fingerprints cover the PLAN's table set: the flows digest
        # never moves on a scrape tick, and the `__metrics__` digest
        # (heartbeat-piggybacked per table) moves on every one — so
        # cached history results invalidate within one heartbeat
        # while flows caches ignore the scrape churn entirely
        local_fp = self.engine.fingerprint(
            self.engine._tables(plan.table))
        key = (plan.normalized(), local_fp, epoch,
               bool(use_rollup),
               tuple(sorted((p, _peer_table_fp(peer_store[p],
                                               plan.table))
                            for p in others)))
        caching = use_cache and self.cache.max_bytes > 0
        if caching:
            hit = self.cache.lookup(key)
            if hit is not None:
                _M_CACHE_HITS.inc()
                doc = dict(hit)
                doc["cache"] = "hit"
                doc["tookMs"] = round(
                    (time.perf_counter() - t0) * 1000, 3)
                QueryEngine._stamp_trace(doc)
                if explain:
                    doc["profile"] = {
                        "engine": "cluster",
                        "cache": "hit",
                        "fingerprint":
                            self.engine.fingerprint_hash(local_fp),
                    }
                return doc
            _M_CACHE_MISSES.inc()
        if down and strict_mode():
            # guaranteed-incomplete: don't burn a full cluster scan
            # just to answer 503
            _M_PEERS_FAILED.inc(len(down))
            raise IncompleteResultError(
                f"distributed query incomplete: peers "
                f"{','.join(sorted(down))} down "
                f"(THEIA_QUERY_STRICT=1)")
        with self._lock:
            self.fanouts += 1
        prof = QueryProfiler.maybe(explain)
        # the pool workers run on other threads: hand them the trace
        # context so each peer fetch (and the traceparent it stamps)
        # joins this query's trace
        ctx = _trace.current_context()
        futs = []
        if live:
            pool = get_pool("query-fanout", self.workers)
            futs = [(p, pool.submit(self._fetch_partial, p, plan,
                                    ctx, use_rollup))
                    for p in live]
        # local partial executes on the coordinator thread while the
        # fan-out is in flight (sharing `prof`, so the local store's
        # per-part scanned/pruned detail lands in the profile)
        stats = {"rowsScanned": 0, "partsScanned": 0, "partsPruned": 0,
                 "granulesScanned": 0, "granulesSkipped": 0}
        results = [self.engine.execute_partial(plan, stats, prof,
                                               use_rollup)]
        failed: List[str] = []
        peer_errors: Dict[str, str] = {}
        bytes_shipped = 0
        for peer, fut in futs:
            try:
                meta, keys, aggs = fut.result()
            except Exception as e:
                failed.append(peer)
                peer_errors[peer] = f"{type(e).__name__}: {e}"
                logger.warning("partial from peer %s failed: %s: %s",
                               peer, type(e).__name__, e)
                continue
            bytes_shipped += int(meta.get("_bytes") or 0)
            for k in stats:
                stats[k] += int(meta.get(k) or 0)
            if prof is not None:
                prof.add_peer(
                    peer, "queried",
                    tookMs=round(float(meta.get("_tookMs") or 0.0), 3),
                    execMs=meta.get("execMs"),
                    bytes=int(meta.get("_bytes") or 0),
                    rowsScanned=int(meta.get("rowsScanned") or 0),
                    partsScanned=int(meta.get("partsScanned") or 0),
                    partsPruned=int(meta.get("partsPruned") or 0),
                    granulesScanned=int(
                        meta.get("granulesScanned") or 0),
                    granulesSkipped=int(
                        meta.get("granulesSkipped") or 0),
                    fingerprint=meta.get("fingerprint"))
            results.append((keys, aggs))
        missing = sorted(down + failed)
        if prof is not None:
            for p in pruned:
                prof.add_peer(p, "pruned",
                              bounds=(peer_store[p].get("bounds")
                                      or None))
            for p in down:
                prof.add_peer(p, "down",
                              reason="no heartbeat inside the "
                                     "liveness timeout")
            for p in failed:
                prof.add_peer(p, "failed", reason=peer_errors.get(p))
        _M_PEERS_QUERIED.inc(len(live) - len(failed))
        _M_PEERS_PRUNED.inc(len(pruned))
        _M_PEERS_FAILED.inc(len(missing))
        _M_FANOUT_BYTES.inc(bytes_shipped)
        if missing and strict_mode():
            raise IncompleteResultError(
                f"distributed query incomplete: peers "
                f"{','.join(missing)} unavailable "
                f"(THEIA_QUERY_STRICT=1)")
        t_merge = time.perf_counter()
        keys, aggs = merge_materialized(plan, results)
        t_fin = time.perf_counter()
        if aggs is None or not len(next(iter(aggs.values()))):
            rows, groups = empty_result(plan)
        else:
            rows, groups = finalize(plan, keys, aggs)
        if prof is not None:
            prof.phase("merge", t_fin - t_merge)
            prof.phase("finalize", time.perf_counter() - t_fin)
        took = time.perf_counter() - t0
        _M_FANOUT_SECONDS.observe(took)
        doc: Dict[str, object] = {
            "plan": plan.to_doc(),
            "rows": rows,
            "groupCount": groups,
            "rowsScanned": stats["rowsScanned"],
            "partsScanned": stats["partsScanned"],
            "partsPruned": stats["partsPruned"],
            "granulesScanned": stats["granulesScanned"],
            "granulesSkipped": stats["granulesSkipped"],
            "engine": "cluster",
            "peers": {
                "total": len(self.cmap.order),
                "queried": len(live) - len(failed),
                "pruned": len(pruned),
                "failed": len(missing),
            },
            "bytesShipped": bytes_shipped,
            "partial": bool(missing),
            "tookMs": round(took * 1000, 3),
            "cache": "miss" if caching else "off",
        }
        if missing:
            doc["missingPeers"] = missing
            with self._lock:
                self.partial_results += 1
        # cache only COMPLETE results whose key truly covers every
        # peer's state: a peer without a heartbeat-reported
        # fingerprint could change under an unchanged key — and never
        # the profile (a later hit would serve a stale per-peer story)
        if caching and not missing and all(
                _peer_table_fp(peer_store[p], plan.table)
                for p in others):
            self.cache.store(key, doc)
            doc = dict(doc)
        QueryEngine._stamp_trace(doc)   # before slow capture
        profile = None
        if prof is not None:
            profile = prof.doc(
                engine="cluster",
                cache=doc["cache"],
                fingerprint=self.engine.fingerprint_hash(local_fp),
                rowsScanned=stats["rowsScanned"],
                partsScanned=stats["partsScanned"],
                partsPruned=stats["partsPruned"],
                granulesScanned=stats["granulesScanned"],
                granulesSkipped=stats["granulesSkipped"],
                bytesShipped=bytes_shipped,
            )
            # the matched count (and any per-part detail) covers the
            # COORDINATOR'S local store only — peers profile their
            # own executions; label it so
            matched = profile.pop("rowsMatched", None)
            if matched is not None:
                profile["rowsMatchedLocal"] = matched
            SLOW_QUERIES.observe(plan, doc, prof, profile)
        if explain and profile is not None:
            doc["profile"] = profile
        return doc

    def _fetch_partial(self, peer: str, plan: QueryPlan, ctx=None,
                       use_rollup: bool = True):
        """One peer's partial over the cluster transport (persistent
        connection; `net.send`/`peer.partition` fault sites fire
        inside, so partition drills sever the read path too). Runs on
        a pool worker: `ctx` is the coordinator request's trace
        context, re-activated here so the wire request carries it."""
        t0 = time.perf_counter()
        with _trace.child_span("query.fanout", ctx, peer=peer):
            raw = self.transport.request_raw(
                peer, "/query/partial",
                data=json.dumps({"plan": plan.to_doc(),
                                 "rollup": bool(use_rollup)}).encode(),
                headers={"Content-Type": "application/json"},
                timeout=self.timeout)
        meta, batch = unpack_partial(raw)
        meta["_bytes"] = len(raw)
        meta["_tookMs"] = (time.perf_counter() - t0) * 1000
        keys, aggs = partial_from_batch(plan, batch)
        return meta, keys, aggs

    # -- operator surface --------------------------------------------------

    def stats(self) -> Dict[str, object]:
        """Doc for /healthz `query.distributed`."""
        with self._lock:
            fanouts = self.fanouts
            partials = self.partial_results
        return {
            "mode": "scatter-gather",
            "peers": len(self.cmap.order),
            "fanouts": fanouts,
            "partialResults": partials,
            "strict": strict_mode(),
            "fanoutTimeoutSeconds": self.timeout,
            "cache": self.cache.stats(),
        }


def serve_partial(engine, plan: QueryPlan,
                  node_id: str = "",
                  use_rollup: bool = True) -> bytes:
    """Server half of the fan-out (manager/api.py `/query/partial`):
    execute the local partial and pack the TQPF frame. The meta
    carries this node's scan stats (the coordinator sums them into
    the result doc) and its CURRENT store fingerprint."""
    t0 = time.perf_counter()
    stats = {"rowsScanned": 0, "partsScanned": 0, "partsPruned": 0,
             "granulesScanned": 0, "granulesSkipped": 0}
    keys, aggs = engine.execute_partial(plan, stats,
                                        use_rollup=use_rollup)
    _M_PARTIALS_SERVED.inc()
    meta: Dict[str, object] = {"node": node_id, **stats,
                               "fingerprint": engine.fingerprint_hash(
                                   engine.fingerprint(
                                       engine._tables(plan.table))),
                               "execMs": round(
                                   (time.perf_counter() - t0) * 1000,
                                   3)}
    return pack_partial(meta, plan, keys, aggs)
