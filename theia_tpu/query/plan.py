"""Query plans: the normalized, validated description of one filtered
aggregation the engine executes over the flow store.

The plan is deliberately small — a time window, a conjunction of
column predicates, a group-by key list, and a list of aggregates with
a top-K order — because that is the read shape the reference serves
from ClickHouse (the Grafana panels and the analytics jobs' SQL are
all `SELECT keys, agg(metrics) WHERE window AND predicates GROUP BY
keys ORDER BY agg LIMIT k`). Everything in a plan resolves against the
table SCHEMA at parse time, so a malformed query dies as a 400 at the
API edge, never inside a part decode.

Normalization matters beyond validation: `normalized()` is the
cache-key half of the query-result cache (engine.py) — two requests
spelling the same query differently (filter order, op aliases,
defaulted fields) must hash identically.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, List, Optional, Tuple

from ..schema import FLOW_SCHEMA, METRICS_SCHEMA, METRICS_TABLE

#: queryable tables: name → (schema, default window-start column,
#: default window-end column). `flows` is the data plane;
#: `__metrics__` is the self-scraped metrics history (obs/history.py)
#: — its rows are point-in-time samples, so both window columns
#: default to the sample time (a half-open [start, end) window over
#: `timeInserted`), and the same plan grammar that answers Grafana-
#: shaped flow queries answers "p95 ingest latency, last 6h".
QUERYABLE_TABLES: Dict[str, tuple] = {
    "flows": (FLOW_SCHEMA, "flowStartSeconds", "flowEndSeconds"),
    METRICS_TABLE: (METRICS_SCHEMA, "timeInserted", "timeInserted"),
}

#: filter operators, canonical spelling → accepted aliases
_OP_ALIASES = {
    "eq": ("eq", "=", "=="),
    "ne": ("ne", "!=", "<>"),
    "ge": ("ge", ">="),
    "gt": ("gt", ">"),
    "le": ("le", "<="),
    "lt": ("lt", "<"),
    "in": ("in",),
}
_CANON_OP = {alias: op for op, aliases in _OP_ALIASES.items()
             for alias in aliases}

#: aggregate operators the kernels implement
AGG_OPS = ("count", "sum", "min", "max", "mean")

#: default top-K when the caller does not bound the group-by (0 = all)
DEFAULT_K = 100


class PlanError(ValueError):
    """Malformed query (unknown column/op, bad types) — a client
    error (HTTP 400), never an engine bug."""


@dataclasses.dataclass(frozen=True)
class Filter:
    """One column predicate. String columns take string values (eq/ne/
    in); numeric columns take integers (any op)."""

    column: str
    op: str
    value: object           # str | int | tuple for `in`

    def to_doc(self) -> Dict[str, object]:
        v = self.value
        return {"column": self.column, "op": self.op,
                "value": list(v) if isinstance(v, tuple) else v}


@dataclasses.dataclass(frozen=True)
class Aggregate:
    """One output aggregate; `label` is its result-row key."""

    op: str
    column: Optional[str]   # None only for count

    @property
    def label(self) -> str:
        if self.op == "count":
            return "count"
        return f"{self.op}({self.column})"

    def to_doc(self) -> Dict[str, object]:
        return {"op": self.op, "column": self.column}


@dataclasses.dataclass(frozen=True)
class QueryPlan:
    """A validated, normalized query over one queryable table
    (`flows`, or the `__metrics__` history table)."""

    group_by: Tuple[str, ...]
    aggregates: Tuple[Aggregate, ...]
    filters: Tuple[Filter, ...]
    start: Optional[int]
    end: Optional[int]
    time_column: str
    end_column: str
    k: int
    order_by: str            # an aggregate label
    table: str = "flows"

    # -- normalization -----------------------------------------------------

    def to_doc(self) -> Dict[str, object]:
        """Canonical JSON-able form (sorted filters, explicit
        defaults) — the cache key substrate and the doc echoed back to
        API clients."""
        return {
            "table": self.table,
            "groupBy": list(self.group_by),
            "aggregates": [a.to_doc() for a in self.aggregates],
            "filters": sorted((f.to_doc() for f in self.filters),
                              key=lambda d: json.dumps(d,
                                                       sort_keys=True)),
            "start": self.start,
            "end": self.end,
            "timeColumn": self.time_column,
            "endColumn": self.end_column,
            "k": self.k,
            "orderBy": self.order_by,
        }

    def normalized(self) -> str:
        return json.dumps(self.to_doc(), sort_keys=True,
                          separators=(",", ":"))

    def fingerprint(self) -> str:
        return hashlib.sha1(
            self.normalized().encode("utf-8")).hexdigest()

    # -- column sets (what the engine must touch) --------------------------

    def columns_touched(self) -> Tuple[str, ...]:
        """Every column the plan reads — the column-subset a cold-part
        decode needs (everything else's bytes are skipped on disk)."""
        cols = list(self.group_by)
        for a in self.aggregates:
            if a.column is not None:
                cols.append(a.column)
        for f in self.filters:
            cols.append(f.column)
        if self.start is not None:
            cols.append(self.time_column)
        if self.end is not None:
            cols.append(self.end_column)
        out: List[str] = []
        for c in cols:
            if c not in out:
                out.append(c)
        return tuple(out)


def _schema_column(schema, name: str):
    for c in schema:
        if c.name == name:
            return c
    raise PlanError(f"unknown column {name!r}")


def _parse_filter(doc: Dict[str, object], schema) -> Filter:
    if not isinstance(doc, dict):
        raise PlanError(f"filter must be an object, got {doc!r}")
    name = doc.get("column")
    col = _schema_column(schema, str(name))
    op = _CANON_OP.get(str(doc.get("op", "eq")).strip().lower())
    if op is None:
        raise PlanError(f"unknown filter op {doc.get('op')!r}")
    value = doc.get("value")
    if op == "in":
        if not isinstance(value, (list, tuple)) or not value:
            raise PlanError(
                f"filter {name}: `in` needs a non-empty list")
        if col.is_string:
            value = tuple(str(v) for v in value)
        else:
            value = tuple(int(v) for v in value)
    elif col.is_string:
        if op not in ("eq", "ne"):
            raise PlanError(
                f"filter {name}: string columns support eq/ne/in, "
                f"not {op}")
        value = str(value)
    else:
        try:
            value = int(value)   # all flow numerics are integer-typed
        except (TypeError, ValueError):
            raise PlanError(
                f"filter {name}: numeric column needs an integer, "
                f"got {value!r}")
    return Filter(str(name), op, value)


def _parse_aggregate(doc, schema) -> Aggregate:
    if isinstance(doc, str):
        # "sum:octetDeltaCount" / "count" shorthand (CLI, GET params)
        op, _, column = doc.partition(":")
        doc = {"op": op, "column": column or None}
    op = str(doc.get("op", "")).strip().lower()
    if op not in AGG_OPS:
        raise PlanError(
            f"unknown aggregate op {doc.get('op')!r} "
            f"(expected one of {AGG_OPS})")
    column = doc.get("column")
    if op == "count":
        return Aggregate("count", None)
    if not column:
        raise PlanError(f"aggregate {op} needs a column")
    col = _schema_column(schema, str(column))
    if col.is_string:
        raise PlanError(
            f"aggregate {op}({column}): string columns cannot be "
            f"aggregated (group by them instead)")
    return Aggregate(op, str(column))


def parse_plan(doc: Dict[str, object], schema=None) -> QueryPlan:
    """Build a validated QueryPlan from a request body (or any dict in
    the same shape). Raises PlanError (a ValueError → HTTP 400) on
    anything malformed. The plan's `table` (default `flows`) picks the
    schema every column resolves against and the window-column
    defaults; an explicit `schema` argument overrides (tests querying
    synthetic tables)."""
    if not isinstance(doc, dict):
        raise PlanError("query body must be a JSON object")
    table = str(doc.get("table") or "flows")
    default_time, default_end = "flowStartSeconds", "flowEndSeconds"
    if schema is None:
        if table not in QUERYABLE_TABLES:
            raise PlanError(
                f"unknown table {table!r} (expected one of "
                f"{sorted(QUERYABLE_TABLES)})")
        schema, default_time, default_end = QUERYABLE_TABLES[table]
    group_by = doc.get("groupBy") or []
    if isinstance(group_by, str):
        group_by = [g for g in group_by.split(",") if g]
    group_cols = []
    for g in group_by:
        _schema_column(schema, str(g))
        if str(g) in group_cols:
            raise PlanError(f"duplicate group-by column {g!r}")
        group_cols.append(str(g))
    aggs_doc = doc.get("aggregates") or doc.get("agg") or []
    if isinstance(aggs_doc, (str, dict)):
        aggs_doc = [aggs_doc]
    aggregates = [_parse_aggregate(a, schema) for a in aggs_doc]
    if not aggregates:
        aggregates = [Aggregate("count", None)]
    labels = [a.label for a in aggregates]
    if len(set(labels)) != len(labels):
        raise PlanError(f"duplicate aggregates: {labels}")
    filters = tuple(_parse_filter(f, schema)
                    for f in (doc.get("filters") or []))

    def _opt_int(key):
        v = doc.get(key)
        if v is None or v == "":
            return None
        try:
            return int(v)
        except (TypeError, ValueError):
            raise PlanError(f"{key} must be an integer, got {v!r}")

    start, end = _opt_int("start"), _opt_int("end")
    time_column = str(doc.get("timeColumn") or default_time)
    end_column = str(doc.get("endColumn") or default_end)
    for name in (time_column, end_column):
        if _schema_column(schema, name).is_string:
            # the window compares integers; a dictionary column here
            # would die inside the encoded-part evaluator (a 500)
            # instead of at the API edge (a 400)
            raise PlanError(
                f"window column {name!r} is a string column — the "
                f"time window needs a numeric/datetime column")
    k = _opt_int("k")
    if k is None:
        k = DEFAULT_K if group_cols else 0
    if k < 0:
        raise PlanError(f"k must be >= 0, got {k}")
    order_by = str(doc.get("orderBy") or labels[0])
    if order_by not in labels:
        raise PlanError(
            f"orderBy {order_by!r} is not one of the aggregates "
            f"{labels}")
    return QueryPlan(
        group_by=tuple(group_cols),
        aggregates=tuple(aggregates),
        filters=filters,
        start=start, end=end,
        time_column=time_column, end_column=end_column,
        k=int(k), order_by=order_by, table=table)


def plan_from_params(params: Dict[str, str],
                     schema=None) -> QueryPlan:
    """GET /query adapter: flat query-string params → plan doc.

    `table=flows|__metrics__` · `group_by=a,b` ·
    `agg=sum:col,count` · `start`/`end` ·
    `time_column`/`end_column` · `k` · `order_by` ·
    `where=col:op:value;col2:op:v1|v2` (values for `in` joined
    with `|`)."""
    doc: Dict[str, object] = {}
    if params.get("table"):
        doc["table"] = params["table"]
    if params.get("group_by"):
        doc["groupBy"] = params["group_by"]
    if params.get("agg"):
        doc["aggregates"] = [a for a in params["agg"].split(",") if a]
    filters: List[Dict[str, object]] = []
    for clause in (params.get("where") or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        bits = clause.split(":", 2)
        if len(bits) != 3:
            raise PlanError(
                f"where clause {clause!r} is not column:op:value")
        column, op, raw = bits
        value: object = raw
        if _CANON_OP.get(op.strip().lower()) == "in":
            value = raw.split("|")
        filters.append({"column": column, "op": op, "value": value})
    if filters:
        doc["filters"] = filters
    for src, dst in (("start", "start"), ("end", "end"),
                     ("k", "k"), ("order_by", "orderBy"),
                     ("time_column", "timeColumn"),
                     ("end_column", "endColumn")):
        if params.get(src):
            doc[dst] = params[src]
    return parse_plan(doc, schema)
