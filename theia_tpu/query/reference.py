"""Slow-but-correct reference query executor — the parity oracle.

Runs any QueryPlan over fully DECODED rows (a `ColumnarBatch` in table
code space, i.e. whatever `Table.scan()`/`select()` returns) with the
most obvious possible numpy: plain boolean masks for the filters,
`np.unique(..., return_inverse=True)` to factorize the group keys, and
`np.<ufunc>.at` accumulation for the aggregates. Deliberately a
DIFFERENT code path from query/kernels.py (lexsort + reduceat /
jitted segment reductions): the randomized oracle suite compares the
two bit-for-bit, so a bug in either one trips the gate instead of
hiding in shared code.

This executor is also the production read path for the FLAT engine
and any store without part structure — correctness first, speed from
the parts engine (the PR-7 pattern: the old path keeps working while
the new one proves itself against it).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..schema import ColumnarBatch
from .plan import QueryPlan
from .result import empty_result, finalize, lower_specs

#: kept in sync with kernels: partial merge semantics for `.at` ufuncs
_ACC_UFUNC = {"sum": np.add, "min": np.minimum, "max": np.maximum}


def filter_mask(plan: QueryPlan, batch: ColumnarBatch,
                dicts) -> np.ndarray:
    """Row mask over a decoded (table-coded) batch: the time window
    plus every plan filter, AND-combined. String predicates resolve
    through `dicts` (string → code) so the comparison is integer work
    even here."""
    n = len(batch)
    mask = np.ones(n, dtype=bool)
    if plan.start is not None:
        mask &= np.asarray(batch[plan.time_column]) >= plan.start
    if plan.end is not None:
        mask &= np.asarray(batch[plan.end_column]) < plan.end
    for f in plan.filters:
        col = np.asarray(batch[f.column])
        d = dicts.get(f.column) if dicts else None
        if d is not None:
            values = (f.value if isinstance(f.value, tuple)
                      else (f.value,))
            codes = [c for c in (d.lookup(str(v)) for v in values)
                     if c is not None]
            if f.op == "ne":
                m = (~np.isin(col, codes) if codes
                     else np.ones(n, dtype=bool))
            else:   # eq / in
                m = (np.isin(col, codes) if codes
                     else np.zeros(n, dtype=bool))
        elif f.op == "in":
            m = np.isin(col, np.asarray(f.value, np.int64))
        else:
            v = f.value
            m = {"eq": col == v, "ne": col != v,
                 "ge": col >= v, "gt": col > v,
                 "le": col <= v, "lt": col < v}[f.op]
        mask &= m
    return mask


def reference_partial(plan: QueryPlan, batch: ColumnarBatch, dicts
                      ) -> Optional[Tuple[np.ndarray,
                                          Dict[str, np.ndarray]]]:
    """(unique group-key matrix [g, k] int64 in table code space,
    {lowered label: int64 [g]}) for one decoded batch, or None when no
    row survives the filters. np.unique + ufunc.at — the independent
    implementation the kernels are checked against."""
    specs = lower_specs(plan)
    mask = filter_mask(plan, batch, dicts)
    if not mask.any():
        return None
    if plan.group_by:
        keys = np.stack([np.asarray(batch[g], np.int64)[mask]
                         for g in plan.group_by], axis=1)
        uniq, inverse = np.unique(keys, axis=0, return_inverse=True)
        inverse = inverse.reshape(-1)
    else:
        uniq = np.zeros((1, 0), np.int64)
        inverse = np.zeros(int(mask.sum()), np.int64)
    g = len(uniq)
    aggs: Dict[str, np.ndarray] = {}
    for label, op, column in specs:
        if op == "count":
            acc = np.zeros(g, np.int64)
            np.add.at(acc, inverse, 1)
        else:
            vals = np.asarray(batch[column], np.int64)[mask]
            if op == "sum":
                acc = np.zeros(g, np.int64)
            elif op == "min":
                acc = np.full(g, np.iinfo(np.int64).max, np.int64)
            else:
                acc = np.full(g, np.iinfo(np.int64).min, np.int64)
            _ACC_UFUNC[op].at(acc, inverse, vals)
        aggs[label] = acc
    return uniq, aggs


def materialize_keys(plan: QueryPlan, uniq: np.ndarray, dicts, schema
                     ) -> List[np.ndarray]:
    """Group-key code columns → output values (strings decoded via
    the table dictionaries, numerics passed through)."""
    out: List[np.ndarray] = []
    for j, name in enumerate(plan.group_by):
        codes = uniq[:, j]
        d = dicts.get(name) if dicts else None
        out.append(d.decode(codes) if d is not None
                   else codes.astype(np.int64))
    return out


def reference_execute(plan: QueryPlan, batch: ColumnarBatch, dicts,
                      schema=None
                      ) -> Tuple[List[Dict[str, object]], int, int]:
    """Execute `plan` over one decoded batch. Returns
    (rows, group_count, rows_scanned)."""
    partial = reference_partial(plan, batch, dicts)
    if partial is None:
        rows, groups = empty_result(plan)
        return rows, groups, len(batch)
    uniq, aggs = partial
    keys = materialize_keys(plan, uniq, dicts, schema)
    rows, groups = finalize(plan, keys, aggs)
    return rows, groups, len(batch)
