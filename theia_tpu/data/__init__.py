from .synth import SynthConfig, generate_flows, DEFAULT_START  # noqa: F401
