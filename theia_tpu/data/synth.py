"""Synthetic Antrea flow-record generator.

Produces `ColumnarBatch`es against the full flow schema, shaped like the data
the reference's e2e suite inserts directly via SQL for job tests (reference:
test/e2e/framework.go:112 `insertQueryflowtable`, and the iperf-driven rows
documented at test/e2e/flowvisibility_test.go:46-90): pod-to-pod /
pod-to-service / pod-to-external connections with per-connection throughput
time series, plus injected anomaly spikes so the detectors have ground truth.

Every benchmark and most tests sit on top of this module.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, Optional

import numpy as np

from ..schema import FLOW_SCHEMA, ColumnarBatch, StringDictionary

# 2021-01-01 00:00:00 UTC — arbitrary fixed epoch so tests are deterministic.
DEFAULT_START = 1609459200

FLOW_TYPE_INTRA_NODE = 1
FLOW_TYPE_INTER_NODE = 2
FLOW_TYPE_TO_EXTERNAL = 3


@dataclasses.dataclass
class SynthConfig:
    n_series: int = 64           # number of distinct connections (pod pairs)
    points_per_series: int = 60  # flow records per connection
    interval_seconds: int = 1    # spacing of flowEndSeconds within a series
    start_time: int = DEFAULT_START
    n_namespaces: int = 4
    n_nodes: int = 3
    pods_per_namespace: int = 8
    n_services: int = 4
    external_fraction: float = 0.1   # fraction of series going to external IPs
    service_fraction: float = 0.3    # fraction of series going via a Service
    base_throughput: float = 1.0e6   # bytes/s scale
    anomaly_fraction: float = 0.1    # fraction of series given a spike
    anomaly_magnitude: float = 20.0  # spike = magnitude * base
    protected_fraction: float = 0.0  # fraction with NP verdicts already set
    # every record carries the emitting cluster's UUID (multicluster
    # deployments stamp distinct values, test/e2e_mc/multicluster_test.go)
    cluster_uuid: str = "8a6a2e0e-0000-4000-8000-000000000001"
    seed: int = 0


def _pod_labels(ns_idx: int, app_idx: int) -> str:
    # Sorted-key JSON to match the reference's canonical label strings
    # (anomaly_detection.py:644 json.dumps(..., sort_keys=True)).
    return json.dumps({"app": f"app-{ns_idx}-{app_idx}"}, sort_keys=True)


def generate_flows(cfg: SynthConfig,
                   dicts: Optional[Dict[str, StringDictionary]] = None
                   ) -> ColumnarBatch:
    rng = np.random.default_rng(cfg.seed)
    S, T = cfg.n_series, cfg.points_per_series
    n = S * T

    ns_idx = rng.integers(0, cfg.n_namespaces, size=S)
    src_pod_idx = rng.integers(0, cfg.pods_per_namespace, size=S)
    dst_ns_idx = rng.integers(0, cfg.n_namespaces, size=S)
    dst_pod_idx = rng.integers(0, cfg.pods_per_namespace, size=S)
    src_node_idx = rng.integers(0, cfg.n_nodes, size=S)
    dst_node_idx = rng.integers(0, cfg.n_nodes, size=S)

    u = rng.random(size=S)
    is_external = u < cfg.external_fraction
    is_service = (~is_external) & (u < cfg.external_fraction
                                   + cfg.service_fraction)

    src_port = rng.integers(32768, 61000, size=S)
    dst_port = np.where(is_external, 443,
                        np.where(is_service, 80,
                                 rng.integers(5201, 5210, size=S)))
    proto = np.full(S, 6)  # TCP

    # Throughput series: noisy base + optional anomaly spike at a random step.
    base = cfg.base_throughput * (0.5 + rng.random(size=(S, 1)))
    noise = rng.normal(1.0, 0.05, size=(S, T))
    series = base * np.clip(noise, 0.1, None)
    anomalous = rng.random(size=S) < cfg.anomaly_fraction
    spike_t = rng.integers(T // 2, T, size=S)
    spike = (np.arange(T)[None, :] == spike_t[:, None]) & anomalous[:, None]
    series = np.where(spike, base * cfg.anomaly_magnitude, series)
    series = series.astype(np.int64)

    flow_end = (cfg.start_time
                + np.arange(T, dtype=np.int64)[None, :] * cfg.interval_seconds
                + np.zeros((S, 1), dtype=np.int64))
    flow_start = np.full((S, T), cfg.start_time - 10, dtype=np.int64)

    protected = rng.random(size=S) < cfg.protected_fraction

    def rep(per_series: np.ndarray) -> np.ndarray:
        return np.repeat(per_series, T)

    src_ns = np.array([f"ns-{i}" for i in ns_idx], dtype=object)
    dst_ns = np.array([f"ns-{i}" for i in dst_ns_idx], dtype=object)
    src_pod = np.array(
        [f"pod-{a}-{b}" for a, b in zip(ns_idx, src_pod_idx)], dtype=object)
    dst_pod = np.array(
        [f"pod-{a}-{b}" for a, b in zip(dst_ns_idx, dst_pod_idx)],
        dtype=object)
    src_labels = np.array(
        [_pod_labels(a, b) for a, b in zip(ns_idx, src_pod_idx)],
        dtype=object)
    dst_labels = np.array(
        [_pod_labels(a, b) for a, b in zip(dst_ns_idx, dst_pod_idx)],
        dtype=object)
    src_ip = np.array([f"10.0.{a}.{b}" for a, b in
                       zip(ns_idx, src_pod_idx)], dtype=object)
    dst_ip = np.where(
        is_external,
        np.array([f"203.0.113.{i % 250}" for i in range(S)], dtype=object),
        np.array([f"10.0.{a}.{b}" for a, b in
                  zip(dst_ns_idx, dst_pod_idx)], dtype=object))
    svc_name = np.where(
        is_service,
        np.array([f"ns-{a}/svc-{i % cfg.n_services}:http" for i, a in
                  enumerate(dst_ns_idx)], dtype=object),
        np.array([""] * S, dtype=object))
    cluster_ip = np.where(is_service,
                          np.array([f"10.96.0.{i % cfg.n_services + 1}"
                                    for i in range(S)], dtype=object),
                          np.array([""] * S, dtype=object))

    # External destinations have no dst pod context.
    dst_pod = np.where(is_external, "", dst_pod)
    dst_ns_out = np.where(is_external, "", dst_ns)
    dst_labels = np.where(is_external, "", dst_labels)
    dst_node = np.array([f"node-{i}" for i in dst_node_idx], dtype=object)
    dst_node = np.where(is_external, "", dst_node)

    flow_type = np.where(
        is_external, FLOW_TYPE_TO_EXTERNAL,
        np.where(src_node_idx == dst_node_idx, FLOW_TYPE_INTRA_NODE,
                 FLOW_TYPE_INTER_NODE))

    ing_np = np.where(protected & ~is_external,
                      np.array([f"allow-ingress-{i % 5}" for i in range(S)],
                               dtype=object), "")
    eg_np = np.where(protected,
                     np.array([f"allow-egress-{i % 5}" for i in range(S)],
                              dtype=object), "")

    octet_delta = (series * cfg.interval_seconds).astype(np.int64)

    str_cols = {
        "sourceIP": rep(src_ip),
        "destinationIP": rep(dst_ip),
        "sourcePodName": rep(src_pod),
        "sourcePodNamespace": rep(src_ns),
        "sourceNodeName": rep(np.array(
            [f"node-{i}" for i in src_node_idx], dtype=object)),
        "destinationPodName": rep(dst_pod),
        "destinationPodNamespace": rep(dst_ns_out),
        "destinationNodeName": rep(dst_node),
        "destinationClusterIP": rep(cluster_ip),
        "destinationServicePortName": rep(svc_name),
        "ingressNetworkPolicyName": rep(ing_np),
        "ingressNetworkPolicyNamespace": rep(
            np.where(ing_np != "", dst_ns, "")),
        "ingressNetworkPolicyRuleName": rep(
            np.where(ing_np != "", "rule-0", "")),
        "egressNetworkPolicyName": rep(eg_np),
        "egressNetworkPolicyNamespace": rep(
            np.where(eg_np != "", src_ns, "")),
        "egressNetworkPolicyRuleName": rep(
            np.where(eg_np != "", "rule-0", "")),
        "tcpState": rep(np.array(["ESTABLISHED"] * S, dtype=object)),
        "sourcePodLabels": rep(src_labels),
        "destinationPodLabels": rep(dst_labels),
        "clusterUUID": rep(np.array(
            [cfg.cluster_uuid] * S, dtype=object)),
        "egressName": rep(np.array([""] * S, dtype=object)),
        "egressIP": rep(np.array([""] * S, dtype=object)),
    }

    num_cols = {
        "timeInserted": flow_end.ravel(),
        "flowStartSeconds": flow_start.ravel(),
        "flowEndSeconds": flow_end.ravel(),
        "flowEndSecondsFromSourceNode": flow_end.ravel(),
        "flowEndSecondsFromDestinationNode": flow_end.ravel(),
        "flowEndReason": np.full(n, 3),
        "sourceTransportPort": rep(src_port),
        "destinationTransportPort": rep(dst_port),
        "protocolIdentifier": rep(proto),
        "packetTotalCount": np.cumsum(
            np.maximum(octet_delta // 1400, 1), axis=1).ravel(),
        "octetTotalCount": np.cumsum(octet_delta, axis=1).ravel(),
        "packetDeltaCount": np.maximum(octet_delta.ravel() // 1400, 1),
        "octetDeltaCount": octet_delta.ravel(),
        "reversePacketTotalCount": np.cumsum(
            np.maximum(octet_delta // 28000, 1), axis=1).ravel(),
        "reverseOctetTotalCount": np.cumsum(
            octet_delta // 20, axis=1).ravel(),
        "reversePacketDeltaCount": np.maximum(
            octet_delta.ravel() // 28000, 1),
        "reverseOctetDeltaCount": octet_delta.ravel() // 20,
        "destinationServicePort": rep(np.where(is_service, 80, 0)),
        "ingressNetworkPolicyRuleAction": rep(
            np.where(protected & ~is_external, 1, 0)),
        "ingressNetworkPolicyType": rep(
            np.where(protected & ~is_external, 1, 0)),
        "egressNetworkPolicyRuleAction": rep(np.where(protected, 1, 0)),
        "egressNetworkPolicyType": rep(np.where(protected, 1, 0)),
        "flowType": rep(flow_type),
        "throughput": series.ravel(),
        "reverseThroughput": series.ravel() // 20,
        "throughputFromSourceNode": series.ravel(),
        "throughputFromDestinationNode": series.ravel(),
        "reverseThroughputFromSourceNode": series.ravel() // 20,
        "reverseThroughputFromDestinationNode": series.ravel() // 20,
        "trusted": np.zeros(n),
    }

    dicts = dict(dicts or {})
    cols: Dict[str, np.ndarray] = {}
    for col in FLOW_SCHEMA:
        if col.is_string:
            d = dicts.setdefault(col.name, StringDictionary())
            cols[col.name] = d.encode(str_cols[col.name])
        else:
            cols[col.name] = np.asarray(num_cols[col.name],
                                        dtype=col.host_dtype)
    batch = ColumnarBatch(cols, dicts)
    batch.ground_truth_anomalous = anomalous  # type: ignore[attr-defined]
    return batch
