"""Shared helpers: leveled logging w/ support-bundle ring buffer,
input validation (job names, K8s quantities, algo enums), env config.
Reference: pkg/util/ (utils.go, env/env.go) and klog usage throughout.
"""

from .atomic import atomic_write  # noqa: F401
from .faults import FaultError  # noqa: F401
from .env import (  # noqa: F401
    DEFAULT_NAMESPACE,
    env_float,
    env_int,
    get_manager_addr,
    get_theia_namespace,
)
from .logging import (  # noqa: F401
    Logger,
    clear_logs,
    dump_logs,
    get_logger,
    get_verbosity,
    set_verbosity,
)
from .validation import (  # noqa: F401
    AGG_FLOWS,
    POLICY_TYPES,
    TAD_ALGOS,
    parse_job_name,
    parse_k8s_quantity,
    split_job_name,
    validate_agg_flow,
    validate_algo,
    validate_k8s_quantity,
    validate_policy_type,
)
