"""Atomic file publication: write to a same-directory temp file, then
os.replace onto the destination. A reader (or a crash) at any moment
sees either the old complete file or the new complete file, never a
torn one. Shared by the store snapshot path, the checkpointer, and the
runner's progress file.
"""

from __future__ import annotations

import os
import tempfile
from typing import Callable


def atomic_write(path: str, write_fn: Callable[[str], None],
                 suffix: str = "") -> None:
    """Run `write_fn(tmp_path)` then atomically publish tmp as `path`.

    `suffix` matters when the writer appends one itself (np.savez adds
    .npz to names without it — pass suffix=".npz" so the temp name
    already carries it and the replace source exists).
    """
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-", suffix=suffix)
    os.close(fd)
    try:
        write_fn(tmp)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
