"""Leveled logging with an in-memory ring buffer.

Re-provides the reference's klog usage (structured leveled logging with
`-v` verbosity on every binary, reference pkg/theia/commands/root.go and
cmd/theia-manager/theia-manager.go:117 log-file monitoring): messages
above the configured verbosity are dropped, the rest go to stderr AND a
bounded in-memory ring so the support bundle can ship recent logs the
way the reference's ManagerDumper copies log files out of pods
(pkg/support/dump.go:55-66).
"""

from __future__ import annotations

import collections
import sys
import threading
import time
from typing import Deque, Optional
from ..analysis.lockdep import named_lock

_RING_CAPACITY = 5000

_lock = named_lock("utils.logging")
_verbosity = 0
_ring: Deque[str] = collections.deque(maxlen=_RING_CAPACITY)


def set_verbosity(v: int) -> None:
    """Global `-v` level: 0 = info/warn/error only, higher enables
    matching `logger.v(n)` messages."""
    global _verbosity
    _verbosity = int(v)


def get_verbosity() -> int:
    return _verbosity


def dump_logs() -> str:
    """All retained log lines, oldest first (support-bundle payload)."""
    with _lock:
        return "\n".join(_ring)


def clear_logs() -> None:
    with _lock:
        _ring.clear()


def _emit(level: str, name: str, msg: str, stream: bool = True) -> None:
    ts = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime())
    line = f"{ts} {level} {name}: {msg}"
    with _lock:
        _ring.append(line)
    if stream:
        print(line, file=sys.stderr)


class Logger:
    """Named logger; `v(2).info(...)` mirrors klog.V(2).Infof."""

    def __init__(self, name: str, level: Optional[int] = None) -> None:
        self.name = name
        self._level = level  # None = unconditional

    def v(self, level: int) -> "Logger":
        return Logger(self.name, level)

    def _enabled(self) -> bool:
        return self._level is None or self._level <= _verbosity

    def info(self, msg: str, *args: object) -> None:
        if self._enabled():
            _emit("I", self.name, msg % args if args else msg,
                  stream=self._level is None or _verbosity > 0)

    def warning(self, msg: str, *args: object) -> None:
        _emit("W", self.name, msg % args if args else msg)

    def error(self, msg: str, *args: object) -> None:
        _emit("E", self.name, msg % args if args else msg)


def get_logger(name: str) -> Logger:
    return Logger(name)
