"""Environment helpers.

Re-provides pkg/util/env/env.go: the operating namespace comes from the
POD_NAMESPACE env var (injected by the deployment manifest) with the
`flow-visibility` default, and service endpoints can be overridden by
env the way CLICKHOUSE_URL/USERNAME/PASSWORD override discovery
(pkg/util/clickhouse/clickhouse.go:35-37,109-133).
"""

from __future__ import annotations

import os

DEFAULT_NAMESPACE = "flow-visibility"


def get_theia_namespace() -> str:
    return os.environ.get("POD_NAMESPACE", DEFAULT_NAMESPACE)


def get_manager_addr(default: str = "http://127.0.0.1:11347") -> str:
    """Manager endpoint, overridable via THEIA_MANAGER_ADDR (the CLI's
    --manager-addr flag wins over this)."""
    return os.environ.get("THEIA_MANAGER_ADDR", default)


def env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"env {name}={raw!r} is not an integer")


def env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"env {name}={raw!r} is not a number")
