"""Named lazy thread pools shared across the store tier.

One registry instead of per-module singleton boilerplate: pools are
created on first use and live for the process (daemon threads; the
work items are short CPU-bound tasks whose native kernels release the
GIL).
"""

from __future__ import annotations

import concurrent.futures
import threading
from typing import Dict

_pools: Dict[str, concurrent.futures.ThreadPoolExecutor] = {}
_lock = threading.Lock()


def get_pool(name: str,
             max_workers: int) -> concurrent.futures.ThreadPoolExecutor:
    """The process-wide pool registered under `name` (created with
    `max_workers` on first call; later calls reuse it as-is)."""
    with _lock:
        pool = _pools.get(name)
        if pool is None:
            pool = _pools[name] = concurrent.futures.ThreadPoolExecutor(
                max_workers=max_workers, thread_name_prefix=name)
        return pool
