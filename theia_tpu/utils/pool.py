"""Named lazy thread pools shared across the store tier.

One registry instead of per-module singleton boilerplate: pools are
created on first use and live for the process (daemon threads; the
work items are short CPU-bound tasks whose native kernels release the
GIL).
"""

from __future__ import annotations

import concurrent.futures
import threading
from typing import Dict

from .logging import get_logger
from ..analysis.lockdep import named_lock

logger = get_logger("pool")

_pools: Dict[str, concurrent.futures.ThreadPoolExecutor] = {}
_sizes: Dict[str, int] = {}
#: (name, requested) pairs already warned about — one log line per
#: distinct mismatch, not one per call on a hot path
_warned: set = set()
_lock = named_lock("utils.pool")


def get_pool(name: str,
             max_workers: int) -> concurrent.futures.ThreadPoolExecutor:
    """The process-wide pool registered under `name` (created with
    `max_workers` on first call; later calls reuse it as-is). A later
    call asking for a DIFFERENT size gets the existing pool — but the
    mismatch is logged once, so a mis-sized pool is diagnosable
    instead of silently throttling its second caller."""
    with _lock:
        pool = _pools.get(name)
        if pool is None:
            pool = _pools[name] = concurrent.futures.ThreadPoolExecutor(
                max_workers=max_workers, thread_name_prefix=name)
            _sizes[name] = max_workers
        elif _sizes.get(name) != max_workers and \
                (name, max_workers) not in _warned:
            _warned.add((name, max_workers))
            logger.warning(
                "pool %r already created with max_workers=%d; "
                "ignoring requested max_workers=%d (first caller "
                "wins for the process lifetime)",
                name, _sizes.get(name, 0), max_workers)
        return pool
