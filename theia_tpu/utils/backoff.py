"""Capped exponential backoff — the one schedule every supervisor
shares.

Reference: wait.Backoff in k8s.io/apimachinery (the Step() schedule
the reference's controllers lean on). Four supervisors here — job
retries, replica repair, reconciler passes, CLI polling — back off
the same way; the arithmetic lives once so a semantics fix (jitter,
overflow) lands everywhere.
"""

from __future__ import annotations

import random
from typing import Optional


def capped_backoff(base: float, cap: float, attempt: int) -> float:
    """Delay before retry number `attempt` (1-based):
    min(cap, base * 2**(attempt-1)). Exponent is clamped so a
    long-failing supervisor never computes a bignum just to throw it
    away against the cap."""
    if attempt < 1:
        raise ValueError(f"attempt is 1-based, got {attempt}")
    if attempt > 64:
        return cap
    return min(cap, base * (2 ** (attempt - 1)))


def jittered_backoff(base: float, cap: float, attempt: int,
                     rng: Optional[random.Random] = None) -> float:
    """`capped_backoff` with equal jitter — uniform in [0.5x, 1x] of
    the capped delay, so a fleet of producers rejected by the same
    429 does not retry in lockstep (the thundering-herd retry is
    exactly what an overloaded manager cannot absorb). Pass a seeded
    `rng` for reproducible schedules in tests."""
    d = capped_backoff(base, cap, attempt)
    r = rng if rng is not None else random
    return d * (0.5 + 0.5 * r.random())
