"""Capped exponential backoff — the one schedule every supervisor
shares.

Reference: wait.Backoff in k8s.io/apimachinery (the Step() schedule
the reference's controllers lean on). Four supervisors here — job
retries, replica repair, reconciler passes, CLI polling — back off
the same way; the arithmetic lives once so a semantics fix (jitter,
overflow) lands everywhere.
"""

from __future__ import annotations


def capped_backoff(base: float, cap: float, attempt: int) -> float:
    """Delay before retry number `attempt` (1-based):
    min(cap, base * 2**(attempt-1)). Exponent is clamped so a
    long-failing supervisor never computes a bignum just to throw it
    away against the cap."""
    if attempt < 1:
        raise ValueError(f"attempt is 1-based, got {attempt}")
    if attempt > 64:
        return cap
    return min(cap, base * (2 ** (attempt - 1)))
