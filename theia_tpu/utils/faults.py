"""Deterministic fault injection — the failure-domain test harness.

The reference platform is only trustworthy because its failure paths
run constantly in production (ClickHouse replicas replay from peers,
the Spark Operator retries and deadline-kills jobs); a reproduction
whose failure paths never execute has no failure paths. This module
arms named *fault points* compiled into the hot paths so tests, CI,
and operators can drive real faults deterministically:

    THEIA_FAULTS="store.insert:error:0.5,runner.exec:hang,replica.write:error@2"

Grammar (comma-separated entries):

    entry       := site ["#" target] ":" mode [":" probability] ["@" nth]
    mode        := "error" | "hang"
    target      := per-instance scoping: the rule fires only for hits
                   whose ctx `peer` (or `target`) equals this value —
                   `peer.partition#node2:error` severs only the node2
                   link, the deterministic per-peer partition drill; a
                   bare site matches every hit of that site
    probability := float in (0, 1]      (default 1.0; seeded RNG, so a
                                         given seed replays one firing
                                         pattern exactly)
    nth         := 1-based hit index    (one-shot: fire on exactly the
                                         nth invocation of that site,
                                         never again; overrides
                                         probability)

Instrumented sites:

    store.insert      FlowDatabase.insert_flows (fires once per
                      physical store — once per replica in a fan-out,
                      once per resync re-insert)
    replica.write     ReplicatedFlowDatabase per-replica fan-out write
                      (ctx: replica index, op)
    checkpoint.save   Checkpointer.checkpoint, before the snapshot
    wal.append        WriteAheadLog.append, before any bytes are
                      written (an injected error fails the insert —
                      no acknowledgement without durability)
    wal.fsync         WriteAheadLog.sync, before flush+fsync (the
                      sync-policy durability point)
    wal.rotate        WAL segment rotation, before the old segment is
                      sealed
    runner.spawn      JobController subprocess dispatch, before Popen
    runner.exec       job execution: thread dispatch fires in-process;
                      the runner child fires after argv parse (exits
                      TRANSIENT_EXIT_CODE on an injected error so the
                      controller classifies it transient)
    reconciler.pass   DeclarativeReconciler.reconcile_once
    net.send          cluster transport, before any bytes leave for a
                      peer (replication shipping, ingest forwarding,
                      heartbeats; ctx: peer, path)
    net.recv          cluster API handler, on receipt of a peer's
                      request before it is processed (ctx: peer, path)
    peer.partition    both directions of one peer link: fired inside
                      net.send AND net.recv, so arming it severs the
                      link symmetrically — the network-partition drill
    admission.pressure  AdmissionController.admit, before any check:
                      "error" forces the admission plane to reject the
                      request (429 + Retry-After, reason "fault") —
                      the deterministic overload drill; "hang" stalls
                      the request inside admission. Combine with
                      THEIA_ADMISSION_FORCE_LEVEL=<rung> to pin any
                      brownout rung instead of just the reject rung.
    state.spill       working-set tier eviction (ingest/state_tier.py),
                      before any gather/encode/insert — an injected
                      error fails the micro-batch with hot state fully
                      intact, so the retry re-runs the identical spill
    state.promote     working-set tier promotion of re-arriving spilled
                      series, before any warm/cold state is consumed
    state.age_out     warm-block aging to the cold (store-only) tier;
                      an injected error defers the maintenance round —
                      never fails the batch

Modes: "error" raises FaultError (callers treat it like any I/O
error); "hang" sleeps THEIA_FAULT_HANG_SECONDS (default 3600 — long
enough that only a supervisor kill ends it) and then proceeds.

Arming: the module arms itself from THEIA_FAULTS at import (so a
spawned runner child inherits the operator's faults through its
environment), or programmatically via arm()/disarm() for tests. The
disarmed fast path is one global read — free on hot paths.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
import time
from typing import Dict, List, Optional

from ..obs import metrics as _metrics
from ..analysis.lockdep import named_lock

MODES = ("error", "hang")

#: The fault-site registry: every `fire("<site>")` literal in the
#: package must name a member, and every member must be fired
#: somewhere — both directions enforced by the static lint pass
#: (theia_tpu/analysis/lint.py), so a renamed or removed site cannot
#: silently strand the operator docs above or a drill script.
KNOWN_SITES = (
    "store.insert",
    "replica.write",
    "checkpoint.save",
    "wal.append",
    "wal.fsync",
    "wal.rotate",
    "runner.spawn",
    "runner.exec",
    "reconciler.pass",
    "net.send",
    "net.recv",
    "peer.partition",
    "admission.pressure",
    "wire.decode",
    "wire.gather",
    "state.spill",
    "state.promote",
    "state.age_out",
)

_M_FIRINGS = _metrics.counter(
    "theia_fault_firings_total",
    "Armed fault points that actually injected (raised or hung)",
    labelnames=("site", "mode"))


class FaultError(Exception):
    """An injected fault (carries the site that fired)."""

    def __init__(self, site: str, detail: str = "") -> None:
        super().__init__(f"injected fault at {site}"
                         + (f" ({detail})" if detail else ""))
        self.site = site


@dataclasses.dataclass(frozen=True)
class FaultRule:
    site: str
    mode: str
    probability: float = 1.0
    nth: Optional[int] = None   # 1-based one-shot hit index


def parse_spec(spec: str) -> Dict[str, FaultRule]:
    """THEIA_FAULTS grammar → site-keyed rules (last entry per site
    wins). Raises ValueError on malformed entries — fail fast at arm
    time, not silently at fire time."""
    rules: Dict[str, FaultRule] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        site, sep, rest = entry.partition(":")
        if not sep or not site or not rest:
            raise ValueError(
                f"fault entry {entry!r} is not site:mode[:prob][@nth]")
        nth: Optional[int] = None
        if "@" in rest:
            rest, _, nth_s = rest.rpartition("@")
            try:
                nth = int(nth_s)
            except ValueError:
                raise ValueError(
                    f"fault entry {entry!r}: @nth must be an integer")
            if nth < 1:
                raise ValueError(
                    f"fault entry {entry!r}: @nth is 1-based")
        tokens = rest.split(":")
        mode = tokens[0]
        if mode not in MODES:
            raise ValueError(
                f"fault entry {entry!r}: mode must be one of {MODES}")
        probability = 1.0
        if len(tokens) > 1 and tokens[1]:
            try:
                probability = float(tokens[1])
            except ValueError:
                raise ValueError(
                    f"fault entry {entry!r}: probability must be a "
                    f"number")
            if not 0.0 < probability <= 1.0:
                raise ValueError(
                    f"fault entry {entry!r}: probability must be in "
                    f"(0, 1]")
        if len(tokens) > 2:
            raise ValueError(f"fault entry {entry!r}: too many fields")
        rules[site] = FaultRule(site=site, mode=mode,
                                probability=probability, nth=nth)
    return rules


class FaultInjector:
    """Armed rule set + per-site hit counters + seeded RNG. All state
    is behind one lock; fire() is the only hot-path entry."""

    def __init__(self, rules: Dict[str, FaultRule], seed: int = 0,
                 hang_seconds: Optional[float] = None) -> None:
        self.rules = dict(rules)
        self.seed = seed
        self.hang_seconds = (
            float(os.environ.get("THEIA_FAULT_HANG_SECONDS", "3600"))
            if hang_seconds is None else float(hang_seconds))
        self._rng = random.Random(seed)
        self._counts: Dict[str, int] = {}
        self._lock = named_lock("faults.injector")
        self._release = threading.Event()

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def armed_sites(self) -> List[str]:
        return sorted(self.rules)

    def release_hangs(self) -> None:
        """Unblock every in-progress (and future) hang — the test-side
        escape hatch when no supervisor kill is in play."""
        self._release.set()

    def fire(self, site: str, **ctx: object) -> None:
        """One instrumented hit of `site`: count it, then inject per
        the armed rule (no rule → free no-op). A rule armed with a
        `site#target` key fires only when the hit's ctx `peer` (or
        `target`) equals that target — hits and counters are tracked
        under the targeted key, so `@nth` indexes per peer link."""
        key = site
        rule = self.rules.get(site)
        target = ctx.get("peer", ctx.get("target"))
        if target is not None:
            targeted = self.rules.get(f"{site}#{target}")
            if targeted is not None:
                key, rule = f"{site}#{target}", targeted
        if rule is None:
            return
        with self._lock:
            n = self._counts[key] = self._counts.get(key, 0) + 1
            if rule.nth is not None:
                if n != rule.nth:
                    return
            elif rule.probability < 1.0 and \
                    self._rng.random() >= rule.probability:
                return
        _M_FIRINGS.labels(site=key, mode=rule.mode).inc()
        if rule.mode == "hang":
            self._hang()
            return
        detail = ", ".join(f"{k}={v}" for k, v in sorted(ctx.items()))
        raise FaultError(site, detail)

    def _hang(self) -> None:
        deadline = time.monotonic() + self.hang_seconds
        while not self._release.is_set():
            left = deadline - time.monotonic()
            if left <= 0:
                return
            time.sleep(min(0.05, left))


#: the process-wide injector; None = disarmed (the hot-path fast path)
_injector: Optional[FaultInjector] = None


def arm(spec: str, seed: Optional[int] = None,
        hang_seconds: Optional[float] = None) -> FaultInjector:
    """Arm (replacing any previous injector — counters reset)."""
    global _injector
    if seed is None:
        seed = int(os.environ.get("THEIA_FAULT_SEED", "0"))
    _injector = FaultInjector(parse_spec(spec), seed=seed,
                              hang_seconds=hang_seconds)
    return _injector


def arm_from_env() -> Optional[FaultInjector]:
    """(Re-)arm from THEIA_FAULTS; disarms when the env var is unset."""
    global _injector
    spec = os.environ.get("THEIA_FAULTS", "")
    if not spec.strip():
        _injector = None
        return None
    return arm(spec)


def disarm() -> None:
    global _injector
    if _injector is not None:
        _injector.release_hangs()
    _injector = None


def injector() -> Optional[FaultInjector]:
    return _injector


def armed_sites() -> List[str]:
    inj = _injector
    return inj.armed_sites() if inj is not None else []


def fire(site: str, **ctx: object) -> None:
    """Hot-path entry: a single global read when disarmed."""
    inj = _injector
    if inj is not None:
        inj.fire(site, **ctx)


# A spawned child (runner, manager) inherits the operator's armed
# faults through its environment.
if os.environ.get("THEIA_FAULTS", "").strip():
    arm_from_env()
