"""Shared input validation.

Re-provides the reference's validators: job-name/UUID parsing
(ParseRecommendationName / ParseADAlgorithmName, pkg/util/utils.go),
the Kubernetes resource-quantity check applied to driver/executor
core+memory CRD fields (pkg/controller/networkpolicyrecommendation/
controller.go:586-608), and the enum checks the CLI and the TAD
controller apply to --algo / --agg-flow
(pkg/theia/commands/anomaly_detection_run.go,
pkg/controller/anomalydetector/controller.go).
"""

from __future__ import annotations

import re
import uuid
from typing import Tuple

TAD_ALGOS = ("EWMA", "ARIMA", "DBSCAN")
AGG_FLOWS = ("", "pod", "external", "svc")
POLICY_TYPES = ("anp-deny-applied", "anp-deny-all", "k8s-np")

# Kubernetes quantity grammar: signed decimal + optional binary (Ki, Mi,
# ...) / decimal-SI (m, k, M, ..., E=exa) / scientific (e3, E-2) suffix.
# Exponent is tried first so '2e3' parses scientific while bare '12E'
# falls through to the exa suffix, matching K8s disambiguation.
_K8S_QUANTITY_RE = re.compile(
    r"^[+-]?(\d+|\d+\.\d*|\.\d+)"
    r"(Ki|Mi|Gi|Ti|Pi|Ei|[eE][+-]?\d+|[numkKMGTPE])?$")

_SUFFIX_MULTIPLIER = {
    "": 1.0,
    "n": 1e-9, "u": 1e-6, "m": 1e-3,
    "k": 1e3, "K": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15,
    "E": 1e18,
    "Ki": 2.0 ** 10, "Mi": 2.0 ** 20, "Gi": 2.0 ** 30,
    "Ti": 2.0 ** 40, "Pi": 2.0 ** 50, "Ei": 2.0 ** 60,
}


def parse_k8s_quantity(value: str) -> float:
    """'512M' → 512e6, '200m' → 0.2, '1Gi' → 2**30. Raises ValueError
    on anything the K8s quantity grammar rejects."""
    m = _K8S_QUANTITY_RE.match(value.strip())
    if not m:
        raise ValueError(f"invalid resource quantity {value!r}")
    number, suffix = m.group(1), m.group(2) or ""
    if suffix[:1] in ("e", "E") and suffix[1:].lstrip("+-").isdigit():
        return float(number) * 10.0 ** int(suffix[1:])
    return float(number) * _SUFFIX_MULTIPLIER[suffix]


def validate_k8s_quantity(value: str, flag: str) -> str:
    try:
        parse_k8s_quantity(value)
    except ValueError:
        raise ValueError(
            f"{flag} should conform to the Kubernetes resource "
            f"quantity convention (e.g. 200m, 512M, 1Gi): got "
            f"{value!r}")
    return value


def validate_algo(algo: str) -> str:
    if algo not in TAD_ALGOS:
        raise ValueError(
            f"invalid algo {algo!r}: must be one of "
            f"{', '.join(TAD_ALGOS)}")
    return algo


def validate_agg_flow(agg_flow: str) -> str:
    if agg_flow not in AGG_FLOWS:
        raise ValueError(
            f"invalid agg-flow {agg_flow!r}: must be one of "
            f"pod, external, svc")
    return agg_flow


def validate_policy_type(policy_type: str) -> str:
    if policy_type not in POLICY_TYPES:
        raise ValueError(
            f"invalid policyType {policy_type!r}: must be one of "
            f"{', '.join(POLICY_TYPES)}")
    return policy_type


def parse_job_name(name: str, prefix: str) -> str:
    """'pr-<uuid>' → '<uuid>' with UUID validation; raises ValueError
    like the reference's ParseRecommendationName."""
    if not name.startswith(prefix):
        raise ValueError(
            f"invalid job name {name!r}: expected prefix {prefix!r}")
    suffix = name[len(prefix):]
    try:
        uuid.UUID(suffix)
    except ValueError:
        raise ValueError(
            f"invalid job name {name!r}: {suffix!r} is not a UUID")
    return suffix


def split_job_name(name: str) -> Tuple[str, str]:
    """'pr-<uuid>' → ('pr', '<uuid>'); accepts any known prefix."""
    for prefix, kind in (("pr-", "pr"), ("tad-", "tad"), ("dd-", "dd"),
                         ("fpm-", "fpm"), ("sad-", "sad")):
        if name.startswith(prefix):
            return kind, parse_job_name(name, prefix)
    raise ValueError(f"unrecognized job name {name!r}")
