"""One-dispatch fused scoring step for the device-resident hot path.

The sharded ingest engine scores one micro-batch with (per shard) one
jitted heavy-hitter step (CMS scatter + query + k-means) plus one
jitted streaming step (EWMA/Welford gather-scan-scatter) — two
dispatches and two host↔device fetch round trips per shard per batch.
On weak hosts the per-dispatch fixed cost dominates the compute
(ROADMAP item 3: the detector leg caps e2e at ~1.5M rows/s while
native decode does 17.7M), so this module fuses ALL of it — EWMA
update + Welford band + CMS heavy-hitter update + k-means shape
outliers + alert thresholding — across EVERY shard's coalesced slice
into ONE jitted computation: one dispatch, one fetch, per coalesced
micro-batch.

Parity contract: the per-shard math is literally the sharded engine's
— the streaming scan applies `analytics.streaming._update` tick by
tick, and the heavy-hitter half composes the same
`ops.sketch.cms_update/cms_query/kmeans_step` helpers — so on the same
backend, the same per-shard input order produces bit-identical alert
decisions (tests/test_device_path.py holds both engines to that).

The T-tick scan over the [T, U] slot tile has a Pallas TPU kernel
(`THEIA_FUSED_PALLAS=auto|1|0|interpret`): one VMEM-resident pass per
128-lane slot block with the tick loop unrolled in-register, instead of
the lax.scan's per-tick HLO while-loop. `auto` (the default) engages it
only on TPU backends; everywhere else — tier-1 CI included — the plain
jnp scan keeps the semantics on CPU. `interpret` runs the Pallas kernel
through the interpreter so its logic is testable without hardware.
"""

from __future__ import annotations

import os
from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..analytics.streaming import StreamState, _update as _stream_tick
from .ewma import DEFAULT_ALPHA
from .sketch import CmsState, KMeansState, cms_query, cms_update, kmeans_step
from ..utils import get_logger

logger = get_logger("fused_detector")

#: Pallas lane width: the tile scan kernel blocks the slot axis by this
#: (slot tiles are already padded to powers of two >= 64).
PALLAS_BLOCK_U = 128


class ShardInputs(NamedTuple):
    """One shard's coalesced micro-batch slice, host-staged and padded
    (streaming tile from StreamingDetector.build_plan, heavy-hitter
    arrays from heavy_hitters.build_hh_plan)."""
    slots: jnp.ndarray    # [U_pad] int32 state slots (capacity = pad)
    x: jnp.ndarray        # [T_pad, U_pad] float32 values
    active: jnp.ndarray   # [T_pad, U_pad] bool
    keys: jnp.ndarray     # [size] uint32 CMS keys
    vols: jnp.ndarray     # [size] float32 volumes
    q: jnp.ndarray        # [q_size] uint32 heavy-hitter query keys
    feats: jnp.ndarray    # [size, F] float32 k-means features
    valid: jnp.ndarray    # [size] bool


class ShardStepState(NamedTuple):
    """One shard's device-resident detector state between micro-batches."""
    stream: StreamState
    cms: CmsState
    km: KMeansState


class ShardOutputs(NamedTuple):
    anomaly: jnp.ndarray  # [T_pad, U_pad] bool streaming anomalies
    est: jnp.ndarray      # [q_size] float32 sketched volume per query
    total: jnp.ndarray    # scalar float32 post-update sketch total
    dist: jnp.ndarray     # [size] float32 distance to assigned centroid


def _scan_tile(sub: StreamState, x: jnp.ndarray, active: jnp.ndarray,
               alpha) -> Tuple[StreamState, jnp.ndarray]:
    """Reference tick scan: exactly stream_update_sparse's inner loop
    (analytics/streaming.py) over an already-gathered slot subset."""

    def step(carry, inp):
        x_t, act_t = inp
        new, anomaly = _stream_tick(carry, x_t, act_t, alpha)
        return new, anomaly

    return jax.lax.scan(step, sub, (x, active))


def _scan_tile_pallas(sub: StreamState, x: jnp.ndarray,
                      active: jnp.ndarray, alpha: float,
                      interpret: bool) -> Tuple[StreamState, jnp.ndarray]:
    """Pallas version of `_scan_tile`: grid over 128-lane slot blocks,
    the (small, static) tick loop unrolled with state held in
    registers/VMEM — no per-tick HLO loop, one pass over the tile.
    Math is kept line-for-line identical to streaming._update."""
    from jax.experimental import pallas as pl

    t, u = x.shape
    alpha = float(alpha)
    one_minus = 1.0 - alpha

    def kernel(ewma_ref, count_ref, mean_ref, m2_ref, x_ref, act_ref,
               ewma_o, count_o, mean_o, m2_o, anom_o):
        ewma = ewma_ref[0, :]
        count = count_ref[0, :]
        mean = mean_ref[0, :]
        m2 = m2_ref[0, :]
        for tt in range(t):
            xv = x_ref[tt, :]
            act = act_ref[tt, :]
            xa = jnp.where(act, xv, 0.0)
            count = count + act.astype(jnp.int32)
            delta = xa - mean
            mean = jnp.where(act,
                             mean + delta / jnp.maximum(count, 1),
                             mean)
            m2 = jnp.where(act, m2 + delta * (xa - mean), m2)
            ewma = jnp.where(act, one_minus * ewma + alpha * xa, ewma)
            std = jnp.sqrt(m2 / jnp.maximum(count - 1, 1))
            anom_o[tt, :] = (act & (count >= 2)
                             & (jnp.abs(xa - ewma) > std))
        ewma_o[0, :] = ewma
        count_o[0, :] = count
        mean_o[0, :] = mean
        m2_o[0, :] = m2

    def vec():
        return pl.BlockSpec((1, PALLAS_BLOCK_U), lambda i: (0, i))

    def tile():
        return pl.BlockSpec((t, PALLAS_BLOCK_U), lambda i: (0, i))

    outs = pl.pallas_call(
        kernel,
        grid=(u // PALLAS_BLOCK_U,),
        in_specs=[vec(), vec(), vec(), vec(), tile(), tile()],
        out_specs=[vec(), vec(), vec(), vec(), tile()],
        out_shape=[
            jax.ShapeDtypeStruct((1, u), sub.ewma.dtype),
            jax.ShapeDtypeStruct((1, u), sub.count.dtype),
            jax.ShapeDtypeStruct((1, u), sub.mean.dtype),
            jax.ShapeDtypeStruct((1, u), sub.m2.dtype),
            jax.ShapeDtypeStruct((t, u), jnp.bool_),
        ],
        interpret=interpret,
    )(sub.ewma[None, :], sub.count[None, :], sub.mean[None, :],
      sub.m2[None, :], x, active)
    ewma_n, count_n, mean_n, m2_n, anom = outs
    return StreamState(ewma_n[0], count_n[0], mean_n[0], m2_n[0]), anom


def _stream_half(stream: StreamState, inp: ShardInputs, alpha,
                 use_pallas: bool, interpret: bool
                 ) -> Tuple[StreamState, jnp.ndarray]:
    """Gather-scan-scatter over one shard's slot tile (the
    stream_update_sparse shape, Pallas-optional scan core).
    Padding slots hold `capacity`: the gather clamps harmlessly and
    the scatter DROPS them (XLA's documented OOB semantics)."""
    sub = StreamState(*(a[inp.slots] for a in stream))
    if use_pallas and inp.x.shape[1] % PALLAS_BLOCK_U == 0:
        sub, anomalies = _scan_tile_pallas(sub, inp.x, inp.active,
                                           alpha, interpret)
    else:
        sub, anomalies = _scan_tile(sub, inp.x, inp.active, alpha)
    new = StreamState(*(
        full.at[inp.slots].set(part, mode="drop")
        for full, part in zip(stream, sub)))
    return new, anomalies


def _shard_step(state: ShardStepState, inp: ShardInputs, alpha,
                use_pallas: bool, interpret: bool
                ) -> Tuple[ShardStepState, ShardOutputs]:
    new_stream, anomaly = _stream_half(state.stream, inp, alpha,
                                       use_pallas, interpret)
    cms = cms_update(state.cms, inp.keys, inp.vols)
    est = cms_query(cms, inp.q)
    km, _, dist = kmeans_step(state.km, inp.feats, inp.valid)
    return (ShardStepState(new_stream, cms, km),
            ShardOutputs(anomaly, est, cms.total, dist))


@partial(jax.jit, static_argnames=("alpha", "use_pallas", "interpret"))
def fused_step(states: Tuple[ShardStepState, ...],
               inputs: Tuple[ShardInputs, ...],
               alpha: float = DEFAULT_ALPHA,
               use_pallas: bool = False,
               interpret: bool = False
               ) -> Tuple[Tuple[ShardStepState, ...],
                          Tuple[ShardOutputs, ...]]:
    """ONE device dispatch scoring every shard's coalesced slice:
    per-shard state in, per-shard (state', outputs) out. The host
    arrays in `inputs` ride the call (jit batches the transfers), and
    per-connection detector state never leaves the device between
    micro-batches. Retraces once per (shard subset, tile bucket)
    combination — tiles are padded to power-of-two buckets upstream."""
    pairs = tuple(_shard_step(s, i, alpha, use_pallas, interpret)
                  for s, i in zip(states, inputs))
    return tuple(p[0] for p in pairs), tuple(p[1] for p in pairs)


@jax.jit
def gather_state(state: StreamState, slots: jnp.ndarray) -> StreamState:
    """Pull `slots` rows of per-connection state off the device in ONE
    dispatch — the working-set tier's eviction read
    (ingest/state_tier.py). Padding slots carry `capacity`; the gather
    clamps them to the last row (XLA OOB semantics) and the caller
    slices them away."""
    return StreamState(*(a[slots] for a in state))


@jax.jit
def restore_state(state: StreamState, slots: jnp.ndarray,
                  ewma: jnp.ndarray, count: jnp.ndarray,
                  mean: jnp.ndarray, m2: jnp.ndarray) -> StreamState:
    """Scatter promoted / freshly-zeroed state rows into `slots` in ONE
    dispatch — the working-set tier's promotion write. Padding slots
    carry `capacity`, which the scatter DROPS (XLA OOB semantics), so
    every eviction-batch size shares a handful of compiled shapes.
    Zero rows double as slot re-initialization: a reused slot must not
    leak its previous occupant's state."""
    part = (ewma, count, mean, m2)
    return StreamState(*(
        full.at[slots].set(p.astype(full.dtype), mode="drop")
        for full, p in zip(state, part)))


def pallas_mode() -> Tuple[bool, bool]:
    """(use_pallas, interpret) from THEIA_FUSED_PALLAS:
    'auto' (default) enables the Pallas scan on TPU backends only;
    '1' forces it on, '0' off; 'interpret' runs it through the Pallas
    interpreter (CPU testing of the kernel logic)."""
    raw = os.environ.get("THEIA_FUSED_PALLAS", "auto").strip().lower()
    if raw in ("0", "off", "false", "no"):
        return False, False
    if raw == "interpret":
        return True, True
    if raw in ("1", "force", "on", "yes"):
        return True, False
    try:
        backend = jax.default_backend()
    except Exception:
        return False, False
    return backend == "tpu", False
