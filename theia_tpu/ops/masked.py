"""Masked per-series statistics over padded [S, T] tensors.

The analytics jobs batch ragged per-connection time series into padded
tensors with a validity mask; every statistic here honors the mask so the
padding never leaks into results. Sample standard deviation matches Spark's
`stddev_samp` (reference: plugins/anomaly-detection/anomaly_detection.py:
676-684) including its NULL-for-n<2 behavior (we return NaN).
"""

from __future__ import annotations

import jax.numpy as jnp


def masked_count(mask: jnp.ndarray) -> jnp.ndarray:
    return jnp.sum(mask.astype(jnp.int32), axis=-1)


def masked_mean(x: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    n = jnp.maximum(masked_count(mask), 1)
    return jnp.sum(jnp.where(mask, x, 0.0), axis=-1) / n


def masked_stddev_samp(x: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Sample stddev (ddof=1) per series; NaN when fewer than 2 points,
    mirroring SQL stddev_samp returning NULL."""
    n = masked_count(mask)
    mean = masked_mean(x, mask)
    dev = jnp.where(mask, x - mean[..., None], 0.0)
    ss = jnp.sum(dev * dev, axis=-1)
    var = ss / jnp.maximum(n - 1, 1)
    return jnp.where(n >= 2, jnp.sqrt(var), jnp.nan)
