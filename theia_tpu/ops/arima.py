"""Batched walk-forward ARIMA(1,1,1) forecasting.

Reference semantics (plugins/anomaly-detection/anomaly_detection.py:215-309):
for each connection's throughput series x (needs > 3 points, all positive):
  1. Box-Cox transform with MLE lambda           (scipy.stats.boxcox)
  2. train = y[:3]; for each later step t, fit ARIMA(1,1,1) on history
     y[:t] and forecast one step ahead           (statsmodels, re-fit per t)
  3. predictions = train + forecasts, inverse Box-Cox back to levels
  4. anomaly_t = |x_t − pred_t| > stddev_samp(x)
Series that are too short or fail the transform yield no anomalies
(:232-234, :260-264).

TPU-first design: the reference's per-step statsmodels MLE re-fit is the
system's hottest loop (SURVEY §3.5). Here every (series, prefix) pair is
fitted *simultaneously*:

  * Box-Cox lambda by dense grid + parabolic refinement of the profile
    log-likelihood (the same objective scipy optimizes with Brent).
  * ARIMA(1,1,1) = ARMA(1,1) on first differences, estimated per prefix
    with the Hannan–Rissanen two-stage regression — pure masked
    prefix-moment algebra (no iterative optimizer), vmapped over
    [series × prefix].
  * The MA residual recursion is a `lax.scan` over time under `vmap`.

Accuracy delta vs the reference (documented per SURVEY §7 hard-part b):
Hannan–Rissanen is a consistent estimator of the same model but not the
MLE, so individual forecasts differ from an MLE fit; on the synthetic
golden tests (tests/test_tad_golden.py, vs a scipy CSS-MLE fit of the
same model) injected spikes are flagged identically and the only
divergence is within the ≤3-step post-spike recovery window, where
predictions hinge on the estimated (phi, theta).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .masked import masked_count, masked_stddev_samp

MIN_POINTS = 4        # reference requires len > 3  (:232)
_RIDGE = 1e-6
_CLIP = 0.99


def boxcox_llf(lam: jnp.ndarray, x: jnp.ndarray,
               mask: jnp.ndarray) -> jnp.ndarray:
    """Profile log-likelihood of the Box-Cox parameter (scipy's
    boxcox_llf): llf = (λ−1)·Σ log x − n/2·log σ²(y_λ)."""
    n = jnp.maximum(masked_count(mask), 1)
    logx = jnp.where(mask, jnp.log(jnp.where(mask, x, 1.0)), 0.0)
    y = jnp.where(jnp.abs(lam) < 1e-12,
                  logx,
                  (jnp.exp(lam * logx) - 1.0) / jnp.where(
                      jnp.abs(lam) < 1e-12, 1.0, lam))
    y = jnp.where(mask, y, 0.0)
    mean = jnp.sum(y, axis=-1) / n
    var = jnp.sum(jnp.where(mask, (y - mean[..., None]) ** 2, 0.0),
                  axis=-1) / n
    return ((lam - 1.0) * jnp.sum(logx, axis=-1)
            - 0.5 * n * jnp.log(jnp.maximum(var, 1e-300)))


def boxcox_lambda(x: jnp.ndarray, mask: jnp.ndarray,
                  lo: float = -2.0, hi: float = 2.0,
                  n_grid: int = 161) -> jnp.ndarray:
    """MLE lambda per series via grid search + one parabolic refinement
    (scipy uses Brent on the same objective over (-2, 2))."""
    grid = jnp.linspace(lo, hi, n_grid)
    llf = jax.vmap(lambda g: boxcox_llf(g, x, mask))(grid)  # [G, S]
    idx = jnp.argmax(llf, axis=0)
    step = (hi - lo) / (n_grid - 1)
    i = jnp.clip(idx, 1, n_grid - 2)
    f_m1 = jnp.take_along_axis(llf, (i - 1)[None, :], axis=0)[0]
    f_0 = jnp.take_along_axis(llf, i[None, :], axis=0)[0]
    f_p1 = jnp.take_along_axis(llf, (i + 1)[None, :], axis=0)[0]
    denom = f_m1 - 2.0 * f_0 + f_p1
    shift = jnp.where(jnp.abs(denom) > 1e-12,
                      0.5 * (f_m1 - f_p1) / denom, 0.0)
    shift = jnp.clip(shift, -1.0, 1.0)
    lam = grid[i] + shift * step
    return jnp.where(idx == jnp.clip(idx, 1, n_grid - 2), lam, grid[idx])


def boxcox_transform(x, lam):
    lam = lam[..., None]
    safe = jnp.maximum(x, 1e-300)
    return jnp.where(jnp.abs(lam) < 1e-12,
                     jnp.log(safe),
                     (jnp.power(safe, lam) - 1.0) / jnp.where(
                         jnp.abs(lam) < 1e-12, 1.0, lam))


def inv_boxcox(y, lam):
    lam = lam[..., None]
    return jnp.where(jnp.abs(lam) < 1e-12,
                     jnp.exp(y),
                     jnp.power(jnp.maximum(lam * y + 1.0, 1e-300),
                               1.0 / jnp.where(jnp.abs(lam) < 1e-12,
                                               1.0, lam)))


def _fit_prefix(d: jnp.ndarray, w: jnp.ndarray):
    """Hannan–Rissanen ARMA(1,1) fit on one weighted (prefix-masked)
    difference series d [L]; returns (phi, theta).

    Stage 1: AR(1) OLS → provisional residuals.
    Stage 2: OLS of d_t on [d_{t-1}, resid_{t-1}] (2×2 normal equations).
    """
    d_lag = jnp.concatenate([jnp.zeros_like(d[:1]), d[:-1]])
    w_pair = w * jnp.concatenate([jnp.zeros_like(w[:1]), w[:-1]])
    # Stage 1
    a = (jnp.sum(w_pair * d * d_lag)
         / (jnp.sum(w_pair * d_lag * d_lag) + _RIDGE))
    eps1 = (d - a * d_lag) * w_pair  # resid_0 := 0
    e_lag = jnp.concatenate([jnp.zeros_like(eps1[:1]), eps1[:-1]])
    # Stage 2: X = [d_lag, e_lag], solve (XᵀWX + rI) β = XᵀW d
    s11 = jnp.sum(w_pair * d_lag * d_lag) + _RIDGE
    s12 = jnp.sum(w_pair * d_lag * e_lag)
    s22 = jnp.sum(w_pair * e_lag * e_lag) + _RIDGE
    b1 = jnp.sum(w_pair * d_lag * d)
    b2 = jnp.sum(w_pair * e_lag * d)
    det = s11 * s22 - s12 * s12
    det = jnp.where(jnp.abs(det) < 1e-30, 1e-30, det)
    phi = (s22 * b1 - s12 * b2) / det
    theta = (s11 * b2 - s12 * b1) / det
    return (jnp.clip(phi, -_CLIP, _CLIP),
            jnp.clip(theta, -_CLIP, _CLIP))


@functools.partial(jax.jit,
                   static_argnames=("refit_every", "group_chunk"))
def arima_walk_forward(y: jnp.ndarray, mask: jnp.ndarray,
                       refit_every: int = 1,
                       group_chunk: int = 512) -> jnp.ndarray:
    """Walk-forward one-step forecasts for a padded [S, T] Box-Cox batch.

    pred[:, :3] = y[:, :3] (the reference's train prefix is passed
    through, :241-255); pred[:, m] for m ≥ 3 comes from a fit on a
    prefix of y.

    `refit_every=k` groups prefixes: the fit for steps [g·k, (g+1)·k)
    uses the prefix of length max(g·k, 3), and one CSS residual
    recursion per group serves all its steps — k=1 is the reference's
    exact refit-per-step semantics; k>1 trades refit freshness for a
    k× compute cut on long series (the 24h@1s scale where per-step
    refits are infeasible for any implementation). Groups evaluate in
    `group_chunk`-sized chunks via lax.map, so peak memory is
    O(S · group_chunk · T) instead of the O(S · T²) a full vmap over
    prefixes would materialize.
    """
    S, T = y.shape
    k = refit_every
    n_groups = -(-T // k)
    y0 = jnp.where(mask, y, 0.0)

    def per_series(y_row):
        d = y_row[1:] - y_row[:-1]            # [T-1]
        idx = jnp.arange(T - 1)

        def group_preds(g):
            # Fit on the prefix available at the group's first step;
            # CSS recursion eps_t = d_t − φ d_{t-1} − θ eps_{t-1}
            # (eps_0 = 0) runs once with the group's params — eps_t for
            # t < m−1 doesn't depend on the prefix cutoff, so each step
            # m just reads eps[m−2].
            m_fit = jnp.maximum(g * k, 3)
            w = (idx < (m_fit - 1)).astype(y_row.dtype)
            phi, theta = _fit_prefix(d, w)

            def step(eps_prev, t):
                d_prev = jnp.where(t >= 1, d[jnp.maximum(t - 1, 0)],
                                   0.0)
                eps_t = d[t] - phi * d_prev - theta * eps_prev
                eps_t = jnp.where(t == 0, 0.0, eps_t)
                return eps_t, eps_t

            _, eps = jax.lax.scan(step, jnp.array(0.0, y_row.dtype),
                                  idx)
            ms = g * k + jnp.arange(k)
            last = jnp.clip(ms - 2, 0, T - 2)
            d_hat = phi * d[last] + theta * eps[last]
            return y_row[jnp.clip(ms - 1, 0, T - 1)] + d_hat

        gs = jnp.arange(n_groups)
        if n_groups <= group_chunk:
            preds = jax.vmap(group_preds)(gs).reshape(-1)[:T]
        else:
            pad = (-n_groups) % group_chunk
            gs = jnp.concatenate([gs, jnp.zeros(pad, gs.dtype)])
            preds = jax.lax.map(
                jax.vmap(group_preds),
                gs.reshape(-1, group_chunk)).reshape(-1)[:T]
        ms_all = jnp.arange(T)
        return jnp.where(ms_all < 3, y_row, preds)

    return jax.vmap(per_series)(y0)


@functools.partial(jax.jit, static_argnames=("refit_every",))
def arima_scores(x: jnp.ndarray, mask: jnp.ndarray,
                 refit_every: int = 1):
    """Full ARIMA scoring: (pred levels [S,T], stddev [S], anomaly [S,T]).

    Series with ≤ 3 points or any non-positive value produce no anomalies
    and zero algoCalc, matching the reference's error paths (:232-234,
    :260-264: scipy.boxcox raises on x ≤ 0 → caught → None → [False]).
    `refit_every` (see arima_walk_forward) defaults to the reference's
    exact refit-per-step; long-series callers raise it."""
    n = masked_count(mask)
    positive = jnp.all(jnp.where(mask, x > 0, True), axis=-1)
    ok = (n >= MIN_POINTS) & positive
    safe_x = jnp.where(mask & (x > 0), x, 1.0)

    # Normalize each series by its geometric mean before the transform.
    # Raw throughputs are ~1e6-1e9; when the MLE lambda is negative,
    # x^λ underflows the mantissa and (λ·y + 1) cancels — fatally in
    # float32 (the TPU path), noticeably even in float64. With x/gm ≈ 1
    # the transform is well-conditioned in both dtypes; predictions are
    # rescaled back to levels afterwards. (The reference transforms raw
    # values and simply inherits the float64 cancellation.)
    log_gm = jnp.sum(jnp.where(mask, jnp.log(safe_x), 0.0), axis=-1) \
        / jnp.maximum(n, 1)
    gm = jnp.exp(log_gm)[..., None]
    xs = safe_x / gm

    lam = boxcox_lambda(xs, mask)
    y = boxcox_transform(xs, lam)
    # Auto-size the group chunk: each chunk materializes an
    # [S, chunk, T] f32 eps stack — budget it at ~256 MiB so 24h@1s
    # series fit alongside the rest of the working set.
    S, T = x.shape
    chunk = max(1, min(512, (256 << 20) // max(1, 4 * S * T)))
    preds_bc = arima_walk_forward(y, mask, refit_every=refit_every,
                                  group_chunk=chunk)
    preds = inv_boxcox(preds_bc, lam) * gm
    preds = jnp.where(ok[..., None] & mask, preds, 0.0)

    std = masked_stddev_samp(x, mask)
    anomaly = (jnp.abs(x - preds) > std[..., None]) & mask & ok[..., None]
    return preds, std, anomaly
