"""Batched walk-forward ARIMA(1,1,1) forecasting.

Reference semantics (plugins/anomaly-detection/anomaly_detection.py:215-309):
for each connection's throughput series x (needs > 3 points, all positive):
  1. Box-Cox transform with MLE lambda           (scipy.stats.boxcox)
  2. train = y[:3]; for each later step t, fit ARIMA(1,1,1) on history
     y[:t] and forecast one step ahead           (statsmodels, re-fit per t)
  3. predictions = train + forecasts, inverse Box-Cox back to levels
  4. anomaly_t = |x_t − pred_t| > stddev_samp(x)
Series that are too short or fail the transform yield no anomalies
(:232-234, :260-264).

TPU-first design: the reference's per-step statsmodels MLE re-fit is the
system's hottest loop (SURVEY §3.5). Here every (series, prefix) pair is
fitted *simultaneously*:

  * Box-Cox lambda by dense grid + parabolic refinement of the profile
    log-likelihood (the same objective scipy optimizes with Brent).
  * ARIMA(1,1,1) = ARMA(1,1) on first differences, estimated per prefix
    with the Hannan–Rissanen two-stage regression — pure masked
    prefix-moment algebra (no iterative optimizer), vmapped over
    [series × prefix].
  * The MA residual recursion is a `lax.scan` over time under `vmap`.

Accuracy delta vs the reference (documented per SURVEY §7 hard-part b):
Hannan–Rissanen is a consistent estimator of the same model but not the
MLE, so individual forecasts differ from an MLE fit; on the synthetic
golden tests (tests/test_tad_golden.py, vs a scipy CSS-MLE fit of the
same model) injected spikes are flagged identically and the only
divergence is within the ≤3-step post-spike recovery window, where
predictions hinge on the estimated (phi, theta).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .masked import masked_count, masked_stddev_samp

MIN_POINTS = 4        # reference requires len > 3  (:232)
_RIDGE = 1e-6
_CLIP = 0.99


def boxcox_llf(lam: jnp.ndarray, x: jnp.ndarray,
               mask: jnp.ndarray) -> jnp.ndarray:
    """Profile log-likelihood of the Box-Cox parameter (scipy's
    boxcox_llf): llf = (λ−1)·Σ log x − n/2·log σ²(y_λ)."""
    n = jnp.maximum(masked_count(mask), 1)
    logx = jnp.where(mask, jnp.log(jnp.where(mask, x, 1.0)), 0.0)
    y = jnp.where(jnp.abs(lam) < 1e-12,
                  logx,
                  (jnp.exp(lam * logx) - 1.0) / jnp.where(
                      jnp.abs(lam) < 1e-12, 1.0, lam))
    y = jnp.where(mask, y, 0.0)
    mean = jnp.sum(y, axis=-1) / n
    var = jnp.sum(jnp.where(mask, (y - mean[..., None]) ** 2, 0.0),
                  axis=-1) / n
    return ((lam - 1.0) * jnp.sum(logx, axis=-1)
            - 0.5 * n * jnp.log(jnp.maximum(var, 1e-300)))


def boxcox_lambda(x: jnp.ndarray, mask: jnp.ndarray,
                  lo: float = -2.0, hi: float = 2.0,
                  n_grid: int = 161) -> jnp.ndarray:
    """MLE lambda per series via grid search + one parabolic refinement
    (scipy uses Brent on the same objective over (-2, 2))."""
    grid = jnp.linspace(lo, hi, n_grid)
    llf = jax.vmap(lambda g: boxcox_llf(g, x, mask))(grid)  # [G, S]
    idx = jnp.argmax(llf, axis=0)
    step = (hi - lo) / (n_grid - 1)
    i = jnp.clip(idx, 1, n_grid - 2)
    f_m1 = jnp.take_along_axis(llf, (i - 1)[None, :], axis=0)[0]
    f_0 = jnp.take_along_axis(llf, i[None, :], axis=0)[0]
    f_p1 = jnp.take_along_axis(llf, (i + 1)[None, :], axis=0)[0]
    denom = f_m1 - 2.0 * f_0 + f_p1
    shift = jnp.where(jnp.abs(denom) > 1e-12,
                      0.5 * (f_m1 - f_p1) / denom, 0.0)
    shift = jnp.clip(shift, -1.0, 1.0)
    lam = grid[i] + shift * step
    return jnp.where(idx == jnp.clip(idx, 1, n_grid - 2), lam, grid[idx])


def boxcox_transform(x, lam):
    lam = lam[..., None]
    safe = jnp.maximum(x, 1e-300)
    return jnp.where(jnp.abs(lam) < 1e-12,
                     jnp.log(safe),
                     (jnp.power(safe, lam) - 1.0) / jnp.where(
                         jnp.abs(lam) < 1e-12, 1.0, lam))


def inv_boxcox(y, lam):
    lam = lam[..., None]
    return jnp.where(jnp.abs(lam) < 1e-12,
                     jnp.exp(y),
                     jnp.power(jnp.maximum(lam * y + 1.0, 1e-300),
                               1.0 / jnp.where(jnp.abs(lam) < 1e-12,
                                               1.0, lam)))


def _fit_prefix(d: jnp.ndarray, w: jnp.ndarray):
    """Hannan–Rissanen ARMA(1,1) fit on one weighted (prefix-masked)
    difference series d [L]; returns (phi, theta).

    Stage 1: AR(1) OLS → provisional residuals.
    Stage 2: OLS of d_t on [d_{t-1}, resid_{t-1}] (2×2 normal equations).
    """
    d_lag = jnp.concatenate([jnp.zeros_like(d[:1]), d[:-1]])
    w_pair = w * jnp.concatenate([jnp.zeros_like(w[:1]), w[:-1]])
    # Stage 1
    a = (jnp.sum(w_pair * d * d_lag)
         / (jnp.sum(w_pair * d_lag * d_lag) + _RIDGE))
    eps1 = (d - a * d_lag) * w_pair  # resid_0 := 0
    e_lag = jnp.concatenate([jnp.zeros_like(eps1[:1]), eps1[:-1]])
    # Stage 2: X = [d_lag, e_lag], solve (XᵀWX + rI) β = XᵀW d
    s11 = jnp.sum(w_pair * d_lag * d_lag) + _RIDGE
    s12 = jnp.sum(w_pair * d_lag * e_lag)
    s22 = jnp.sum(w_pair * e_lag * e_lag) + _RIDGE
    b1 = jnp.sum(w_pair * d_lag * d)
    b2 = jnp.sum(w_pair * e_lag * d)
    det = s11 * s22 - s12 * s12
    det = jnp.where(jnp.abs(det) < 1e-30, 1e-30, det)
    phi = (s22 * b1 - s12 * b2) / det
    theta = (s11 * b2 - s12 * b1) / det
    return (jnp.clip(phi, -_CLIP, _CLIP),
            jnp.clip(theta, -_CLIP, _CLIP))


def _forecast_one(y: jnp.ndarray, m: jnp.ndarray):
    """One-step forecast ŷ_m from history y[:m] (m ≥ 3), one series.

    y: [T] Box-Cox values. Differences d_t = y_{t+1} − y_t live at
    indices 0..T-2; the prefix uses d[0:m-1].
    """
    T = y.shape[0]
    d = y[1:] - y[:-1]
    idx = jnp.arange(T - 1)
    w = (idx < (m - 1)).astype(y.dtype)
    phi, theta = _fit_prefix(d, w)

    # CSS residual recursion over the prefix: eps_t = d_t − φ d_{t-1}
    # − θ eps_{t-1} (eps conditioned to 0 at t=0), then forecast
    # d̂ = φ·d_{m-2} + θ·eps_{m-2}.
    def step(eps_prev, t):
        d_prev = jnp.where(t >= 1, d[jnp.maximum(t - 1, 0)], 0.0)
        eps_t = d[t] - phi * d_prev - theta * eps_prev
        eps_t = jnp.where((t >= 1) & (t < m - 1), eps_t, eps_prev)
        eps_t = jnp.where(t == 0, 0.0, eps_t)
        return eps_t, eps_t

    eps_last, _ = jax.lax.scan(step, jnp.array(0.0, y.dtype), idx)
    d_last = d[jnp.maximum(m - 2, 0)]
    d_hat = phi * d_last + theta * eps_last
    return y[jnp.maximum(m - 1, 0)] + d_hat


@jax.jit
def arima_walk_forward(y: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Walk-forward one-step forecasts for a padded [S, T] Box-Cox batch.

    pred[:, :3] = y[:, :3] (the reference's train prefix is passed
    through, :241-255); pred[:, m] for m ≥ 3 comes from a fit on y[:, :m].
    All (series, prefix) fits run in parallel.
    """
    S, T = y.shape
    ms = jnp.arange(T)

    def per_series(y_row):
        preds = jax.vmap(lambda m: _forecast_one(y_row, m))(ms)
        return jnp.where(ms < 3, y_row, preds)

    preds = jax.vmap(per_series)(jnp.where(mask, y, 0.0))
    return preds


@jax.jit
def arima_scores(x: jnp.ndarray, mask: jnp.ndarray):
    """Full ARIMA scoring: (pred levels [S,T], stddev [S], anomaly [S,T]).

    Series with ≤ 3 points or any non-positive value produce no anomalies
    and zero algoCalc, matching the reference's error paths (:232-234,
    :260-264: scipy.boxcox raises on x ≤ 0 → caught → None → [False])."""
    n = masked_count(mask)
    positive = jnp.all(jnp.where(mask, x > 0, True), axis=-1)
    ok = (n >= MIN_POINTS) & positive
    safe_x = jnp.where(mask & (x > 0), x, 1.0)

    # Normalize each series by its geometric mean before the transform.
    # Raw throughputs are ~1e6-1e9; when the MLE lambda is negative,
    # x^λ underflows the mantissa and (λ·y + 1) cancels — fatally in
    # float32 (the TPU path), noticeably even in float64. With x/gm ≈ 1
    # the transform is well-conditioned in both dtypes; predictions are
    # rescaled back to levels afterwards. (The reference transforms raw
    # values and simply inherits the float64 cancellation.)
    log_gm = jnp.sum(jnp.where(mask, jnp.log(safe_x), 0.0), axis=-1) \
        / jnp.maximum(n, 1)
    gm = jnp.exp(log_gm)[..., None]
    xs = safe_x / gm

    lam = boxcox_lambda(xs, mask)
    y = boxcox_transform(xs, lam)
    preds_bc = arima_walk_forward(y, mask)
    preds = inv_boxcox(preds_bc, lam) * gm
    preds = jnp.where(ok[..., None] & mask, preds, 0.0)

    std = masked_stddev_samp(x, mask)
    anomaly = (jnp.abs(x - preds) > std[..., None]) & mask & ok[..., None]
    return preds, std, anomaly
