"""On-device kernels: anomaly scoring + masked series statistics."""

from .arima import arima_scores, arima_walk_forward, boxcox_lambda
from .dbscan import dbscan_noise, dbscan_scores
from .ewma import ewma, ewma_scores
from .masked import masked_count, masked_mean, masked_stddev_samp

__all__ = [
    "arima_scores", "arima_walk_forward", "boxcox_lambda",
    "dbscan_noise", "dbscan_scores",
    "ewma", "ewma_scores",
    "masked_count", "masked_mean", "masked_stddev_samp",
]
