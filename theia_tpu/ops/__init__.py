"""On-device kernels: anomaly scoring + masked series statistics."""

from .arima import arima_scores, arima_walk_forward, boxcox_lambda
from .dbscan import dbscan_noise, dbscan_scores
from .drops import drop_scores
from .ewma import ewma, ewma_scores
from .masked import masked_count, masked_mean, masked_stddev_samp
from .sketch import (cms_init, cms_query, cms_update, kmeans_init,
                     kmeans_step)

__all__ = [
    "arima_scores", "arima_walk_forward", "boxcox_lambda",
    "dbscan_noise", "dbscan_scores",
    "drop_scores",
    "ewma", "ewma_scores",
    "masked_count", "masked_mean", "masked_stddev_samp",
    "cms_init", "cms_query", "cms_update", "kmeans_init", "kmeans_step",
]
