"""Pallas TPU kernel for DBSCAN noise detection.

Same math as ops/dbscan.py (reference semantics:
plugins/anomaly-detection/anomaly_detection.py:325-349 — sklearn
DBSCAN(eps, min_samples) noise labels over 1-D throughput values), but
tiled explicitly: the XLA formulation materializes the [S, T, T]
pairwise-distance tensor through HBM, while this kernel streams series
blocks through VMEM and never writes the pairwise tensor back — each
grid step computes a [BS, T, T] neighborhood cube in registers/VMEM,
reduces it to per-point neighbor counts and core-reachability, and
emits only the [BS, T] noise flags. HBM traffic drops from O(S·T²) to
O(S·T).

The block size BS adapts to T so the cube stays within a VMEM budget;
T is padded to the 128-lane boundary with masked-off columns (padding
never changes counts: padded pairs are masked invalid).

On non-TPU backends the kernel runs in interpreter mode, so tests on
the CPU conftest (8 virtual devices) exercise the same code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .dbscan import DEFAULT_EPS, DEFAULT_MIN_SAMPLES

# VMEM budget for the [BS, T, T] neighborhood cube (f32 words).
_CUBE_BUDGET = 1 << 19    # 512k elements ≈ 2 MiB


def _dbscan_kernel(x_ref, m_ref, out_ref, *, eps, min_samples):
    # All broadcasts stay in 32-bit lanes: Mosaic cannot insert a minor
    # dim on i1 vectors, so validity flows through f32 {0,1} products.
    x = x_ref[:]                            # [BS, T] float32
    m = m_ref[:].astype(jnp.float32)        # [BS, T] {0,1}
    within = (jnp.abs(x[:, :, None] - x[:, None, :])
              <= eps).astype(jnp.float32)
    within = within * m[:, :, None] * m[:, None, :]
    counts = jnp.sum(within, axis=-1)       # exact for T < 2^24
    core = jnp.where(counts >= min_samples, m, 0.0)
    reachable = jnp.max(within * core[:, None, :], axis=-1)
    noise = m * (1.0 - core) * (1.0 - jnp.minimum(reachable, 1.0))
    out_ref[:] = noise.astype(jnp.int8)


def _block_series(t_padded: int) -> int:
    return max(1, _CUBE_BUDGET // max(t_padded * t_padded, 1))


@functools.partial(
    jax.jit, static_argnames=("eps", "min_samples", "interpret"))
def dbscan_noise_pallas(x: jnp.ndarray, mask: jnp.ndarray,
                        eps: float = DEFAULT_EPS,
                        min_samples: int = DEFAULT_MIN_SAMPLES,
                        interpret: bool = False) -> jnp.ndarray:
    """Noise flags for a padded [S, T] batch via the Pallas kernel.

    Bit-identical to ops.dbscan.dbscan_noise (tested against it); use
    on TPU where the series batch is large enough that the [S, T, T]
    intermediate would otherwise round-trip HBM.
    """
    s, t = x.shape
    t_pad = -(-max(t, 1) // 128) * 128
    bs = _block_series(t_pad)
    s_pad = -(-max(s, 1) // bs) * bs
    xp = jnp.zeros((s_pad, t_pad), jnp.float32)
    xp = xp.at[:s, :t].set(x.astype(jnp.float32))
    mp = jnp.zeros((s_pad, t_pad), jnp.int8)
    mp = mp.at[:s, :t].set(mask.astype(jnp.int8))

    out = pl.pallas_call(
        functools.partial(_dbscan_kernel, eps=eps,
                          min_samples=min_samples),
        out_shape=jax.ShapeDtypeStruct((s_pad, t_pad), jnp.int8),
        grid=(s_pad // bs,),
        in_specs=[
            pl.BlockSpec((bs, t_pad), lambda i: (i, 0)),
            pl.BlockSpec((bs, t_pad), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bs, t_pad), lambda i: (i, 0)),
        interpret=interpret,
    )(xp, mp)
    return out[:s, :t] != 0
