"""Traffic-drop anomaly scoring kernel.

Re-provides the per-partition statistics of the reference's Snowflake
drop-detection UDTF (snowflake/udfs/udfs/drop_detection/
drop_detection_udf.py:43-56): for each (endpoint, direction) partition's
daily drop-count series, anomaly iff the count falls outside
mean ± 3·stddev_samp, and partitions with fewer than 3 observations are
skipped.

TPU-first: partitions are rows of a padded [S, D] matrix (S partitions ×
D dates, mask marks real observations); the whole fleet scores in one
fused jitted step instead of the reference's per-partition pandas pass.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .masked import masked_count, masked_mean, masked_stddev_samp

MIN_OBSERVATIONS = 3
SIGMA = 3.0


@jax.jit
def drop_scores(counts: jnp.ndarray, mask: jnp.ndarray):
    """counts [S, D] float, mask [S, D] bool → (anomaly [S, D] bool,
    mean [S], stddev [S]). Rows with < MIN_OBSERVATIONS valid entries
    produce no anomalies (UDTF end_partition early return)."""
    counts = counts.astype(jnp.float32)
    mean = masked_mean(counts, mask)
    std = masked_stddev_samp(counts, mask)
    n = masked_count(mask)
    upper = mean + SIGMA * std
    lower = mean - SIGMA * std
    anomaly = (counts > upper[:, None]) | (counts < lower[:, None])
    anomaly &= mask
    anomaly &= (n >= MIN_OBSERVATIONS)[:, None]
    return anomaly, mean, std
