"""Count-Min-Sketch + online k-means device kernels.

The BASELINE north-star streaming config: "Count-Min-Sketch + online
k-means heavy-hitter / DDoS detection at line rate from live Antrea
FlowExporter". Both structures live device-resident and advance one
fused XLA step per ingest micro-batch:

  * CMS — D hash rows x W counters of traffic volume keyed by integer
    flow keys. Update is a scatter-add per row; query is min over the
    D estimates (classic CMS upper bound). Everything is batched: one
    `update` call processes the whole micro-batch.
  * Online k-means — mini-batch k-means (Sculley 2010 web-scale
    formulation: per-batch assignment + per-centroid learning-rate
    update with counts as the rate denominator). Distance computation
    is one [N,K] matmul-shaped pass — MXU work, not a Python loop.

No reference equivalent: Theia has no streaming analytics at all.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

# Distinct odd 32-bit seeds per hash row. All sketch hashing is uint32
# so it works with JAX's default x64-disabled mode (TPU production
# path) as well as the x64 test configuration.
_HASH_SEEDS = (
    0x9E3779B9, 0xBF58476D, 0x94D049BB, 0xD6E8FEB8, 0xA5A5A5A5,
    0xC2B2AE3D,
)


class CmsState(NamedTuple):
    counts: jnp.ndarray    # [D, W] float32 volume counters
    total: jnp.ndarray     # scalar: total volume seen


def cms_init(depth: int = 4, width: int = 8192) -> CmsState:
    if depth > len(_HASH_SEEDS):
        raise ValueError(f"depth must be <= {len(_HASH_SEEDS)}")
    if width <= 0 or width & (width - 1):
        # slot masking is `h & (width-1)` — any other width silently
        # strands counters and inflates collisions
        raise ValueError(f"width must be a power of two, got {width}")
    return CmsState(counts=jnp.zeros((depth, width), jnp.float32),
                    total=jnp.zeros((), jnp.float32))


def _cms_slots(keys: jnp.ndarray, depth: int, width: int) -> jnp.ndarray:
    """keys [N] uint32 → [D, N] counter indices (width power of two);
    murmur3-finalizer mixing, distinct seed per row."""
    rows = []
    for d in range(depth):
        h = keys ^ jnp.uint32(_HASH_SEEDS[d])
        h ^= h >> jnp.uint32(16)
        h *= jnp.uint32(0x85EBCA6B)
        h ^= h >> jnp.uint32(13)
        h *= jnp.uint32(0xC2B2AE35)
        h ^= h >> jnp.uint32(16)
        rows.append((h & jnp.uint32(width - 1)).astype(jnp.int32))
    return jnp.stack(rows)


@partial(jax.jit, static_argnames=("depth", "width"))
def _cms_update(counts, total, keys, volumes, *, depth, width):
    slots = _cms_slots(keys, depth, width)          # [D, N]
    def add_row(row, idx):
        return row.at[idx].add(volumes)
    counts = jax.vmap(add_row)(counts, slots)
    return counts, total + volumes.sum()


def cms_update(state: CmsState, keys: jnp.ndarray,
               volumes: jnp.ndarray) -> CmsState:
    """Scatter one micro-batch of (key, volume) into the sketch."""
    d, w = state.counts.shape
    counts, total = _cms_update(state.counts, state.total,
                                keys.astype(jnp.uint32),
                                volumes.astype(jnp.float32),
                                depth=d, width=w)
    return CmsState(counts, total)


@partial(jax.jit, static_argnames=("depth", "width"))
def _cms_query(counts, keys, *, depth, width):
    slots = _cms_slots(keys, depth, width)          # [D, N]
    ests = jax.vmap(lambda row, idx: row[idx])(counts, slots)
    return ests.min(axis=0)


def cms_query(state: CmsState, keys: jnp.ndarray) -> jnp.ndarray:
    """Estimated volume per key (CMS upper bound, min over rows)."""
    d, w = state.counts.shape
    return _cms_query(state.counts, keys.astype(jnp.uint32),
                      depth=d, width=w)


class KMeansState(NamedTuple):
    centroids: jnp.ndarray   # [K, F]
    counts: jnp.ndarray      # [K] points assigned so far


def kmeans_init(centroids: jnp.ndarray) -> KMeansState:
    centroids = jnp.asarray(centroids, jnp.float32)
    return KMeansState(centroids=centroids,
                       counts=jnp.zeros(centroids.shape[0], jnp.float32))


@jax.jit
def kmeans_step(state: KMeansState, points: jnp.ndarray,
                valid: Optional[jnp.ndarray] = None
                ) -> Tuple[KMeansState, jnp.ndarray, jnp.ndarray]:
    """One mini-batch update. points [N, F] → (state', assignment [N],
    distance [N] to the assigned centroid). `valid` [N] bool masks out
    padding rows (callers pad batches to fixed sizes to avoid per-size
    XLA retraces): invalid rows get assignment/distance but contribute
    nothing to the centroid update."""
    points = points.astype(jnp.float32)
    if valid is None:
        valid = jnp.ones(points.shape[0], bool)
    vf = valid.astype(jnp.float32)
    # [N, K] squared distances as matmul-shaped work (MXU-friendly);
    # full precision so small inter-centroid gaps survive on TPU.
    x2 = (points * points).sum(-1, keepdims=True)
    c2 = (state.centroids * state.centroids).sum(-1)
    d2 = x2 + c2[None, :] - 2.0 * jnp.matmul(
        points, state.centroids.T,
        precision=jax.lax.Precision.HIGHEST)
    assign = jnp.argmin(d2, axis=1)
    dist = jnp.sqrt(jnp.maximum(
        jnp.take_along_axis(d2, assign[:, None], axis=1)[:, 0], 0.0))
    # Mini-batch centroid update: per-centroid batch mean pulled in with
    # learning rate batch_n / (counts + batch_n).
    k = state.centroids.shape[0]
    one_hot = jax.nn.one_hot(assign, k, dtype=jnp.float32) * vf[:, None]
    batch_n = one_hot.sum(0)                                 # [K]
    batch_sum = one_hot.T @ points                           # [K, F]
    new_counts = state.counts + batch_n
    safe_n = jnp.maximum(batch_n, 1.0)
    batch_mean = batch_sum / safe_n[:, None]
    rate = jnp.where(new_counts > 0, batch_n / jnp.maximum(new_counts, 1.0),
                     0.0)
    centroids = (state.centroids
                 + rate[:, None] * (batch_mean - state.centroids))
    return KMeansState(centroids, new_counts), assign, dist
