"""EWMA anomaly scoring as a parallel (associative) scan.

Reference semantics (plugins/anomaly-detection/anomaly_detection.py:146-212):
    ewma_t = (1-α)·ewma_{t-1} + α·x_t,  ewma_{-1} = 0,  α = 0.5
    anomaly_t = |x_t − ewma_t| > stddev_samp(x)

TPU-first design: the recurrence is linear, so instead of the reference's
per-element Python loop it runs as `lax.associative_scan` over the time
axis — O(log T) depth, fully parallel across the [S, T] batch. The whole
scoring step (scan + stddev + threshold) is one fused XLA computation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .masked import masked_stddev_samp

DEFAULT_ALPHA = 0.5


def ewma(x: jnp.ndarray, alpha: float = DEFAULT_ALPHA) -> jnp.ndarray:
    """EWMA along the last axis with implicit zero initial state.

    Solves e_t = a·e_{t-1} + b_t (a = 1-α, b_t = α·x_t) by scanning the
    affine maps (A, B) under composition (A1,B1)∘(A2,B2) = (A1A2, A2B1+B2);
    with e_{-1}=0 the accumulated B is the answer.
    """
    a = jnp.full_like(x, 1.0 - alpha)
    b = alpha * x

    def combine(lhs, rhs):
        a1, b1 = lhs
        a2, b2 = rhs
        return a1 * a2, a2 * b1 + b2

    _, e = jax.lax.associative_scan(combine, (a, b), axis=-1)
    return e


@functools.partial(jax.jit, static_argnames=("alpha",))
def ewma_scores(x: jnp.ndarray, mask: jnp.ndarray,
                alpha: float = DEFAULT_ALPHA):
    """Full EWMA scoring for a padded series batch.

    Padding is squashed to 0 before the scan; because the reference also
    starts from ewma=0 and processes each series whole, leading valid
    points see exactly the reference recurrence as long as padding is
    trailing (the tensorizer guarantees that).

    Returns (ewma [S,T], stddev [S], anomaly [S,T] bool).
    """
    xz = jnp.where(mask, x, 0.0)
    e = ewma(xz, alpha)
    std = masked_stddev_samp(x, mask)
    # NaN stddev (fewer than 2 points) compares False, matching the
    # reference's "too few values" → not anomalous path (:198-201).
    anomaly = (jnp.abs(xz - e) > std[..., None]) & mask
    return e, std, anomaly
