"""DBSCAN outlier scoring as dense pairwise-distance matrix ops.

Reference semantics (plugins/anomaly-detection/anomaly_detection.py:325-349):
sklearn DBSCAN(min_samples=4, eps=2.5e8) over the 1-D throughput values of
one connection; points labeled -1 (noise) are anomalies. The algoCalc
column is a 0.0 placeholder (:312-322).

TPU-first design: general DBSCAN's cluster expansion is data-dependent
control flow, but *noise detection* — all the job needs — is closed-form:

    core_i   = |{j : |x_i − x_j| ≤ eps}| ≥ min_samples   (self included)
    noise_i  = ¬core_i ∧ ¬∃j (core_j ∧ |x_i − x_j| ≤ eps)

i.e. a point is noise iff it is neither a core point nor within eps of
one. That is exactly sklearn's label==-1 set, computed as one [T,T]
masked distance matrix per series — batched matmul-shaped work instead of
sequential region growing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .masked import masked_stddev_samp

DEFAULT_EPS = 2.5e8
DEFAULT_MIN_SAMPLES = 4


@functools.partial(jax.jit, static_argnames=("eps", "min_samples"))
def dbscan_noise(x: jnp.ndarray, mask: jnp.ndarray,
                 eps: float = DEFAULT_EPS,
                 min_samples: int = DEFAULT_MIN_SAMPLES) -> jnp.ndarray:
    """Noise (= anomaly) flags for a padded [S, T] series batch."""
    within = (jnp.abs(x[..., :, None] - x[..., None, :]) <= eps)
    pair_valid = mask[..., :, None] & mask[..., None, :]
    within &= pair_valid
    neighbor_counts = jnp.sum(within, axis=-1)
    core = (neighbor_counts >= min_samples) & mask
    reachable = jnp.any(within & core[..., None, :], axis=-1)
    return mask & ~core & ~reachable


def _interpret() -> bool:
    """Pallas interpreter mode: on for any backend that can't lower
    Mosaic (everything but real TPU). One definition shared by the
    probe and the run path so they can never drift."""
    return jax.default_backend() not in ("tpu", "axon")


@functools.lru_cache(maxsize=1)
def _pallas_usable() -> bool:
    """One-time probe: can the Pallas kernel compile+run on the default
    backend? (True on real TPU; False where Mosaic isn't available —
    the XLA formulation is used there.) Overridable with
    THEIA_TPU_PALLAS=1/0."""
    import os

    flag = os.environ.get("THEIA_TPU_PALLAS", "auto").lower()
    if flag in ("0", "off", "false"):
        return False
    force = flag in ("1", "on", "true")
    if not force and jax.default_backend() not in ("tpu", "axon"):
        return False
    try:
        from .dbscan_pallas import dbscan_noise_pallas

        # Probe the exact configuration dbscan_scores will run with
        # (interpreter mode off-TPU), so a forced enable on a CPU host
        # probes the interpreted kernel, not a doomed Mosaic lowering.
        probe = dbscan_noise_pallas(
            jnp.zeros((2, 4), jnp.float32), jnp.ones((2, 4), bool),
            interpret=_interpret())
        jax.block_until_ready(probe)
        return True
    except Exception:
        if force:
            raise
        return False


def dbscan_scores(x: jnp.ndarray, mask: jnp.ndarray,
                  eps: float = DEFAULT_EPS,
                  min_samples: int = DEFAULT_MIN_SAMPLES,
                  use_pallas: bool | None = None):
    """(algoCalc placeholder zeros, stddev, anomaly) for DBSCAN.

    stddev is still emitted to fill the tadetector row shape (the
    reference computes it in the groupby regardless of algorithm).

    use_pallas=None auto-selects: the tiled Pallas kernel on TPU (no
    [S,T,T] HBM round-trip), the fused XLA formulation elsewhere.
    """
    if use_pallas is None:
        use_pallas = _pallas_usable()
    if use_pallas:
        from .dbscan_pallas import dbscan_noise_pallas

        # Off-TPU, an explicit use_pallas=True runs the kernel in
        # interpreter mode (same code path, testable on the CPU mesh).
        anomaly = dbscan_noise_pallas(
            x, mask, eps=eps, min_samples=min_samples,
            interpret=_interpret())
    else:
        anomaly = dbscan_noise(x, mask, eps=eps,
                               min_samples=min_samples)
    calc = jnp.zeros_like(x)
    std = masked_stddev_samp(x, mask)
    return calc, std, anomaly
