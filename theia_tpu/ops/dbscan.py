"""DBSCAN outlier scoring as dense pairwise-distance matrix ops.

Reference semantics (plugins/anomaly-detection/anomaly_detection.py:325-349):
sklearn DBSCAN(min_samples=4, eps=2.5e8) over the 1-D throughput values of
one connection; points labeled -1 (noise) are anomalies. The algoCalc
column is a 0.0 placeholder (:312-322).

TPU-first design: general DBSCAN's cluster expansion is data-dependent
control flow, but *noise detection* — all the job needs — is closed-form:

    core_i   = |{j : |x_i − x_j| ≤ eps}| ≥ min_samples   (self included)
    noise_i  = ¬core_i ∧ ¬∃j (core_j ∧ |x_i − x_j| ≤ eps)

i.e. a point is noise iff it is neither a core point nor within eps of
one. That is exactly sklearn's label==-1 set, computed as one [T,T]
masked distance matrix per series — batched matmul-shaped work instead of
sequential region growing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .masked import masked_stddev_samp

DEFAULT_EPS = 2.5e8
DEFAULT_MIN_SAMPLES = 4


@functools.partial(jax.jit, static_argnames=("eps", "min_samples"))
def dbscan_noise(x: jnp.ndarray, mask: jnp.ndarray,
                 eps: float = DEFAULT_EPS,
                 min_samples: int = DEFAULT_MIN_SAMPLES) -> jnp.ndarray:
    """Noise (= anomaly) flags for a padded [S, T] series batch."""
    within = (jnp.abs(x[..., :, None] - x[..., None, :]) <= eps)
    pair_valid = mask[..., :, None] & mask[..., None, :]
    within &= pair_valid
    neighbor_counts = jnp.sum(within, axis=-1)
    core = (neighbor_counts >= min_samples) & mask
    reachable = jnp.any(within & core[..., None, :], axis=-1)
    return mask & ~core & ~reachable


def _interpret() -> bool:
    """Pallas interpreter mode: on for any backend that can't lower
    Mosaic (everything but real TPU). One definition shared by the
    probe and the run path so they can never drift."""
    return jax.default_backend() not in ("tpu", "axon")


@functools.lru_cache(maxsize=1)
def _pallas_usable() -> bool:
    """One-time probe: can the Pallas kernel compile+run on the default
    backend? (True on real TPU; False where Mosaic isn't available —
    the XLA formulation is used there.) Overridable with
    THEIA_TPU_PALLAS=1/0."""
    import os

    flag = os.environ.get("THEIA_TPU_PALLAS", "auto").lower()
    if flag in ("0", "off", "false"):
        return False
    force = flag in ("1", "on", "true")
    if not force and jax.default_backend() not in ("tpu", "axon"):
        return False
    try:
        from .dbscan_pallas import dbscan_noise_pallas

        # Probe the exact configuration dbscan_scores will run with
        # (interpreter mode off-TPU), so a forced enable on a CPU host
        # probes the interpreted kernel, not a doomed Mosaic lowering.
        probe = dbscan_noise_pallas(
            jnp.zeros((2, 4), jnp.float32), jnp.ones((2, 4), bool),
            interpret=_interpret())
        jax.block_until_ready(probe)
        return True
    except Exception:
        if force:
            raise
        return False


def dbscan_scores(x: jnp.ndarray, mask: jnp.ndarray,
                  eps: float = DEFAULT_EPS,
                  min_samples: int = DEFAULT_MIN_SAMPLES,
                  use_pallas: bool | None = None):
    """(algoCalc placeholder zeros, stddev, anomaly) for DBSCAN.

    stddev is still emitted to fill the tadetector row shape (the
    reference computes it in the groupby regardless of algorithm).

    use_pallas=None auto-selects: the tiled Pallas kernel on TPU (no
    [S,T,T] HBM round-trip), the fused XLA formulation elsewhere.
    """
    if use_pallas is None:
        use_pallas = _pallas_usable()
    if use_pallas:
        from .dbscan_pallas import dbscan_noise_pallas

        # Off-TPU, an explicit use_pallas=True runs the kernel in
        # interpreter mode (same code path, testable on the CPU mesh).
        anomaly = dbscan_noise_pallas(
            x, mask, eps=eps, min_samples=min_samples,
            interpret=_interpret())
    else:
        anomaly = dbscan_noise(x, mask, eps=eps,
                               min_samples=min_samples)
    calc = jnp.zeros_like(x)
    std = masked_stddev_samp(x, mask)
    return calc, std, anomaly


# -- spatial DBSCAN over [N, F] point embeddings ------------------------
#
# The BASELINE north-star config 3 generalization: "DBSCAN spatial
# anomaly on (srcIP, dstIP, dstPort, bytes) embeddings". Same
# closed-form noise test as the per-series kernel, over euclidean
# distance in feature space, computed in [block, N] tiles so the full
# [N, N] distance matrix never materializes: two lax.scan passes
# (neighbor counts, then core-reachability), each tile one
# matmul-shaped distance evaluation on the MXU.


@functools.partial(jax.jit, static_argnames=("eps", "min_samples",
                                             "block"))
def dbscan_points_noise(points: jnp.ndarray, valid: jnp.ndarray,
                        eps: float, min_samples: int = DEFAULT_MIN_SAMPLES,
                        block: int = 1024) -> jnp.ndarray:
    """Noise flags for [N, F] float points (`valid` masks padding).
    Exact O(N^2) pairwise computation, O(N*block) memory."""
    points = points.astype(jnp.float32)
    n = points.shape[0]
    pad = (-n) % block
    if pad:
        points = jnp.concatenate(
            [points, jnp.zeros((pad, points.shape[1]), jnp.float32)])
        valid = jnp.concatenate([valid, jnp.zeros(pad, bool)])
    nb = points.shape[0] // block
    tiles = points.reshape(nb, block, -1)
    tile_valid = valid.reshape(nb, block)
    eps2 = eps * eps
    x2 = (points * points).sum(-1)

    def within(tile):             # [block, F] -> [block, Npad] bool
        t2 = (tile * tile).sum(-1)
        # HIGHEST precision: the default TPU bf16 matmul's absolute
        # error (~0.4% of the ~scale^2 dot products) would swamp eps^2
        # and corrupt the threshold test.
        d2 = t2[:, None] + x2[None, :] - 2.0 * jnp.matmul(
            tile, points.T, precision=jax.lax.Precision.HIGHEST)
        return d2 <= eps2

    def count_pass(_, tv):
        tile, tvalid = tv
        w = within(tile) & valid[None, :] & tvalid[:, None]
        return None, w.sum(-1)

    _, counts = jax.lax.scan(count_pass, None, (tiles, tile_valid))
    counts = counts.reshape(-1)
    core = (counts >= min_samples) & valid

    def reach_pass(_, tv):
        tile, tvalid = tv
        w = within(tile) & core[None, :] & tvalid[:, None]
        return None, w.any(-1)

    _, reachable = jax.lax.scan(reach_pass, None, (tiles, tile_valid))
    reachable = reachable.reshape(-1)
    noise = valid & ~core & ~reachable
    return noise[:n]
