"""Process-wide metrics registry — Counter / Gauge / Histogram built
for the ingest hot path.

The reference leans on ClickHouse `system.*` tables and Grafana for
operational telemetry; the in-process equivalent must cost ~nothing on
the path it observes, so the primitives are designed around who owns
which lock *already*:

  * Counters are STRIPED: each instance carries N_STRIPES + 1
    float64 slots. A caller that already owns a stripe (an ingest
    detector shard incrementing under its own shard lock) writes its
    slot with NO additional lock — only that caller ever touches it.
    Callers without an owned stripe go through a per-counter lock into
    slot 0. Reads merge the stripes (`sum()`), so totals are exact as
    soon as every writer's increment has retired.
  * Histograms use POWER-OF-TWO buckets backed by fixed numpy arrays
    (one [stripes, buckets] int64 grid + per-stripe sum/count):
    `observe()` is a frexp + three array adds, no allocation, no
    per-bucket search. Bucket bounds are 2^k seconds, so `le` values
    are exact in both float and decimal text exposition.
  * Gauges are cold-path (lock per set); a gauge child can instead be
    bound to a callback evaluated at collect time, for values that are
    cheaper to read on scrape than to maintain on write.

Metric constructors are idempotent per (name): calling
`counter("x", ...)` twice returns the same object, so instrumented
modules declare their handles at import with no registration dance.

Env knobs:

    THEIA_METRICS_STRIPES    stripe count per counter/histogram
                             (default 16)
    THEIA_METRICS_DISABLED   "1"/"true" → every inc/observe/set is a
                             no-op (the bench's overhead A/B switch);
                             also togglable at runtime via
                             disable()/enable()

This module deliberately imports nothing from the rest of theia_tpu
(stdlib + numpy only, plus analysis.lockdep — itself stdlib-only, so
its own locks are witnessed too): utils.faults instruments its
firings here, and utils is imported by everything.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np
from ..analysis.lockdep import named_lock


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


#: owned stripes per counter/histogram (slot 0 is the locked shared
#: slot, so the arrays are N_STRIPES + 1 wide)
N_STRIPES = max(1, _env_int("THEIA_METRICS_STRIPES", 16))

#: histogram bucket bounds: 2^k seconds for k in [EXP_MIN, EXP_MIN +
#: N_BUCKETS) — ~1 µs to ~16 s — plus a +Inf overflow bucket
EXP_MIN = -20
N_BUCKETS = 25

_DISABLED = os.environ.get(
    "THEIA_METRICS_DISABLED", "").strip().lower() in ("1", "true", "yes")


def disable() -> None:
    """Turn every increment/observation into a no-op (collection still
    works — values just stop moving)."""
    global _DISABLED
    _DISABLED = True


def enable() -> None:
    global _DISABLED
    _DISABLED = False


def enabled() -> bool:
    return not _DISABLED


def _label_key(labelnames: Tuple[str, ...],
               labels: Dict[str, object]) -> Tuple[str, ...]:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} != declared {sorted(labelnames)}")
    return tuple(str(labels[n]) for n in labelnames)


class _Metric:
    """Shared child-table machinery for the three metric types."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str,
                 labelnames: Tuple[str, ...] = ()) -> None:
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = named_lock("metrics.children")
        self._default = self._make_child() if not self.labelnames \
            else None

    def _make_child(self):
        raise NotImplementedError

    def labels(self, **labels):
        key = _label_key(self.labelnames, labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child()
            return child

    def children(self) -> List[Tuple[Tuple[str, ...], object]]:
        """(label values, child) pairs, sorted for stable exposition."""
        if self._default is not None:
            return [((), self._default)]
        with self._lock:
            return sorted(self._children.items())

    def zero(self) -> None:
        """Reset every child (tests)."""
        for _, child in self.children():
            child._zero()


class _CounterChild:
    __slots__ = ("_stripes", "_lock")

    def __init__(self) -> None:
        self._stripes = np.zeros(N_STRIPES + 1, np.float64)
        self._lock = named_lock("metrics.counter")

    def inc(self, amount: float = 1.0,
            stripe: Optional[int] = None) -> None:
        """Add `amount`. With `stripe`, the caller asserts it is the
        ONLY concurrent writer of that stripe (it holds the owning
        shard's lock) and skips this counter's lock entirely. A stripe
        outside [0, N_STRIPES) takes the locked path instead — a
        modulo would alias two distinct owners onto one lock-free slot
        and silently lose increments."""
        if _DISABLED:
            return
        if stripe is None or not 0 <= stripe < N_STRIPES:
            with self._lock:
                self._stripes[0] += amount
        else:
            self._stripes[1 + stripe] += amount

    def value(self) -> float:
        return float(self._stripes.sum())

    def _zero(self) -> None:
        with self._lock:
            self._stripes[:] = 0.0


class Counter(_Metric):
    kind = "counter"

    def _make_child(self) -> _CounterChild:
        return _CounterChild()

    def inc(self, amount: float = 1.0,
            stripe: Optional[int] = None) -> None:
        self._default.inc(amount, stripe=stripe)

    def value(self) -> float:
        return self._default.value()


class _GaugeChild:
    __slots__ = ("_value", "_lock", "_callback")

    def __init__(self) -> None:
        self._value = 0.0
        self._lock = named_lock("metrics.gauge")
        self._callback: Optional[Callable[[], float]] = None

    def set(self, value: float) -> None:
        if _DISABLED:
            return
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        if _DISABLED:
            return
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_callback(self, fn: Optional[Callable[[], float]]) -> None:
        """Evaluate `fn` at collect time instead of storing a value —
        for state that is cheaper to read on scrape than to maintain
        on every write."""
        self._callback = fn

    def value(self) -> float:
        if self._callback is not None:
            try:
                return float(self._callback())
            except Exception:
                return float("nan")
        with self._lock:
            return self._value

    def _zero(self) -> None:
        with self._lock:
            self._value = 0.0


class Gauge(_Metric):
    kind = "gauge"

    def _make_child(self) -> _GaugeChild:
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._default.set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default.inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default.dec(amount)

    def set_callback(self, fn: Optional[Callable[[], float]]) -> None:
        self._default.set_callback(fn)

    def value(self) -> float:
        return self._default.value()


def bucket_bounds() -> List[float]:
    """Finite `le` bounds (2^k seconds); +Inf is implicit."""
    return [2.0 ** (EXP_MIN + i) for i in range(N_BUCKETS)]


def bucket_index(value: float) -> int:
    """Index of the first bucket whose bound is >= value (N_BUCKETS =
    the +Inf bucket). A value exactly on a 2^k bound lands IN that
    bucket, matching Prometheus `le` semantics."""
    if value <= 2.0 ** EXP_MIN:
        return 0
    m, e = math.frexp(value)          # value = m * 2^e, m in [0.5, 1)
    k = e - 1 if m == 0.5 else e      # smallest k with value <= 2^k
    idx = k - EXP_MIN
    return idx if idx < N_BUCKETS else N_BUCKETS


class _HistogramChild:
    __slots__ = ("_counts", "_sums", "_ns", "_lock")

    def __init__(self) -> None:
        # rows: stripe slots (0 = locked shared slot); cols: buckets
        # (+Inf last). Fixed allocation — observe() never grows it.
        self._counts = np.zeros((N_STRIPES + 1, N_BUCKETS + 1),
                                np.int64)
        self._sums = np.zeros(N_STRIPES + 1, np.float64)
        self._ns = np.zeros(N_STRIPES + 1, np.int64)
        self._lock = named_lock("metrics.histogram")

    def observe(self, value: float,
                stripe: Optional[int] = None) -> None:
        if _DISABLED:
            return
        b = bucket_index(value)
        if stripe is None or not 0 <= stripe < N_STRIPES:
            # out-of-range stripes take the locked path — aliasing two
            # owners onto one lock-free row would lose observations
            with self._lock:
                self._counts[0, b] += 1
                self._sums[0] += value
                self._ns[0] += 1
        else:
            row = 1 + stripe
            self._counts[row, b] += 1
            self._sums[row] += value
            self._ns[row] += 1

    def snapshot(self) -> Tuple[np.ndarray, float, int]:
        """(cumulative bucket counts incl. +Inf, sum, count) — the
        Prometheus exposition triple."""
        merged = self._counts.sum(axis=0)
        return (np.cumsum(merged),
                float(self._sums.sum()), int(self._ns.sum()))

    def count(self) -> int:
        return int(self._ns.sum())

    def sum(self) -> float:
        return float(self._sums.sum())

    def _zero(self) -> None:
        with self._lock:
            self._counts[:] = 0
            self._sums[:] = 0.0
            self._ns[:] = 0


class Histogram(_Metric):
    kind = "histogram"

    def _make_child(self) -> _HistogramChild:
        return _HistogramChild()

    def observe(self, value: float,
                stripe: Optional[int] = None) -> None:
        self._default.observe(value, stripe=stripe)

    def count(self) -> int:
        return self._default.count()

    def sum(self) -> float:
        return self._default.sum()


class Registry:
    """Name-keyed metric table; constructors are idempotent (same name
    returns the same object; a kind/labels mismatch is a bug and
    raises)."""

    def __init__(self) -> None:
        self._metrics: Dict[str, _Metric] = {}
        self._lock = named_lock("metrics.registry")

    def _get_or_make(self, cls, name: str, help_text: str,
                     labelnames: Tuple[str, ...]):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help_text,
                                              labelnames)
            elif not isinstance(m, cls) or \
                    m.labelnames != tuple(labelnames):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{m.kind} with labels {m.labelnames}")
            return m

    def counter(self, name: str, help_text: str = "",
                labelnames: Iterable[str] = ()) -> Counter:
        return self._get_or_make(Counter, name, help_text,
                                 tuple(labelnames))

    def gauge(self, name: str, help_text: str = "",
              labelnames: Iterable[str] = ()) -> Gauge:
        return self._get_or_make(Gauge, name, help_text,
                                 tuple(labelnames))

    def histogram(self, name: str, help_text: str = "",
                  labelnames: Iterable[str] = ()) -> Histogram:
        return self._get_or_make(Histogram, name, help_text,
                                 tuple(labelnames))

    def collect(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def zero(self) -> None:
        """Reset every metric's values (registrations survive) — test
        isolation for a process-global registry."""
        for m in self.collect():
            m.zero()


#: the process-wide registry every instrumented module registers into
REGISTRY = Registry()


def counter(name: str, help_text: str = "",
            labelnames: Iterable[str] = ()) -> Counter:
    return REGISTRY.counter(name, help_text, labelnames)


def gauge(name: str, help_text: str = "",
          labelnames: Iterable[str] = ()) -> Gauge:
    return REGISTRY.gauge(name, help_text, labelnames)


def histogram(name: str, help_text: str = "",
              labelnames: Iterable[str] = ()) -> Histogram:
    return REGISTRY.histogram(name, help_text, labelnames)
