"""Tracing: trace-aware spans with cross-node context propagation, a
bounded ring of recent spans, and slowest-span exemplars per operation.

Two layers share one ring:

  * **Flight recorder** (PR 3): any code wraps itself in `span("op")`
    (context manager) or `@traced` (decorator); finished spans land in
    a fixed-size ring (newest first on read) and the slowest span per
    operation is kept as an exemplar. Per-thread nesting links a span
    to the operation that enclosed it (`parent`).
  * **Distributed traces** (PR 11): each ingress — a producer
    `POST /ingest`, a coordinator `/query`, a job run, a replication
    ship — mints a W3C-traceparent-style context (128-bit trace id,
    64-bit span id, sampled flag) with `ingress_span(...)`, or adopts
    the one a remote caller stamped on the request
    (`traceparent: 00-<trace>-<span>-<flags>`). Every span that runs
    inside a traced ingress inherits the trace id and records its own
    span id plus its parent's, so the rings of every node in a cluster
    hold the pieces of one cross-node tree — `GET
    /debug/traces?trace=<id>` stitches them (manager/api.py).

Sampling is **head-based and deterministic**: the mint-time decision
is a pure function of the trace id and `THEIA_TRACE_SAMPLE` (default
1.0 — sample everything), so the same trace id decides identically on
every node and every retry. An UNSAMPLED trace still times its spans
but retains nothing and stamps nothing on the wire — with
`THEIA_TRACE_SAMPLE=0` cluster traffic is byte-identical to a build
without tracing.

Span records are plain dicts (JSON-ready for GET /debug/traces):

    {"op", "startTime", "durationMs", "parent", "thread",
     # present under a sampled trace context:
     "traceId", "spanId", "parentSpanId", "node", ...attrs}

Env knobs:

    THEIA_TRACE_RING     ring capacity (default 256; 0 disables
                         recording — span() still times, nothing is
                         kept, cluster-wide)
    THEIA_TRACE_SAMPLE   head-based sampling rate for ingress-minted
                         traces (default 1.0; 0 disables tracing —
                         no contexts, no wire headers)

Recording honors metrics.disable() (one kill switch for the whole obs
plane). Mutating an attr on the yielded span inside the `with` body
(`sp.attrs["rows"] = n`) annotates the record before it is published.
"""

from __future__ import annotations

import collections
import functools
import os
import random
import threading
import time
from typing import Deque, Dict, List, Optional

from . import metrics as _metrics
from ..analysis.lockdep import named_lock


def _ring_capacity() -> int:
    return max(0, _metrics._env_int("THEIA_TRACE_RING", 256))


def _sample_rate(env: Optional[str] = None) -> float:
    """THEIA_TRACE_SAMPLE, optionally overridden by a per-ingress env
    knob (e.g. THEIA_TRACE_SAMPLE_INGEST: high-rate ingresses get
    their own dial so turning them down does not blind the rest)."""
    raw = ""
    if env:
        raw = os.environ.get(env, "")
    if not raw:
        raw = os.environ.get("THEIA_TRACE_SAMPLE", "")
    try:
        return float(raw) if raw else 1.0
    except ValueError:
        return 1.0


#: distinct operations tracked for exemplars (bounds the dict; beyond
#: this, new op names are recorded in the ring but not as exemplars)
MAX_EXEMPLAR_OPS = 128

_lock = named_lock("trace.ring")
_ring: Deque[Dict[str, object]] = collections.deque(
    maxlen=_ring_capacity())
_slowest: Dict[str, Dict[str, object]] = {}
_local = threading.local()

#: this process's node id, stamped on every trace-context span (set by
#: the manager when a cluster is configured; "" on standalone nodes)
_node_id = ""


def set_node_id(node_id: str) -> None:
    global _node_id
    _node_id = str(node_id or "")


def node_id() -> str:
    return _node_id


# -- trace context (W3C traceparent style) ---------------------------------

class TraceContext:
    """One position in a distributed trace: the 128-bit trace id, the
    current span's 64-bit id (what a child or remote callee records as
    its parent), and the head-based sampling decision."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str,
                 sampled: bool) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = bool(sampled)


# ids need uniqueness and sampling spread, not crypto strength — and
# os.urandom is a syscall (~19us in sandboxed containers) paid per
# ingress on the query/ingest hot paths. One urandom-seeded PRNG
# (pid-mixed so forked workers diverge) mints ids at ~1us. CPython's
# getrandbits is C-level and GIL-atomic, so concurrent ingresses
# can't corrupt the generator state.
_id_rng = random.Random(int.from_bytes(os.urandom(16), "big")
                        ^ os.getpid())


def new_trace_id() -> str:
    return f"{_id_rng.getrandbits(128):032x}"


def new_span_id() -> str:
    return f"{_id_rng.getrandbits(64):016x}"


def sampled_for(trace_id: str, rate: Optional[float] = None) -> bool:
    """Deterministic head-based decision: a pure function of the trace
    id and the sampling rate, so every node (and every retry carrying
    the same id) decides identically."""
    if rate is None:
        rate = _sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    try:
        bits = int(trace_id[:8], 16)
    except ValueError:
        return False
    return bits / float(1 << 32) < rate


def format_traceparent(ctx: TraceContext) -> str:
    return (f"00-{ctx.trace_id}-{ctx.span_id}-"
            f"{'01' if ctx.sampled else '00'}")


def parse_traceparent(header: Optional[str]) -> Optional[TraceContext]:
    """`00-<32 hex>-<16 hex>-<2 hex>` → TraceContext, or None for
    anything malformed (a bad header from an old peer must degrade to
    a fresh trace, never to a 500)."""
    if not header:
        return None
    parts = str(header).strip().lower().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if (len(version) != 2 or len(trace_id) != 32
            or len(span_id) != 16 or len(flags) != 2):
        return None
    try:
        int(trace_id, 16), int(span_id, 16), int(flags, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return TraceContext(trace_id, span_id,
                        sampled=bool(int(flags, 16) & 1))


def current_context() -> Optional[TraceContext]:
    """The innermost SAMPLED trace context on this thread (None outside
    any traced ingress, or when the trace is unsampled — callers use
    this to stamp outbound RPCs, and unsampled traces stamp nothing)."""
    stack = getattr(_local, "stack", None)
    if stack:
        ctx = stack[-1].context
        if ctx is not None and ctx.sampled:
            return ctx
    return None


def traceparent() -> Optional[str]:
    """The header value for the current sampled context, or None — the
    one call every outbound transport makes. No sampled context means
    NO header: with sampling off the wire is byte-identical to an
    untraced build."""
    ctx = current_context()
    return format_traceparent(ctx) if ctx is not None else None


class Span:
    """One in-flight operation; finished spans publish as dicts.

    Three flavors share this class:
      * `span(op)` — inherits the thread's context (legacy flight
        recorder when there is none: always published).
      * `ingress_span(op, traceparent=...)` — adopts the remote
        context or mints a fresh one (the trace root).
      * `child_span(op, ctx)` — continues an explicit context on
        another thread (pool workers running one request's fan-out).
    """

    __slots__ = ("op", "attrs", "_t0", "_start", "parent", "context",
                 "_parent_span_id", "_ingress", "_traceparent",
                 "_explicit_ctx", "_sample_env")

    def __init__(self, op: str, attrs: Dict[str, object],
                 ingress: bool = False,
                 traceparent: Optional[str] = None,
                 ctx: Optional[TraceContext] = None,
                 sample_env: Optional[str] = None) -> None:
        self.op = op
        self.attrs = attrs
        self.parent: Optional[str] = None
        self.context: Optional[TraceContext] = None
        self._parent_span_id: Optional[str] = None
        self._ingress = ingress
        self._traceparent = traceparent
        self._explicit_ctx = ctx
        self._sample_env = sample_env
        self._t0 = 0.0
        self._start = 0.0

    def _bind_context(self, enclosing: Optional["Span"]) -> None:
        if self._ingress:
            if _sample_rate(self._sample_env) <= 0.0:
                # tracing off is a LOCAL kill switch: no context
                # minted, nothing retained, no bytes on the wire —
                # even when a peer's sampled traceparent arrives
                self.context = TraceContext("", "", False)
                return
            remote = parse_traceparent(self._traceparent)
            if remote is not None:
                trace_id = remote.trace_id
                self._parent_span_id = remote.span_id
                sampled = remote.sampled
            else:
                trace_id = new_trace_id()
                sampled = sampled_for(trace_id,
                                      _sample_rate(self._sample_env))
            self.context = TraceContext(trace_id, new_span_id(),
                                        sampled)
            return
        parent_ctx = self._explicit_ctx
        if parent_ctx is None and enclosing is not None:
            parent_ctx = enclosing.context
        if parent_ctx is None:
            return                      # legacy span: no trace context
        if not parent_ctx.sampled:
            self.context = TraceContext(parent_ctx.trace_id, "", False)
            return
        self._parent_span_id = parent_ctx.span_id
        self.context = TraceContext(parent_ctx.trace_id, new_span_id(),
                                    True)

    def __enter__(self) -> "Span":
        stack = getattr(_local, "stack", None)
        if stack is None:
            stack = _local.stack = []
        enclosing = stack[-1] if stack else None
        self.parent = enclosing.op if enclosing is not None else None
        self._bind_context(enclosing)
        stack.append(self)
        self._start = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        duration = time.perf_counter() - self._t0
        stack = getattr(_local, "stack", None)
        if stack:
            stack.pop()
        if not _metrics.enabled():
            return
        if self.context is not None and not self.context.sampled:
            return   # unsampled trace: timed, never retained
        record: Dict[str, object] = {
            "op": self.op,
            "startTime": self._start,
            "durationMs": round(duration * 1e3, 4),
            "parent": self.parent,
            "thread": threading.current_thread().name,
        }
        if self.context is not None:
            record["traceId"] = self.context.trace_id
            record["spanId"] = self.context.span_id
            if self._parent_span_id:
                record["parentSpanId"] = self._parent_span_id
            record["node"] = _node_id
        if exc_type is not None:
            record["error"] = exc_type.__name__
        record.update(self.attrs)
        _publish(record)


def _publish(record: Dict[str, object]) -> None:
    # THEIA_TRACE_RING=0 promises NO span retention — exemplars are
    # retained state too (attrs carry stream ids and job names), so
    # the knob turns them off with the ring.
    if not _ring.maxlen:
        return
    op = str(record["op"])
    with _lock:
        _ring.append(record)
        best = _slowest.get(op)
        if best is None:
            if len(_slowest) < MAX_EXEMPLAR_OPS:
                _slowest[op] = record
        elif record["durationMs"] > best["durationMs"]:
            _slowest[op] = record


def record(op: str, start_time: float, duration_s: float,
           **attrs: object) -> None:
    """Publish an already-timed span (hot paths that keep their own
    stopwatches and only record the interesting tail). Under a sampled
    trace context the record joins the trace; under an unsampled one
    it is dropped with the rest of the trace."""
    if not _metrics.enabled():
        return
    rec: Dict[str, object] = {
        "op": op,
        "startTime": start_time,
        "durationMs": round(duration_s * 1e3, 4),
        "parent": current_op(),
        "thread": threading.current_thread().name,
    }
    stack = getattr(_local, "stack", None)
    if stack:
        ctx = stack[-1].context
        if ctx is not None:
            if not ctx.sampled:
                return
            rec["traceId"] = ctx.trace_id
            rec["spanId"] = new_span_id()
            rec["parentSpanId"] = ctx.span_id
            rec["node"] = _node_id
    rec.update(attrs)
    _publish(rec)


def span(op: str, **attrs: object) -> Span:
    """Context manager timing one operation:

        with span("ingest.request", stream=sid) as sp:
            ...
            sp.attrs["rows"] = n
    """
    return Span(op, dict(attrs))


def ingress_span(op: str, traceparent: Optional[str] = None,
                 sample_env: Optional[str] = None,
                 **attrs: object) -> Span:
    """A request-boundary span: adopts the remote trace context from a
    `traceparent` header, or mints a fresh (deterministically sampled)
    one. Everything nested under it — including on other threads via
    child_span — shares the trace id. `sample_env` names an env knob
    that overrides THEIA_TRACE_SAMPLE for THIS ingress (high-rate
    paths get their own dial)."""
    return Span(op, dict(attrs), ingress=True, traceparent=traceparent,
                sample_env=sample_env)


def child_span(op: str, ctx: Optional[TraceContext],
               **attrs: object) -> Span:
    """Continue an explicit context on ANOTHER thread (a pool worker
    running one slice of a request captured with current_context()).
    ctx=None means the originating request was untraced/unsampled —
    the child span times but retains nothing."""
    if ctx is None:
        ctx = TraceContext("", "", False)
    return Span(op, dict(attrs), ctx=ctx)


def traced(op: Optional[str] = None):
    """Decorator form of span(); the op name defaults to the function's
    qualified name."""
    def wrap(fn):
        name = op or fn.__qualname__

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with span(name):
                return fn(*args, **kwargs)
        return inner
    return wrap


def current_op() -> Optional[str]:
    """The innermost span op on this thread (None outside any span)."""
    stack = getattr(_local, "stack", None)
    return stack[-1].op if stack else None


def recent(limit: int = 100) -> List[Dict[str, object]]:
    """Most recent finished spans, newest first."""
    with _lock:
        out = list(_ring)
    out.reverse()
    return out[:max(0, limit)]


def spans_for_trace(trace_id: str) -> List[Dict[str, object]]:
    """Every retained span of one trace, oldest first — the local half
    of the cluster-stitched GET /debug/traces?trace=<id>."""
    tid = str(trace_id).strip().lower()
    with _lock:
        return [dict(rec) for rec in _ring
                if rec.get("traceId") == tid]


def slowest() -> Dict[str, Dict[str, object]]:
    """op → its slowest recorded span (the exemplar)."""
    with _lock:
        return {op: dict(rec) for op, rec in sorted(_slowest.items())}


def reset() -> None:
    """Drop the ring and exemplars (tests)."""
    with _lock:
        _ring.clear()
        _slowest.clear()
