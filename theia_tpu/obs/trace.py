"""Lightweight tracing: spans with per-thread context, a bounded ring
of recent spans, and slowest-span exemplars per operation.

Not a distributed tracer — a flight recorder. Every instrumented
operation wraps itself in `span("op")` (context manager) or `@traced`
(decorator); finished spans land in a fixed-size ring (newest first on
read) and the slowest span seen per operation is kept as an exemplar,
so "why was ingest slow at 14:03" has an answer without a profiler
attached. Per-thread context links a span to the operation that
enclosed it (`parent`), which is how a slow store insert inside a slow
ingest request reads as one story.

Span records are plain dicts (JSON-ready for GET /debug/traces):

    {"op", "startTime", "durationMs", "parent", "thread", ...attrs}

Env knobs:

    THEIA_TRACE_RING   ring capacity (default 256; 0 disables
                       recording — span() still times, nothing is kept)

Recording honors metrics.disable() (one kill switch for the whole obs
plane). Mutating an attr on the yielded span inside the `with` body
(`sp.attrs["rows"] = n`) annotates the record before it is published.
"""

from __future__ import annotations

import collections
import functools
import threading
import time
from typing import Deque, Dict, List, Optional

from . import metrics as _metrics


def _ring_capacity() -> int:
    return max(0, _metrics._env_int("THEIA_TRACE_RING", 256))


#: distinct operations tracked for exemplars (bounds the dict; beyond
#: this, new op names are recorded in the ring but not as exemplars)
MAX_EXEMPLAR_OPS = 128

_lock = threading.Lock()
_ring: Deque[Dict[str, object]] = collections.deque(
    maxlen=_ring_capacity())
_slowest: Dict[str, Dict[str, object]] = {}
_local = threading.local()


class Span:
    """One in-flight operation; finished spans publish as dicts."""

    __slots__ = ("op", "attrs", "_t0", "_start", "parent")

    def __init__(self, op: str, attrs: Dict[str, object]) -> None:
        self.op = op
        self.attrs = attrs
        self.parent: Optional[str] = None
        self._t0 = 0.0
        self._start = 0.0

    def __enter__(self) -> "Span":
        stack = getattr(_local, "stack", None)
        if stack is None:
            stack = _local.stack = []
        self.parent = stack[-1] if stack else None
        stack.append(self.op)
        self._start = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        duration = time.perf_counter() - self._t0
        stack = getattr(_local, "stack", None)
        if stack:
            stack.pop()
        if not _metrics.enabled():
            return
        record: Dict[str, object] = {
            "op": self.op,
            "startTime": self._start,
            "durationMs": round(duration * 1e3, 4),
            "parent": self.parent,
            "thread": threading.current_thread().name,
        }
        if exc_type is not None:
            record["error"] = exc_type.__name__
        record.update(self.attrs)
        _publish(record)


def _publish(record: Dict[str, object]) -> None:
    # THEIA_TRACE_RING=0 promises NO span retention — exemplars are
    # retained state too (attrs carry stream ids and job names), so
    # the knob turns them off with the ring.
    if not _ring.maxlen:
        return
    op = str(record["op"])
    with _lock:
        _ring.append(record)
        best = _slowest.get(op)
        if best is None:
            if len(_slowest) < MAX_EXEMPLAR_OPS:
                _slowest[op] = record
        elif record["durationMs"] > best["durationMs"]:
            _slowest[op] = record


def record(op: str, start_time: float, duration_s: float,
           **attrs: object) -> None:
    """Publish an already-timed span (hot paths that keep their own
    stopwatches and only record the interesting tail)."""
    if not _metrics.enabled():
        return
    rec: Dict[str, object] = {
        "op": op,
        "startTime": start_time,
        "durationMs": round(duration_s * 1e3, 4),
        "parent": current_op(),
        "thread": threading.current_thread().name,
    }
    rec.update(attrs)
    _publish(rec)


def span(op: str, **attrs: object) -> Span:
    """Context manager timing one operation:

        with span("ingest.request", stream=sid) as sp:
            ...
            sp.attrs["rows"] = n
    """
    return Span(op, dict(attrs))


def traced(op: Optional[str] = None):
    """Decorator form of span(); the op name defaults to the function's
    qualified name."""
    def wrap(fn):
        name = op or fn.__qualname__

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with span(name):
                return fn(*args, **kwargs)
        return inner
    return wrap


def current_op() -> Optional[str]:
    """The innermost span op on this thread (None outside any span)."""
    stack = getattr(_local, "stack", None)
    return stack[-1] if stack else None


def recent(limit: int = 100) -> List[Dict[str, object]]:
    """Most recent finished spans, newest first."""
    with _lock:
        out = list(_ring)
    out.reverse()
    return out[:max(0, limit)]


def slowest() -> Dict[str, Dict[str, object]]:
    """op → its slowest recorded span (the exemplar)."""
    with _lock:
        return {op: dict(rec) for op, rec in sorted(_slowest.items())}


def reset() -> None:
    """Drop the ring and exemplars (tests)."""
    with _lock:
        _ring.clear()
        _slowest.clear()
