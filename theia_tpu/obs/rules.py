"""Declarative alert rules evaluated over the stored metrics history.

The scrape-to-store loop (obs/history.py) makes every process metric a
queryable time series; this module closes the alerting half of the
reference's Grafana-over-ClickHouse promise: rules are declared in a
JSON file (`THEIA_ALERT_RULES`, hot-reloaded on mtime change), and
each scrape tick they are evaluated THROUGH THE QUERY PLANE — the same
`table=__metrics__` plans any dashboard issues, so on a routing-mesh
node a rule sees the whole cluster's series (the PR-10 coordinator
fans the evaluation out), and what a rule computed is exactly what an
operator can reproduce with `theia query --table __metrics__`. The
streaming-evaluation framing is arXiv:1607.02480's: rules are standing
queries over the arriving series, not batch jobs.

Two rule types:

  * **threshold** — fold one metric's samples over a trailing
    `window` with `agg` (max / min / mean / rate) and compare against
    `threshold` with `op`. `rate` is the counter increase over the
    window divided by its span, computed PER SERIES (each labels ×
    node child is its own monotone counter, whose `max(valueMax) -
    min(valueMin)` is its exact window increase — raw or rolled up)
    and summed across the matching series; folding distinct children
    into one min/max would difference unrelated levels.
  * **burn_rate** — the SRE multi-window pattern: the rule names two
    (or more) `windows` (short, long) and fires only when EVERY
    window's rate breaches `threshold` — the short window makes
    detection fast, the long window keeps a brief spike from paging.
    With a `denominator` metric the rate is a ratio of increases
    (error budget burn); without one it is an absolute rate/s.

**Hysteresis.** A rule fires only after `for_ticks` consecutive
breached evaluations and resolves only after `clear_ticks` consecutive
clear ones, so a series oscillating around the threshold cannot flap
an alert per tick. Transitions (and only transitions) are published to
the alert ring — the same `/alerts` surface the ingest detectors feed
— as `kind: "rule"` entries carrying rule name, state, observed value,
and threshold.

`per_node: true` groups the evaluation by the `node` column: each node
key tracks its own hysteresis state, so "one node's ingest is slow"
fires for that node and names it, while the healthy nodes stay quiet.

Rule grammar (JSON file: a list, or `{"rules": [...]}`):

    {"name": "ingest-slow",
     "type": "threshold",            // default
     "metric": "theia_ingest_seconds_sum",
     "labels": "",                   // optional exact labels match
     "per_node": true,               // group + alert per node
     "agg": "rate",                  // max | min | mean | rate
     "window": 300,                  // seconds
     "op": ">=",                     // >= > <= < (default >=)
     "threshold": 1.5,
     "for_ticks": 2, "clear_ticks": 2}

    {"name": "error-burn",
     "type": "burn_rate",
     "metric": "theia_ingest_errors_total",
     "denominator": "theia_ingest_batches_total",
     "denominator_labels": "",    // denominator's OWN selector;
                                  // omit to inherit `labels` (the
                                  // mean-latency _sum/_count shape)
     "windows": [300, 3600],
     "threshold": 0.01}

A malformed file never takes working rules down: the previous rule set
keeps evaluating and the parse error is surfaced in the status doc
(`GET /alerts` → `rules.loadError`, `theia alerts --rules`).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from ..schema import METRICS_VALUE_SCALE
from ..utils.logging import get_logger
from . import metrics as _metrics
from ..analysis.lockdep import named_lock

logger = get_logger("obs.rules")

DEFAULT_WINDOW = 300
DEFAULT_FOR_TICKS = 2
DEFAULT_CLEAR_TICKS = 2

_AGGS = ("max", "min", "mean", "rate")
_OPS = {">=": lambda v, t: v >= t, ">": lambda v, t: v > t,
        "<=": lambda v, t: v <= t, "<": lambda v, t: v < t}

_M_EVALS = _metrics.counter(
    "theia_alert_rule_evaluations_total",
    "Alert-rule evaluations, by rule and outcome (ok / error)",
    labelnames=("rule", "result"))
_M_FIRING = _metrics.counter(
    "theia_alert_rule_firing_total",
    "Alert-rule firing transitions (pending->firing), by rule",
    labelnames=("rule",))


class RuleError(ValueError):
    """A rule document is malformed (unknown type/agg/op, missing
    fields) — a config error reported in the status doc, never an
    engine crash."""


class Rule:
    """One validated rule."""

    def __init__(self, doc: Dict[str, object]) -> None:
        if not isinstance(doc, dict):
            raise RuleError(f"rule must be an object, got {doc!r}")
        self.name = str(doc.get("name") or "").strip()
        if not self.name:
            raise RuleError("rule needs a non-empty `name`")
        self.type = str(doc.get("type") or "threshold")
        if self.type not in ("threshold", "burn_rate"):
            raise RuleError(
                f"rule {self.name}: unknown type {self.type!r}")
        self.metric = str(doc.get("metric") or "").strip()
        if not self.metric:
            raise RuleError(f"rule {self.name}: needs a `metric`")
        self.labels = str(doc.get("labels") or "")
        self.per_node = bool(doc.get("per_node"))
        self.op = str(doc.get("op") or ">=")
        if self.op not in _OPS:
            raise RuleError(
                f"rule {self.name}: unknown op {self.op!r} "
                f"(expected one of {sorted(_OPS)})")
        try:
            self.threshold = float(doc["threshold"])
        except (KeyError, TypeError, ValueError):
            raise RuleError(
                f"rule {self.name}: needs a numeric `threshold`")
        self.for_ticks = max(1, int(doc.get("for_ticks",
                                            DEFAULT_FOR_TICKS)))
        self.clear_ticks = max(1, int(doc.get("clear_ticks",
                                              DEFAULT_CLEAR_TICKS)))
        if self.type == "threshold":
            self.agg = str(doc.get("agg") or "max")
            if self.agg not in _AGGS:
                raise RuleError(
                    f"rule {self.name}: unknown agg {self.agg!r} "
                    f"(expected one of {_AGGS})")
            self.windows = (int(doc.get("window", DEFAULT_WINDOW)),)
            self.denominator = None
        else:
            self.agg = "rate"
            wins = doc.get("windows") or (DEFAULT_WINDOW,
                                          DEFAULT_WINDOW * 12)
            if not isinstance(wins, (list, tuple)) or not wins:
                raise RuleError(
                    f"rule {self.name}: `windows` must be a "
                    f"non-empty list of seconds")
            self.windows = tuple(int(w) for w in wins)
            self.denominator = (str(doc["denominator"])
                                if doc.get("denominator") else None)
            # denominator label selector: absent → inherit the
            # numerator's `labels` (the mean-latency _sum/_count
            # pattern); explicit "" → unfiltered (the error-vs-total
            # ratio, where inheriting the error selector would make
            # the ratio identically 1)
            dl = doc.get("denominator_labels")
            self.denominator_labels = (None if dl is None
                                       else str(dl))
        if any(w <= 0 for w in self.windows):
            raise RuleError(
                f"rule {self.name}: windows must be positive")

    def to_doc(self) -> Dict[str, object]:
        doc: Dict[str, object] = {
            "name": self.name, "type": self.type,
            "metric": self.metric, "op": self.op,
            "threshold": self.threshold, "agg": self.agg,
            "windows": list(self.windows),
            "forTicks": self.for_ticks,
            "clearTicks": self.clear_ticks,
        }
        if self.labels:
            doc["labels"] = self.labels
        if self.per_node:
            doc["perNode"] = True
        if self.denominator:
            doc["denominator"] = self.denominator
            if self.denominator_labels is not None:
                doc["denominatorLabels"] = self.denominator_labels
        return doc


class _SeriesState:
    """Hysteresis state for one (rule, node) key."""

    __slots__ = ("firing", "breach_streak", "clear_streak",
                 "since", "value")

    def __init__(self) -> None:
        self.firing = False
        self.breach_streak = 0
        self.clear_streak = 0
        self.since: Optional[float] = None
        self.value: Optional[float] = None


def parse_rules(raw: str) -> List[Rule]:
    """Parse a THEIA_ALERT_RULES document (a JSON list, or an object
    with a `rules` list). Raises RuleError on anything malformed —
    the whole file is rejected, so a typo cannot silently drop one
    rule while keeping its neighbors."""
    try:
        doc = json.loads(raw)
    except ValueError as e:
        raise RuleError(f"rules file is not valid JSON: {e}")
    if isinstance(doc, dict):
        doc = doc.get("rules")
    if not isinstance(doc, list):
        raise RuleError(
            "rules file must be a JSON list (or {\"rules\": [...]})")
    rules = [Rule(d) for d in doc]
    names = [r.name for r in rules]
    if len(set(names)) != len(names):
        raise RuleError(f"duplicate rule names: {names}")
    return rules


class RulesEngine:
    """Evaluates the loaded rule set each scrape tick over the stored
    `__metrics__` series, tracking hysteresis per (rule, node) and
    publishing firing/resolved transitions to the alert sink.

    `execute` is a callable(plan_doc) -> result doc — the manager
    wires the same engine `/query` serves (the cluster coordinator on
    a routing mesh), so rules see exactly what dashboards see."""

    def __init__(self, execute: Callable[[Dict[str, object]],
                                         Dict[str, object]],
                 alert_sink: Optional[Callable[[Dict[str, object]],
                                               None]] = None,
                 path: Optional[str] = None) -> None:
        self.execute = execute
        self.alert_sink = alert_sink
        self.path = (os.environ.get("THEIA_ALERT_RULES", "")
                     if path is None else path)
        self.rules: List[Rule] = []
        self.load_error: Optional[str] = None
        self.loaded_at: Optional[float] = None
        self._mtime: Optional[float] = None
        self._states: Dict[tuple, _SeriesState] = {}
        self._lock = named_lock("rules.engine")
        self.evaluations = 0
        self.transitions = 0
        self.reload()

    # -- loading -----------------------------------------------------------

    def reload(self, force: bool = False) -> bool:
        """(Re)load the rules file when its mtime moved (or `force`).
        A parse error KEEPS the previous rule set evaluating and
        records the error for the status doc. Returns True when the
        active set changed."""
        if not self.path:
            return False
        try:
            mtime = os.stat(self.path).st_mtime
        except OSError as e:
            # recorded unconditionally: the path was explicitly
            # configured, so "unreadable since the very first load"
            # (a typo'd THEIA_ALERT_RULES) must surface in the status
            # doc too, not only "file vanished after a good load"
            self.load_error = f"rules file unreadable: {e}"
            return False
        if not force and mtime == self._mtime:
            return False
        self._mtime = mtime
        try:
            with open(self.path) as f:
                rules = parse_rules(f.read())
        except (OSError, RuleError) as e:
            self.load_error = str(e)
            logger.error("alert rules reload failed (keeping %d "
                         "previous rules): %s", len(self.rules), e)
            return False
        self.load_error = None
        self.loaded_at = time.time()
        with self._lock:
            self.rules = rules
            live = {r.name for r in rules}
            # drop state for removed rules; surviving rules keep
            # their hysteresis across a reload
            self._states = {k: v for k, v in self._states.items()
                            if k[0] in live}
        logger.info("alert rules loaded: %d from %s",
                    len(rules), self.path)
        return True

    # -- evaluation --------------------------------------------------------

    def _window_values(self, rule: Rule, window: int, now: int,
                       metric: Optional[str] = None,
                       labels: Optional[str] = None
                       ) -> Dict[str, Dict[str, float]]:
        """One metric folded over [now-window, now] → {node_key:
        {agg values in NATURAL units}}. node_key is '' unless the
        rule is per_node. The plan ALWAYS groups by (labels, node) —
        distinct label children and distinct nodes are distinct
        monotone series, so `increase` must be computed PER SERIES
        (max - min of one cumulative series is its exact window
        increase) and then summed; folding all children in one
        aggregate would report e.g. level(ok) - level(error), an
        absolute level, not any window's increase. min/max/mean fold
        across series exactly either way. The plan's end is now+1:
        samples stamped at the current tick are part of the window
        that triggered them."""
        metric = rule.metric if metric is None else metric
        labels = rule.labels if labels is None else labels
        filters = [{"column": "metric", "op": "eq", "value": metric}]
        if labels:
            filters.append({"column": "labels", "op": "eq",
                            "value": labels})
        doc: Dict[str, object] = {
            "table": "__metrics__",
            "groupBy": "labels,node",
            "filters": filters,
            "start": int(now) - int(window), "end": int(now) + 1,
            "aggregates": ["max:valueMax", "min:valueMin",
                           "sum:valueSum", "sum:valueCount"],
            "k": 0,
        }
        result = self.execute(doc)
        if result.get("partial"):
            # a degraded fan-out DROPS the missing peers' series —
            # counting their absence as clear ticks would resolve an
            # alert on exactly the node in trouble. Raising makes
            # evaluate() count an error evaluation and freeze state,
            # the same failed-query contract.
            raise RuntimeError(
                "partial cluster result (missing peers: "
                + ",".join(map(str, result.get("missingPeers") or []))
                + ")")
        s = float(METRICS_VALUE_SCALE)
        acc: Dict[str, Dict[str, float]] = {}
        for row in result.get("rows") or []:
            if int(row.get("sum(valueCount)") or 0) <= 0:
                continue   # the empty-window convention row
            key = str(row.get("node", "")) if rule.per_node else ""
            vmax = row["max(valueMax)"] / s
            vmin = row["min(valueMin)"] / s
            cur = acc.get(key)
            if cur is None:
                acc[key] = {"max": vmax, "min": vmin,
                            "vsum": row["sum(valueSum)"] / s,
                            "vcount": float(row["sum(valueCount)"]),
                            "increase": vmax - vmin}
            else:
                cur["max"] = max(cur["max"], vmax)
                cur["min"] = min(cur["min"], vmin)
                cur["vsum"] += row["sum(valueSum)"] / s
                cur["vcount"] += float(row["sum(valueCount)"])
                cur["increase"] += vmax - vmin
        return {k: {"max": v["max"], "min": v["min"],
                    "mean": v["vsum"] / v["vcount"],
                    "increase": v["increase"]}
                for k, v in acc.items()}

    def _rates(self, rule: Rule, window: int, now: int
               ) -> Dict[str, float]:
        """Burn rate per node key for one window: increase/second, or
        an increase ratio when the rule names a denominator. The
        denominator carries its OWN label selector
        (`denominator_labels`): OMITTED inherits the numerator's
        `labels` — the mean-latency `_sum`/`_count` shape, where both
        series share one selector — while an error-vs-total ratio
        whose numerator selects the error child must set it
        explicitly (`""` for unfiltered) or the ratio collapses to
        error/error = 1.0."""
        num = self._window_values(rule, window, now)
        if rule.denominator is None:
            return {k: v["increase"] / window for k, v in num.items()}
        den = self._window_values(rule, window, now,
                                  metric=rule.denominator,
                                  labels=rule.denominator_labels)
        out: Dict[str, float] = {}
        for k, v in num.items():
            d = den.get(k, {}).get("increase", 0.0)
            out[k] = (v["increase"] / d) if d > 0 else 0.0
        return out

    def _evaluate_rule(self, rule: Rule, now: int
                       ) -> Dict[str, tuple]:
        """{node_key: (observed value, breached)} for one rule.
        Threshold rules fold one window with `agg` and compare;
        burn_rate rules breach only when EVERY window's rate breaches
        (the reported value is the short window's — the one that
        moves first)."""
        breach = _OPS[rule.op]
        if rule.type == "threshold":
            window = rule.windows[0]
            vals = self._window_values(rule, window, now)
            out: Dict[str, tuple] = {}
            for k, v in vals.items():
                value = (v["increase"] / window if rule.agg == "rate"
                         else v[rule.agg])
                out[k] = (value, breach(value, rule.threshold))
            return out
        per_window = [self._rates(rule, w, now) for w in rule.windows]
        keys = set().union(*per_window) if per_window else set()
        return {k: (per_window[0].get(k, 0.0),
                    all(breach(pw.get(k, 0.0), rule.threshold)
                        for pw in per_window))
                for k in keys}

    def _transition(self, rule: Rule, node: str, state: _SeriesState,
                    firing: bool, now: int) -> None:
        state.firing = firing
        state.since = float(now)
        self.transitions += 1
        if firing:
            _M_FIRING.labels(rule=rule.name).inc()
        alert: Dict[str, object] = {
            "kind": "rule",
            "rule": rule.name,
            "state": "firing" if firing else "resolved",
            "metric": rule.metric,
            "value": state.value,
            "threshold": rule.threshold,
            "op": rule.op,
            "windows": list(rule.windows),
            "anomalous": bool(firing),
        }
        if node:
            alert["node"] = node
        logger.warning("alert rule %s %s%s: value=%s threshold=%s %s",
                       rule.name,
                       "FIRING" if firing else "resolved",
                       f" [node {node}]" if node else "",
                       state.value, rule.op, rule.threshold)
        if self.alert_sink is not None:
            self.alert_sink(alert)

    def evaluate(self, now: Optional[int] = None) -> int:
        """One evaluation pass over every loaded rule (hot-reloading
        first). Returns the number of state transitions published. A
        rule whose query fails counts an `error` evaluation and keeps
        its current state — a broken store must not mass-resolve
        every alert."""
        now = int(time.time()) if now is None else int(now)
        self.reload()
        transitions = 0
        for rule in list(self.rules):
            try:
                observed = self._evaluate_rule(rule, now)
            except Exception as e:
                _M_EVALS.labels(rule=rule.name, result="error").inc()
                logger.error("rule %s evaluation failed: %s",
                             rule.name, e)
                continue
            _M_EVALS.labels(rule=rule.name, result="ok").inc()
            self.evaluations += 1
            with self._lock:
                keys = set(observed) | {
                    k[1] for k in self._states if k[0] == rule.name}
                for node in keys:
                    st = self._states.setdefault(
                        (rule.name, node), _SeriesState())
                    value, is_breach = observed.get(node,
                                                    (None, False))
                    st.value = value
                    if is_breach:
                        st.breach_streak += 1
                        st.clear_streak = 0
                        if not st.firing and \
                                st.breach_streak >= rule.for_ticks:
                            self._transition(rule, node, st, True,
                                             now)
                            transitions += 1
                    else:
                        st.clear_streak += 1
                        st.breach_streak = 0
                        if st.firing and \
                                st.clear_streak >= rule.clear_ticks:
                            self._transition(rule, node, st, False,
                                             now)
                            transitions += 1
        return transitions

    # -- operator surface --------------------------------------------------

    def firing(self) -> List[Dict[str, object]]:
        with self._lock:
            return [{"rule": name, "node": node,
                     "value": st.value, "since": st.since}
                    for (name, node), st in sorted(self._states.items())
                    if st.firing]

    def doc(self) -> Dict[str, object]:
        """Status doc for GET /alerts (`rules`) and
        `theia alerts --rules`."""
        with self._lock:
            states = []
            for (name, node), st in sorted(self._states.items()):
                entry: Dict[str, object] = {
                    "rule": name,
                    "state": "firing" if st.firing else "ok",
                    "value": st.value,
                    "breachStreak": st.breach_streak,
                }
                if node:
                    entry["node"] = node
                if st.since is not None:
                    entry["since"] = st.since
                states.append(entry)
            out: Dict[str, object] = {
                "path": self.path,
                "rules": [r.to_doc() for r in self.rules],
                "states": states,
                "evaluations": self.evaluations,
                "transitions": self.transitions,
            }
        if self.load_error:
            out["loadError"] = self.load_error
        if self.loaded_at is not None:
            out["loadedAt"] = self.loaded_at
        return out
