"""Self-scraped metrics history: the process Registry as stored time
series (scrape-to-store), with cascaded downsampling retention.

The reference's observability promise is dashboards over the COLUMN
STORE: Grafana never scrapes live processes, it queries ClickHouse
history (PAPER.md §1). `theia top` was the anti-pattern half of our
plane — it diffs two live scrapes, so every question about the past
("was ingest slow an hour ago?") was unanswerable. This module closes
that loop with the ARIMA_PLUS discipline (analytics live INSIDE the
store, arXiv:2510.24452): a supervised loop snapshots the process-wide
Registry every `THEIA_METRICS_SCRAPE_INTERVAL` seconds and appends
rows to the parts-backed `__metrics__` result table — counters as
cumulative totals, histograms as bucket counts + sum + count, gauges
as points — which the existing query plane (local engine, PR-10
scatter-gather, EXPLAIN, slow capture) serves like any other table.

**Downsampling tiers (the ROADMAP item-5 rollup prototype).** Raw 15s
points age into 1m rows after `THEIA_METRICS_ROLLUP_1M_SECONDS` and
1m rows into 1h rows after `THEIA_METRICS_ROLLUP_1H_SECONDS`, by
PART SURGERY: eligible sealed parts are decoded, folded per
(metric, labels, node, kind, time-bucket), and atomically swapped for
one rollup part — readers see either the raw parts or the rollup,
never neither. The fold is EXACT for the mergeable aggregate columns
(valueMin/Max/Sum/Count fold as min/max/sum/sum; `value` keeps the
bucket's last sample, which for cumulative counters is the exact
bucket-end total), so windowed min/max/sum/count/mean queries are
bit-identical whether they scan raw points or rollup parts. Rollup
writes bypass the WAL deliberately: the raw scrape inserts are
journaled, so crash recovery replays raw rows and the next
maintenance pass re-derives the same rollups — journaling both would
double-count the window on replay.

**Retention.** Rows older than `THEIA_METRICS_RETENTION_SECONDS` are
deleted each tick (a short, dedicated horizon — metrics history is an
operational ring, not flow data).

**Cluster behavior.** Every node scrapes ITSELF and stamps its `node`
column, so the PR-10 coordinator answers "p95 ingest latency per
node, last 6h" from any routing-mesh node. On a leader/follower
topology only write-accepting nodes insert (a follower's WAL is a
byte-identical continuation of the leader's log — local writes would
corrupt log matching); followers still run downsampling + retention,
which are WAL-invisible and deterministic, so copies converge.

Staleness contract: stored series are as-of the last scrape tick —
up to one interval behind live `/metrics`; scrape-time gauges are
refreshed through the same hook `GET /metrics` uses, so both
surfaces agree at the tick.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..schema import (METRICS_SCHEMA, METRICS_TABLE,
                      METRICS_VALUE_SCALE, ColumnarBatch)
from ..utils.backoff import capped_backoff
from ..utils.env import env_float, env_int
from ..utils.logging import get_logger
from . import metrics as _metrics

logger = get_logger("obs.history")

DEFAULT_SCRAPE_INTERVAL = 15.0
DEFAULT_RETENTION_SECONDS = 86400
#: raw points roll to 1m rows once older than this
DEFAULT_ROLLUP_1M_SECONDS = 3600
#: 1m rows roll to 1h rows once older than this
DEFAULT_ROLLUP_1H_SECONDS = 21600
#: the memtable force-seals once it spans this much time, so scrape
#: rows become prunable sorted parts on a steady cadence
SEAL_SPAN_SECONDS = 60

#: (target resolution seconds, env knob, default age) — cascade order
ROLLUP_TIERS = (
    (60, "THEIA_METRICS_ROLLUP_1M_SECONDS", DEFAULT_ROLLUP_1M_SECONDS),
    (3600, "THEIA_METRICS_ROLLUP_1H_SECONDS",
     DEFAULT_ROLLUP_1H_SECONDS),
)

_M_ROWS = _metrics.counter(
    "theia_metrics_history_rows_total",
    "Series sample rows appended to the __metrics__ history table by "
    "the scrape loop")
_M_TICKS = _metrics.counter(
    "theia_metrics_history_ticks_total",
    "Metrics-history loop ticks, by outcome",
    labelnames=("result",))
_M_ROLLUPS = _metrics.counter(
    "theia_metrics_history_rollups_total",
    "Downsampling part-surgery passes that replaced raw/finer parts "
    "with a coarser rollup part, by target resolution",
    labelnames=("resolution",))
_M_EXPIRED = _metrics.counter(
    "theia_metrics_history_rows_expired_total",
    "History rows deleted by THEIA_METRICS_RETENTION_SECONDS")


def scrape_interval() -> float:
    """THEIA_METRICS_SCRAPE_INTERVAL (seconds; <= 0 disables)."""
    return env_float("THEIA_METRICS_SCRAPE_INTERVAL",
                     DEFAULT_SCRAPE_INTERVAL)


def _label_string(labelnames: Tuple[str, ...],
                  labelvalues: Tuple[str, ...],
                  extra: Optional[Tuple[str, str]] = None) -> str:
    pairs = list(zip(labelnames, labelvalues))
    if extra is not None:
        pairs.append(extra)
    return ",".join(f"{k}={v}" for k, v in sorted(pairs))


def _scale(value: float) -> int:
    """Float sample → int64 micro-units (NaN — e.g. a gauge callback
    that raised — is recorded as 0 rather than poisoning int math)."""
    v = float(value)
    if v != v or v in (float("inf"), float("-inf")):
        return 0
    return int(round(v * METRICS_VALUE_SCALE))


def snapshot_registry_rows(now: int, node: str = "",
                           registry: Optional[object] = None,
                           resolution: Optional[int] = None
                           ) -> List[Dict[str, object]]:
    """One scrape: the registry's current state as `__metrics__` row
    dicts (raw resolution). Counters/gauges yield one row per child;
    histograms yield `_bucket` (cumulative, `le` in labels), `_sum`,
    and `_count` series — exactly the exposition's series set, so a
    stored query and a live scrape name the same things. `resolution`
    is the CALLER's actual sampling cadence (the loop passes its
    configured interval — re-reading the env here would stamp the
    default on a loop constructed with a different one)."""
    reg = registry if registry is not None else _metrics.REGISTRY
    if resolution is not None:
        res = max(1, int(round(resolution)))
    else:
        res = max(1, int(round(scrape_interval()))) \
            if scrape_interval() > 0 else 1
    rows: List[Dict[str, object]] = []

    def add(metric: str, labels: str, kind: str, value: float) -> None:
        v = _scale(value)
        rows.append({
            "timeInserted": int(now), "metric": metric,
            "labels": labels, "node": node, "kind": kind,
            "resolution": res, "value": v, "valueMin": v,
            "valueMax": v, "valueSum": v, "valueCount": 1})

    for metric in reg.collect():
        for labelvalues, child in metric.children():
            if metric.kind == "histogram":
                cumulative, total, count = child.snapshot()
                bounds = _metrics.bucket_bounds() + [float("inf")]
                for bound, c in zip(bounds, cumulative):
                    le = ("+Inf" if bound == float("inf")
                          else repr(float(bound)))
                    add(f"{metric.name}_bucket",
                        _label_string(metric.labelnames, labelvalues,
                                      extra=("le", le)),
                        "bucket", float(c))
                labels = _label_string(metric.labelnames, labelvalues)
                add(f"{metric.name}_sum", labels, "sum", total)
                add(f"{metric.name}_count", labels, "count",
                    float(count))
            else:
                add(metric.name,
                    _label_string(metric.labelnames, labelvalues),
                    metric.kind, child.value())
    return rows


# -- table resolution ------------------------------------------------------

def metrics_table(db):
    """The `__metrics__` proxy/table of any store topology (inserts go
    through it so replicated fan-out and WAL hooks apply)."""
    return db.result_tables[METRICS_TABLE]


def concrete_metrics_tables(db) -> List[object]:
    """The physical `__metrics__` tables behind a topology — one per
    shard × replica — for the maintenance passes (downsample/retention
    run the same deterministic transform on every copy; a down replica
    heals through the existing truncate+resync path). The replicated
    proxy is unwrapped FIRST: `_ReplicatedTable.__getattr__` forwards
    unknown attributes (including `tables`) to the ACTIVE replica, so
    probing for the sharded shape first would silently maintain only
    the active copy of a replicated-of-sharded store; recursing per
    replica covers every nesting either way."""
    rt = metrics_table(db)
    rdb = getattr(rt, "_db", None)
    if rdb is not None and hasattr(rdb, "replicas"):   # replicated
        out: List[object] = []
        for r in rdb.replicas:
            out.extend(concrete_metrics_tables(r))
        return out
    if hasattr(rt, "tables"):           # sharded DistributedTable
        return list(rt.tables)
    return [rt]


# -- downsampling (part surgery) -------------------------------------------

#: the `__metrics__` fold shape: series identity keys, the exactly-
#: mergeable aggregate columns, and the latest-sample `value` (exact
#: bucket-end totals for cumulative counters)
_FOLD_KEYS = ("metric", "labels", "node", "kind")
_FOLD_MERGE = {"valueMin": "min", "valueMax": "max",
               "valueSum": "sum", "valueCount": "sum"}


def downsample_table(table, now: int,
                     tiers: Sequence[Tuple[int, int]]) -> int:
    """One cascade pass over one concrete PartTable, through the
    SHARED part-surgery loop (query/rollup.py downsample_parts — the
    same sealed-part selection + atomic replace_parts swap the
    rollup-view tiers use). Returns parts replaced; a swap that loses
    to a concurrent merge/demote aborts for this tier and the next
    pass retries against fresh state."""
    from ..query.rollup import downsample_parts, fold_rows_to_buckets

    def fold(batch: ColumnarBatch, resolution: int):
        return fold_rows_to_buckets(
            batch, resolution, _FOLD_KEYS, _FOLD_MERGE,
            time_column="timeInserted",
            resolution_column="resolution",
            last_columns=("value",))

    per = downsample_parts(table, now, tiers, fold,
                           time_column="timeInserted",
                           resolution_column="resolution")
    for resolution, replaced in per.items():
        _M_ROLLUPS.labels(resolution=str(resolution)).inc()
    return sum(per.values())


class MetricsHistoryLoop:
    """Supervised scrape-to-store driver (the RetentionLoop
    discipline): every `THEIA_METRICS_SCRAPE_INTERVAL` seconds one
    `run_once()` — scrape the registry into the `__metrics__` table,
    force-seal a memtable spanning >= SEAL_SPAN_SECONDS, run the
    downsample cascade, expire rows past the retention horizon. A
    failed tick backs off with the shared schedule instead of
    hammering a broken store; `run_once(now=...)` is injectable so
    tests drive synthetic clocks synchronously."""

    def __init__(self, db,
                 interval: Optional[float] = None,
                 node: Optional[str] = None,
                 refresh: Optional[Callable[[], None]] = None,
                 accepts_writes: Optional[Callable[[], bool]] = None,
                 retention_seconds: Optional[int] = None,
                 tiers: Optional[Sequence[Tuple[int, int]]] = None,
                 rules: Optional[object] = None,
                 backoff_cap: float = 300.0) -> None:
        self.db = db
        #: optional RulesEngine (obs/rules.py) evaluated once per
        #: tick, AFTER scrape+maintain so rules see this tick's rows
        self.rules = rules
        self.interval = (scrape_interval() if interval is None
                         else float(interval))
        self._node = node
        self.refresh = refresh
        self.accepts_writes = accepts_writes
        self.retention_seconds = (
            env_int("THEIA_METRICS_RETENTION_SECONDS",
                    DEFAULT_RETENTION_SECONDS)
            if retention_seconds is None else int(retention_seconds))
        self.tiers: Tuple[Tuple[int, int], ...] = tuple(
            tiers if tiers is not None else
            ((res, env_int(knob, default))
             for res, knob, default in ROLLUP_TIERS))
        self.backoff_cap = backoff_cap
        self.ticks = 0
        self.rows_recorded = 0
        self.rows_expired = 0
        self.parts_rolled_up = 0
        self.failures = 0
        self.consecutive_failures = 0
        self.current_delay = self.interval
        self._last_seal = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="theia-metrics-history")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=15)

    def _loop(self) -> None:
        while not self._stop.wait(self.current_delay):
            self.run_once()

    # -- one tick ----------------------------------------------------------

    def node_id(self) -> str:
        if self._node is not None:
            return self._node
        from . import trace as _trace
        return _trace.node_id() or ""

    def scrape(self, now: Optional[int] = None) -> int:
        """Scrape the registry into the table (WAL-journaled insert);
        returns rows appended. Skipped on nodes that must not take
        local writes (a follower's WAL is the leader's log)."""
        if self.accepts_writes is not None and \
                not self.accepts_writes():
            return 0
        now = int(time.time()) if now is None else int(now)
        if self.refresh is not None:
            try:
                self.refresh()
            except Exception:
                pass   # stale scrape-time gauges beat a lost tick
        rows = snapshot_registry_rows(now, node=self.node_id(),
                                      resolution=self.interval)
        if not rows:
            return 0
        table = metrics_table(self.db)
        # a facade without table-level dicts (the sharded
        # DistributedTable routes to per-shard tables, each owning
        # its own) takes a fresh-dict batch — Table.insert adopts
        # foreign dictionaries on append
        batch = ColumnarBatch.from_rows(rows, METRICS_SCHEMA,
                                        getattr(table, "dicts", None))
        table.insert(batch)
        self.rows_recorded += len(rows)
        _M_ROWS.inc(len(rows))
        # force-seal on a time cadence so scrape rows become sorted,
        # prunable parts (size-based sealing would hold ~an hour of
        # samples in the memtable)
        if now - self._last_seal >= SEAL_SPAN_SECONDS:
            for t in concrete_metrics_tables(self.db):
                seal = getattr(t, "seal", None)
                if callable(seal):
                    seal()
            self._last_seal = now
        return len(rows)

    def maintain(self, now: Optional[int] = None) -> Dict[str, int]:
        """Downsample cascade + retention over every concrete table."""
        now = int(time.time()) if now is None else int(now)
        rolled = 0
        expired = 0
        for t in concrete_metrics_tables(self.db):
            rolled += downsample_table(t, now, self.tiers)
            if self.retention_seconds > 0:
                n = t.delete_older_than(now - self.retention_seconds)
                expired += n
        self.parts_rolled_up += rolled
        self.rows_expired += expired
        if expired:
            _M_EXPIRED.inc(expired)
        return {"partsRolledUp": rolled, "rowsExpired": expired}

    def run_once(self, now: Optional[int] = None) -> int:
        """One supervised tick; returns rows recorded (0 on failure)."""
        try:
            recorded = self.scrape(now)
            self.maintain(now)
        except Exception as e:
            self.failures += 1
            self.consecutive_failures += 1
            self.current_delay = capped_backoff(
                max(self.interval, 0.001) * 2, self.backoff_cap,
                self.consecutive_failures)
            _M_TICKS.labels(result="error").inc()
            logger.error(
                "metrics-history tick failed (%d consecutive): %s; "
                "backing off %.1fs", self.consecutive_failures, e,
                self.current_delay)
            return 0
        if self.consecutive_failures:
            logger.info("metrics history recovered after %d failed "
                        "ticks", self.consecutive_failures)
        self.consecutive_failures = 0
        self.current_delay = self.interval
        self.ticks += 1
        _M_TICKS.labels(result="ok").inc()
        if self.rules is not None:
            # rules ride the tick but fail independently: a broken
            # rule set must not back the scrape loop off (the rules
            # engine already counts per-rule evaluation errors)
            try:
                self.rules.evaluate(now)
            except Exception as e:
                logger.error("alert-rule evaluation failed: %s", e)
        return recorded

    def stats(self) -> Dict[str, object]:
        """Operator doc (merged into GET /healthz as `metricsHistory`)."""
        try:
            rows = len(metrics_table(self.db))
        except Exception:
            rows = None
        return {
            "intervalSeconds": self.interval,
            "retentionSeconds": self.retention_seconds,
            "rollupTiers": [
                {"resolutionSeconds": r, "afterSeconds": a}
                for r, a in self.tiers],
            "ticks": self.ticks,
            "rowsRecorded": self.rows_recorded,
            "rowsStored": rows,
            "rowsExpired": self.rows_expired,
            "partsRolledUp": self.parts_rolled_up,
            "failures": self.failures,
        }
