"""Self-observability: metrics + tracing for the theia-tpu process.

The reference platform observes *itself* through ClickHouse `system.*`
tables, klog, and provisioned Grafana dashboards. This package is that
plane for the reproduction:

  * `obs.metrics` — process-wide Counter/Gauge/Histogram registry
    built for the ingest hot path (striped counters, power-of-two
    numpy-backed histograms).
  * `obs.trace`   — lightweight spans with per-thread context, a
    bounded ring of recent spans, and slowest-span exemplars per op.
  * `obs.prom`    — Prometheus text exposition (`GET /metrics` on the
    manager) and the parser `theia top` diffs into live rates.
"""

from . import metrics, prom, trace  # noqa: F401
from .metrics import (  # noqa: F401
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    Registry,
    counter,
    gauge,
    histogram,
)
from .trace import (  # noqa: F401
    child_span,
    current_context,
    ingress_span,
    span,
    traced,
    traceparent,
)
