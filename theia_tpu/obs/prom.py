"""Prometheus text exposition (version 0.0.4) + a matching parser.

`render()` turns the process registry into the text format every
Prometheus-compatible scraper ingests — the role the reference's
Grafana/ClickHouse `system.*` pipeline plays, served here by the
manager as `GET /metrics`. `parse()` is the inverse for the two
in-repo consumers: `theia top` (which diffs successive scrapes into a
live rates table) and the exposition golden tests (render → parse
round-trips exactly).

Rendering rules (the subset of the format we emit):

  * one `# HELP` / `# TYPE` pair per metric, metrics sorted by name,
    children sorted by label values — byte-stable output for a given
    registry state;
  * counters are emitted under their declared name (all ours end in
    `_total` by convention, enforced by a test);
  * histograms emit `<name>_bucket{le="..."}` cumulative counts
    (+Inf last), `<name>_sum`, `<name>_count`;
  * label values are escaped per the spec (backslash, quote, newline).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from . import metrics as _metrics
from . import trace as _trace

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label(value: str) -> str:
    return (value.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _escape_help(value: str) -> str:
    return value.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(labelnames: Tuple[str, ...],
                labelvalues: Tuple[str, ...],
                extra: Optional[Tuple[str, str]] = None) -> str:
    pairs = [f'{n}="{_escape_label(v)}"'
             for n, v in zip(labelnames, labelvalues)]
    if extra is not None:
        pairs.append(f'{extra[0]}="{extra[1]}"')
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v).is_integer() and abs(v) < 2 ** 53:
        return str(int(v))
    return repr(float(v))


def _fmt_le(bound: float) -> str:
    return "+Inf" if math.isinf(bound) else _fmt_value(bound)


def render(registry: Optional[_metrics.Registry] = None) -> str:
    reg = registry if registry is not None else _metrics.REGISTRY
    lines: List[str] = []
    for metric in reg.collect():
        lines.append(f"# HELP {metric.name} "
                     f"{_escape_help(metric.help)}")
        lines.append(f"# TYPE {metric.name} {metric.kind}")
        for labelvalues, child in metric.children():
            if metric.kind == "histogram":
                cumulative, total, count = child.snapshot()
                bounds = _metrics.bucket_bounds() + [float("inf")]
                for bound, c in zip(bounds, cumulative):
                    lab = _fmt_labels(metric.labelnames, labelvalues,
                                      extra=("le", _fmt_le(bound)))
                    lines.append(
                        f"{metric.name}_bucket{lab} {int(c)}")
                lab = _fmt_labels(metric.labelnames, labelvalues)
                lines.append(
                    f"{metric.name}_sum{lab} {_fmt_value(total)}")
                lines.append(f"{metric.name}_count{lab} {count}")
            else:
                lab = _fmt_labels(metric.labelnames, labelvalues)
                lines.append(
                    f"{metric.name}{lab} "
                    f"{_fmt_value(child.value())}")
    return "\n".join(lines) + "\n"


def _parse_labels(raw: str) -> Tuple[Tuple[str, str], ...]:
    """`a="x",b="y"` → (("a","x"), ("b","y")) with unescaping."""
    out: List[Tuple[str, str]] = []
    i = 0
    while i < len(raw):
        eq = raw.index("=", i)
        name = raw[i:eq].strip().lstrip(",").strip()
        if raw[eq + 1] != '"':
            raise ValueError(f"malformed label value near {raw[eq:]!r}")
        j = eq + 2
        buf: List[str] = []
        while raw[j] != '"':
            if raw[j] == "\\":
                nxt = raw[j + 1]
                buf.append({"n": "\n", '"': '"', "\\": "\\"}
                           .get(nxt, "\\" + nxt))
                j += 2
            else:
                buf.append(raw[j])
                j += 1
        out.append((name, "".join(buf)))
        i = j + 1
    return tuple(out)


def parse(text: str) -> Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                             float]:
    """Exposition text → {(series name, sorted label pairs): value}.
    Histogram series parse like any other (`x_bucket`, `x_sum`,
    `x_count` are distinct names). Comment/HELP/TYPE lines are
    skipped."""
    out: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if "{" in line:
            name, rest = line.split("{", 1)
            labels_raw, value_raw = rest.rsplit("}", 1)
            labels = tuple(sorted(_parse_labels(labels_raw)))
        else:
            name, value_raw = line.split(None, 1)
            labels = ()
        value_raw = value_raw.strip()
        if value_raw == "+Inf":
            value = float("inf")
        elif value_raw == "-Inf":
            value = float("-inf")
        else:
            value = float(value_raw)
        out[(name.strip(), labels)] = value
    return out


def traces_doc(limit: int = 100) -> Dict[str, object]:
    """The GET /debug/traces payload: recent spans (newest first) and
    the slowest exemplar per operation."""
    return {
        "recent": _trace.recent(limit),
        "slowest": _trace.slowest(),
    }
