"""theia_tpu — a TPU-native network observability & analytics framework.

Re-implements the capabilities of antrea-io/theia (Kubernetes network flow
observability: flow store, Grafana dashboards, NetworkPolicy recommendation,
throughput anomaly detection) with a JAX/XLA/Pallas compute tier designed for
TPU, instead of the reference's Spark/JVM batch tier.

Subpackages:
  schema    — the 46+-column Antrea flow record schema and columnar encoding
  store     — in-memory columnar flow store with materialized views, TTL,
              retention monitoring and versioned schema migration
  ingest    — native (C++) and pure-python ingest paths into columnar blocks
  ops       — on-device kernels: EWMA/ARIMA/DBSCAN, segment reductions,
              sketches (Count-Min), online k-means
  analytics — the TAD and NPR jobs (reference: plugins/anomaly-detection,
              plugins/policy-recommendation)
  parallel  — device meshes, sharded scoring, sequence-parallel scans
  runner    — the tpu-job-runner with the reference Spark-job CLI contract
  manager   — control plane: REST API groups + job controllers
  cli       — the `theia` command line interface
"""

__version__ = "0.1.0"
