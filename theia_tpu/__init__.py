"""theia_tpu — a TPU-native network observability & analytics framework.

Re-implements the capabilities of antrea-io/theia (Kubernetes network flow
observability: flow store, NetworkPolicy recommendation, throughput anomaly
detection, manager REST API, `theia` CLI) with a JAX/XLA compute tier
designed for TPU, instead of the reference's Spark/JVM batch tier.

Subpackages:
  schema    — the 52-column Antrea flow record schema and columnar encoding
  store     — in-memory columnar flow database: flows + result tables,
              materialized views (pod/node/policy), TTL eviction, retention
              monitor, save/load persistence
  data      — synthetic Antrea flow generator (benchmarks + tests)
  ops       — on-device kernels: EWMA/ARIMA/DBSCAN anomaly scoring,
              masked series statistics, Count-Min-Sketch + online
              k-means, traffic-drop scoring, spatial DBSCAN
  analytics — the TAD, NPR, and drop-detection jobs (reference:
              plugins/anomaly-detection, plugins/policy-recommendation,
              snowflake/udfs drop_detection), plus streaming
              heavy-hitter/DDoS alerts, frequent-pattern mining, and
              spatial flow-embedding outliers
  dashboards — the 8 reference dashboards as server-rendered SVG +
              JSON data API
  parallel  — device meshes and sharded scoring (shard_map over series)
  runner    — the tpu-job-runner honoring the reference Spark-job CLI
              contract, with progress reporting
  manager   — control plane: intelligence/stats API + job controller state
              machine (NEW→SCHEDULED→RUNNING→COMPLETED/FAILED)
  cli       — the `theia` command line interface
  ingest    — ingest paths into columnar blocks
  utils     — shared helpers
"""

__version__ = "0.7.0"
