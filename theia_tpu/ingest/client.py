"""Producer-side ingest client: exactly-once retried POST /ingest.

The manager's overload-control plane (manager/admission.py) answers
over-capacity requests with **429 + Retry-After** and transient
unavailability with **503**; a producer that times out or gets shed
must RETRY THE SAME BATCH — and the retry must not double-insert if
the first attempt actually landed (ack lost on the wire, manager
killed after the WAL append). This client implements that contract so
every producer (the `theia ingest` CLI, bench.py's overload legs,
operator scripts) gets it right once:

  * every batch is stamped `?stream=<id>&seq=<n>` — the manager's
    per-stream dedup window makes a retry idempotent, including
    across a manager kill -9 + WAL recovery;
  * 429 sleeps `Retry-After` (the precise `retryAfterSeconds` from
    the JSON body when present) plus jittered capped backoff, so a
    rejected fleet does not return in lockstep;
  * 503 / connection errors sleep jittered capped backoff alone;
  * any other HTTP error (400 malformed payload, 401/403 auth) is
    permanent and raised immediately — retrying a payload the manager
    called malformed would reset the stream forever.

TFB2 discipline note: blocks from one BlockEncoder carry dictionary
DELTAS, so a rejected block must be retried (not skipped) before the
next block is sent — exactly what `send()` does. Duplicate acks do
not decode on the manager, so a retry after a lost ack leaves the
stream's delta chain consistent.
"""

from __future__ import annotations

import http.client
import json
import os
import random
import ssl
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from typing import Callable, Dict, Optional

from ..obs import trace as _trace
from ..utils.backoff import jittered_backoff
from ..utils.logging import get_logger

logger = get_logger("ingest-client")


class IngestError(Exception):
    """Permanent ingest failure (malformed payload, auth, or retry
    budget exhausted)."""


def parse_retry_after(headers, body: str) -> float:
    """The one place the 429 retry-hint fallback chain lives (shared
    with the CLI's error taxonomy): the precise `retryAfterSeconds`
    float from the JSON body when present, else the integer
    Retry-After header, else 1s."""
    try:
        ra = json.loads(body).get("retryAfterSeconds")
        if ra is not None:
            return max(0.0, float(ra))
    except Exception:
        pass
    try:
        return max(0.0, float(headers.get("Retry-After", "1")))
    except (TypeError, ValueError):
        return 1.0


def default_ingest_format() -> str:
    """Producer-side wire format: THEIA_INGEST_FORMAT = `tblk`
    (default — self-contained columnar blocks, stateless decode) or
    `tfb2` (the stateful dictionary-delta stream format, kept for
    mixed fleets and downgrade paths). The server needs no matching
    knob: it content-negotiates every request by magic bytes."""
    fmt = (os.environ.get("THEIA_INGEST_FORMAT", "") or "tblk")
    fmt = fmt.strip().lower()
    if fmt not in ("tblk", "tfb2"):
        raise ValueError(
            f"THEIA_INGEST_FORMAT {fmt!r} is not tblk|tfb2")
    return fmt


def make_block_encoder(fmt: Optional[str] = None, schema=None,
                       dicts=None):
    """The one producer-side encoder factory (CLI, bench, tests):
    returns a `TblkEncoder` or `BlockEncoder` per `fmt` (default:
    `default_ingest_format()`), both exposing `encode(batch) ->
    bytes`."""
    from .native import FLOW_SCHEMA, BlockEncoder, TblkEncoder
    fmt = fmt or default_ingest_format()
    cls = TblkEncoder if fmt == "tblk" else BlockEncoder
    return cls(schema=schema or FLOW_SCHEMA, dicts=dicts)


class IngestClient:
    """One producer stream against a manager's POST /ingest.

    Cluster-aware: `addr` may be a LIST of manager endpoints (or a
    comma-separated string) — on connection refusal / 5xx the client
    fails over to the next endpoint under the same jittered backoff,
    so a producer rides a leader failover without reconfiguration. A
    `307 + Location` answer (a follower pointing at the current
    leader, or a non-owner node pointing at the shard owner) re-targets
    the client immediately, without burning a backoff sleep."""

    def __init__(self, addr, stream: Optional[str] = None,
                 token: str = "", ca_cert: Optional[str] = None,
                 timeout: float = 30.0, max_attempts: int = 12,
                 backoff_base: float = 0.2, backoff_cap: float = 10.0,
                 rng: Optional[random.Random] = None,
                 sleep: Callable[[float], None] = time.sleep) -> None:
        if isinstance(addr, str):
            addrs = [a.strip() for a in addr.split(",") if a.strip()]
        else:
            addrs = [str(a).strip() for a in addr]
        if not addrs:
            raise ValueError("at least one manager address required")
        self.addrs = [a.rstrip("/") for a in addrs]
        self._addr_i = 0
        self.stream = stream or f"p-{uuid.uuid4().hex[:12]}"
        self.token = token
        self.timeout = timeout
        self.max_attempts = int(max_attempts)
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self._rng = rng if rng is not None else random.Random()
        self._sleep = sleep
        self._ctx = (ssl.create_default_context(cafile=ca_cert)
                     if ca_cert else None)
        self.seq = 0
        self._encoder = None   # lazy, built by send_batch()
        # producer-side ledger (the bench/CLI summary surface)
        self.rows_acked = 0
        self.batches_acked = 0
        self.duplicates = 0
        self.rejected = 0     # 429 responses absorbed
        self.retries = 0      # 503/connection retries absorbed
        self.failovers = 0    # endpoint rotations after a failure
        self.redirects = 0    # 307 Location re-targets honored

    @property
    def addr(self) -> str:
        """The endpoint currently in use (failover/redirect move it)."""
        return self.addrs[self._addr_i]

    def _fail_over(self) -> None:
        """Rotate to the next configured endpoint (no-op with one)."""
        if len(self.addrs) > 1:
            self._addr_i = (self._addr_i + 1) % len(self.addrs)
            self.failovers += 1

    def _redirect_to(self, location: str) -> bool:
        """Honor a Location-style redirect: re-target this client at
        the indicated node's base address (added to the endpoint list
        if new). Returns False for an unusable Location."""
        try:
            parts = urllib.parse.urlsplit(location)
        except ValueError:
            return False
        if not parts.scheme or not parts.netloc:
            return False
        base = f"{parts.scheme}://{parts.netloc}"
        if base not in self.addrs:
            self.addrs.append(base)
        self._addr_i = self.addrs.index(base)
        self.redirects += 1
        return True

    def _headers(self, content_type: str = "application/octet-stream"
                 ) -> Dict[str, str]:
        h = {"Content-Type": content_type}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        # a router forward running inside a sampled trace context
        # stamps the context on the wire, so the owner node's spans
        # join the originating trace; producers outside any trace (the
        # CLI, the bench) add nothing — the wire is unchanged
        tp = _trace.traceparent()
        if tp:
            h["traceparent"] = tp
        return h

    def send(self, payload: bytes, seq: Optional[int] = None,
             stream: Optional[str] = None) -> Dict[str, object]:
        """POST one batch, retrying until acknowledged (or the attempt
        budget runs out). Returns the manager's ack; `duplicate: true`
        means a previous attempt already landed — the ledger counts it
        once either way. `stream` overrides this client's stream id
        for one send (the cluster router stamps origin-scoped
        sub-streams through one shared client per peer)."""
        if stream is None:
            stream = self.stream
            if seq is None:
                self.seq += 1
                seq = self.seq
            else:
                self.seq = max(self.seq, int(seq))
        # an explicit stream with seq=None stays UNSTAMPED (the
        # router forwarding an unstamped producer batch): at-least-
        # once, the pre-seq contract — the auto-increment belongs to
        # the client's own stream only
        last: Optional[str] = None
        redirects_left = len(self.addrs) + 4
        for attempt in range(1, self.max_attempts + 1):
            url = (f"{self.addr}/ingest?"
                   f"stream={urllib.parse.quote(stream)}"
                   + (f"&seq={seq}" if seq is not None else ""))
            try:
                req = urllib.request.Request(
                    url, method="POST", data=payload,
                    headers=self._headers())
                with urllib.request.urlopen(
                        req, timeout=self.timeout,
                        context=self._ctx) as resp:
                    out = json.loads(resp.read())
                if out.get("duplicate"):
                    self.duplicates += 1
                else:
                    self.rows_acked += int(out.get("rows", 0))
                self.batches_acked += 1
                return out
            except urllib.error.HTTPError as e:
                body = e.read().decode(errors="replace")
                if e.code in (307, 308):
                    # "not the node you want": a follower naming the
                    # leader, a non-owner naming the shard owner —
                    # re-target and retry immediately (no backoff; the
                    # named node is presumed healthy)
                    loc = e.headers.get("Location", "")
                    redirects_left -= 1
                    if redirects_left >= 0 and self._redirect_to(loc):
                        logger.v(1).info(
                            "ingest stream=%s redirected to %s",
                            stream, self.addr)
                        continue
                    raise IngestError(
                        f"batch seq={seq} redirect refused "
                        f"(Location {loc!r}: unusable or a redirect "
                        f"loop)")
                if e.code == 429:
                    self.rejected += 1
                    delay = (parse_retry_after(e.headers, body)
                             + jittered_backoff(self.backoff_base,
                                                self.backoff_cap,
                                                attempt, self._rng))
                    last = f"429: {body[:200]}"
                elif e.code >= 500:
                    # 503 unavailable AND 500: the server records the
                    # ack whenever the insert leg succeeded even if
                    # the request then 500'd (detector exception) —
                    # retrying the same seq either lands the batch or
                    # collects the duplicate ack; aborting would lose
                    # it. Only 4xx (malformed payload, auth) is
                    # permanent.
                    self.retries += 1
                    delay = jittered_backoff(self.backoff_base,
                                             self.backoff_cap,
                                             attempt, self._rng)
                    last = f"{e.code}: {body[:200]}"
                    # a 5xx node may be mid-failover: try a peer next
                    self._fail_over()
                else:
                    raise IngestError(
                        f"batch seq={seq} permanently rejected "
                        f"({e.code}): {body[:500]}")
            except (OSError, http.client.HTTPException) as e:
                # Transport failure at ANY phase: URLError (connect),
                # raw socket.timeout/TimeoutError (urllib does NOT
                # wrap read-phase timeouts), RemoteDisconnected /
                # BadStatusLine (mid-response hangup) — all OSError or
                # HTTPException. The retry-with-same-seq discipline
                # makes "timed out but landed" safe: the manager
                # answers the retry duplicate:true.
                self.retries += 1
                delay = jittered_backoff(self.backoff_base,
                                         self.backoff_cap, attempt,
                                         self._rng)
                last = (f"unreachable: "
                        f"{getattr(e, 'reason', None) or e!r}")
                # connection refused / timed out: rotate endpoints so
                # a killed leader doesn't eat the whole retry budget
                self._fail_over()
            if attempt >= self.max_attempts:
                break   # budget spent — don't sleep just to raise
            logger.v(1).info(
                "ingest stream=%s seq=%d attempt %d/%d: %s; retrying "
                "in %.2fs", self.stream, seq, attempt,
                self.max_attempts, last, delay)
            self._sleep(delay)
        raise IngestError(
            f"batch seq={seq} not acknowledged after "
            f"{self.max_attempts} attempts (last: {last})")

    def send_batch(self, batch, seq: Optional[int] = None,
                   stream: Optional[str] = None) -> Dict[str, object]:
        """Encode a ColumnarBatch ONCE (per THEIA_INGEST_FORMAT) and
        send it — the producer-side half of the zero-copy path: with
        the TBLK default these exact column bytes are what admission
        charges, the router gathers, and the WAL journals."""
        if self._encoder is None:
            self._encoder = make_block_encoder()
        return self.send(self._encoder.encode(batch), seq=seq,
                         stream=stream)

    def request_json(self, method: str, path: str,
                     doc: Optional[Dict] = None,
                     timeout: Optional[float] = None
                     ) -> Dict[str, object]:
        """One JSON API request under the SAME endpoint-failover /
        redirect / backoff machinery as `send()` — so a CLI verb (the
        `theia query` read path) works against ANY cluster node:
        connection refusal and 5xx rotate endpoints, 429 honors
        Retry-After, 307/308 re-target at the node named in Location.
        Unlike `send()` this carries no ingest ledger or seq contract;
        it is for idempotent control/read calls."""
        raw = self.request_raw(method, path, doc=doc, timeout=timeout)
        return json.loads(raw) if raw else {}

    def request_text(self, method: str, path: str,
                     timeout: Optional[float] = None) -> str:
        """`request_json` for text bodies (the Prometheus exposition
        `theia top --cluster` scrapes per node) — same failover/
        redirect/backoff machinery, no JSON decode."""
        return self.request_raw(method, path,
                                timeout=timeout).decode(
                                    errors="replace")

    def request_raw(self, method: str, path: str,
                    doc: Optional[Dict] = None,
                    timeout: Optional[float] = None) -> bytes:
        payload = (json.dumps(doc).encode() if doc is not None
                   else None)
        headers = self._headers(content_type="application/json")
        last: Optional[str] = None
        redirects_left = len(self.addrs) + 4
        for attempt in range(1, self.max_attempts + 1):
            try:
                req = urllib.request.Request(
                    self.addr + path, method=method, data=payload,
                    headers=headers)
                with urllib.request.urlopen(
                        req, timeout=timeout or self.timeout,
                        context=self._ctx) as resp:
                    return resp.read()
            except urllib.error.HTTPError as e:
                body = e.read().decode(errors="replace")
                if e.code in (307, 308):
                    loc = e.headers.get("Location", "")
                    redirects_left -= 1
                    if redirects_left >= 0 and self._redirect_to(loc):
                        logger.v(1).info("%s %s redirected to %s",
                                         method, path, self.addr)
                        continue
                    raise IngestError(
                        f"{method} {path} redirect refused "
                        f"(Location {loc!r}: unusable or a loop)")
                if e.code == 429:
                    self.rejected += 1
                    delay = (parse_retry_after(e.headers, body)
                             + jittered_backoff(self.backoff_base,
                                                self.backoff_cap,
                                                attempt, self._rng))
                    last = f"429: {body[:200]}"
                elif e.code >= 500:
                    self.retries += 1
                    delay = jittered_backoff(self.backoff_base,
                                             self.backoff_cap,
                                             attempt, self._rng)
                    last = f"{e.code}: {body[:200]}"
                    self._fail_over()
                else:
                    raise IngestError(
                        f"{method} {path} failed ({e.code}): "
                        f"{body[:500]}")
            except (OSError, http.client.HTTPException) as e:
                self.retries += 1
                delay = jittered_backoff(self.backoff_base,
                                         self.backoff_cap, attempt,
                                         self._rng)
                last = (f"unreachable: "
                        f"{getattr(e, 'reason', None) or e!r}")
                self._fail_over()
            if attempt >= self.max_attempts:
                break
            logger.v(1).info(
                "%s %s attempt %d/%d: %s; retrying in %.2fs",
                method, path, attempt, self.max_attempts, last, delay)
            self._sleep(delay)
        raise IngestError(
            f"{method} {path} not answered after "
            f"{self.max_attempts} attempts (last: {last})")

    def summary(self) -> Dict[str, object]:
        return {
            "stream": self.stream,
            "batchesAcked": self.batches_acked,
            "rowsAcked": self.rows_acked,
            "duplicates": self.duplicates,
            "rejected429": self.rejected,
            "transientRetries": self.retries,
            "failovers": self.failovers,
            "redirects": self.redirects,
        }
