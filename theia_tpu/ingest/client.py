"""Producer-side ingest client: exactly-once retried POST /ingest.

The manager's overload-control plane (manager/admission.py) answers
over-capacity requests with **429 + Retry-After** and transient
unavailability with **503**; a producer that times out or gets shed
must RETRY THE SAME BATCH — and the retry must not double-insert if
the first attempt actually landed (ack lost on the wire, manager
killed after the WAL append). This client implements that contract so
every producer (the `theia ingest` CLI, bench.py's overload legs,
operator scripts) gets it right once:

  * every batch is stamped `?stream=<id>&seq=<n>` — the manager's
    per-stream dedup window makes a retry idempotent, including
    across a manager kill -9 + WAL recovery;
  * 429 sleeps `Retry-After` (the precise `retryAfterSeconds` from
    the JSON body when present) plus jittered capped backoff, so a
    rejected fleet does not return in lockstep;
  * 503 / connection errors sleep jittered capped backoff alone;
  * any other HTTP error (400 malformed payload, 401/403 auth) is
    permanent and raised immediately — retrying a payload the manager
    called malformed would reset the stream forever.

TFB2 discipline note: blocks from one BlockEncoder carry dictionary
DELTAS, so a rejected block must be retried (not skipped) before the
next block is sent — exactly what `send()` does. Duplicate acks do
not decode on the manager, so a retry after a lost ack leaves the
stream's delta chain consistent.
"""

from __future__ import annotations

import http.client
import json
import random
import ssl
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from typing import Callable, Dict, Optional

from ..utils.backoff import jittered_backoff
from ..utils.logging import get_logger

logger = get_logger("ingest-client")


class IngestError(Exception):
    """Permanent ingest failure (malformed payload, auth, or retry
    budget exhausted)."""


def parse_retry_after(headers, body: str) -> float:
    """The one place the 429 retry-hint fallback chain lives (shared
    with the CLI's error taxonomy): the precise `retryAfterSeconds`
    float from the JSON body when present, else the integer
    Retry-After header, else 1s."""
    try:
        ra = json.loads(body).get("retryAfterSeconds")
        if ra is not None:
            return max(0.0, float(ra))
    except Exception:
        pass
    try:
        return max(0.0, float(headers.get("Retry-After", "1")))
    except (TypeError, ValueError):
        return 1.0


class IngestClient:
    """One producer stream against a manager's POST /ingest."""

    def __init__(self, addr: str, stream: Optional[str] = None,
                 token: str = "", ca_cert: Optional[str] = None,
                 timeout: float = 30.0, max_attempts: int = 12,
                 backoff_base: float = 0.2, backoff_cap: float = 10.0,
                 rng: Optional[random.Random] = None,
                 sleep: Callable[[float], None] = time.sleep) -> None:
        self.addr = addr.rstrip("/")
        self.stream = stream or f"p-{uuid.uuid4().hex[:12]}"
        self.token = token
        self.timeout = timeout
        self.max_attempts = int(max_attempts)
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self._rng = rng if rng is not None else random.Random()
        self._sleep = sleep
        self._ctx = (ssl.create_default_context(cafile=ca_cert)
                     if ca_cert else None)
        self.seq = 0
        # producer-side ledger (the bench/CLI summary surface)
        self.rows_acked = 0
        self.batches_acked = 0
        self.duplicates = 0
        self.rejected = 0     # 429 responses absorbed
        self.retries = 0      # 503/connection retries absorbed

    def _headers(self) -> Dict[str, str]:
        h = {"Content-Type": "application/octet-stream"}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    def send(self, payload: bytes,
             seq: Optional[int] = None) -> Dict[str, object]:
        """POST one batch, retrying until acknowledged (or the attempt
        budget runs out). Returns the manager's ack; `duplicate: true`
        means a previous attempt already landed — the ledger counts it
        once either way."""
        if seq is None:
            self.seq += 1
            seq = self.seq
        else:
            self.seq = max(self.seq, int(seq))
        url = (f"{self.addr}/ingest?"
               f"stream={urllib.parse.quote(self.stream)}&seq={seq}")
        last: Optional[str] = None
        for attempt in range(1, self.max_attempts + 1):
            try:
                req = urllib.request.Request(
                    url, method="POST", data=payload,
                    headers=self._headers())
                with urllib.request.urlopen(
                        req, timeout=self.timeout,
                        context=self._ctx) as resp:
                    out = json.loads(resp.read())
                if out.get("duplicate"):
                    self.duplicates += 1
                else:
                    self.rows_acked += int(out.get("rows", 0))
                self.batches_acked += 1
                return out
            except urllib.error.HTTPError as e:
                body = e.read().decode(errors="replace")
                if e.code == 429:
                    self.rejected += 1
                    delay = (parse_retry_after(e.headers, body)
                             + jittered_backoff(self.backoff_base,
                                                self.backoff_cap,
                                                attempt, self._rng))
                    last = f"429: {body[:200]}"
                elif e.code >= 500:
                    # 503 unavailable AND 500: the server records the
                    # ack whenever the insert leg succeeded even if
                    # the request then 500'd (detector exception) —
                    # retrying the same seq either lands the batch or
                    # collects the duplicate ack; aborting would lose
                    # it. Only 4xx (malformed payload, auth) is
                    # permanent.
                    self.retries += 1
                    delay = jittered_backoff(self.backoff_base,
                                             self.backoff_cap,
                                             attempt, self._rng)
                    last = f"{e.code}: {body[:200]}"
                else:
                    raise IngestError(
                        f"batch seq={seq} permanently rejected "
                        f"({e.code}): {body[:500]}")
            except (OSError, http.client.HTTPException) as e:
                # Transport failure at ANY phase: URLError (connect),
                # raw socket.timeout/TimeoutError (urllib does NOT
                # wrap read-phase timeouts), RemoteDisconnected /
                # BadStatusLine (mid-response hangup) — all OSError or
                # HTTPException. The retry-with-same-seq discipline
                # makes "timed out but landed" safe: the manager
                # answers the retry duplicate:true.
                self.retries += 1
                delay = jittered_backoff(self.backoff_base,
                                         self.backoff_cap, attempt,
                                         self._rng)
                last = (f"unreachable: "
                        f"{getattr(e, 'reason', None) or e!r}")
            if attempt >= self.max_attempts:
                break   # budget spent — don't sleep just to raise
            logger.v(1).info(
                "ingest stream=%s seq=%d attempt %d/%d: %s; retrying "
                "in %.2fs", self.stream, seq, attempt,
                self.max_attempts, last, delay)
            self._sleep(delay)
        raise IngestError(
            f"batch seq={seq} not acknowledged after "
            f"{self.max_attempts} attempts (last: {last})")

    def summary(self) -> Dict[str, object]:
        return {
            "stream": self.stream,
            "batchesAcked": self.batches_acked,
            "rowsAcked": self.rows_acked,
            "duplicates": self.duplicates,
            "rejected429": self.rejected,
            "transientRetries": self.retries,
        }
