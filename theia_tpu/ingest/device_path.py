"""Device-resident scoring pipeline: cross-shard micro-batch
coalescing, one fused device step per micro-batch, double-buffered
host↔device transfer.

The sharded detector engine (manager/ingest.py) scores each request's
batch shard by shard under shard locks — per batch it pays N_shards ×
(2 dispatches + 2 fetches) and allocates fresh tile/feature arrays
every time (the transfer leg measured allocation-bound at 0.49 GB/s).
This engine replaces that hot loop with a pipeline:

  1. **Coalescing.** Score requests from all ingest shards land in a
     bounded queue; the scorer thread drains whatever is waiting (up
     to THEIA_FUSED_RING_ROWS rows) and gathers the key/value columns
     of every pending block *directly from the decode output* into
     reused staging buffers — no per-shard ColumnarBatch copies (the
     sharded path slices all ~52 columns per shard; this path touches
     only the ~10 the detectors read).
  2. **One fused step.** The whole coalesced micro-batch — every
     shard's slice — is scored by ops/fused_detector.fused_step: EWMA
     update + Welford band + CMS heavy-hitter update + k-means shape
     outliers + alert thresholding in ONE jitted dispatch, with
     per-connection StreamState (and the CMS/centroid state) living on
     device between micro-batches instead of round-tripping.
  3. **Double buffering.** Staging buffers alternate between two
     generations and a dispatched step's results are fetched only
     after the NEXT step has been dispatched, so host staging/decode
     of batch N+1 overlaps device scoring of batch N. The queue is
     bounded: its depth is exported as a gauge and feeds the PR 5
     admission pressure ladder, so sustained device slowness browns
     out scoring instead of growing an invisible backlog.

Alert parity: the per-shard math is the sharded engine's own
(ops/fused_detector.py reuses streaming._update and the sketch
helpers), the host-side slot mapping and tick bucketing are the same
code (StreamingDetector.build_plan), and shards are thresholded in
index order against the same eventually-consistent cross-shard totals
— so a producer that awaits each ack (one block per step, the
documented determinism contract) gets bit-identical alert streams from
either engine. Under concurrent producers, coalescing folds multiple
blocks into one statistical micro-batch for the heavy-hitter leg
(volumes sum once, centroids take one mini-batch step) while the
per-connection EWMA/Welford recurrence still sees every point in
per-shard arrival order, tick by tick.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..analytics import heavy_hitters as _hh
from ..analytics.streaming import (
    CONNECTION_KEY_COLUMNS,
    StreamPlan,
    alert_record,
)
from ..obs import metrics as _metrics
from ..ops import fused_detector as _ops
from ..utils import get_logger
from ..utils.env import env_float, env_int

logger = get_logger("device_path")

_M_STEP = _metrics.histogram(
    "theia_fused_step_seconds",
    "One fused scoring step: staging + single-dispatch kernel over "
    "every shard's coalesced slice + result fetch")
_M_QDEPTH = _metrics.gauge(
    "theia_fused_queue_depth",
    "Score requests waiting for the fused pipeline (bounded queue; "
    "feeds the admission pressure ladder)")
_M_ROWS = _metrics.histogram(
    "theia_fused_batch_rows", "Rows per coalesced fused step")
_M_BLOCKS = _metrics.histogram(
    "theia_fused_coalesced_blocks",
    "Decoded blocks coalesced into one fused step")
_M_STEPS = _metrics.counter(
    "theia_fused_steps_total", "Fused scoring steps dispatched")

#: positions of the IP columns within CONNECTION_KEY_COLUMNS (the
#: heavy-hitter leg reads them out of the already-gathered key matrix
#: instead of gathering the batch columns a second time)
_KEY_SRC = CONNECTION_KEY_COLUMNS.index("sourceIP")
_KEY_DST = CONNECTION_KEY_COLUMNS.index("destinationIP")

#: decode-batch columns gathered besides the connection key (value /
#: time / heavy-hitter features) — everything the detectors read
_EXTRA_COLUMNS = ("flowEndSeconds", "octetDeltaCount",
                  "packetDeltaCount")

#: mirror of manager/ingest.py MAX_ALERTS (kept literal: the manager
#: imports this module, not the other way round) — only the newest
#: survive the ring, so only those are worth decoding
_MAX_DESCRIBED_ALERTS = 1000


class _StagingPool:
    """Reused host staging buffers, double-buffered by generation.

    `get` hands out the prefix view of a power-of-two-capacity buffer
    keyed by (tag, trailing shape, dtype) — a steady workload hits the
    same buckets every step and never allocates (the 'pinned, reused
    host staging arrays' the transfer leg needs; allocation was the
    bound, not the copy). Two generations alternate so the arrays
    staged for step N are not rewritten until step N+1 has been
    dispatched AND step N's results fetched — a backend that aliases
    host memory into device buffers (CPU XLA's zero-copy path) never
    sees a buffer mutate under a live computation.
    """

    def __init__(self, generations: int = 2) -> None:
        self._gens: List[Dict[tuple, np.ndarray]] = [
            {} for _ in range(generations)]
        self._live = 0
        self.hits = 0
        self.misses = 0

    def advance(self) -> None:
        self._live = (self._live + 1) % len(self._gens)

    def get(self, tag, shape, dtype) -> np.ndarray:
        shape = tuple(shape)
        cap = (_hh.pad_bucket(shape[0], minimum=8),) + shape[1:]
        key = (tag, cap[1:], np.dtype(dtype).str)
        pool = self._gens[self._live]
        arr = pool.get(key)
        if arr is None or arr.shape[0] < cap[0]:
            arr = pool[key] = np.empty(cap, dtype)
            self.misses += 1
        else:
            self.hits += 1
        return arr[:shape[0]]


class _ScoreItem:
    """One request's remapped batch waiting for (or riding) a step."""

    __slots__ = ("batch", "shard_rows", "future", "t_arrival", "rows")

    def __init__(self, batch, shard_rows: Dict[int, Optional[np.ndarray]],
                 t_arrival: float) -> None:
        self.batch = batch
        #: shard index -> row indices (None = every row of the batch)
        self.shard_rows = shard_rows
        self.future: Future = Future()
        self.t_arrival = t_arrival
        self.rows = len(batch)


class _ShardWork:
    """Host-side bookkeeping for one shard's slice of one step."""

    __slots__ = ("shard", "splan", "hplan", "times", "vals",
                 "item_of", "row_of", "segments", "dst", "n")

    def __init__(self, shard, splan, hplan, times, vals, item_of,
                 row_of, segments, dst, n) -> None:
        self.shard = shard
        self.splan = splan
        self.hplan = hplan
        self.times = times
        self.vals = vals
        self.item_of = item_of
        self.row_of = row_of
        #: [(item index, start, stop)] coalescing segments, item order
        self.segments = segments
        self.dst = dst
        self.n = n


class _Step:
    """A dispatched-but-unresolved fused step (the in-flight half of
    the double buffer)."""

    __slots__ = ("items", "work", "outputs", "t0")

    def __init__(self, items, work, outputs, t0) -> None:
        self.items = items
        self.work = work
        self.outputs = outputs
        self.t0 = t0


class FusedDetectorEngine:
    """Drop-in scoring engine behind IngestManager
    (THEIA_DETECTOR_ENGINE=fused): same DetectorShard state objects,
    same (hh_alerts, conn_alerts, n_conn) contract as the sharded
    score path, scored through the coalescing fused pipeline."""

    def __init__(self, shards: Sequence, shard_totals: np.ndarray,
                 on_scored: Optional[Callable[[int, int], None]] = None,
                 queue_capacity: Optional[int] = None,
                 max_step_rows: Optional[int] = None,
                 step_timeout: Optional[float] = None) -> None:
        if not shards:
            raise ValueError("fused engine needs at least one shard")
        alphas = {s.streaming.alpha for s in shards}
        vcols = {s.streaming.value_column for s in shards}
        if len(alphas) != 1 or len(vcols) != 1:
            raise ValueError(
                "fused engine requires a uniform detector config "
                f"across shards (alpha={alphas}, value={vcols})")
        self.shards = list(shards)
        self.alpha = float(next(iter(alphas)))
        self.value_column = next(iter(vcols))
        #: the injectable latency clock (tests pin it); alert latency
        #: is enqueue -> resolve, the whole pipeline a point traversed
        self.clock = self.shards[0].streaming.clock
        self._totals = shard_totals
        self._on_scored = on_scored
        self.queue_capacity = (queue_capacity
                               or env_int("THEIA_FUSED_QUEUE", 8))
        self.max_step_rows = (max_step_rows
                              or env_int("THEIA_FUSED_RING_ROWS",
                                         131072))
        self.step_timeout = (step_timeout
                             or env_float("THEIA_FUSED_STEP_TIMEOUT",
                                          120.0))
        self._queue: _queue.Queue = _queue.Queue(self.queue_capacity)
        self._staging = _StagingPool()
        self._use_pallas, self._interpret = _ops.pallas_mode()
        self.steps = 0
        self.coalesced_blocks = 0
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="theia-fused-scorer")
        self._thread.start()

    # -- public surface --------------------------------------------------

    def queue_depth(self) -> int:
        """Live pipeline backlog — the admission pressure signal."""
        return self._queue.qsize()

    def stats(self) -> Dict[str, object]:
        """Operator doc for /healthz ingest.engine and `theia top`."""
        return {
            "queueDepth": self.queue_depth(),
            "queueCapacity": self.queue_capacity,
            "maxStepRows": self.max_step_rows,
            "steps": self.steps,
            "coalescedBlocks": self.coalesced_blocks,
            "pallas": bool(self._use_pallas),
            "stagingHits": self._staging.hits,
            "stagingMisses": self._staging.misses,
        }

    def score(self, scored, shard_ids: Optional[np.ndarray]
              ) -> Tuple[List, List[Dict[str, object]], int]:
        """Queue one globally-remapped batch for the next fused step
        and wait for its slice of the results. Same contract as the
        sharded path's score_batch tail: (heavy-hitter alerts,
        described connection alerts, raw connection-alert count)."""
        if self._closed.is_set():
            raise RuntimeError("fused scoring engine is closed")
        if len(scored) == 0:
            return [], [], 0
        if shard_ids is None:
            shard_rows: Dict[int, Optional[np.ndarray]] = {0: None}
        else:
            shard_rows = {}
            for s in range(len(self.shards)):
                idx = np.flatnonzero(shard_ids == s)
                if idx.size:
                    shard_rows[s] = (None if idx.size == len(scored)
                                     else idx)
        item = _ScoreItem(scored, shard_rows, self.clock())
        try:
            self._queue.put(item, timeout=self.step_timeout)
        except _queue.Full:
            raise RuntimeError(
                f"fused scoring queue stalled (capacity "
                f"{self.queue_capacity}, no step completed in "
                f"{self.step_timeout:.0f}s)")
        _M_QDEPTH.set(self._queue.qsize())
        deadline = time.monotonic() + self.step_timeout
        while True:
            try:
                # short poll instead of one long wait: an item that
                # slipped into the queue after the scorer's final
                # straggler drain (score/close race) must fail fast,
                # not sit out the whole step timeout
                return item.future.result(timeout=0.25)
            except _FutureTimeout:
                if not self._thread.is_alive() \
                        and not item.future.done():
                    raise RuntimeError(
                        "fused scoring engine closed")
                if time.monotonic() >= deadline:
                    raise RuntimeError(
                        f"fused scoring step not resolved within "
                        f"{self.step_timeout:.0f}s")

    def close(self, timeout: float = 10.0) -> None:
        """Stop the scorer (idempotent): queued work is still scored,
        then the thread exits; anything enqueued after close fails."""
        if self._closed.is_set() and not self._thread.is_alive():
            return
        try:
            self._queue.put_nowait(None)   # wake + mark closed
        except _queue.Full:
            self._closed.set()
        self._thread.join(timeout=timeout)
        self._closed.set()

    # -- scorer thread ---------------------------------------------------

    def _run(self) -> None:
        pending: Optional[_Step] = None
        while True:
            try:
                got = self._queue.get(timeout=0.05)
            except _queue.Empty:
                if pending is not None:
                    self._finish(pending)
                    pending = None
                if self._closed.is_set():
                    break
                continue
            if got is None:
                self._closed.set()
                continue
            items = [got]
            rows = got.rows
            # Coalesce whatever else is already waiting (bounded by
            # the ring row capacity) — cross-shard blocks from any
            # number of producers fold into ONE device step.
            while rows < self.max_step_rows:
                try:
                    nxt = self._queue.get_nowait()
                except _queue.Empty:
                    break
                if nxt is None:
                    self._closed.set()
                    break
                items.append(nxt)
                rows += nxt.rows
            _M_QDEPTH.set(self._queue.qsize())
            try:
                step = self._dispatch(items, rows)
            except Exception as e:   # noqa: BLE001 — fail the batch, not the loop
                logger.error("fused step dispatch failed: %s", e,
                             exc_info=True)
                for it in items:
                    if not it.future.done():
                        it.future.set_exception(e)
                if pending is not None:
                    # the failed dispatch already advanced the staging
                    # generation, so the NEXT successful dispatch
                    # would land back on the pending step's buffers —
                    # resolve it before that can happen
                    self._finish(pending)
                    pending = None
                continue
            # Double buffer: resolve the PREVIOUS step only after this
            # one is in flight — host staging of N+1 just overlapped
            # device scoring of N.
            if pending is not None:
                self._finish(pending)
            pending = step
            if self._queue.empty():
                # idle: don't sit on results waiting for traffic
                self._finish(pending)
                pending = None
        if pending is not None:
            self._finish(pending)
        # fail any stragglers enqueued after close
        while True:
            try:
                it = self._queue.get_nowait()
            except _queue.Empty:
                break
            if it is not None and not it.future.done():
                it.future.set_exception(
                    RuntimeError("fused scoring engine closed"))

    def _dispatch(self, items: List[_ScoreItem],
                  total_rows: int) -> Optional[_Step]:
        t0 = time.perf_counter()
        self._staging.advance()
        work: List[_ShardWork] = []
        states = []
        inputs = []
        for s, shard in enumerate(self.shards):
            segments: List[Tuple[int, Optional[np.ndarray], int, int]] = []
            n_s = 0
            for ii, it in enumerate(items):
                if s not in it.shard_rows:
                    continue
                idx = it.shard_rows[s]
                cnt = len(it.batch) if idx is None else len(idx)
                if cnt == 0:
                    continue
                segments.append((ii, idx, n_s, n_s + cnt))
                n_s += cnt
            if n_s == 0:
                continue

            def st(tag, shape, dtype, _s=s):
                return self._staging.get((_s, tag), shape, dtype)

            # Direct gather from the decode output into the staging
            # ring: only the columns the detectors read, no per-shard
            # ColumnarBatch copies.
            k6 = st("k6", (n_s, len(CONNECTION_KEY_COLUMNS)), np.int64)
            vals = st("vals", (n_s,), np.float64)
            times = st("times", (n_s,), np.int64)
            oct64 = st("oct", (n_s,), np.float64)
            pkt64 = st("pkt", (n_s,), np.float64)
            item_of = st("item", (n_s,), np.int32)
            row_of = st("row", (n_s,), np.int64)
            for ii, idx, a, b in segments:
                cols = items[ii].batch.columns
                for j, c in enumerate(CONNECTION_KEY_COLUMNS):
                    col = cols[c]
                    k6[a:b, j] = col if idx is None else col[idx]
                for buf, name in (
                        (vals, self.value_column),
                        (times, _EXTRA_COLUMNS[0]),
                        (oct64, _EXTRA_COLUMNS[1]),
                        (pkt64, _EXTRA_COLUMNS[2])):
                    col = cols[name]
                    buf[a:b] = col if idx is None else col[idx]
                item_of[a:b] = ii
                if idx is None:
                    row_of[a:b] = np.arange(b - a)
                else:
                    row_of[a:b] = idx
            splan = shard.streaming.build_plan(k6, vals, staging=st)
            if splan is None:
                # every row's series was dropped (capacity overflow):
                # the heavy-hitter half still advances, the streaming
                # half rides a no-op tile (all-padding slots gather-
                # clamp and scatter-drop, active all False)
                splan = StreamPlan(
                    slots=np.full(64, shard.streaming.capacity,
                                  np.int32),
                    x=np.zeros((1, 64), np.float32),
                    active=np.zeros((1, 64), bool),
                    row_idx=np.full((1, 64), -1, np.int64),
                    present=np.zeros(0, np.int64))
            hplan = _hh.build_hh_plan(
                k6[:, _KEY_DST], k6[:, _KEY_SRC], oct64, pkt64,
                staging=st)
            states.append(_ops.ShardStepState(
                shard.streaming.state, shard.heavy.cms,
                shard.heavy.kmeans))
            inputs.append(_ops.ShardInputs(
                slots=splan.slots, x=splan.x, active=splan.active,
                keys=hplan.keys, vols=hplan.vols, q=hplan.q,
                feats=hplan.feats, valid=hplan.valid))
            work.append(_ShardWork(shard, splan, hplan, times, vals,
                                   item_of, row_of, segments,
                                   k6[:, _KEY_DST], n_s))
        if not work:
            for it in items:
                if not it.future.done():
                    it.future.set_result(([], [], 0))
            return None
        new_states, outputs = self._call_kernel(tuple(states),
                                                tuple(inputs))
        # State stays device-resident between micro-batches: assign
        # the (possibly still-computing, async-dispatched) handles now.
        for w, ns in zip(work, new_states):
            w.shard.streaming.state = ns.stream
            w.shard.heavy.cms = ns.cms
            w.shard.heavy.kmeans = ns.km
        self.steps += 1
        self.coalesced_blocks += len(items)
        _M_STEPS.inc()
        _M_BLOCKS.observe(len(items))
        _M_ROWS.observe(total_rows)
        return _Step(items, work, outputs, t0)

    def _call_kernel(self, states, inputs):
        if self._use_pallas:
            try:
                return _ops.fused_step(states, inputs,
                                       alpha=self.alpha,
                                       use_pallas=True,
                                       interpret=self._interpret)
            except Exception as e:   # noqa: BLE001
                logger.error(
                    "Pallas fused kernel failed (%s); falling back to "
                    "the jnp scan permanently for this engine", e)
                self._use_pallas = False
        return _ops.fused_step(states, inputs, alpha=self.alpha,
                               use_pallas=False)

    def _finish(self, step: Optional[_Step]) -> None:
        if step is None:
            return
        items = step.items
        try:
            outs = jax.device_get(step.outputs)
            _M_STEP.observe(time.perf_counter() - step.t0)
            now = self.clock()
            per_hh: List[List] = [[] for _ in items]
            per_conn: List[List] = [[] for _ in items]
            per_n = [0] * len(items)
            dst_dict = None
            for it in items:
                d = it.batch.dicts.get("destinationIP")
                if d is not None:
                    dst_dict = d
                    break
            # Shards threshold in index order (work is built that
            # way): shard s sees this step's fresh totals for shards
            # < s and the previous totals for shards > s — the same
            # eventually-consistent discipline as the sharded path's
            # in-order visit.
            for w, out in zip(step.work, outs):
                if self._on_scored is not None:
                    self._on_scored(w.n, w.shard.index)
                extra = float(self._totals.sum()
                              - self._totals[w.shard.index])
                hits = w.shard.heavy.threshold(
                    w.hplan, out.est, out.total, out.dist, extra,
                    dst_dict)
                self._totals[w.shard.index] = \
                    w.shard.heavy.total_volume
                for alert, row, code in hits:
                    if row >= 0:
                        # shape outlier: row-scoped, exact attribution
                        per_hh[int(w.item_of[row])].append(alert)
                    else:
                        # heavy hitter: batch-scoped — attribute to
                        # every coalesced block that carried the
                        # destination (each would have alerted had it
                        # been scored alone; alerts are rare, the
                        # membership probe is per alert, not per row)
                        for ii, _, a, b in w.segments:
                            if np.any(w.dst[a:b] == code):
                                per_hh[ii].append(alert)
                anom = np.asarray(out.anomaly)
                if anom.any():
                    for t, c in np.argwhere(anom):
                        r = int(w.splan.row_idx[t, c])
                        if r < 0:
                            continue
                        ii = int(w.item_of[r])
                        per_n[ii] += 1
                        per_conn[ii].append(
                            (w, r, int(w.splan.present[c])))
            for ii, it in enumerate(items):
                latency = now - it.t_arrival
                conn: List[Dict[str, object]] = []
                # newest-survive cap, mirroring the sharded path's
                # per-request MAX_ALERTS decode bound
                for w, r, slot in per_conn[ii][-_MAX_DESCRIBED_ALERTS:]:
                    row = int(w.row_of[r])
                    d = alert_record(slot, w.times[r], w.vals[r],
                                     latency)
                    for c in CONNECTION_KEY_COLUMNS:
                        cd = it.batch.dicts.get(c)
                        code = int(it.batch[c][row])
                        d[c] = (cd.decode_one(code)
                                if cd is not None else code)
                    d["kind"] = "connection_anomaly"
                    conn.append(d)
                if not it.future.done():
                    it.future.set_result(
                        (per_hh[ii], conn, per_n[ii]))
        except Exception as e:   # noqa: BLE001 — fail the step's batches, not the loop
            logger.error("fused step resolve failed: %s", e,
                         exc_info=True)
            if self._use_pallas:
                # Async dispatch means a Pallas kernel that compiles
                # but fails at EXECUTION surfaces here (device_get),
                # not in _call_kernel — disable it so the next step
                # takes the jnp path instead of re-dispatching the
                # same broken kernel forever.
                logger.error(
                    "disabling the Pallas fused kernel after a "
                    "resolve-time failure; subsequent steps use the "
                    "jnp scan")
                self._use_pallas = False
            for it in items:
                if not it.future.done():
                    it.future.set_exception(e)
