"""Ingest paths: wire bytes -> columnar blocks (native C++ + fallback)."""

from .native import TsvDecoder, encode_tsv, native_available

__all__ = ["TsvDecoder", "encode_tsv", "native_available"]
