"""Ingest paths: wire bytes -> columnar blocks (native C++ + fallback),
plus the exactly-once producer client (client.py)."""

from .client import IngestClient, IngestError, default_ingest_format, \
    make_block_encoder
from .native import (
    BLOCK_MAGIC,
    TBLK_MAGIC,
    BlockEncoder,
    TblkEncoder,
    TsvDecoder,
    decode_tblk,
    encode_tsv,
    native_available,
)

__all__ = ["BLOCK_MAGIC", "TBLK_MAGIC", "BlockEncoder", "TblkEncoder",
           "TsvDecoder", "decode_tblk", "encode_tsv",
           "native_available", "IngestClient", "IngestError",
           "default_ingest_format", "make_block_encoder"]
