"""Ingest paths: wire bytes -> columnar blocks (native C++ + fallback),
plus the exactly-once producer client (client.py)."""

from .client import IngestClient, IngestError
from .native import (
    BLOCK_MAGIC,
    BlockEncoder,
    TsvDecoder,
    encode_tsv,
    native_available,
)

__all__ = ["BLOCK_MAGIC", "BlockEncoder", "TsvDecoder", "encode_tsv",
           "native_available", "IngestClient", "IngestError"]
