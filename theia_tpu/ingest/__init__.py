"""Ingest paths: wire bytes -> columnar blocks (native C++ + fallback)."""

from .native import (
    BLOCK_MAGIC,
    BlockEncoder,
    TsvDecoder,
    encode_tsv,
    native_available,
)

__all__ = ["BLOCK_MAGIC", "BlockEncoder", "TsvDecoder", "encode_tsv",
           "native_available"]
