"""Flow-state working-set tier: hot device slots, hashed-columnar DRAM
spill, exact promote-on-re-arrival.

The streaming detector's per-connection state lives in fixed
device-resident slot arrays; before this module, slot-capacity
overflow was a hard drop (`theia_detector_series_dropped_total`) — a
cluster tracking tens of millions of concurrent connections sheds
exactly the long-tail flows where scans and exfiltration live
(ROADMAP open item 3). This module adopts the working-set
architecture of arXiv:1902.04143: keep the *active* flow set hot,
spill idle state to a compact DRAM tier, restore it exactly on
re-arrival — so the slot budget becomes a memory-bandwidth knob
instead of a correctness cliff.

Three tiers per detector shard:

  hot   the existing device slot arrays (`StreamState`), now with a
        host-side per-slot last-touched generation counter. Occupancy
        crossing `THEIA_STATE_HOT_WATERMARK` evicts LRU-by-generation
        victims down to `THEIA_STATE_EVICT_TO` — one jitted gather per
        eviction batch, never per-row Python.
  warm  evicted state blocks in DRAM, stored in the parts/WAL
        width-reduced column encoding (`store/wire.py` — the same
        codec the WAL record body and part files use), keyed by the
        packed connection key. Promotion on re-arrival decodes only
        the state columns of only the blocks that hold hits and
        scatters them back in the same jitted step that zeroes
        brand-new slots — promoted state is bit-identical to
        never-evicted state (float32 survives the f64 column round
        trip exactly).
  cold  every spill is ALSO appended to the `detstate` result table,
        which rides the standard store planes (WAL journal, snapshot,
        replication, resync) — so spilled state survives kill -9 and
        failover. Warm blocks idle past `THEIA_STATE_AGE_OUT_SECONDS`
        are dropped from DRAM; their keys fall back to a hash-indexed
        cold map resolved against the table on re-arrival.

Identity across restarts: dictionary codes are NOT restart-stable, so
the durable rows key on `keyHash` — a 64-bit BLAKE2b digest of the
string-resolved connection 6-tuple — and recovery rebuilds each
shard's cold index from the table by re-hashing the stored strings.

Batching contract: `WorkingSetTier.assign` runs inside
`StreamingDetector.build_plan` — i.e. inside the fused micro-batch
step's host half AND each sharded-engine shard pass — and is
O(distinct keys) Python + O(1) extra device dispatches per
micro-batch, the same discipline as the slot mapping it replaces
(profile-asserted in tests/test_state_tier.py).

Fault sites: ``state.spill`` / ``state.promote`` fire BEFORE any tier
mutation, so an injected error fails the batch with state intact (the
retry re-runs the identical spill/promote); ``state.age_out`` is
caught and deferred — aging out is maintenance, not correctness.
"""

from __future__ import annotations

import hashlib
import os
import time
import weakref
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..obs import metrics as _metrics
from ..store import wire as _wire
from ..utils import get_logger
from ..utils.faults import FaultError
from ..utils.faults import fire as _fire_fault

logger = get_logger("state_tier")

#: the durable spill table's name in store.RESULT_TABLE_SCHEMAS —
#: registering it there is what buys WAL/snapshot/replication/resync
#: coverage for free
DETSTATE_TABLE = "detstate"

#: the state columns of one spilled slot, in StreamState field order —
#: both the warm block encoding and the detstate table use these names
STATE_COLUMNS = ("ewma", "count", "mean", "m2")

_M_EVICTIONS = _metrics.counter(
    "theia_state_evictions_total",
    "Hot detector slots spilled to the warm DRAM tier "
    "(LRU-by-generation eviction at the occupancy watermark)")
_M_PROMOTIONS = _metrics.counter(
    "theia_state_promotions_total",
    "Spilled connection series promoted back to hot device slots on "
    "re-arrival, by source tier",
    labelnames=("tier",))
_M_AGE_OUTS = _metrics.counter(
    "theia_state_age_outs_total",
    "Warm spill-block entries aged out of DRAM to the cold "
    "(store-resident) tier")
_M_OVERFLOW = _metrics.counter(
    "theia_state_overflow_total",
    "Distinct keys a single micro-batch could not admit because every "
    "hot slot was touched by that same batch (the keys retry on their "
    "next arrival — not a permanent drop)")

#: live tiers, for the scrape-time occupancy gauges (weak: a closed
#: manager's tiers drop out of the sums on their own)
_LIVE_TIERS: "weakref.WeakSet[WorkingSetTier]" = weakref.WeakSet()

_G_HOT = _metrics.gauge(
    "theia_state_hot_series",
    "Connection series currently resident in hot device slots, "
    "summed over every live working-set tier in the process")
_G_SPILLED = _metrics.gauge(
    "theia_state_spilled_series",
    "Connection series currently spilled out of hot slots "
    "(warm DRAM blocks + cold store-only index), summed over every "
    "live working-set tier")
_G_HOT.set_callback(
    lambda: float(sum(t.n_hot for t in _LIVE_TIERS)))
_G_SPILLED.set_callback(
    lambda: float(sum(t.spilled_count for t in _LIVE_TIERS)))

#: generation value marking a free slot (never a victim candidate;
#: real generations count up from 1)
_FREE = np.iinfo(np.int64).max


def enabled() -> bool:
    """THEIA_STATE_TIER=1 opts the manager's detector shards into the
    working-set tier. Off by default: the legacy drop-at-capacity
    behavior is load-bearing for sizing experiments and is what the
    seed tests assert."""
    return os.environ.get("THEIA_STATE_TIER", "").strip().lower() in (
        "1", "on", "true", "yes")


class TierConfig(NamedTuple):
    """Eviction/aging policy knobs (all THEIA_STATE_* envs)."""
    hot_watermark: float = 0.9    # evict when occupancy would cross
    evict_to: float = 0.7         # ...down to this occupancy
    age_out_seconds: float = 900.0  # warm block idle age; 0 = never

    @classmethod
    def from_env(cls) -> "TierConfig":
        d = cls()
        return cls(
            hot_watermark=float(os.environ.get(
                "THEIA_STATE_HOT_WATERMARK", d.hot_watermark)),
            evict_to=float(os.environ.get(
                "THEIA_STATE_EVICT_TO", d.evict_to)),
            age_out_seconds=float(os.environ.get(
                "THEIA_STATE_AGE_OUT_SECONDS", d.age_out_seconds)))


def key_hash(resolved: Tuple) -> int:
    """Restart-stable 64-bit identity of one string-resolved
    connection 6-tuple (the `keyHash` column). BLAKE2b, not crc32:
    at tens of millions of tracked flows a 32-bit space collides with
    near certainty (birthday bound ~77k)."""
    h = hashlib.blake2b("|".join(str(p) for p in resolved).encode(),
                        digest_size=8)
    return int.from_bytes(h.digest(), "little", signed=True)


def default_resolver(keys: np.ndarray) -> List[Tuple]:
    """Resolver for standalone detectors (tests, bench): the raw int64
    key codes ARE the identity — stable for the process lifetime,
    which is all an un-stored tier needs. The manager supplies a
    string-decoding resolver for restart-stable durable identity."""
    return [tuple(int(v) for v in row) for row in keys]


class _SpillBlock:
    """One eviction batch in the warm tier: the state columns as an
    encoded TBLK column section (width-reduced, the WAL/parts codec),
    plus numpy sidecars for the keys so classification and age-out
    never decode the body."""

    __slots__ = ("body", "keys", "hashes", "seqs", "live", "n_live",
                 "spilled_at")

    def __init__(self, body: bytes, keys: np.ndarray,
                 hashes: np.ndarray, seqs: np.ndarray,
                 spilled_at: float) -> None:
        self.body = body
        self.keys = keys            # [N, 6] int64 packed key rows
        self.hashes = hashes        # [N] int64 keyHash
        self.seqs = seqs            # [N] int64 spill sequence
        self.live = np.ones(len(keys), bool)
        self.n_live = len(keys)
        self.spilled_at = spilled_at


class SpillStore:
    """Adapter between a tier and the `detstate` result table — the
    cold/durable plane. Rows accumulate per spill (latest `seq` wins
    on read); `prune` compacts superseded rows."""

    #: columns a cold-promote scan materializes (numeric only — no
    #: string decode on the promote path)
    _SCAN_COLUMNS = ("keyHash", "seq") + STATE_COLUMNS

    def __init__(self, table) -> None:
        self.table = table

    def append(self, rows: Sequence[Dict[str, object]]) -> None:
        """Journal one eviction batch (Table.insert → WAL before
        visibility: no spill acknowledgement without durability)."""
        self.table.insert_rows(rows)

    def lookup(self, hashes: Sequence[int]) -> Dict[int, Tuple]:
        """keyHash → (ewma, count, mean, m2) at the LATEST spill seq,
        for the given hashes. One vectorized isin over the table scan;
        Python only over the matched rows (cold hits are rare)."""
        if not hashes or self.table is None or len(self.table) == 0:
            return {}
        data = self.table.select(columns=list(self._SCAN_COLUMNS))
        kh = np.asarray(data["keyHash"], np.int64)
        idx = np.flatnonzero(np.isin(kh, np.asarray(list(hashes),
                                                    np.int64)))
        best: Dict[int, Tuple[int, Tuple]] = {}
        seqs = data["seq"]
        for i in idx:
            h, s = int(kh[i]), int(seqs[i])
            cur = best.get(h)
            if cur is None or s > cur[0]:
                best[h] = (s, tuple(
                    data[c][i] for c in STATE_COLUMNS))
        return {h: v for h, (_, v) in best.items()}

    def prune(self) -> int:
        """Delete rows superseded by a later spill of the same key
        (store maintenance — recovery and cold promotes only ever read
        the latest seq). Returns rows deleted."""
        if self.table is None or len(self.table) == 0:
            return 0
        data = self.table.select(columns=["keyHash", "seq"])
        kh = np.asarray(data["keyHash"], np.int64)
        sq = np.asarray(data["seq"], np.int64)
        order = np.lexsort((sq, kh))
        stale = np.zeros(len(kh), bool)
        # in (hash, seq) order, every row whose successor shares its
        # hash is superseded
        stale[order[:-1]] = kh[order[1:]] == kh[order[:-1]]
        if not stale.any():
            return 0
        try:
            return self.table.delete_where(stale)
        except ValueError:
            # an insert raced the scan; next maintenance round prunes
            return 0

    @staticmethod
    def recover_cold_indexes(table, n_shards: int,
                             shard_of: Callable[[str], int]
                             ) -> List[Dict[int, int]]:
        """Rebuild each shard's cold index (keyHash → latest seq) from
        the recovered table — the startup half of crash recovery. The
        shard assignment re-derives from the destination STRING
        (restart-stable), never from dictionary codes. O(rows) once at
        startup."""
        indexes: List[Dict[int, int]] = [dict()
                                         for _ in range(n_shards)]
        if table is None or len(table) == 0:
            return indexes
        data = table.select(columns=["keyHash", "seq",
                                     "destinationIP"])
        dst_d = data.dicts.get("destinationIP")
        dst = data["destinationIP"]
        kh = data["keyHash"]
        sq = data["seq"]
        for i in range(len(kh)):
            s = shard_of(dst_d.decode_one(int(dst[i]))
                         if dst_d is not None else str(dst[i]))
            idx = indexes[s % n_shards]
            h, q = int(kh[i]), int(sq[i])
            if q >= idx.get(h, -1):
                idx[h] = q
        return indexes


class WorkingSetTier:
    """The per-shard three-tier state store. Single-writer, like the
    detector it attaches to: the caller serializes `assign` (shard
    lock on the sharded engine, the one scorer thread on the fused
    engine), so the tier needs no lock of its own."""

    def __init__(self, config: Optional[TierConfig] = None,
                 store: Optional[SpillStore] = None,
                 key_resolver: Optional[Callable] = None,
                 cold_index: Optional[Dict[int, int]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 wall_clock: Callable[[], float] = time.time) -> None:
        self.config = config or TierConfig()
        self.store = store
        self.resolver = key_resolver or default_resolver
        self.clock = clock
        self.wall_clock = wall_clock
        self.det = None
        self.capacity = 0
        self.gen = np.zeros(0, np.int64)
        self._free: List[int] = []
        self.generation = 0
        self.seq = 0
        self._next_block = 0
        self.n_hot = 0
        #: packed key bytes → (block id, row) for warm-resident state
        self.warm: Dict[bytes, Tuple[int, int]] = {}
        self.blocks: Dict[int, _SpillBlock] = {}
        #: keyHash → latest spill seq for store-only (cold) state;
        #: seeded by SpillStore.recover_cold_indexes after a restart
        self.cold: Dict[int, int] = dict(cold_index or {})
        self.evictions = 0
        self.promotions_warm = 0
        self.promotions_cold = 0
        self.age_outs = 0
        self.overflow = 0
        _LIVE_TIERS.add(self)

    # -- wiring -----------------------------------------------------------

    def attach(self, detector) -> None:
        """Bind to a StreamingDetector (called from its __init__):
        slot bookkeeping switches from bump allocation to the tier's
        free list + generation array."""
        self.det = detector
        self.capacity = detector.capacity
        self.gen = np.full(self.capacity, _FREE, np.int64)
        self._free = list(range(self.capacity - 1, -1, -1))
        detector._slot_keys = [None] * self.capacity

    @property
    def spilled_count(self) -> int:
        """Series currently out of hot slots — the admission plane's
        spill-pressure signal and the `theia top` 'spilled' figure."""
        return len(self.warm) + len(self.cold)

    def stats(self) -> Dict[str, object]:
        return {
            "hotSeries": self.n_hot,
            "warmSeries": len(self.warm),
            "coldSeries": len(self.cold),
            "warmBlocks": len(self.blocks),
            "evictions": self.evictions,
            "promotions": self.promotions_warm + self.promotions_cold,
            "ageOuts": self.age_outs,
            "overflow": self.overflow,
        }

    # -- the per-micro-batch entry ----------------------------------------

    def assign(self, det, uniq: np.ndarray) -> np.ndarray:
        """Slot assignment for one micro-batch's distinct keys
        (`uniq`: the packed-void unique key array from build_plan).
        Hot hits refresh their generation; misses are promoted from
        warm/cold or admitted fresh — after evicting LRU victims if
        occupancy would cross the watermark. Returns int64 slots
        (≥ 0 except transient overflow, which returns -1 for this
        batch only). All device work is one gather (eviction) plus one
        scatter (promotion + zero-init), whatever the batch holds."""
        self.generation += 1
        g = self.generation
        u = len(uniq)
        key_bytes = [uniq[i].tobytes() for i in range(u)]
        slots = np.fromiter(
            (det._slots.get(kb, -1) for kb in key_bytes),
            dtype=np.int64, count=u)
        hot = slots >= 0
        if hot.any():
            self.gen[slots[hot]] = g
        miss = np.flatnonzero(~hot)
        if miss.size:
            keys_mat = uniq.view(np.int64).reshape(u, 6)
            slots[miss] = self._admit(det, g,
                                      [key_bytes[i] for i in miss],
                                      keys_mat[miss])
        self._age_out_tick()
        return slots

    # -- admission: classify → evict → promote+allocate --------------------

    def _admit(self, det, g: int, miss_keys: List[bytes],
               miss_mat: np.ndarray) -> np.ndarray:
        n_miss = len(miss_keys)
        # classify: warm by packed key; otherwise resolve + hash once
        # per missing key to probe the cold index
        warm_hits: List[Tuple[int, int, int]] = []   # (i, block, row)
        rest: List[int] = []
        for i, kb in enumerate(miss_keys):
            e = self.warm.get(kb)
            if e is not None:
                warm_hits.append((i, e[0], e[1]))
            else:
                rest.append(i)
        cold_hits: List[Tuple[int, int]] = []        # (i, keyHash)
        if rest and self.cold:
            resolved = self.resolver(miss_mat[rest])
            still_new: List[int] = []
            for j, i in enumerate(rest):
                h = key_hash(resolved[j])
                if h in self.cold:
                    cold_hits.append((i, h))
                else:
                    still_new.append(i)
            rest = still_new

        # evict before allocating, if admitting the misses would cross
        # the watermark; victims are LRU-by-generation among occupied
        # slots NOT touched by this batch
        high = int(self.config.hot_watermark * self.capacity)
        if self.n_hot + n_miss > max(high, 1):
            want = self.n_hot + n_miss \
                - int(self.config.evict_to * self.capacity)
            cand = np.flatnonzero(self.gen < g)   # occupied, untouched
            k = min(max(want, 0), cand.size)
            if k > 0:
                part = np.argpartition(self.gen[cand], k - 1)[:k]
                self._spill(det, cand[part])

        # one scatter restores promoted state AND zero-inits brand-new
        # slots; assemble its payload in miss order
        ewma = np.zeros(n_miss, np.float32)
        count = np.zeros(n_miss, np.int32)
        mean = np.zeros(n_miss, np.float32)
        m2 = np.zeros(n_miss, np.float32)
        if warm_hits or cold_hits:
            _fire_fault("state.promote",
                        warm=len(warm_hits), cold=len(cold_hits))
        if warm_hits:
            self._promote_warm(warm_hits, miss_keys,
                               ewma, count, mean, m2)
        if cold_hits:
            self._promote_cold(cold_hits, ewma, count, mean, m2)

        # allocate slots (free list); keys beyond the free slots are a
        # transient overflow — every slot is held by THIS batch, so
        # there is nothing left to evict. They retry next arrival.
        n_admit = min(n_miss, len(self._free))
        if n_admit < n_miss:
            n_over = n_miss - n_admit
            self.overflow += n_over
            _M_OVERFLOW.inc(n_over)
            logger.v(1).info(
                "state tier overflow: %d keys deferred (hot budget %d "
                "fully held by one micro-batch)", n_over,
                self.capacity)
        out = np.full(n_miss, -1, np.int64)
        if n_admit == 0:
            return out
        new_slots = np.asarray(
            [self._free.pop() for _ in range(n_admit)], np.int64)
        for j in range(n_admit):
            s = int(new_slots[j])
            det._slots[miss_keys[j]] = s
            det._slot_keys[s] = miss_keys[j]
        out[:n_admit] = new_slots
        self.gen[new_slots] = g
        self.n_hot += n_admit
        det._n_alloc = self.n_hot
        det.state = _restore(det.state, new_slots, self.capacity,
                             ewma[:n_admit], count[:n_admit],
                             mean[:n_admit], m2[:n_admit])
        return out

    # -- spill (hot → warm + cold) -----------------------------------------

    def _spill(self, det, victims: np.ndarray) -> None:
        """Evict `victims` (slot ids): one jitted gather, one wire
        encode, one durable table append — THEN the in-memory index
        flip, so an injected/real failure anywhere leaves hot state
        fully intact for the retry."""
        _fire_fault("state.spill", n=int(victims.size))
        k = int(victims.size)
        keys_b = [det._slot_keys[int(s)] for s in victims]
        keys_mat = np.stack([np.frombuffer(kb, np.int64)
                             for kb in keys_b])
        vals = _gather(det.state, victims, self.capacity, k)
        seqs = np.arange(self.seq, self.seq + k, dtype=np.int64)
        self.seq += k
        resolved = self.resolver(keys_mat)
        hashes = np.fromiter((key_hash(t) for t in resolved),
                             np.int64, count=k)
        from ..schema import ColumnarBatch
        body = _wire.encode_columns_body(ColumnarBatch(
            {"ewma": vals[0].astype(np.float64),
             "count": vals[1].astype(np.int64),
             "mean": vals[2].astype(np.float64),
             "m2": vals[3].astype(np.float64)}, {}))
        if self.store is not None:
            now = int(self.wall_clock())
            self.store.append([
                {"sourceIP": str(t[0]),
                 "destinationIP": str(t[2]),
                 "sourceTransportPort": int(t[1]),
                 "destinationTransportPort": int(t[3]),
                 "protocolIdentifier": int(t[4]),
                 "flowStartSeconds": int(t[5]),
                 "ewma": float(vals[0][j]),
                 "count": int(vals[1][j]),
                 "mean": float(vals[2][j]),
                 "m2": float(vals[3][j]),
                 "seq": int(seqs[j]),
                 "keyHash": int(hashes[j]),
                 "timeSpilled": now}
                for j, t in enumerate(resolved)])
        # durable: now flip the in-memory tiers
        bid = self._next_block
        self._next_block += 1
        self.blocks[bid] = _SpillBlock(body, keys_mat, hashes, seqs,
                                       self.clock())
        for j, kb in enumerate(keys_b):
            self.warm[kb] = (bid, j)
            del det._slots[kb]
            det._slot_keys[int(victims[j])] = None
            # a re-spill supersedes any cold entry for the same key
            self.cold.pop(int(hashes[j]), None)
        self.gen[victims] = _FREE
        self._free.extend(int(s) for s in victims)
        self.n_hot -= k
        det._n_alloc = self.n_hot
        self.evictions += k
        _M_EVICTIONS.inc(k)

    # -- promotion (warm/cold → hot) ---------------------------------------

    def _promote_warm(self, hits: List[Tuple[int, int, int]],
                      miss_keys: List[bytes],
                      ewma, count, mean, m2) -> None:
        by_block: Dict[int, List[Tuple[int, int]]] = {}
        for i, bid, row in hits:
            by_block.setdefault(bid, []).append((i, row))
        for bid, pairs in by_block.items():
            block = self.blocks[bid]
            batch, _ = _wire.decode_columns(
                memoryview(block.body), 0,
                columns=frozenset(STATE_COLUMNS))
            rows = np.asarray([r for _, r in pairs], np.int64)
            idx = np.asarray([i for i, _ in pairs], np.int64)
            ewma[idx] = batch["ewma"][rows].astype(np.float32)
            count[idx] = batch["count"][rows].astype(np.int32)
            mean[idx] = batch["mean"][rows].astype(np.float32)
            m2[idx] = batch["m2"][rows].astype(np.float32)
            block.live[rows] = False
            block.n_live -= len(rows)
            for i, _ in pairs:
                del self.warm[miss_keys[i]]
            if block.n_live <= 0:
                del self.blocks[bid]
        self.promotions_warm += len(hits)
        _M_PROMOTIONS.labels(tier="warm").inc(len(hits))

    def _promote_cold(self, hits: List[Tuple[int, int]],
                      ewma, count, mean, m2) -> None:
        found = (self.store.lookup([h for _, h in hits])
                 if self.store is not None else {})
        n = 0
        for i, h in hits:
            self.cold.pop(h, None)
            row = found.get(h)
            if row is None:
                # index entry with no surviving store row (pruned
                # away, or a torn mid-spill WAL record discarded at
                # recovery): admit as a fresh series
                continue
            ewma[i] = np.float32(row[0])
            count[i] = np.int32(row[1])
            mean[i] = np.float32(row[2])
            m2[i] = np.float32(row[3])
            n += 1
        if n:
            self.promotions_cold += n
            _M_PROMOTIONS.labels(tier="cold").inc(n)

    # -- aging (warm → cold) -----------------------------------------------

    def _age_out_tick(self) -> None:
        age = self.config.age_out_seconds
        if age <= 0 or not self.blocks:
            return
        now = self.clock()
        for bid in [b for b, blk in self.blocks.items()
                    if now - blk.spilled_at > age]:
            try:
                _fire_fault("state.age_out", block=bid)
            except FaultError as e:
                # maintenance, not correctness: defer this round
                logger.v(1).info("age-out deferred by fault: %s", e)
                return
            block = self.blocks.pop(bid)
            rows = np.flatnonzero(block.live)
            for r in rows:
                del self.warm[block.keys[r].tobytes()]
                h = int(block.hashes[r])
                s = int(block.seqs[r])
                if s >= self.cold.get(h, -1):
                    self.cold[h] = s
            self.age_outs += len(rows)
            _M_AGE_OUTS.inc(len(rows))


# -- jitted slot transfer (one dispatch per direction) ---------------------

def _pad_pow2(n: int, minimum: int = 64) -> int:
    size = minimum
    while size < n:
        size <<= 1
    return size


def _gather(state, slots: np.ndarray, capacity: int,
            k: int) -> Tuple[np.ndarray, ...]:
    """Gather `k` slots' state to host as numpy arrays — ONE jitted
    dispatch, padded to power-of-two buckets so eviction batches of
    any size hit a handful of compiled shapes."""
    from ..ops.fused_detector import gather_state
    pad = np.full(_pad_pow2(k), capacity - 1, np.int32)
    pad[:k] = slots
    sub = gather_state(state, pad)
    return tuple(np.asarray(a)[:k] for a in sub)


def _restore(state, slots: np.ndarray, capacity: int,
             ewma, count, mean, m2):
    """Scatter promoted + zero-init state into `slots` — ONE jitted
    dispatch; padding rides the capacity sentinel (XLA OOB scatter
    drops it)."""
    from ..ops.fused_detector import restore_state
    n = len(slots)
    p = _pad_pow2(n)
    slots_pad = np.full(p, capacity, np.int32)
    slots_pad[:n] = slots
    z32 = np.zeros(p, np.float32)
    zi = np.zeros(p, np.int32)
    e, c, me, m = z32.copy(), zi, z32.copy(), z32.copy()
    e[:n], me[:n], m[:n] = ewma, mean, m2
    c = np.zeros(p, np.int32)
    c[:n] = count
    return restore_state(state, slots_pad, e, c, me, m)
