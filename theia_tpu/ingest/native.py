"""Native (C++) TSV flow-record decoder, with a pure-Python fallback.

The ingest contract (SURVEY §7 step 2): wire bytes → fixed-width
columnar arrays + shared string dictionaries, fast enough that the
storage tier — not the parser — is the bottleneck. The reference leans
on ClickHouse's C++ parsers for this; here it's native/flowblock.cc
loaded via ctypes (no pybind11 in the image), compiled on first use
with g++ -O3.

Wire format: TabSeparated rows in flow-schema column order (the same
shape a ClickHouse `INSERT ... FORMAT TabSeparated` carries, and what
`encode_tsv` emits for tests/benchmarks).

Dictionary discipline: the decoder owns per-column hash tables seeded
from the store's StringDictionary; after each decode the newly minted
codes are replayed into the Python dictionary in order, so both sides
agree code-for-code and batches drop into the store with zero
re-encoding.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from ..analysis.lockdep import named_lock
from typing import Dict, Optional

import numpy as np

from ..schema import FLOW_SCHEMA, ColumnarBatch, ColumnKind, \
    StringDictionary
from ..store import wire as _wire

_KIND_CODE = {"int": 0, "float": 1, "string": 2}

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "flowblock.cc")
_SRC_SERIES = os.path.join(_REPO_ROOT, "native", "seriesbuild.cc")
_SRC_GROUPSUM = os.path.join(_REPO_ROOT, "native", "groupsum.cc")
_ALL_SRCS = (_SRC, _SRC_SERIES, _SRC_GROUPSUM)
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_build")


def _so_path() -> str:
    """Content-hashed artifact name: a stale .so can never be picked up
    (and dlopen caches by pathname, so rebuilding under the SAME name
    would return the already-loaded stale handle — the name must
    change with the sources)."""
    import hashlib
    h = hashlib.sha1()
    for src in _ALL_SRCS:
        with open(src, "rb") as f:
            h.update(f.read())
    return os.path.join(_BUILD_DIR, f"flowblock-{h.hexdigest()[:12]}.so")

_lib_lock = named_lock("native.lib")
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _column_kind_code(col) -> int:
    if col.is_string:
        return _KIND_CODE["string"]
    if col.kind == ColumnKind.F64:
        return _KIND_CODE["float"]
    return _KIND_CODE["int"]


def _load_library() -> Optional[ctypes.CDLL]:
    """Build (if needed) and load the native decoder; None on failure."""
    global _lib, _build_error
    with _lib_lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            os.makedirs(_BUILD_DIR, exist_ok=True)
            so = _so_path()
            if not os.path.exists(so):
                _compile(so)
            _lib = _bind(ctypes.CDLL(so))
        except (OSError, subprocess.CalledProcessError,
                AttributeError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            _build_error = f"native ingest unavailable: {detail}"
        return _lib


def _compile(so: str) -> None:
    # Per-process scratch name, atomically published: a concurrent
    # builder racing on a shared tmp path could otherwise publish a
    # half-written .so under the content-hashed (never-rebuilt) name.
    tmp = f"{so}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
             "-o", tmp, *_ALL_SRCS],
            check=True, capture_output=True, text=True)
        os.replace(tmp, so)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.fb_new.restype = ctypes.c_void_p
    lib.fb_new.argtypes = [ctypes.c_int32,
                           ctypes.POINTER(ctypes.c_int32)]
    lib.fb_seed.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                            ctypes.c_char_p, ctypes.c_int64]
    lib.fb_decode.restype = ctypes.c_int64
    lib.fb_decode.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32)]
    lib.fb_decode_block.restype = ctypes.c_int64
    lib.fb_decode_block.argtypes = lib.fb_decode.argtypes
    lib.fb_decode_block2.restype = ctypes.c_int64
    lib.fb_decode_block2.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_void_p)]
    lib.fb_dict_size.restype = ctypes.c_int64
    lib.fb_dict_size.argtypes = [ctypes.c_void_p,
                                 ctypes.c_int32]
    lib.fb_dict_get.restype = ctypes.c_void_p
    lib.fb_dict_get.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64)]
    lib.fb_free.argtypes = [ctypes.c_void_p]
    lib.sb_build.restype = ctypes.c_void_p
    lib.sb_build.argtypes = [
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int32]
    lib.sb_dims.argtypes = [ctypes.c_void_p,
                            ctypes.POINTER(ctypes.c_int64),
                            ctypes.POINTER(ctypes.c_int64)]
    lib.sb_fill.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_uint8)]
    lib.sb_free.argtypes = [ctypes.c_void_p]
    lib.gs_build.restype = ctypes.c_void_p
    lib.gs_build.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32]
    lib.gs_dims.argtypes = [ctypes.c_void_p,
                            ctypes.POINTER(ctypes.c_int64)]
    lib.gs_fill.argtypes = [ctypes.c_void_p,
                            ctypes.POINTER(ctypes.c_int64),
                            ctypes.POINTER(ctypes.c_int64)]
    lib.gs_free.argtypes = [ctypes.c_void_p]
    return lib


def native_available() -> bool:
    return _load_library() is not None


class TsvDecoder:
    """Decode TabSeparated flow rows into ColumnarBatches.

    Uses the native decoder when available, else the Python fallback.
    Dictionaries passed in are kept in sync (codes match exactly).
    """

    def __init__(self, schema=FLOW_SCHEMA,
                 dicts: Optional[Dict[str, StringDictionary]] = None,
                 force_python: bool = False) -> None:
        self.schema = schema
        self.dicts = dict(dicts or {})
        for col in schema:
            if col.is_string:
                self.dicts.setdefault(col.name, StringDictionary())
        self._numeric_cols = [c for c in schema if not c.is_string]
        self._string_cols = [c for c in schema if c.is_string]
        # Per-column plane width/dtype for the TFB2 wire format: string
        # codes are int32, numerics travel at their host width.
        self._col_dtype = [np.dtype(np.int32) if c.is_string
                           else np.dtype(c.host_dtype) for c in schema]
        self._col_width = [d.itemsize for d in self._col_dtype]
        self._widths_arr = (ctypes.c_int32 * len(schema))(
            *self._col_width)
        self._lib = None if force_python else _load_library()
        self._handle = None
        # How many python-dictionary entries the native side has seen,
        # per column index — lets each decode() replay entries added by
        # OTHER ingest paths (from_rows, a second decoder) before
        # parsing, so codes never diverge.
        self._synced_len: Dict[int, int] = {}
        if self._lib is not None:
            kinds = (ctypes.c_int32 * len(schema))(
                *[_column_kind_code(c) for c in schema])
            self._handle = self._lib.fb_new(len(schema), kinds)
            for i, col in enumerate(schema):
                if col.is_string:
                    self._synced_len[i] = 0
            self._push_python_dicts()

    def __del__(self):
        if getattr(self, "_handle", None) and self._lib is not None:
            self._lib.fb_free(self._handle)
            self._handle = None

    @property
    def is_native(self) -> bool:
        return self._handle is not None

    def decode(self, payload: bytes,
               max_rows: Optional[int] = None) -> ColumnarBatch:
        """Decode a TSV payload. `max_rows` is a hard bound: exceeding
        it raises (identically on both paths) rather than silently
        truncating."""
        stripped = payload.strip(b"\n")
        # bytes.count, not split: splitting an 80 MiB payload into row
        # objects just to count them costs more than the native parse.
        n_rows = (stripped.count(b"\n") + 1) if stripped else 0
        if max_rows is not None and n_rows > max_rows:
            raise ValueError(
                f"payload has {n_rows} rows, max_rows={max_rows}")
        if self._handle is not None:
            return self._decode_native(payload, max(n_rows, 1))
        return self._decode_python(payload)

    # -- native path -----------------------------------------------------

    def _push_python_dicts(self) -> None:
        """Seed entries other ingest paths added to the shared Python
        dictionaries since the last decode; afterwards both sides hold
        identical code tables (native never leads Python: its minted
        codes are replayed back in _sync_dicts)."""
        for i, col in enumerate(self.schema):
            if not col.is_string:
                continue
            d = self.dicts[col.name]
            start = self._synced_len[i]
            pending = d.entries_since(start)
            for s in pending:
                raw = s.encode()
                self._lib.fb_seed(self._handle, i, raw, len(raw))
            self._synced_len[i] = start + len(pending)
            native_n = self._lib.fb_dict_size(self._handle, i)
            if native_n != self._synced_len[i]:
                raise RuntimeError(
                    f"dictionary desync on {col.name}: python "
                    f"{self._synced_len[i]} entries, native {native_n}")

    def _decode_native(self, payload: bytes,
                       max_rows: int) -> ColumnarBatch:
        self._push_python_dicts()
        n_num = len(self._numeric_cols)
        n_str = len(self._string_cols)
        # empty, not zeros: the decoder writes every cell of each parsed
        # row, and only [:n] is read back.
        ints = np.empty((n_num, max_rows), np.int64)
        codes = np.empty((n_str, max_rows), np.int32)
        n = self._lib.fb_decode(
            self._handle, payload, len(payload), max_rows,
            ints.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if n < 0:
            raise ValueError(f"malformed TSV at row {-(n + 1)}")
        self._sync_dicts()
        return self._planes_to_batch(ints, codes, int(n))

    def _planes_to_batch(self, ints: np.ndarray, codes: np.ndarray,
                         n: int) -> ColumnarBatch:
        cols: Dict[str, np.ndarray] = {}
        num_i = str_i = 0
        for col in self.schema:
            if col.is_string:
                cols[col.name] = codes[str_i, :n].copy()
                str_i += 1
            elif col.kind == ColumnKind.F64:
                cols[col.name] = ints[num_i, :n].view(np.float64).copy()
                num_i += 1
            else:
                cols[col.name] = ints[num_i, :n].astype(col.host_dtype)
                num_i += 1
        return ColumnarBatch(cols, self.dicts)

    # -- binary columnar blocks ------------------------------------------

    def decode_block(self, payload: bytes) -> ColumnarBatch:
        """Decode one BLOCK_MAGIC binary columnar block (see
        encode_block) — the fast wire path: raw column planes are
        bulk-copied, with only the dictionary *delta* carried as text.
        Analogue of ClickHouse's column-major native protocol, which is
        how the reference's FlowAggregator actually inserts
        (clickhouse-go `tcp://…:9000`, pkg/util/clickhouse/clickhouse.go:125).
        """
        if len(payload) < 16 or payload[:4] not in (BLOCK_MAGIC,
                                                    BLOCK_MAGIC_V1):
            raise ValueError("not a flow block payload")
        v2 = payload[:4] == BLOCK_MAGIC
        n_rows = int(np.frombuffer(payload, np.int64, 1, 4)[0])
        # Output allocation is sized from the header, so sanity-bound it
        # against what the payload could possibly carry before trusting
        # a (possibly corrupt/hostile) row count.
        row_bytes = sum(self._col_width) if v2 else (
            8 * len(self._numeric_cols) + 4 * len(self._string_cols))
        if n_rows < 0 or n_rows * row_bytes > len(payload):
            raise ValueError(
                f"flow block claims {n_rows} rows but carries only "
                f"{len(payload)} bytes")
        if self._handle is not None and v2:
            return self._decode_block2_native(payload, n_rows)
        if self._handle is not None:
            self._push_python_dicts()
            ints = np.empty((len(self._numeric_cols), max(n_rows, 1)),
                            np.int64)
            codes = np.empty((len(self._string_cols), max(n_rows, 1)),
                             np.int32)
            n = self._lib.fb_decode_block(
                self._handle, payload, len(payload), max(n_rows, 1),
                ints.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            # The native decoder validates the whole block before
            # mutating any state, so every error leaves the decoder
            # (and the shared dictionaries) untouched.
            if n < 0:
                raise ValueError(self._BLOCK_ERRORS.get(
                    n, f"malformed flow block ({n})"))
            self._sync_dicts()
            return self._planes_to_batch(ints, codes, int(n))
        return self._decode_block_python(payload, n_rows, v2)

    _BLOCK_ERRORS = {
        -2: "dictionary desync: block's delta base does not match the "
            "decoder's dictionary (blocks must be decoded in stream "
            "order)",
        -4: "flow block carries string codes outside its dictionary",
        -5: "dictionary desync: block's delta repeats an existing or "
            "intra-delta entry",
    }

    def _decode_block2_native(self, payload: bytes,
                              n_rows: int) -> ColumnarBatch:
        """TFB2 fast path: planes land directly in the final per-column
        arrays (no widening buffer, no re-narrowing pass). All columns
        live in ONE allocation (8-byte-aligned slices) — one np.empty
        instead of 52 per block."""
        self._push_python_dicts()
        n = max(n_rows, 1)
        offsets = []
        total = 0
        for w in self._col_width:
            total = (total + 7) & ~7      # keep every slice 8B-aligned
            offsets.append(total)
            total += n * w
        buf = np.empty(total, np.uint8)
        arrays = [np.frombuffer(buf.data, dt, n, off)
                  for dt, off in zip(self._col_dtype, offsets)]
        base = buf.ctypes.data
        out = (ctypes.c_void_p * len(arrays))(
            *[base + off for off in offsets])
        n = self._lib.fb_decode_block2(
            self._handle, payload, len(payload), max(n_rows, 1),
            self._widths_arr, out)
        if n < 0:
            raise ValueError(self._BLOCK_ERRORS.get(
                n, f"malformed flow block ({n})"))
        self._sync_dicts()
        return ColumnarBatch(
            {col.name: arr[:n] for col, arr in zip(self.schema, arrays)},
            self.dicts)

    def _decode_block_python(self, payload: bytes, n_rows: int,
                             v2: bool = True) -> ColumnarBatch:
        """Mirrors the native decoder's discipline: the whole block is
        parsed and validated into locals first; the shared dictionaries
        are only touched once nothing can fail."""
        off = 12
        n_cols = int(np.frombuffer(payload, np.int32, 1, off)[0])
        off += 4
        if n_cols != len(self.schema):
            raise ValueError(
                f"block has {n_cols} columns, schema has "
                f"{len(self.schema)}")
        deltas: Dict[str, list] = {}
        limits: Dict[str, int] = {}
        for col in self._string_cols:
            if off + 8 > len(payload):
                raise ValueError("malformed flow block (truncated)")
            base, count = np.frombuffer(payload, np.int32, 2, off)
            off += 8
            if count < 0:
                raise ValueError("malformed flow block (bad delta)")
            d = self.dicts[col.name]
            if int(base) != len(d):
                raise ValueError(
                    "dictionary desync: block's delta base does not "
                    "match the decoder's dictionary (blocks must be "
                    "decoded in stream order)")
            entries = []
            seen = set()
            for _ in range(int(count)):
                if off + 4 > len(payload):
                    raise ValueError(
                        "malformed flow block (truncated)")
                ln = int(np.frombuffer(payload, np.int32, 1, off)[0])
                off += 4
                if ln < 0 or off + ln > len(payload):
                    raise ValueError(
                        "malformed flow block (truncated)")
                s = payload[off:off + ln].decode()
                off += ln
                # novelty: a duplicate (of an existing entry or within
                # the delta) would desync the append-only code sequence
                if d.lookup(s) is not None or s in seen:
                    raise ValueError(
                        f"dictionary desync on {col.name}: delta "
                        f"repeats entry {s!r}")
                seen.add(s)
                entries.append(s)
            deltas[col.name] = entries
            limits[col.name] = int(base) + len(entries)
        cols: Dict[str, np.ndarray] = {}
        for i, col in enumerate(self.schema):
            if v2:
                width, dtype = self._col_width[i], self._col_dtype[i]
            else:
                width = 4 if col.is_string else 8
                dtype = np.int32 if col.is_string else np.int64
            if off + n_rows * width > len(payload):
                raise ValueError("malformed flow block (truncated)")
            if col.is_string:
                codes = np.frombuffer(payload, np.int32, n_rows,
                                      off).copy()
                if len(codes) and (codes.min() < 0
                                   or codes.max() >= limits[col.name]):
                    raise ValueError(
                        "flow block carries string codes outside its "
                        "dictionary")
                cols[col.name] = codes
            elif v2:
                cols[col.name] = np.frombuffer(payload, dtype, n_rows,
                                               off).copy()
            else:
                raw = np.frombuffer(payload, np.int64, n_rows, off)
                if col.kind == ColumnKind.F64:
                    cols[col.name] = raw.view(np.float64).copy()
                else:
                    cols[col.name] = raw.astype(col.host_dtype)
            off += n_rows * width
        # -- commit: everything validated, now mint the delta entries.
        for col in self._string_cols:
            d = self.dicts[col.name]
            base = limits[col.name] - len(deltas[col.name])
            for i, s in enumerate(deltas[col.name]):
                code = d.encode_one(s)
                if code != base + i:
                    raise ValueError(
                        f"dictionary desync on {col.name}: {s!r} -> "
                        f"{code}, expected {base + i}")
        return ColumnarBatch(cols, self.dicts)

    def _sync_dicts(self) -> None:
        """Replay codes minted by the native decoder into the Python
        dictionaries, preserving code order."""
        for i, col in enumerate(self.schema):
            if not col.is_string:
                continue
            d = self.dicts[col.name]
            native_n = self._lib.fb_dict_size(self._handle, i)
            for idx in range(self._synced_len[i], native_n):
                ln = ctypes.c_int64()
                ptr = self._lib.fb_dict_get(self._handle, i, idx,
                                            ctypes.byref(ln))
                s = ctypes.string_at(ptr, ln.value).decode()
                code = d.encode_one(s)
                if code != idx:
                    raise RuntimeError(
                        f"dictionary desync on {col.name}: {s!r} -> "
                        f"{code}, native {idx}")
            self._synced_len[i] = native_n

    # -- python fallback -------------------------------------------------

    def _decode_python(self, payload: bytes) -> ColumnarBatch:
        lines = [ln for ln in payload.split(b"\n") if ln]
        n = len(lines)
        fields = [ln.split(b"\t") for ln in lines]
        cols: Dict[str, np.ndarray] = {}
        for i, col in enumerate(self.schema):
            raw = [f[i] if i < len(f) else b"" for f in fields]
            if col.is_string:
                d = self.dicts[col.name]
                cols[col.name] = d.encode(
                    [r.decode() for r in raw]) if n else np.zeros(
                        0, np.int32)
            elif col.kind == ColumnKind.F64:
                cols[col.name] = np.asarray(
                    [float(r) if r else 0.0 for r in raw], np.float64)
            else:
                cols[col.name] = np.asarray(
                    [int(r) if r else 0 for r in raw], col.host_dtype)
        return ColumnarBatch(cols, self.dicts)


# Current wire format: TFB2 (native-width column planes). TFB1 blocks
# (8-byte-widened numeric planes) are still accepted on decode.
BLOCK_MAGIC = b"TFB2"
BLOCK_MAGIC_V1 = b"TFB1"


class BlockEncoder:
    """Producer side of the binary columnar block format.

    Tracks, per string column, how many dictionary entries the receiving
    decoder has already seen; each block carries only the delta. Blocks
    from one encoder must be decoded in order by one decoder (the same
    discipline as a ClickHouse native-protocol connection).
    """

    def __init__(self, schema=FLOW_SCHEMA,
                 dicts: Optional[Dict[str, StringDictionary]] = None
                 ) -> None:
        self.schema = schema
        self.dicts = dict(dicts or {})
        for col in schema:
            if col.is_string:
                self.dicts.setdefault(col.name, StringDictionary())
        # Every StringDictionary (Python and native) is born with "" at
        # code 0, so the first delta starts at entry 1.
        self._sent = {c.name: 1 for c in schema if c.is_string}

    def encode(self, batch: ColumnarBatch) -> bytes:
        """Render a batch as one block. The batch's string columns must
        be coded against this encoder's dictionaries; foreign-dictionary
        batches are re-encoded transparently."""
        n_rows = len(batch)
        parts = [BLOCK_MAGIC,
                 np.int64(n_rows).tobytes(),
                 np.int32(len(self.schema)).tobytes()]
        code_cols: Dict[str, np.ndarray] = {}
        for col in self.schema:
            if not col.is_string:
                continue
            d = self.dicts[col.name]
            if batch.dicts.get(col.name) is d:
                code_cols[col.name] = np.asarray(batch[col.name],
                                                 np.int32)
            else:   # re-encode against our dictionary
                code_cols[col.name] = d.encode(
                    list(batch.strings(col.name))).astype(np.int32)
            base = self._sent[col.name]
            delta = d.entries_since(base)
            parts.append(np.asarray([base, len(delta)],
                                    np.int32).tobytes())
            for s in delta:
                raw = s.encode()
                parts.append(np.int32(len(raw)).tobytes())
                parts.append(raw)
            self._sent[col.name] = base + len(delta)
        for col in self.schema:
            if col.is_string:
                parts.append(np.ascontiguousarray(
                    code_cols[col.name], np.int32).tobytes())
            else:
                # TFB2: numerics travel at their host width.
                parts.append(np.ascontiguousarray(
                    batch[col.name], col.host_dtype).tobytes())
        return b"".join(parts)


# TFB3 / "TBLK": the self-contained columnar block format
# (store/wire.py — the same bytes the WAL journals and parts store).
# Unlike TFB2 there is NO per-connection dictionary delta chain: every
# block carries its own batch-unique strings, so blocks from any
# number of producers decode statelessly, in any order, on any shard —
# and the receiver journals the column bytes verbatim instead of
# decode→re-encode. The server content-negotiates per request by
# magic; THEIA_INGEST_FORMAT picks the producer-side default
# (ingest/client.py).
TBLK_MAGIC = _wire.BLOCK_MAGIC
decode_tblk = _wire.decode_block


class TblkEncoder:
    """Producer side of the TFB3/TBLK block format — `encode(batch)`
    API-compatible with `BlockEncoder` so producers swap by
    constructor. Stateless (no delta cursors): one encoder may serve
    any number of connections concurrently, and a retried block is
    byte-identical regardless of what was sent in between."""

    def __init__(self, schema=FLOW_SCHEMA,
                 dicts: Optional[Dict[str, StringDictionary]] = None
                 ) -> None:
        self.schema = schema
        self.dicts = dict(dicts or {})
        for col in schema:
            if col.is_string:
                self.dicts.setdefault(col.name, StringDictionary())

    def encode(self, batch: ColumnarBatch) -> bytes:
        """Render a batch as one self-contained block. String columns
        missing a dictionary on the batch fall back to this encoder's
        (they must be coded against it — same contract as sharing a
        dictionary with BlockEncoder)."""
        missing = [c.name for c in self.schema
                   if c.is_string and c.name in batch.columns
                   and c.name not in batch.dicts]
        if missing:
            batch = ColumnarBatch(
                batch.columns,
                {**{n: self.dicts[n] for n in missing}, **batch.dicts})
        return _wire.encode_block(batch)


def encode_tsv(batch: ColumnarBatch, schema=FLOW_SCHEMA) -> bytes:
    """Render a batch as TabSeparated wire bytes (tests/benchmarks)."""
    columns = []
    for col in schema:
        if col.is_string:
            columns.append(batch.strings(col.name))
        else:
            columns.append(batch[col.name])
    rows = []
    for i in range(len(batch)):
        rows.append("\t".join(str(c[i]) for c in columns))
    return ("\n".join(rows) + "\n").encode()


def build_padded_series(keys: np.ndarray, times: np.ndarray,
                        values: np.ndarray, op: str,
                        dtype=np.float64):
    """Native tensorize: group rows by [n, k] int64 key tuples into
    padded per-series time arrays (native/seriesbuild.cc).

    Returns (key_mat [S,k] int64, values [S,T] dtype, times [S,T] int64,
    mask [S,T] bool) with series in lexicographic key order and points
    in time order — bit-identical to the numpy group_reduce +
    _pack_and_pad path in analytics/series.py. Duplicate (key, time)
    rows reduce with `op` ("max" or "sum"). Returns None when the
    native library is unavailable (caller falls back to numpy).
    """
    lib = _load_library()
    if lib is None:
        return None
    keys = np.ascontiguousarray(keys, np.int64)
    times = np.ascontiguousarray(times, np.int64)
    values = np.ascontiguousarray(values, np.int64)
    n, k = keys.shape
    handle = lib.sb_build(
        keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        times.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, k, 0 if op == "max" else 1)
    try:
        S = ctypes.c_int64()
        T = ctypes.c_int64()
        lib.sb_dims(handle, ctypes.byref(S), ctypes.byref(T))
        s, t = S.value, T.value
        key_mat = np.empty((s, k), np.int64)
        vals = np.empty((s, t), np.float64)
        ts = np.empty((s, t), np.int64)
        mask = np.empty((s, t), np.uint8)
        lib.sb_fill(
            handle,
            key_mat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            ts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            mask.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    finally:
        lib.sb_free(handle)
    return key_mat, vals.astype(dtype, copy=False), ts, \
        mask.astype(bool)


def native_group_sum(key_cols, value_cols):
    """Native GROUP BY...SUM over column arrays (native/groupsum.cc):
    one hash pass, no sort, no row-major staging in Python — the
    materialized-view insert hot path. Group order is arbitrary
    (SummingMergeTree parts are re-grouped exactly at read time).

    key_cols / value_cols: sequences of 1-D int32/int64 arrays of equal
    length. Returns (keys [g,k] int64, sums [g,m] int64), or None when
    the native library is unavailable.
    """
    lib = _load_library()
    if lib is None:
        return None
    key_cols = [np.ascontiguousarray(a) for a in key_cols]
    value_cols = [np.ascontiguousarray(a) for a in value_cols]
    for a in (*key_cols, *value_cols):
        if a.dtype not in (np.dtype(np.int32), np.dtype(np.int64)):
            return None   # unexpected dtype → numpy fallback
    n = len(key_cols[0]) if key_cols else 0
    for a in (*key_cols, *value_cols):
        if len(a) != n:  # C reads n cells per column — no OOB reads
            raise ValueError(
                f"column length mismatch: {len(a)} != {n}")
    k, m = len(key_cols), len(value_cols)
    kp = (ctypes.c_void_p * k)(*[a.ctypes.data for a in key_cols])
    kw = (ctypes.c_int32 * k)(*[a.dtype.itemsize for a in key_cols])
    vp = (ctypes.c_void_p * max(m, 1))(
        *[a.ctypes.data for a in value_cols])
    vw = (ctypes.c_int32 * max(m, 1))(
        *[a.dtype.itemsize for a in value_cols])
    handle = lib.gs_build(kp, kw, n, k, vp, vw, m)
    try:
        g = ctypes.c_int64()
        lib.gs_dims(handle, ctypes.byref(g))
        keys = np.empty((g.value, k), np.int64)
        sums = np.empty((g.value, m), np.int64)
        lib.gs_fill(
            handle,
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            sums.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    finally:
        lib.gs_free(handle)
    return keys, sums
