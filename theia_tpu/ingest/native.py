"""Native (C++) TSV flow-record decoder, with a pure-Python fallback.

The ingest contract (SURVEY §7 step 2): wire bytes → fixed-width
columnar arrays + shared string dictionaries, fast enough that the
storage tier — not the parser — is the bottleneck. The reference leans
on ClickHouse's C++ parsers for this; here it's native/flowblock.cc
loaded via ctypes (no pybind11 in the image), compiled on first use
with g++ -O3.

Wire format: TabSeparated rows in flow-schema column order (the same
shape a ClickHouse `INSERT ... FORMAT TabSeparated` carries, and what
`encode_tsv` emits for tests/benchmarks).

Dictionary discipline: the decoder owns per-column hash tables seeded
from the store's StringDictionary; after each decode the newly minted
codes are replayed into the Python dictionary in order, so both sides
agree code-for-code and batches drop into the store with zero
re-encoding.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Optional

import numpy as np

from ..schema import FLOW_SCHEMA, ColumnarBatch, ColumnKind, \
    StringDictionary

_KIND_CODE = {"int": 0, "float": 1, "string": 2}

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "flowblock.cc")
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_build")
_SO = os.path.join(_BUILD_DIR, "flowblock.so")

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _column_kind_code(col) -> int:
    if col.is_string:
        return _KIND_CODE["string"]
    if col.kind == ColumnKind.F64:
        return _KIND_CODE["float"]
    return _KIND_CODE["int"]


def _load_library() -> Optional[ctypes.CDLL]:
    """Build (if needed) and load the native decoder; None on failure."""
    global _lib, _build_error
    with _lib_lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            os.makedirs(_BUILD_DIR, exist_ok=True)
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                subprocess.run(
                    ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                     "-o", _SO, _SRC],
                    check=True, capture_output=True, text=True)
            lib = ctypes.CDLL(_SO)
            lib.fb_new.restype = ctypes.c_void_p
            lib.fb_new.argtypes = [ctypes.c_int32,
                                   ctypes.POINTER(ctypes.c_int32)]
            lib.fb_seed.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                    ctypes.c_char_p, ctypes.c_int64]
            lib.fb_decode.restype = ctypes.c_int64
            lib.fb_decode.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32)]
            lib.fb_dict_size.restype = ctypes.c_int64
            lib.fb_dict_size.argtypes = [ctypes.c_void_p,
                                         ctypes.c_int32]
            lib.fb_dict_get.restype = ctypes.c_void_p
            lib.fb_dict_get.argtypes = [
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64)]
            lib.fb_free.argtypes = [ctypes.c_void_p]
            _lib = lib
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            _build_error = f"native ingest unavailable: {detail}"
        return _lib


def native_available() -> bool:
    return _load_library() is not None


class TsvDecoder:
    """Decode TabSeparated flow rows into ColumnarBatches.

    Uses the native decoder when available, else the Python fallback.
    Dictionaries passed in are kept in sync (codes match exactly).
    """

    def __init__(self, schema=FLOW_SCHEMA,
                 dicts: Optional[Dict[str, StringDictionary]] = None,
                 force_python: bool = False) -> None:
        self.schema = schema
        self.dicts = dict(dicts or {})
        for col in schema:
            if col.is_string:
                self.dicts.setdefault(col.name, StringDictionary())
        self._numeric_cols = [c for c in schema if not c.is_string]
        self._string_cols = [c for c in schema if c.is_string]
        self._lib = None if force_python else _load_library()
        self._handle = None
        # How many python-dictionary entries the native side has seen,
        # per column index — lets each decode() replay entries added by
        # OTHER ingest paths (from_rows, a second decoder) before
        # parsing, so codes never diverge.
        self._synced_len: Dict[int, int] = {}
        if self._lib is not None:
            kinds = (ctypes.c_int32 * len(schema))(
                *[_column_kind_code(c) for c in schema])
            self._handle = self._lib.fb_new(len(schema), kinds)
            for i, col in enumerate(schema):
                if col.is_string:
                    self._synced_len[i] = 0
            self._push_python_dicts()

    def __del__(self):
        if getattr(self, "_handle", None) and self._lib is not None:
            self._lib.fb_free(self._handle)
            self._handle = None

    @property
    def is_native(self) -> bool:
        return self._handle is not None

    def decode(self, payload: bytes,
               max_rows: Optional[int] = None) -> ColumnarBatch:
        """Decode a TSV payload. `max_rows` is a hard bound: exceeding
        it raises (identically on both paths) rather than silently
        truncating."""
        n_rows = len(payload.strip(b"\n").split(b"\n")) if payload \
            else 0
        if max_rows is not None and n_rows > max_rows:
            raise ValueError(
                f"payload has {n_rows} rows, max_rows={max_rows}")
        if self._handle is not None:
            return self._decode_native(payload, max(n_rows, 1))
        return self._decode_python(payload)

    # -- native path -----------------------------------------------------

    def _push_python_dicts(self) -> None:
        """Seed entries other ingest paths added to the shared Python
        dictionaries since the last decode; afterwards both sides hold
        identical code tables (native never leads Python: its minted
        codes are replayed back in _sync_dicts)."""
        for i, col in enumerate(self.schema):
            if not col.is_string:
                continue
            d = self.dicts[col.name]
            start = self._synced_len[i]
            with d._lock:
                pending = list(d._strings[start:])
            for s in pending:
                raw = s.encode()
                self._lib.fb_seed(self._handle, i, raw, len(raw))
            self._synced_len[i] = start + len(pending)
            native_n = self._lib.fb_dict_size(self._handle, i)
            if native_n != self._synced_len[i]:
                raise RuntimeError(
                    f"dictionary desync on {col.name}: python "
                    f"{self._synced_len[i]} entries, native {native_n}")

    def _decode_native(self, payload: bytes,
                       max_rows: int) -> ColumnarBatch:
        self._push_python_dicts()
        n_num = len(self._numeric_cols)
        n_str = len(self._string_cols)
        # empty, not zeros: the decoder writes every cell of each parsed
        # row, and only [:n] is read back.
        ints = np.empty((n_num, max_rows), np.int64)
        codes = np.empty((n_str, max_rows), np.int32)
        n = self._lib.fb_decode(
            self._handle, payload, len(payload), max_rows,
            ints.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if n < 0:
            raise ValueError(f"malformed TSV at row {-(n + 1)}")
        self._sync_dicts()
        cols: Dict[str, np.ndarray] = {}
        num_i = str_i = 0
        for col in self.schema:
            if col.is_string:
                cols[col.name] = codes[str_i, :n].copy()
                str_i += 1
            elif col.kind == ColumnKind.F64:
                cols[col.name] = ints[num_i, :n].view(np.float64).copy()
                num_i += 1
            else:
                cols[col.name] = ints[num_i, :n].astype(col.host_dtype)
                num_i += 1
        return ColumnarBatch(cols, self.dicts)

    def _sync_dicts(self) -> None:
        """Replay codes minted by the native decoder into the Python
        dictionaries, preserving code order."""
        for i, col in enumerate(self.schema):
            if not col.is_string:
                continue
            d = self.dicts[col.name]
            native_n = self._lib.fb_dict_size(self._handle, i)
            for idx in range(self._synced_len[i], native_n):
                ln = ctypes.c_int64()
                ptr = self._lib.fb_dict_get(self._handle, i, idx,
                                            ctypes.byref(ln))
                s = ctypes.string_at(ptr, ln.value).decode()
                code = d.encode_one(s)
                if code != idx:
                    raise RuntimeError(
                        f"dictionary desync on {col.name}: {s!r} -> "
                        f"{code}, native {idx}")
            self._synced_len[i] = native_n

    # -- python fallback -------------------------------------------------

    def _decode_python(self, payload: bytes) -> ColumnarBatch:
        lines = [ln for ln in payload.split(b"\n") if ln]
        n = len(lines)
        fields = [ln.split(b"\t") for ln in lines]
        cols: Dict[str, np.ndarray] = {}
        for i, col in enumerate(self.schema):
            raw = [f[i] if i < len(f) else b"" for f in fields]
            if col.is_string:
                d = self.dicts[col.name]
                cols[col.name] = d.encode(
                    [r.decode() for r in raw]) if n else np.zeros(
                        0, np.int32)
            elif col.kind == ColumnKind.F64:
                cols[col.name] = np.asarray(
                    [float(r) if r else 0.0 for r in raw], np.float64)
            else:
                cols[col.name] = np.asarray(
                    [int(r) if r else 0 for r in raw], col.host_dtype)
        return ColumnarBatch(cols, self.dicts)


def encode_tsv(batch: ColumnarBatch, schema=FLOW_SCHEMA) -> bytes:
    """Render a batch as TabSeparated wire bytes (tests/benchmarks)."""
    columns = []
    for col in schema:
        if col.is_string:
            columns.append(batch.strings(col.name))
        else:
            columns.append(batch[col.name])
    rows = []
    for i in range(len(batch)):
        rows.append("\t".join(str(c[i]) for c in columns))
    return ("\n".join(rows) + "\n").encode()
