"""Ingest routing: forward decoded rows to their owner-shard node.

The reference fronts its shard grid with a Distributed table: an
insert lands anywhere, the engine re-routes each row to the shard that
owns its sharding key. The equivalent here: every peer in a routing
mesh (`--role peer`) accepts `POST /ingest`, splits the decoded batch
by the same stable destination hash the in-process detector shards use
(crc32 of the destination string into the peer-list order), keeps its
own rows, and forwards the rest as self-contained `TREC` record
payloads (the WAL record encoding — no stream delta chains, so any
node decodes them statelessly).

Exactly-once is BY CONSTRUCTION, not best-effort: a forwarded slice is
stamped `stream=<producer stream>@<origin node>, seq=<producer seq>` —
the origin's retry re-splits the batch identically (the hash is a pure
function of the rows), so each owner's dedup window resolves the
re-forward `duplicate:true`; the origin's own slice dedups under the
same `@<self>` sub-stream before touching store or detectors. The
producer-facing ack is recorded only after every slice landed, so a
crashed origin's retry settles every slice idempotently. Forwarding
reuses IngestClient wholesale: jittered capped backoff, Retry-After
honor, 5xx/transport retries — a routed retry storm behaves exactly
like a producer retry storm.

TREC payloads themselves are never re-routed (they are pre-routed by
their origin); a disagreeing peer list between nodes is a deployment
error the docs call out, not something the router loops on.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..utils.env import env_int
from ..utils.logging import get_logger
from ..analysis.lockdep import named_lock

logger = get_logger("cluster")

_M_FWD_ROWS = _metrics.counter(
    "theia_router_forwarded_rows_total",
    "Rows forwarded to their owner-shard node", labelnames=("peer",))
_M_FWD_BATCHES = _metrics.counter(
    "theia_router_forwarded_batches_total",
    "Forwarded sub-batches, by outcome (ok / duplicate / failed)",
    labelnames=("result",))
_M_FWD_SECONDS = _metrics.histogram(
    "theia_router_forward_seconds",
    "Wall time of one forwarded sub-batch (send + owner ack)")
_M_LOCAL_ROWS = _metrics.counter(
    "theia_router_local_rows_total",
    "Rows this node owned and kept local")


class RouterForwardError(Exception):
    """A forwarded slice could not be acknowledged by its owner (after
    the client's full retry budget) — HTTP 503: the producer retries
    the whole batch; every already-landed slice resolves
    duplicate:true."""


class IngestRouter:
    """Splits decoded batches by owner node and forwards remote slices
    through per-peer IngestClients."""

    def __init__(self, cmap, token: str = "",
                 ca_cert: Optional[str] = None,
                 max_attempts: Optional[int] = None,
                 timeout: float = 30.0) -> None:
        from ..ingest.client import IngestClient
        self.cmap = cmap
        self.self_id = cmap.self_id
        self._client_cls = IngestClient
        self._token = token
        self._ca_cert = ca_cert
        self._timeout = timeout
        self.max_attempts = (env_int("THEIA_ROUTER_ATTEMPTS", 8)
                             if max_attempts is None
                             else int(max_attempts))
        self._clients: Dict[str, object] = {}
        self._clients_lock = named_lock("router.clients")
        self._pool = ThreadPoolExecutor(
            max_workers=max(2, 2 * len(cmap.order)),
            thread_name_prefix="theia-router")
        #: id(dict) -> (dict ref, owner index per code), grown lazily —
        #: each destination string is hashed ONCE; rows partition by a
        #: pure integer gather afterwards (the _dst_shard discipline)
        self._owner_lut: Dict[int, Tuple[object, np.ndarray]] = {}
        self.forwarded_rows = 0
        self.forward_failures = 0

    def close(self) -> None:
        self._pool.shutdown(wait=False)

    def _client(self, peer: str):
        with self._clients_lock:
            c = self._clients.get(peer)
            if c is None:
                c = self._clients[peer] = self._client_cls(
                    self.cmap.addr(peer), stream=f"router-{self.self_id}",
                    token=self._token, ca_cert=self._ca_cert,
                    timeout=self._timeout,
                    max_attempts=self.max_attempts)
            return c

    def sub_stream(self, stream: str) -> str:
        """The origin-scoped dedup namespace for forwarded (and local)
        slices of a producer batch: distinct origins forwarding the
        same producer stream id cannot collide on (stream, seq)."""
        return f"{stream}@{self.self_id}"

    # -- split -------------------------------------------------------------

    def split(self, batch) -> Tuple[object, List[Tuple[str, object]]]:
        """(local slice, [(peer, remote slice), ...]) by stable
        destination hash. Row order inside each slice is batch order —
        per-connection detector order is preserved on the owner."""
        n_peers = len(self.cmap.order)
        if n_peers <= 1 or "destinationIP" not in batch.columns:
            return batch, []
        codes = np.asarray(batch["destinationIP"], np.int64)
        d = batch.dicts.get("destinationIP")
        if d is None:
            return batch, []
        owners = self._owners_for(codes, d)
        self_i = self.cmap.order.index(self.self_id)
        out: List[Tuple[str, object]] = []
        if bool(np.all(owners == self_i)):
            return batch, []
        for i, peer in enumerate(self.cmap.order):
            if i == self_i:
                continue
            idx = np.flatnonzero(owners == i)
            if idx.size:
                out.append((peer, batch.take(idx)))
        local_idx = np.flatnonzero(owners == self_i)
        local = batch.take(local_idx)
        _M_LOCAL_ROWS.inc(len(local))
        return local, out

    def split_wire(self, wire) -> Optional[Tuple[bytes, List[
            Tuple[str, bytes, int]]]]:
        """Split an ENCODED TBLK column section by owner WITHOUT a
        full-batch decode: only the destinationIP column (plus its
        unique-string table) is decoded to compute owners; every slice
        — remote and local — is then cut by column GATHER on the
        encoded bytes (store/wire.py), so a 52-column batch never
        round-trips through decode→take→re-encode just to be
        forwarded. Remote slices are shipped as self-contained TREC
        payloads (exactly what `split`+`_send` produce, so owners
        cannot tell the paths apart).

        Returns None when no routing is needed — single-node mesh,
        no destination column, or every row already local — in which
        case the caller decodes the original payload whole; otherwise
        (local column section bytes, [(peer, TREC payload, rows)]).

        The owner LUT is keyed per dictionary; TBLK blocks carry fresh
        per-block dictionaries, so unlike the TFB2 path the LUT does
        not amortize across a stream — the per-request cost is one
        crc32 per unique destination, which the skipped re-encode
        repays many times over."""
        n_peers = len(self.cmap.order)
        if n_peers <= 1:
            return None
        from ..store import wire as _wirefmt
        from ..store.wal import RECORD_MAGIC, pack_table_header
        sub, end = _wirefmt.decode_columns(
            wire, 0, columns=frozenset(("destinationIP",)))
        if end != len(wire):
            raise _wirefmt.WireCorruption(
                f"block has {len(wire) - end} trailing bytes")
        d = sub.dicts.get("destinationIP")
        if "destinationIP" not in sub.columns or d is None:
            return None
        codes = np.asarray(sub["destinationIP"], np.int64)
        owners = self._owners_for(codes, d)
        self_i = self.cmap.order.index(self.self_id)
        if bool(np.all(owners == self_i)):
            return None
        thead = RECORD_MAGIC + pack_table_header("flows")
        remote: List[Tuple[str, bytes, int]] = []
        for i, peer in enumerate(self.cmap.order):
            if i == self_i:
                continue
            idx = np.flatnonzero(owners == i)
            if idx.size:
                parts, _ = _wirefmt.gather_parts(wire, idx)
                remote.append(
                    (peer, thead + b"".join(bytes(p) for p in parts),
                     int(idx.size)))
        local_idx = np.flatnonzero(owners == self_i)
        lparts, _ = _wirefmt.gather_parts(wire, local_idx)
        _M_LOCAL_ROWS.inc(int(local_idx.size))
        return b"".join(bytes(p) for p in lparts), remote

    def _owners_for(self, codes: np.ndarray, d) -> np.ndarray:
        """Owner peer INDEX per row. The per-dictionary LUT caches the
        hash of every code minted so far; dictionaries only grow, so
        the cache extends monotonically. The entry HOLDS the
        dictionary and verifies identity — keying by bare id() would
        let CPython reuse a reset stream's address and serve a stale
        LUT for a brand-new dictionary."""
        key = id(d)
        entry = self._owner_lut.get(key)
        lut = entry[1] if entry is not None and entry[0] is d else None
        have = 0 if lut is None else len(lut)
        need = int(codes.max()) + 1 if len(codes) else 0
        if have < need:
            order = self.cmap.order
            fresh = np.fromiter(
                (order.index(self.cmap.owner_of(s))
                 for s in d.decode(np.arange(have, need))),
                dtype=np.int64, count=need - have)
            lut = (fresh if lut is None
                   else np.concatenate([lut, fresh]))
            self._owner_lut[key] = (d, lut)
            if len(self._owner_lut) > 64:
                # stream resets mint fresh dictionaries; drop stale LUTs
                self._owner_lut = {key: (d, lut)}
        return lut[codes]

    # -- forward -----------------------------------------------------------

    def forward_all(self, remote: List[Tuple[str, object]],
                    stream: str, seq: Optional[int]) -> List:
        """Start one forward per remote slice; returns futures for
        `await_all`. The request thread's trace context is captured
        HERE (the pool workers run on other threads) so each forward's
        span — and the traceparent it stamps on the wire — joins the
        originating ingest trace."""
        sub = self.sub_stream(stream)
        ctx = _trace.current_context()
        return [self._pool.submit(self._send, peer, part, sub, seq,
                                  ctx)
                for peer, part in remote]

    def forward_all_wire(self, remote: List[Tuple[str, bytes, int]],
                         stream: str, seq: Optional[int]) -> List:
        """`forward_all` for `split_wire` output: the TREC payloads
        are already cut by column gather, so the pool workers only
        POST bytes."""
        sub = self.sub_stream(stream)
        ctx = _trace.current_context()
        return [self._pool.submit(self._send_payload, peer, payload,
                                  rows, sub, seq, ctx)
                for peer, payload, rows in remote]

    def _send(self, peer: str, part, sub_stream: str,
              seq: Optional[int], ctx=None) -> Dict[str, object]:
        from ..store.wal import RECORD_MAGIC, encode_record_body
        payload = RECORD_MAGIC + encode_record_body("flows", part)
        return self._send_payload(peer, payload, len(part),
                                  sub_stream, seq, ctx)

    def _send_payload(self, peer: str, payload: bytes, n_rows: int,
                      sub_stream: str, seq: Optional[int],
                      ctx=None) -> Dict[str, object]:
        import time as _time

        from ..utils.faults import fire as _fire_fault
        # the data plane is part of a partition drill too: a severed
        # link drops forwards exactly like replication and heartbeats
        _fire_fault("net.send", peer=peer, path="/ingest")
        _fire_fault("peer.partition", peer=peer, path="/ingest")
        t0 = _time.perf_counter()
        with _trace.child_span("router.forward", ctx, peer=peer,
                               rows=n_rows):
            out = self._client(peer).send(payload, seq=seq,
                                          stream=sub_stream)
        _M_FWD_SECONDS.observe(_time.perf_counter() - t0)
        _M_FWD_ROWS.labels(peer=peer).inc(n_rows)
        _M_FWD_BATCHES.labels(
            result="duplicate" if out.get("duplicate") else "ok").inc()
        return out

    def await_all(self, futures: List) -> Tuple[int, int]:
        """(remote rows acked, duplicate slices). Raises
        RouterForwardError when any slice exhausted its retry budget —
        the producer retries the whole batch and every landed slice
        resolves duplicate:true."""
        rows = 0
        dups = 0
        first_err: Optional[Exception] = None
        for fut in futures:
            try:
                out = fut.result()
                rows += int(out.get("rows") or 0)
                if out.get("duplicate"):
                    dups += 1
            except Exception as e:
                _M_FWD_BATCHES.labels(result="failed").inc()
                self.forward_failures += 1
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise RouterForwardError(
                f"forwarded slice not acknowledged by its owner: "
                f"{first_err}")
        self.forwarded_rows += rows
        return rows, dups

    def stats(self) -> Dict[str, object]:
        return {
            "peers": len(self.cmap.order),
            "self": self.self_id,
            "forwardedRows": self.forwarded_rows,
            "forwardFailures": self.forward_failures,
        }
