"""WAL log-shipping replication: leader shippers, follower apply,
quorum acks, and part-manifest catch-up resync.

The PR-4 WAL was built self-contained (records carry their own string
dictionaries) precisely so a log written on one node replays on
another; this module ships it. Because frames ship byte-for-byte, the
TBLK zero-copy ingest path composes for free: a record whose body is
the producer's received column section journaled verbatim
(store/wire.py) replicates as those same bytes — the leader never
re-encodes, and the follower's log stays a byte-identical
continuation. One shipper thread per follower reads
raw frames from the leader's on-disk log above the follower's acked
LSN and POSTs them to the follower's `/cluster/replicate`; the
follower appends them VERBATIM to its own log (leader LSNs preserved —
its log is a byte-identical continuation, so `kill -9` + standard WAL
replay recovers a follower to an exact leader position) and applies
each record through the logical insert path (views update, dedup tags
seed the live window).

**Handshake (log matching).** Before streaming, the shipper verifies
the follower's (last LSN, last body CRC) against the leader's own
frame at that LSN. A match resumes frame shipping exactly there; a
mismatch, an unknown CRC, or a follower beyond the GC horizon
(WalShipGap) triggers a wholesale **resync**: the leader captures
(position, records) under its WAL quiesce latch — sealed cold parts
ship their file bodies verbatim, the PR-7 "ship sealed parts" path —
and the follower truncates, applies, resets its log to the leader's
position, and resumes frame shipping above it ("then the WAL tail").

**Ack quorum (THEIA_REPL_ACKS).** `leader` acknowledges after the
local WAL append alone; `quorum` waits until a majority of the
cluster (leader included) holds the batch's LSN; `all` waits for every
follower. The ingest path's durability gate calls `wait_durable(lsn)`
— a quorum that cannot be met within THEIA_REPL_ACK_TIMEOUT raises
ReplicationLagError (HTTP 503: retryable, the producer's retry is
idempotent via the dedup window). On the majority side of a partition
quorum still clears — degraded, not failed; the minority side refuses
acks rather than diverge.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..store.wal import WalShipGap
from ..utils.backoff import capped_backoff
from ..utils.env import env_float, env_int
from ..utils.logging import get_logger
from .transport import PeerUnreachable
from ..analysis.lockdep import named_condition, named_lock

logger = get_logger("cluster")

#: THEIA_REPL_ACKS values, least to most durable
ACK_POLICIES = ("leader", "quorum", "all")

#: resync stream envelope: magic, version, crc algo, reserved,
#: header-json length
_SNC_MAGIC = b"TSNC"
_SNC_HEADER = struct.Struct("<4sBBHI")
_SNC_REC = struct.Struct("<QI")        # body length, body crc

_M_SHIPPED_RECORDS = _metrics.counter(
    "theia_repl_shipped_records_total",
    "WAL records shipped to followers (counted per follower)")
_M_SHIPPED_BYTES = _metrics.counter(
    "theia_repl_shipped_bytes_total",
    "Raw frame bytes shipped to followers")
_M_ACKED = _metrics.gauge(
    "theia_repl_acked_lsn",
    "Highest LSN each follower has acknowledged (appended to its own "
    "log and applied)", labelnames=("peer",))
_M_LAG = _metrics.gauge(
    "theia_repl_lag_records",
    "Leader LSN minus the follower's acked LSN", labelnames=("peer",))
_M_RESYNCS = _metrics.counter(
    "theia_repl_resyncs_total",
    "Wholesale part-manifest catch-up resyncs shipped to followers")
_M_QUORUM_WAIT = _metrics.histogram(
    "theia_repl_quorum_wait_seconds",
    "Time the ingest ack path waited for the configured follower "
    "ack quorum")
_M_QUORUM_TIMEOUTS = _metrics.counter(
    "theia_repl_quorum_timeouts_total",
    "Ingest acks refused because the ack quorum could not be met in "
    "time (HTTP 503; the producer's retry is dedup-idempotent)")
_M_APPLIED_RECORDS = _metrics.counter(
    "theia_repl_applied_records_total",
    "Shipped WAL records applied on this node (follower side)")
_M_APPLIED_ROWS = _metrics.counter(
    "theia_repl_applied_rows_total",
    "Rows applied from shipped WAL records (follower side)")


class ReplicationLagError(Exception):
    """The configured ack quorum cannot be met right now (followers
    down/lagging/partitioned) — HTTP 503: retry later, the dedup
    window makes the retry idempotent."""


class StaleReadError(Exception):
    """A bounded-staleness follower read exceeded the staleness budget
    (HTTP 503 — read from the leader or retry after catch-up)."""


def default_ack_policy() -> str:
    raw = (os.environ.get("THEIA_REPL_ACKS", "") or "quorum").strip()
    if raw not in ACK_POLICIES:
        raise ValueError(
            f"THEIA_REPL_ACKS {raw!r}: expected one of {ACK_POLICIES}")
    return raw


def pack_resync_stream(position: int, position_crc: Optional[int],
                       term: int, records,
                       dedup_entries: List[Tuple[str, int, int]],
                       algo: int, crc_fn) -> bytes:
    """Serialize one wholesale resync: envelope header (position +
    handshake token + term + the leader's live dedup entries, so
    exactly-once survives a resync'd failover) followed by
    length-prefixed, checksummed record bodies."""
    header = json.dumps({
        "position": int(position),
        "positionCrc": position_crc,
        "term": int(term),
        "dedup": [[s, int(q), int(r)] for s, q, r in dedup_entries],
    }).encode()
    out = [_SNC_HEADER.pack(_SNC_MAGIC, 1, algo, 0, len(header)),
           header]
    for body in records:
        body = bytes(body)
        crc = (crc_fn(body, 0) & 0xFFFFFFFF) if crc_fn else 0
        out.append(_SNC_REC.pack(len(body), crc))
        out.append(body)
    return b"".join(out)


def unpack_resync_stream(data: bytes):
    """Inverse of pack_resync_stream: (header dict, body iterator)."""
    from ..store.wal import WalCorruption, _checksum_fn
    if len(data) < _SNC_HEADER.size:
        raise WalCorruption("short resync envelope")
    magic, ver, algo, _, hlen = _SNC_HEADER.unpack_from(data, 0)
    if magic != _SNC_MAGIC or ver != 1:
        raise WalCorruption("bad resync envelope magic/version")
    off = _SNC_HEADER.size
    header = json.loads(data[off:off + hlen])
    off += hlen
    crc_fn = _checksum_fn(algo)

    def bodies(off=off):
        while off < len(data):
            if off + _SNC_REC.size > len(data):
                raise WalCorruption("truncated resync record header")
            blen, crc = _SNC_REC.unpack_from(data, off)
            off += _SNC_REC.size
            if off + blen > len(data):
                raise WalCorruption("truncated resync record body")
            body = data[off:off + blen]
            if crc_fn is not None and \
                    (crc_fn(body, 0) & 0xFFFFFFFF) != crc:
                raise WalCorruption("resync record checksum mismatch")
            off += blen
            yield body

    return header, bodies()


class _Follower:
    """Leader-side state for one follower link."""

    def __init__(self, peer: str) -> None:
        self.peer = peer
        self.acked = -1            # -1 = handshake pending
        self.status = "handshake"  # handshake|streaming|resyncing|unreachable
        self.last_error: Optional[str] = None
        self.resyncs = 0
        self.shipped_records = 0
        self.fails = 0


class ReplicationLeader:
    """Ships this node's WAL to every follower; tracks acked LSNs;
    answers the ingest path's quorum waits."""

    def __init__(self, db, transport, followers: List[str],
                 acks: Optional[str] = None,
                 term: int = 1,
                 ack_timeout: Optional[float] = None,
                 ship_bytes: Optional[int] = None,
                 idle_wait: float = 0.05,
                 dedup_dump: Optional[Callable[[], List[tuple]]] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.db = db
        self.transport = transport
        self.acks = acks if acks is not None else default_ack_policy()
        if self.acks not in ACK_POLICIES:
            raise ValueError(
                f"ack policy {self.acks!r}: expected one of "
                f"{ACK_POLICIES}")
        self.term = int(term)
        self.ack_timeout = (env_float("THEIA_REPL_ACK_TIMEOUT", 10.0)
                            if ack_timeout is None
                            else float(ack_timeout))
        if ship_bytes is None:
            # frames ship in batched POSTs up to this budget: every
            # frame pending when the shipper wakes rides ONE request
            # (one connection-pool roundtrip, one follower fsync),
            # which is what turns concurrent producers into larger
            # ship batches instead of more roundtrips. The old
            # THEIA_REPL_SHIP_BYTES spelling is honored for
            # deployments that pinned it.
            legacy = os.environ.get("THEIA_REPL_SHIP_BYTES")
            self.ship_bytes = (
                int(legacy) if legacy
                else env_int("THEIA_REPL_BATCH_BYTES", 256 << 10))
        else:
            self.ship_bytes = int(ship_bytes)
        self.idle_wait = idle_wait
        self.dedup_dump = dedup_dump
        self._clock = clock
        self._cond = named_condition("repl.leader")
        self._followers: Dict[str, _Follower] = {
            p: _Follower(p) for p in followers}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        for peer in self._followers:
            t = threading.Thread(
                target=self._ship_loop, args=(peer,), daemon=True,
                name=f"theia-repl-ship-{peer}")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=10)

    # -- ack bookkeeping ---------------------------------------------------

    def required_follower_acks(self) -> int:
        """Followers that must hold an LSN before it is quorum-durable:
        leader → 0; all → every follower; quorum → a majority of the
        whole cluster (leader included) minus the leader itself."""
        n_followers = len(self._followers)
        if self.acks == "leader" or n_followers == 0:
            return 0
        if self.acks == "all":
            return n_followers
        cluster = n_followers + 1
        return (cluster // 2 + 1) - 1

    def acked_followers(self, lsn: int) -> int:
        with self._cond:
            return sum(1 for f in self._followers.values()
                       if f.acked >= lsn)

    def note_appended(self) -> None:
        """Ingest-path hint that new records exist — wakes shippers
        without waiting out the idle poll."""
        with self._cond:
            self._cond.notify_all()

    def wait_durable(self, lsn: Optional[int],
                     timeout: Optional[float] = None) -> None:
        """Block until the configured quorum of followers acked `lsn`.
        Raises ReplicationLagError on timeout — the caller answers 503
        and the producer retries (idempotent via the dedup window)."""
        need = self.required_follower_acks()
        if need <= 0 or lsn is None:
            return
        lsn = int(lsn)
        deadline = self._clock() + (self.ack_timeout
                                    if timeout is None else timeout)
        t0 = time.perf_counter()
        with self._cond:
            self._cond.notify_all()   # wake shippers for this append
            while True:
                acked = sum(1 for f in self._followers.values()
                            if f.acked >= lsn)
                if acked >= need:
                    break
                left = deadline - self._clock()
                if left <= 0:
                    _M_QUORUM_TIMEOUTS.inc()
                    raise ReplicationLagError(
                        f"ack quorum not met: {acked}/{need} followers "
                        f"at LSN {lsn} within {self.ack_timeout:g}s "
                        f"(policy {self.acks})")
                self._cond.wait(min(left, 0.25))
        _M_QUORUM_WAIT.observe(time.perf_counter() - t0)

    def quorum_lag(self) -> int:
        """Lag of the follower that CLEARS the quorum (the `need`-th
        best acked): the admission plane's replication-pressure signal.
        A dead follower outside the quorum does not register — only
        risk to the ack path does."""
        need = self.required_follower_acks()
        if need <= 0:
            return 0
        pos = self.db.wal_position() or 0
        with self._cond:
            acked = sorted((f.acked for f in self._followers.values()),
                           reverse=True)
        mark = acked[need - 1] if need <= len(acked) else -1
        return max(0, int(pos) - max(mark, 0))

    # -- the shipper -------------------------------------------------------

    def _ship_loop(self, peer: str) -> None:
        f = self._followers[peer]
        while not self._stop.is_set():
            try:
                if f.acked < 0:
                    self._handshake(f)
                advanced = self._ship_once(f)
                f.fails = 0
                if not advanced:
                    with self._cond:
                        self._cond.wait(self.idle_wait)
            except _NeedsResync:
                try:
                    self._resync(f)
                    f.fails = 0
                except (PeerUnreachable, Exception) as e:
                    self._note_failure(f, e)
            except PeerUnreachable as e:
                self._note_failure(f, e)
            except Exception as e:      # keep the link alive
                self._note_failure(f, e)

    def _note_failure(self, f: _Follower, e: Exception) -> None:
        f.fails += 1
        f.status = "unreachable"
        f.last_error = f"{type(e).__name__}: {e}"
        # re-handshake after a disconnect: the follower may have
        # restarted (recovered from its own log) or been resynced
        with self._cond:
            f.acked = -1
            self._cond.notify_all()
        delay = capped_backoff(0.1, 5.0, f.fails)
        logger.v(1).info("replication to %s failed (%s); retry in "
                         "%.1fs", f.peer, e, delay)
        self._stop.wait(delay)

    def _handshake(self, f: _Follower) -> None:
        """Log-matching: resume streaming exactly where the follower's
        log ends, or declare a resync."""
        doc = self.transport.request(f.peer, "/cluster/ping")
        wal = doc.get("wal") or {}
        lsn = int(wal.get("lsn") or 0)
        crc = wal.get("crc")
        own = self.db.wal_position() or 0
        if lsn == 0:
            with self._cond:
                f.acked = 0
                self._cond.notify_all()
            f.status = "streaming"
            return
        if lsn > own or crc is None:
            raise _NeedsResync(
                f"follower at LSN {lsn} (crc {crc}) vs leader {own}")
        ours = self.db.wal_body_crc_at(lsn)
        if ours is None or int(ours) != int(crc):
            raise _NeedsResync(
                f"log mismatch at LSN {lsn}: follower crc {crc}, "
                f"leader {ours}")
        with self._cond:
            f.acked = lsn
            self._cond.notify_all()
        f.status = "streaming"
        logger.info("follower %s resumes frame shipping above LSN %d",
                    f.peer, lsn)

    def _ship_once(self, f: _Follower) -> bool:
        """Ship one batch of frames; returns True when the follower
        advanced (more may be pending)."""
        pos = self.db.wal_position() or 0
        if f.acked >= pos:
            f.status = "streaming"
            return False
        try:
            frames, last, algo = self.db.wal_read_frames(
                f.acked, max_bytes=self.ship_bytes)
        except WalShipGap as e:
            raise _NeedsResync(str(e))
        if not frames:
            return False
        # each ship batch is a trace root: the follower's apply span
        # joins it via the traceparent the transport stamps (minted
        # only when frames actually move — idle polls trace nothing)
        with _trace.ingress_span("repl.ship", peer=f.peer,
                                 bytes=len(frames)):
            doc = self.transport.request(
                f.peer, "/cluster/replicate", data=frames,
                headers={"Content-Type": "application/octet-stream",
                         "X-Theia-Algo": str(algo),
                         "X-Theia-Term": str(self.term),
                         "X-Theia-Leader-Lsn": str(pos)})
        if doc.get("needResync"):
            raise _NeedsResync(f"follower {f.peer} requested resync")
        acked = int(doc.get("ackedLsn") or 0)
        with self._cond:
            f.acked = max(f.acked, acked)
            self._cond.notify_all()
        f.status = "streaming"
        f.shipped_records += int(doc.get("applied") or 0)
        _M_SHIPPED_RECORDS.inc(int(doc.get("applied") or 0))
        _M_SHIPPED_BYTES.inc(len(frames))
        _M_ACKED.labels(peer=f.peer).set(f.acked)
        _M_LAG.labels(peer=f.peer).set(
            max(0, (self.db.wal_position() or 0) - f.acked))
        return True

    def _resync(self, f: _Follower) -> None:
        """Wholesale part-manifest catch-up: capture under the quiesce
        latch, ship parts + memtable + result tables + the live dedup
        window, land the follower at `position`, resume frames above."""
        from ..store.wal import _WRITE_ALGO, _write_crc
        f.status = "resyncing"
        logger.warning("resyncing follower %s wholesale (beyond frame "
                       "catch-up)", f.peer)
        position, position_crc, records = self.db.resync_export()
        dedup = (self.dedup_dump() if self.dedup_dump is not None
                 else [])
        payload = pack_resync_stream(position, position_crc, self.term,
                                     records, dedup, _WRITE_ALGO,
                                     _write_crc)
        with _trace.ingress_span("repl.resync", peer=f.peer,
                                 bytes=len(payload)):
            doc = self.transport.request(
                f.peer, "/cluster/resync", data=payload,
                headers={"Content-Type": "application/octet-stream"},
                timeout=max(self.transport.timeout, 120.0))
        acked = int(doc.get("ackedLsn") or 0)
        with self._cond:
            f.acked = acked
            self._cond.notify_all()
        f.status = "streaming"
        f.resyncs += 1
        _M_RESYNCS.inc()
        _M_ACKED.labels(peer=f.peer).set(acked)
        logger.info("follower %s resynced at LSN %d (%d resync bytes)",
                    f.peer, acked, len(payload))

    # -- operator surface --------------------------------------------------

    def stats(self) -> Dict[str, object]:
        pos = 0
        try:
            pos = self.db.wal_position() or 0
        except Exception:
            pass
        with self._cond:
            followers = [{
                "peer": f.peer,
                "ackedLsn": f.acked,
                "lag": max(0, pos - f.acked) if f.acked >= 0 else None,
                "status": f.status,
                "resyncs": f.resyncs,
                **({"lastError": f.last_error} if f.last_error else {}),
            } for f in self._followers.values()]
        return {
            "role": "leader",
            "term": self.term,
            "acks": self.acks,
            "requiredFollowerAcks": self.required_follower_acks(),
            "lastLsn": pos,
            "quorumLag": self.quorum_lag(),
            "followers": followers,
        }


class _NeedsResync(Exception):
    """Internal shipper signal: frame catch-up impossible, go
    wholesale."""


class FollowerApplier:
    """Follower-side server half: applies shipped frames / resync
    streams to the local store, seeds the live dedup window, and
    answers bounded-staleness read checks."""

    def __init__(self, db, dedup=None,
                 max_staleness: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.db = db
        self.dedup = dedup
        self.max_staleness = (
            env_float("THEIA_REPL_MAX_STALENESS", 30.0)
            if max_staleness is None else float(max_staleness))
        self._clock = clock
        self._lock = named_lock("repl.follower")
        self.leader_lsn = 0
        self.leader_term = 0
        self.leader_id: Optional[str] = None
        self.last_contact: Optional[float] = None
        self.applied_rows = 0
        self.resyncs = 0
        #: divergent tail extracted by the last resync, for the caller
        #: (ClusterNode) to re-ingest through the new leader's dedup
        self.pending_tail: List[tuple] = []

    def handle_replicate(self, data: bytes, algo: int, term: int,
                         leader_lsn: int,
                         leader_id: Optional[str]) -> Dict[str, object]:
        from ..store.wal import WalError
        with self._lock:
            self.leader_term = max(self.leader_term, int(term))
            self.leader_lsn = max(self.leader_lsn, int(leader_lsn))
            self.leader_id = leader_id or self.leader_id
            self.last_contact = self._clock()
        try:
            out = self.db.apply_replicated_frames(data, algo)
        except WalError as e:
            # a gap (we missed a batch mid-stream) or closed log: ask
            # the leader to re-handshake/resync rather than 500
            logger.warning("replicate apply failed (%s); requesting "
                           "resync", e)
            return {"needResync": True,
                    "ackedLsn": self.db.wal_position() or 0}
        for stream, seq, rows, _total in out["acks"]:
            if self.dedup is not None:
                self.dedup.record(stream, seq, rows)
        with self._lock:
            self.applied_rows += int(out["rows"])
        if out["applied"]:
            _M_APPLIED_RECORDS.inc(int(out["applied"]))
            _M_APPLIED_ROWS.inc(int(out["rows"]))
        return {"ackedLsn": int(out["ackedLsn"]),
                "applied": int(out["applied"]),
                "rows": int(out["rows"])}

    def handle_resync(self, data: bytes,
                      leader_id: Optional[str]) -> Dict[str, object]:
        header, bodies = unpack_resync_stream(data)
        position = int(header.get("position") or 0)
        # extract the divergent tail BEFORE truncation: tagged batches
        # in our log that the new leader may never have seen re-ingest
        # through its dedup window (acked ones resolve duplicate:true)
        tail = []
        try:
            tail = self.db.wal_tail_tagged_records(0)
        except Exception as e:
            logger.error("tail extraction before resync failed: %s", e)
        rows = self.db.resync_apply(bodies, position,
                                    header.get("positionCrc"))
        if self.dedup is not None:
            for ent in header.get("dedup") or []:
                try:
                    stream, seq, n = ent[0], int(ent[1]), int(ent[2])
                except (TypeError, ValueError, IndexError):
                    continue
                self.dedup.record(stream, seq, n)
        with self._lock:
            self.leader_term = max(self.leader_term,
                                   int(header.get("term") or 0))
            self.leader_lsn = max(self.leader_lsn, position)
            self.leader_id = leader_id or self.leader_id
            self.last_contact = self._clock()
            self.resyncs += 1
            self.pending_tail = tail
        logger.warning(
            "resynced from leader at LSN %d: %d rows applied, %d "
            "tagged tail batches held for re-ingest", position, rows,
            len(tail))
        return {"ackedLsn": position, "rows": rows,
                "tailBatches": len(tail)}

    def take_pending_tail(self) -> List[tuple]:
        with self._lock:
            tail, self.pending_tail = self.pending_tail, []
        return tail

    # -- bounded-staleness reads -------------------------------------------

    def staleness(self) -> Dict[str, object]:
        with self._lock:
            applied = self.db.wal_position() or 0
            lag = max(0, self.leader_lsn - applied)
            age = (None if self.last_contact is None
                   else self._clock() - self.last_contact)
        return {"appliedLsn": applied, "leaderLsn": self.leader_lsn,
                "lagRecords": lag,
                "leaderContactAgeSeconds":
                    None if age is None else round(age, 3)}

    def check_read_staleness(self) -> None:
        """Gate a follower read: raise StaleReadError when this copy
        has not heard from the leader within the staleness budget
        (THEIA_REPL_MAX_STALENESS seconds; <= 0 disables — reads are
        then unbounded-staleness, the operator's call)."""
        if self.max_staleness <= 0:
            return
        with self._lock:
            age = (None if self.last_contact is None
                   else self._clock() - self.last_contact)
        if age is None or age > self.max_staleness:
            raise StaleReadError(
                f"follower read refused: no leader contact for "
                f"{'ever' if age is None else f'{age:.1f}s'} "
                f"(budget {self.max_staleness:g}s) — read from the "
                f"leader or retry after catch-up")

    def stats(self) -> Dict[str, object]:
        doc = self.staleness()
        with self._lock:
            doc.update({
                "role": "follower",
                "term": self.leader_term,
                "leader": self.leader_id,
                "appliedRows": self.applied_rows,
                "resyncs": self.resyncs,
                "maxStalenessSeconds": self.max_staleness,
            })
        return doc
