"""Cluster membership: static seed config + heartbeat liveness.

The reference runs a fixed shards×replicas ClickHouse grid declared in
Helm values and coordinated by ZooKeeper (SURVEY.md §1); membership is
configuration, liveness is runtime. The same split here:

  * **Seed config** — `THEIA_CLUSTER_PEERS` / `--peers` names every
    node once, identically on every node (order matters: shard
    placement hashes into the PEER LIST ORDER, so two nodes with
    different orderings would route the same destination differently):

        THEIA_CLUSTER_PEERS="node0=http://10.0.0.1:11347,node1=http://10.0.0.2:11347"

    Bare addresses get positional ids (`node0`, `node1`, ...).
    `THEIA_CLUSTER_SELF` / `--node-id` names this node's entry.

  * **Liveness** — `HeartbeatLoop` probes every peer's
    `GET /cluster/ping` on a fixed interval; a peer whose last
    successful probe is older than `THEIA_CLUSTER_PEER_TIMEOUT`
    seconds is `down`. Probes ride the cluster transport, so the
    `net.send` / `peer.partition` fault sites sever them exactly like
    replication traffic — a partition drill takes liveness down WITH
    the data plane, never separately.

Placement: `owner_of(destination)` is the same stable crc32 placement
the in-process detector shards use (manager/ingest.py
`shard_of_destination`), lifted to the peer list — identical across
processes, restarts, and ingestion orders, so every node computes the
same owner without coordination.
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Tuple

from ..obs import metrics as _metrics
from ..utils.env import env_float
from ..utils.logging import get_logger
from ..analysis.lockdep import named_lock

logger = get_logger("cluster")

_M_PEER_UP = _metrics.gauge(
    "theia_cluster_peer_up",
    "1 while the peer's last heartbeat probe succeeded within the "
    "liveness timeout, else 0", labelnames=("peer",))
_M_HEARTBEATS = _metrics.counter(
    "theia_cluster_heartbeats_total",
    "Heartbeat probes sent, by outcome", labelnames=("result",))
_M_HEARTBEAT_RTT = _metrics.histogram(
    "theia_cluster_heartbeat_rtt_seconds",
    "Round-trip time of successful heartbeat probes, per peer — the "
    "cluster's live link-latency read (`theia top` renders the "
    "per-peer average in its cluster header)", labelnames=("peer",))


class ClusterConfigError(ValueError):
    """Malformed peer spec / unknown self id — fail at startup, not at
    the first forwarded batch."""


def parse_peers(spec: str) -> "List[Tuple[str, str]]":
    """`THEIA_CLUSTER_PEERS` grammar → ordered (node_id, base_url)
    pairs. Entries are `id=url` or bare `url` (positional ids
    `node<i>`); ids must be unique. The ORDER is part of the cluster
    contract (placement hashes into it)."""
    out: List[Tuple[str, str]] = []
    seen = set()
    for i, entry in enumerate(
            e.strip() for e in (spec or "").split(",")):
        if not entry:
            continue
        if "=" in entry.split("://", 1)[0]:
            node_id, _, addr = entry.partition("=")
            node_id = node_id.strip()
        else:
            node_id, addr = f"node{i}", entry
        addr = addr.strip().rstrip("/")
        if not addr.startswith(("http://", "https://")):
            raise ClusterConfigError(
                f"peer {entry!r}: address must be http(s)://host:port")
        if not node_id or node_id in seen:
            raise ClusterConfigError(
                f"peer {entry!r}: duplicate or empty node id")
        seen.add(node_id)
        out.append((node_id, addr))
    return out


class ClusterMap:
    """The static peer list + this node's identity + live heartbeat
    state. Thread-safe; the clock is injectable so liveness transitions
    are deterministic under test."""

    def __init__(self, peers: List[Tuple[str, str]], self_id: str,
                 peer_timeout: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if not peers:
            raise ClusterConfigError("empty peer list")
        ids = [p for p, _ in peers]
        if self_id not in ids:
            raise ClusterConfigError(
                f"--node-id {self_id!r} is not in the peer list "
                f"{ids}")
        self.peers: Dict[str, str] = dict(peers)
        self.order: List[str] = ids
        self.self_id = self_id
        self.peer_timeout = (
            env_float("THEIA_CLUSTER_PEER_TIMEOUT", 5.0)
            if peer_timeout is None else float(peer_timeout))
        self._clock = clock
        self._lock = named_lock("cluster.map")
        #: peer -> (last success monotonic, last ping doc)
        self._seen: Dict[str, Tuple[float, Dict[str, object]]] = {}
        self._last_err: Dict[str, str] = {}
        #: liveness-transition counter (see membership_epoch)
        self._epoch = 0
        self._alive_snap: Optional[Tuple[str, ...]] = None

    def others(self) -> List[str]:
        return [p for p in self.order if p != self.self_id]

    def addr(self, node_id: str) -> str:
        return self.peers[node_id]

    def owner_of(self, destination: str) -> str:
        """Stable owner node for a destination string — crc32 of the
        UTF-8 bytes into the peer-list order (the detector-shard
        placement, lifted to the cluster)."""
        h = zlib.crc32(destination.encode("utf-8", "surrogatepass"))
        return self.order[h % len(self.order)]

    # -- liveness ----------------------------------------------------------

    def mark_alive(self, peer: str,
                   info: Optional[Dict[str, object]] = None) -> None:
        with self._lock:
            self._seen[peer] = (self._clock(), dict(info or {}))
            self._last_err.pop(peer, None)
        _M_PEER_UP.labels(peer=peer).set(1)

    def mark_failed(self, peer: str, err: str) -> None:
        with self._lock:
            self._last_err[peer] = err
        if not self.is_alive(peer):
            _M_PEER_UP.labels(peer=peer).set(0)

    def is_alive(self, peer: str) -> bool:
        if peer == self.self_id:
            return True
        with self._lock:
            seen = self._seen.get(peer)
        return (seen is not None
                and self._clock() - seen[0] <= self.peer_timeout)

    def alive(self) -> List[str]:
        return [p for p in self.order if self.is_alive(p)]

    def membership_epoch(self) -> int:
        """Monotone counter of OBSERVED liveness transitions: any peer
        flipping alive ↔ down since the last call bumps it, so "the
        membership changed" is one integer comparison — the cluster
        query cache keys on it (query/distributed.py), and a peer
        coming back structurally invalidates every cached
        partial-coverage decision."""
        current = tuple(self.alive())
        with self._lock:
            if current != self._alive_snap:
                self._alive_snap = current
                self._epoch += 1
            return self._epoch

    def peer_info(self, peer: str) -> Dict[str, object]:
        with self._lock:
            seen = self._seen.get(peer)
            return dict(seen[1]) if seen else {}

    def snapshot(self) -> Dict[str, object]:
        """Operator view (served under /healthz `cluster.peers`)."""
        now = self._clock()
        out = []
        with self._lock:
            for p in self.order:
                seen = self._seen.get(p)
                doc: Dict[str, object] = {
                    "id": p, "addr": self.peers[p],
                    "self": p == self.self_id,
                }
                if p == self.self_id:
                    doc["up"] = True
                else:
                    doc["up"] = (seen is not None
                                 and now - seen[0] <= self.peer_timeout)
                    if seen is not None:
                        doc["lastSeenAgoSeconds"] = round(
                            now - seen[0], 3)
                        doc.update({k: v for k, v in seen[1].items()
                                    if k in ("role", "term",
                                             "appliedLsn", "lastLsn")})
                    if p in self._last_err:
                        doc["lastError"] = self._last_err[p]
                out.append(doc)
        return {"self": self.self_id, "peers": out}


class HeartbeatLoop:
    """Background liveness prober: `probe(peer)` → ping doc (raises on
    failure). The default probe is wired by ClusterNode to the cluster
    transport's GET /cluster/ping; tests inject both probe and clock
    and drive `beat_once()` directly — no sleeps."""

    def __init__(self, cmap: ClusterMap,
                 probe: Callable[[str], Dict[str, object]],
                 interval: Optional[float] = None,
                 on_seen: Optional[Callable[
                     [str, Dict[str, object]], None]] = None) -> None:
        self.cmap = cmap
        self.probe = probe
        self.interval = (env_float("THEIA_CLUSTER_HEARTBEAT", 1.0)
                         if interval is None else float(interval))
        self.on_seen = on_seen
        self.beats = 0
        #: peer -> last successful probe RTT in seconds (served under
        #: /healthz `cluster.heartbeatRttSeconds`)
        self.last_rtt: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name="theia-cluster-heartbeat")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.beat_once()
            except Exception as e:   # keep beating after a bad pass
                logger.error("heartbeat pass failed: %s", e)

    def beat_once(self) -> List[str]:
        """Probe every other peer once; returns the ids that answered."""
        alive: List[str] = []
        for peer in self.cmap.others():
            t0 = time.perf_counter()
            try:
                info = self.probe(peer)
            except Exception as e:
                _M_HEARTBEATS.labels(result="failed").inc()
                self.cmap.mark_failed(peer, f"{type(e).__name__}: {e}")
            else:
                rtt = time.perf_counter() - t0
                _M_HEARTBEATS.labels(result="ok").inc()
                _M_HEARTBEAT_RTT.labels(peer=peer).observe(rtt)
                self.last_rtt[peer] = rtt
                self.cmap.mark_alive(peer, info)
                if self.on_seen is not None:
                    self.on_seen(peer, info)
                alive.append(peer)
        self.beats += 1
        return alive
