"""Cluster-internal HTTP transport: one choke point for every byte
that crosses a node boundary.

All peer traffic — heartbeats, WAL frame shipping, resync streams,
promote RPCs — goes through `ClusterTransport`, which is where the
network-level fault sites live:

    net.send           before any bytes leave for a peer
    net.recv           on the server side, before a peer's request is
                       processed (fired by the API handler via
                       `fire_recv`)
    peer.partition     BOTH directions of one link — checked inside
                       net.send and net.recv, so arming
                       `peer.partition#node2:error` severs the node2
                       link symmetrically: the deterministic
                       network-partition drill (utils/faults.py
                       grammar; per-peer targeting via `#<peer>`)

Requests carry `X-Theia-Node` (the sender's id) so the receiving side
can attribute the hit to a link, and the bearer token when the cluster
is authenticated (peers authenticate to each other exactly like
producers do — one token, the deployment's service secret).
"""

from __future__ import annotations

import json
import ssl
import urllib.error
import urllib.request
from typing import Dict, Optional

from ..utils.faults import fire as _fire_fault
from ..utils.logging import get_logger

logger = get_logger("cluster")

#: header carrying the sender's node id on every cluster request
NODE_HEADER = "X-Theia-Node"


class PeerUnreachable(Exception):
    """Transport-level failure talking to a peer (connect/read error,
    5xx, or an armed partition fault) — retryable, the peer may heal."""

    def __init__(self, peer: str, detail: str) -> None:
        super().__init__(f"peer {peer} unreachable: {detail}")
        self.peer = peer


def fire_recv(peer: Optional[str], path: str) -> None:
    """Server-side fault hook: the API handler calls this with the
    request's X-Theia-Node before processing a /cluster/* request, so
    a partition drill drops inbound traffic too (a real partition is
    symmetric)."""
    if peer:
        _fire_fault("net.recv", peer=peer, path=path)
        _fire_fault("peer.partition", peer=peer, path=path)


class ClusterTransport:
    """Minimal JSON/bytes HTTP client for peer calls."""

    def __init__(self, cmap, token: str = "",
                 ca_cert: Optional[str] = None,
                 timeout: float = 10.0) -> None:
        self.cmap = cmap
        self.token = token
        self.timeout = float(timeout)
        self._ctx = (ssl.create_default_context(cafile=ca_cert)
                     if ca_cert else None)

    def _headers(self, extra: Optional[Dict[str, str]] = None
                 ) -> Dict[str, str]:
        h = {NODE_HEADER: self.cmap.self_id}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        if extra:
            h.update(extra)
        return h

    def request(self, peer: str, path: str,
                data: Optional[bytes] = None,
                headers: Optional[Dict[str, str]] = None,
                timeout: Optional[float] = None) -> Dict[str, object]:
        """One GET (data=None) or POST to `peer`; returns the parsed
        JSON body. Raises PeerUnreachable on transport failure / 5xx /
        armed partition; an HTTP 4xx surfaces as-is (a protocol error,
        not a connectivity one)."""
        url = self.cmap.addr(peer) + path
        req = urllib.request.Request(
            url, data=data, headers=self._headers(headers),
            method="POST" if data is not None else "GET")
        try:
            _fire_fault("net.send", peer=peer, path=path)
            _fire_fault("peer.partition", peer=peer, path=path)
            with urllib.request.urlopen(
                    req, timeout=timeout or self.timeout,
                    context=self._ctx) as resp:
                raw = resp.read()
        except urllib.error.HTTPError as e:
            body = e.read().decode(errors="replace")
            if e.code >= 500:
                raise PeerUnreachable(peer, f"{e.code}: {body[:200]}")
            raise
        except Exception as e:
            # URLError (connect), raw socket timeouts, hangups — and
            # FaultError from an armed net/partition site: all the
            # same "link is down" class to the caller
            raise PeerUnreachable(
                peer, f"{type(e).__name__}: "
                      f"{getattr(e, 'reason', None) or e}")
        try:
            return json.loads(raw) if raw else {}
        except json.JSONDecodeError as e:
            raise PeerUnreachable(peer, f"undecodable response: {e}")
