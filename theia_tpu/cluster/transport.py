"""Cluster-internal HTTP transport: one choke point for every byte
that crosses a node boundary.

All peer traffic — heartbeats, WAL frame shipping, resync streams,
promote RPCs, query-partial fan-out — goes through `ClusterTransport`,
which is where the network-level fault sites live:

    net.send           before any bytes leave for a peer
    net.recv           on the server side, before a peer's request is
                       processed (fired by the API handler via
                       `fire_recv`)
    peer.partition     BOTH directions of one link — checked inside
                       net.send and net.recv, so arming
                       `peer.partition#node2:error` severs the node2
                       link symmetrically: the deterministic
                       network-partition drill (utils/faults.py
                       grammar; per-peer targeting via `#<peer>`)

Connections are PERSISTENT: each peer keeps a small stack of idle
`http.client` connections reused across requests (heartbeats at 1 Hz,
a frame ship per ingest batch, and a partial per distributed query
used to pay a fresh TCP handshake each) and reconnects on error. A
request that fails on a REUSED connection before any response byte is
retried once on a fresh one — the classic keep-alive race where the
peer closed the idle socket; every cluster POST is idempotent by
design (duplicate frame ships are skipped, resyncs and partials are
pure), so the single silent retry is safe.

Requests carry `X-Theia-Node` (the sender's id) so the receiving side
can attribute the hit to a link, and the bearer token when the cluster
is authenticated (peers authenticate to each other exactly like
producers do — one token, the deployment's service secret). When the
calling thread runs inside a SAMPLED trace context (obs/trace.py), a
`traceparent` header rides along too, so the receiving node's spans
join the originating trace; unsampled/untraced requests carry no
header — with tracing disabled the wire is byte-identical.
"""

from __future__ import annotations

import http.client
import io
import json
import ssl
import threading
import urllib.error
import urllib.parse
from typing import Dict, List, Optional, Tuple

from ..obs import trace as _trace
from ..utils.faults import fire as _fire_fault
from ..utils.logging import get_logger
from ..analysis.lockdep import named_lock

logger = get_logger("cluster")

#: header carrying the sender's node id on every cluster request
NODE_HEADER = "X-Theia-Node"


class PeerUnreachable(Exception):
    """Transport-level failure talking to a peer (connect/read error,
    5xx, or an armed partition fault) — retryable, the peer may heal."""

    def __init__(self, peer: str, detail: str) -> None:
        super().__init__(f"peer {peer} unreachable: {detail}")
        self.peer = peer


def fire_recv(peer: Optional[str], path: str) -> None:
    """Server-side fault hook: the API handler calls this with the
    request's X-Theia-Node before processing a /cluster/* (or
    /query/partial) request, so a partition drill drops inbound
    traffic too (a real partition is symmetric)."""
    if peer:
        _fire_fault("net.recv", peer=peer, path=path)
        _fire_fault("peer.partition", peer=peer, path=path)


class ClusterTransport:
    """Minimal JSON/bytes HTTP client for peer calls, with per-peer
    persistent connection reuse."""

    #: idle connections kept per peer (heartbeat + shipper + a couple
    #: of concurrent query fan-outs share the stack; excess closes)
    MAX_IDLE_PER_PEER = 4

    def __init__(self, cmap, token: str = "",
                 ca_cert: Optional[str] = None,
                 timeout: float = 10.0) -> None:
        self.cmap = cmap
        self.token = token
        self.timeout = float(timeout)
        self._ctx = (ssl.create_default_context(cafile=ca_cert)
                     if ca_cert else None)
        self._idle: Dict[str, List[http.client.HTTPConnection]] = {}
        self._idle_lock = named_lock("transport.idle")
        self._closed = False

    # -- connection pool ---------------------------------------------------

    def _new_conn(self, peer: str,
                  timeout: float) -> http.client.HTTPConnection:
        import socket as _socket
        url = urllib.parse.urlsplit(self.cmap.addr(peer))
        if url.scheme == "https":
            ctx = self._ctx or ssl.create_default_context()
            conn = http.client.HTTPSConnection(
                url.hostname, url.port, timeout=timeout, context=ctx)
        else:
            conn = http.client.HTTPConnection(
                url.hostname, url.port, timeout=timeout)
        conn.connect()
        # TCP_NODELAY: a request is several small send()s (status
        # line, headers, body); on a REUSED connection Nagle + the
        # peer's delayed ACK turns each into a ~40ms stall — the
        # whole point of persistent connections is sub-ms peer calls
        conn.sock.setsockopt(_socket.IPPROTO_TCP,
                             _socket.TCP_NODELAY, 1)
        return conn

    def _acquire(self, peer: str, timeout: float
                 ) -> Tuple[http.client.HTTPConnection, bool]:
        """(connection, was_reused). A pooled connection gets the
        caller's timeout re-applied (resyncs run longer than pings)."""
        with self._idle_lock:
            stack = self._idle.get(peer)
            conn = stack.pop() if stack else None
        if conn is not None:
            conn.timeout = timeout
            if conn.sock is not None:
                conn.sock.settimeout(timeout)
            return conn, True
        return self._new_conn(peer, timeout), False

    def _release(self, peer: str,
                 conn: http.client.HTTPConnection) -> None:
        with self._idle_lock:
            if not self._closed:
                stack = self._idle.setdefault(peer, [])
                if len(stack) < self.MAX_IDLE_PER_PEER:
                    stack.append(conn)
                    return
        conn.close()

    def close(self) -> None:
        """Drop every pooled connection (node shutdown)."""
        with self._idle_lock:
            self._closed = True
            conns = [c for stack in self._idle.values()
                     for c in stack]
            self._idle.clear()
        for c in conns:
            try:
                c.close()
            except Exception:
                pass

    def pool_stats(self) -> Dict[str, int]:
        with self._idle_lock:
            return {p: len(s) for p, s in self._idle.items()}

    # -- requests ----------------------------------------------------------

    def _headers(self, extra: Optional[Dict[str, str]] = None
                 ) -> Dict[str, str]:
        h = {NODE_HEADER: self.cmap.self_id}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        tp = _trace.traceparent()
        if tp:
            h["traceparent"] = tp
        if extra:
            h.update(extra)
        return h

    def request(self, peer: str, path: str,
                data: Optional[bytes] = None,
                headers: Optional[Dict[str, str]] = None,
                timeout: Optional[float] = None) -> Dict[str, object]:
        """One GET (data=None) or POST to `peer`; returns the parsed
        JSON body. Raises PeerUnreachable on transport failure / 5xx /
        armed partition; an HTTP 4xx surfaces as urllib HTTPError (a
        protocol error, not a connectivity one)."""
        raw = self.request_raw(peer, path, data=data, headers=headers,
                               timeout=timeout)
        try:
            return json.loads(raw) if raw else {}
        except json.JSONDecodeError as e:
            raise PeerUnreachable(peer, f"undecodable response: {e}")

    def request_raw(self, peer: str, path: str,
                    data: Optional[bytes] = None,
                    headers: Optional[Dict[str, str]] = None,
                    timeout: Optional[float] = None) -> bytes:
        """`request` without the JSON decode — binary answers (query
        partial frames) read the body verbatim."""
        try:
            _fire_fault("net.send", peer=peer, path=path)
            _fire_fault("peer.partition", peer=peer, path=path)
        except Exception as e:
            raise PeerUnreachable(peer,
                                  f"{type(e).__name__}: {e}")
        t = timeout or self.timeout
        method = "POST" if data is not None else "GET"
        for attempt in (0, 1):
            conn, reused = self._acquire(peer, t)
            try:
                conn.request(method, path, body=data,
                             headers=self._headers(headers))
                resp = conn.getresponse()
                body = resp.read()
            except Exception as e:
                conn.close()
                if reused and attempt == 0 and isinstance(
                        e, (OSError, http.client.HTTPException)) \
                        and not isinstance(e, TimeoutError):
                    # stale keep-alive: the peer closed the idle
                    # socket under us — one silent retry on a FRESH
                    # connection (cluster POSTs are idempotent). A
                    # TIMEOUT is not that race (it manifests as an
                    # immediate reset, never a full timeout): a slow
                    # peer must not be waited on twice or re-execute
                    # the request.
                    continue
                raise PeerUnreachable(
                    peer, f"{type(e).__name__}: "
                          f"{getattr(e, 'reason', None) or e}")
            if resp.will_close:
                conn.close()
            else:
                self._release(peer, conn)
            if resp.status >= 500:
                raise PeerUnreachable(
                    peer, f"{resp.status}: "
                          f"{body[:200].decode(errors='replace')}")
            if resp.status >= 400:
                raise urllib.error.HTTPError(
                    self.cmap.addr(peer) + path, resp.status,
                    body.decode(errors="replace"), resp.headers,
                    io.BytesIO(body))
            return body
        raise PeerUnreachable(peer, "retry budget exhausted")
