"""ClusterNode: one manager's seat in the cluster.

Ties the membership map, heartbeat loop, and the role-specific plane
together behind the handful of hooks the manager needs:

  * role `leader`    — runs ReplicationLeader (WAL shippers toward
                       every other peer), provides the ingest
                       durability gate (quorum acks) and the
                       replication-lag admission signal.
  * role `follower`  — runs FollowerApplier (applies shipped frames /
                       resyncs), redirects `POST /ingest` to the
                       current leader (307 + Location), gates follower
                       reads on bounded staleness, and re-ingests a
                       divergent tail through the leader's dedup
                       window after a resync.
  * role `peer`      — routing mesh: every node accepts ingest and
                       runs IngestRouter (no replication plane).

Failover is WAL-delimited cutover: `POST /cluster/promote` on a
follower declares an LSN; the follower refuses (409) unless its
applied position covers it, then bumps the term and starts shipping to
the others. The demoted leader discovers the higher term through
heartbeats, steps down automatically, and its next handshake fails the
log-matching check → wholesale resync, with its unacked tagged tail
re-posted through the new leader's `/ingest` — acknowledged batches
resolve `duplicate:true` via the dedup window, unreplicated ones land.
Exactly the PR-5 exactly-once contract, operating across nodes.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional

from ..utils.env import env_float

from ..utils.logging import get_logger
from .membership import (
    ClusterConfigError,
    ClusterMap,
    HeartbeatLoop,
    parse_peers,
)
from .replication import (
    FollowerApplier,
    ReplicationLeader,
    StaleReadError,
)
from .router import IngestRouter, RouterForwardError
from .transport import ClusterTransport, PeerUnreachable
from ..analysis.lockdep import named_lock

logger = get_logger("cluster")

ROLES = ("leader", "follower", "peer")


class ClusterStateError(Exception):
    """A cluster control operation conflicts with this node's current
    state (promote below the applied LSN, promote on a leader, ...) —
    HTTP 409."""


def default_role() -> str:
    raw = (os.environ.get("THEIA_CLUSTER_ROLE", "") or "peer").strip()
    if raw not in ROLES:
        raise ClusterConfigError(
            f"THEIA_CLUSTER_ROLE {raw!r}: expected one of {ROLES}")
    return raw


class ClusterNode:
    """One node's cluster runtime. Constructed by TheiaManagerServer
    when a peer list is configured; `start()` after the HTTP socket is
    bound (peers probe us back), `stop()` on shutdown."""

    def __init__(self, db, ingest,
                 peers: Optional[str] = None,
                 self_id: Optional[str] = None,
                 role: Optional[str] = None,
                 token: str = "",
                 ca_cert: Optional[str] = None,
                 acks: Optional[str] = None,
                 heartbeat_interval: Optional[float] = None,
                 query_engine=None,
                 clock=None) -> None:
        spec = (peers if peers is not None
                else os.environ.get("THEIA_CLUSTER_PEERS", ""))
        parsed = parse_peers(spec)
        if not parsed:
            raise ClusterConfigError("empty --peers/THEIA_CLUSTER_PEERS")
        self_id = (self_id
                   or os.environ.get("THEIA_CLUSTER_SELF", "").strip()
                   or parsed[0][0])
        kwargs = {} if clock is None else {"clock": clock}
        self.cmap = ClusterMap(parsed, self_id, **kwargs)
        # the bounds-scan throttle rides the same injectable clock as
        # the heartbeat loop (tests step it without sleeping)
        self._clock = clock if clock is not None else time.monotonic
        self.db = db
        self.ingest = ingest
        self.role = role if role is not None else default_role()
        if self.role not in ROLES:
            raise ClusterConfigError(
                f"role {self.role!r}: expected one of {ROLES}")
        self._acks = acks
        self.term = 1
        self.token = token
        # Scatter-gather read path: heartbeats piggyback this node's
        # store fingerprint + time bounds so coordinators can cache
        # and prune (query/distributed.py). Optional — a node without
        # a query engine just pings without the store doc.
        self.query_engine = query_engine
        self._store_doc_cache: Optional[Dict[str, object]] = None
        self._store_doc_at = 0.0
        self._bounds_interval = env_float(
            "THEIA_CLUSTER_BOUNDS_INTERVAL", 5.0)
        self.transport = ClusterTransport(self.cmap, token=token,
                                          ca_cert=ca_cert)
        self._lock = named_lock("cluster.node")
        self.leader: Optional[ReplicationLeader] = None
        self.follower: Optional[FollowerApplier] = None
        self.router: Optional[IngestRouter] = None
        if self.role in ("leader", "follower"):
            self._require_replicable_db()
        if self.role == "leader":
            self.leader = self._make_leader()
        elif self.role == "follower":
            self.follower = self._make_follower()
        else:
            self.router = IngestRouter(self.cmap, token=token,
                                       ca_cert=ca_cert)
            if self.router is not None:
                ingest.router = self.router
        self.heartbeat = HeartbeatLoop(
            self.cmap,
            probe=lambda p: self.transport.request(p, "/cluster/ping"),
            interval=heartbeat_interval,
            on_seen=self._peer_seen)
        self._started = False

    def _require_replicable_db(self) -> None:
        if not callable(getattr(self.db, "wal_read_frames", None)):
            raise ClusterConfigError(
                "cluster replication roles need an UNWRAPPED "
                "FlowDatabase (no --shards/--replicas: cross-node "
                "shipping replaces the in-process fan-out; cross-node "
                "sharding is the router's job)")
        if self.db._wal is None:
            raise ClusterConfigError(
                "cluster replication requires --wal-dir (replication "
                "ships the WAL; there is nothing to ship without one)")

    def _make_leader(self) -> ReplicationLeader:
        dedup = getattr(self.ingest, "dedup", None)
        return ReplicationLeader(
            self.db, self.transport, followers=self.cmap.others(),
            acks=self._acks, term=self.term,
            dedup_dump=(dedup.dump if dedup is not None else None))

    def _make_follower(self) -> FollowerApplier:
        return FollowerApplier(
            self.db, dedup=getattr(self.ingest, "dedup", None))

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
        self.heartbeat.start()
        if self.leader is not None:
            self.leader.start()
        logger.info("cluster node %s up: role=%s peers=%s",
                    self.cmap.self_id, self.role,
                    ",".join(self.cmap.order))

    def stop(self) -> None:
        self.heartbeat.stop()
        if self.leader is not None:
            self.leader.stop()
        if self.router is not None:
            self.router.close()
        self.transport.close()

    # -- ingest-path hooks -------------------------------------------------

    def accepts_ingest(self) -> bool:
        return self.role != "follower"

    def leader_addr(self) -> Optional[str]:
        """Where a follower redirects producers (307 Location)."""
        if self.role == "leader":
            return self.cmap.addr(self.cmap.self_id)
        fol = self.follower
        if fol is not None and fol.leader_id in self.cmap.peers:
            return self.cmap.addr(fol.leader_id)
        # config fallback: the first peer is the conventional initial
        # leader until heartbeats teach us better
        others = self.cmap.others()
        return self.cmap.addr(others[0]) if others else None

    def durability_gate(self) -> None:
        """Called by the ingest path after the local insert leg
        (wired unconditionally — the role is checked HERE, so a
        follower promoted mid-flight starts enforcing the quorum):
        wake the shippers for the fresh append, then block the
        acknowledgement until the configured follower quorum holds the
        batch. Policy `leader` still gets the wake (sub-poll-interval
        shipping latency) without the wait."""
        leader = self.leader
        if leader is not None:
            leader.note_appended()
            leader.wait_durable(self.db.wal_position())

    def repl_lag(self) -> int:
        """Admission pressure signal: records the ack quorum is
        trailing behind the leader's log (leader role), or how stale
        this follower copy is (follower role)."""
        if self.leader is not None:
            return self.leader.quorum_lag()
        if self.follower is not None:
            return int(self.follower.staleness()["lagRecords"])
        return 0

    def check_query_staleness(self) -> None:
        if self.follower is not None:
            self.follower.check_read_staleness()

    # -- server-side handlers (wired by manager/api.py) --------------------

    def ping_doc(self) -> Dict[str, object]:
        doc: Dict[str, object] = {
            "node": self.cmap.self_id,
            "role": self.role,
            "term": self.current_term(),
            "appliedLsn": self.db.wal_position()
            if callable(getattr(self.db, "wal_position", None)) else None,
        }
        hs = getattr(self.db, "wal_handshake", None)
        if callable(hs):
            doc["wal"] = hs()
        if self.query_engine is not None:
            sd = self._store_ping_doc()
            if sd is not None:
                doc["store"] = sd
        return doc

    def _store_ping_doc(self) -> Optional[Dict[str, object]]:
        """Heartbeat piggyback for the scatter-gather read path: the
        CURRENT store fingerprint (coordinators key their cluster
        result cache on it — any insert/seal/merge here invalidates
        them within one heartbeat) plus per-table time bounds and row
        count (coordinators prune peers whose data cannot overlap a
        query window). The fingerprint is always fresh; the bounds
        scan is throttled to THEIA_CLUSTER_BOUNDS_INTERVAL — while a
        store is actively changing inside the throttle window only
        the bare fingerprint ships, so stale-narrow bounds can never
        wrongly prune this node. Per-table digests ride alongside
        (`tables`): coordinators key their cluster cache on the PLAN
        table's digest, so a scrape tick moving this node's
        `__metrics__` digest invalidates metrics-history results
        within one heartbeat without churning the flows digest that
        keys everything else."""
        try:
            fp = self.query_engine.fingerprint_hash()
            tfp = self.query_engine.table_fingerprints()
        except Exception:
            return None   # e.g. every replica down: peers skip pruning
        cached = self._store_doc_cache
        if cached is not None and cached.get("fingerprint") == fp:
            # bounds/rows describe the FLOWS tables only, so an
            # unchanged flows fingerprint keeps them valid — a scrape
            # tick refreshes just the per-table digest map instead of
            # re-running the O(rows) bounds scan every interval
            if cached.get("tables") != tfp:
                cached = dict(cached)
                cached["tables"] = tfp
                self._store_doc_cache = cached
            return cached
        now = self._clock()
        if cached is not None and \
                now - self._store_doc_at < self._bounds_interval:
            return {"fingerprint": fp, "tables": tfp}
        doc: Dict[str, object] = {"fingerprint": fp, "tables": tfp}
        try:
            rows = 0
            tabs: List[Dict[str, tuple]] = []
            for t in self.query_engine._tables():
                n = len(t)
                rows += n
                if n:
                    tb = getattr(t, "time_bounds", None)
                    tabs.append(tb() if callable(tb) else {})
            doc["rows"] = rows
            # a column's bounds are only safe when EVERY non-empty
            # table reported it — a shard with unknown bounds could
            # hold rows outside the others' range
            bounds: Dict[str, List[int]] = {}
            if tabs:
                for col in tabs[0]:
                    if all(col in tb for tb in tabs):
                        bounds[col] = [
                            int(min(tb[col][0] for tb in tabs)),
                            int(max(tb[col][1] for tb in tabs))]
            doc["bounds"] = bounds
        except Exception as e:
            logger.v(1).info("store bounds scan failed: %s", e)
        self._store_doc_cache = doc
        self._store_doc_at = now
        return doc

    def current_term(self) -> int:
        if self.follower is not None:
            return max(self.term, self.follower.leader_term)
        return self.term

    def handle_replicate(self, data: bytes,
                         headers) -> Dict[str, object]:
        term = int(headers.get("X-Theia-Term", "0") or 0)
        sender = headers.get("X-Theia-Node")
        if self.role == "leader":
            if term > self.term:
                # a newer leader exists: this node lost a failover it
                # never saw — step down and take the frames
                self.step_down(leader_id=sender, term=term)
            else:
                raise ClusterStateError(
                    f"node {self.cmap.self_id} is the leader "
                    f"(term {self.term}); not accepting replication "
                    f"from term {term}")
        if self.follower is None:
            raise ClusterStateError(
                f"role {self.role} does not accept replication")
        return self.follower.handle_replicate(
            data, algo=int(headers.get("X-Theia-Algo", "0") or 0),
            term=term,
            leader_lsn=int(headers.get("X-Theia-Leader-Lsn", "0") or 0),
            leader_id=sender)

    def handle_resync(self, data: bytes, headers) -> Dict[str, object]:
        sender = headers.get("X-Theia-Node")
        if self.role == "leader":
            term = 0
            try:
                from .replication import unpack_resync_stream
                term = int(unpack_resync_stream(data)[0].get("term")
                           or 0)
            except Exception:
                pass
            if term > self.term:
                self.step_down(leader_id=sender, term=term)
            else:
                raise ClusterStateError(
                    f"node {self.cmap.self_id} is the leader; not "
                    f"accepting a resync from term {term}")
        if self.follower is None:
            raise ClusterStateError(
                f"role {self.role} does not accept resyncs")
        out = self.follower.handle_resync(data, leader_id=sender)
        tail = self.follower.take_pending_tail()
        if tail and sender:
            self._schedule_tail_reingest(tail, sender)
        return out

    def promote(self, at_lsn: Optional[int] = None) -> Dict[str, object]:
        """WAL-delimited cutover: this follower becomes the leader at
        (at least) `at_lsn`. Refused unless the applied position
        covers the declared LSN — promoting an earlier copy would
        silently drop acknowledged records."""
        with self._lock:
            if self.role == "leader":
                raise ClusterStateError(
                    f"{self.cmap.self_id} is already the leader "
                    f"(term {self.term})")
            applied = self.db.wal_position() or 0
            if at_lsn is not None and applied < int(at_lsn):
                raise ClusterStateError(
                    f"cannot promote at LSN {at_lsn}: this follower "
                    f"has applied only {applied}")
            old_term = self.current_term()
            self.term = old_term + 1
            self.role = "leader"
            self.follower = None
            self.leader = self._make_leader()
            self.leader.term = self.term
            if self._started:
                self.leader.start()
        logger.warning(
            "node %s PROMOTED to leader at LSN %d (term %d)",
            self.cmap.self_id, applied, self.term)
        return {"node": self.cmap.self_id, "role": self.role,
                "term": self.term, "atLsn": applied}

    def step_down(self, leader_id: Optional[str],
                  term: int) -> None:
        """Demote this (stale) leader: a peer proved a higher term.
        The new leader's next handshake fails log matching → resync,
        and the divergent tagged tail re-ingests through its dedup
        window."""
        with self._lock:
            if self.role != "leader":
                return
            old = self.leader
            self.role = "follower"
            self.leader = None
            self.term = max(self.term, int(term))
            self.follower = self._make_follower()
            if leader_id:
                self.follower.leader_id = leader_id
                self.follower.leader_term = int(term)
        if old is not None:
            old.stop()
        logger.warning(
            "node %s STEPPED DOWN: peer %s leads at term %d",
            self.cmap.self_id, leader_id, term)

    def _peer_seen(self, peer: str, info: Dict[str, object]) -> None:
        """Heartbeat observation hook: a peer claiming leadership at a
        higher term demotes us (the healed-partition rejoin path); a
        follower learns who the current leader is for redirects."""
        try:
            role = info.get("role")
            term = int(info.get("term") or 0)
        except (TypeError, ValueError):
            return
        if role != "leader":
            return
        if self.role == "leader" and term > self.term:
            self.step_down(leader_id=peer, term=term)
            return
        fol = self.follower
        if fol is not None:
            with self._lock:
                # re-check under the lock: a racing promote() may have
                # just retired this follower object
                if self.follower is fol and term >= fol.leader_term:
                    fol.leader_id = peer
                    fol.leader_term = term

    # -- demoted-leader tail re-ingest -------------------------------------

    def _schedule_tail_reingest(self, tail: List[tuple],
                                leader_peer: str) -> None:
        t = threading.Thread(
            target=self._reingest_tail, args=(tail, leader_peer),
            daemon=True, name="theia-cluster-tail-reingest")
        t.start()

    def _reingest_tail(self, tail: List[tuple],
                       leader_peer: str) -> None:
        """Re-post every tagged batch from the pre-resync log through
        the current leader's /ingest: already-acknowledged batches
        resolve duplicate:true (the dedup window was replicated /
        resynced), unreplicated ones land — zero acked-row loss, zero
        duplication."""
        from ..ingest.client import IngestClient, IngestError
        from ..store.wal import RECORD_MAGIC
        try:
            addr = self.cmap.addr(leader_peer)
        except KeyError:
            logger.error("tail re-ingest: unknown leader %r",
                         leader_peer)
            return
        client = IngestClient(addr, stream="tail-reingest",
                              token=self.token)
        landed = dups = failed = 0
        for stream, seq, body in tail:
            try:
                out = client.send(RECORD_MAGIC + bytes(body), seq=seq,
                                  stream=stream)
            except (IngestError, Exception) as e:
                failed += 1
                logger.error("tail re-ingest (stream=%r seq=%s) "
                             "failed: %s", stream, seq, e)
                continue
            if out.get("duplicate"):
                dups += 1
            else:
                landed += 1
        logger.warning(
            "tail re-ingest through %s done: %d duplicate:true "
            "(already acknowledged), %d landed, %d failed",
            leader_peer, dups, landed, failed)

    # -- operator surface --------------------------------------------------

    def health_doc(self) -> Dict[str, object]:
        doc: Dict[str, object] = {
            "role": self.role,
            "term": self.current_term(),
            "peers": self.cmap.snapshot(),
        }
        rtts = dict(self.heartbeat.last_rtt)
        if rtts:
            doc["heartbeatRttSeconds"] = {
                p: round(v, 6) for p, v in sorted(rtts.items())}
        degraded = False
        others = self.cmap.others()
        down = [p for p in others if not self.cmap.is_alive(p)]
        if down:
            doc["peersDown"] = down
            degraded = True
        if self.leader is not None:
            repl = self.leader.stats()
            doc["replication"] = repl
            if any(f["status"] != "streaming"
                   for f in repl["followers"]):
                degraded = True
        if self.follower is not None:
            doc["replication"] = self.follower.stats()
        if self.router is not None:
            doc["router"] = self.router.stats()
        doc["degraded"] = degraded
        return doc
