"""Multi-node cluster tier: membership, WAL log-shipping replication,
ingest routing, partition-tolerant failover (docs/cluster.md)."""

from .membership import (
    ClusterConfigError,
    ClusterMap,
    HeartbeatLoop,
    parse_peers,
)
from .node import ClusterNode, ClusterStateError
from .replication import (
    ACK_POLICIES,
    FollowerApplier,
    ReplicationLagError,
    ReplicationLeader,
    StaleReadError,
)
from .router import IngestRouter, RouterForwardError
from .transport import ClusterTransport, PeerUnreachable

__all__ = [
    "ACK_POLICIES",
    "ClusterConfigError",
    "ClusterMap",
    "ClusterNode",
    "ClusterStateError",
    "ClusterTransport",
    "FollowerApplier",
    "HeartbeatLoop",
    "IngestRouter",
    "PeerUnreachable",
    "ReplicationLagError",
    "ReplicationLeader",
    "RouterForwardError",
    "StaleReadError",
    "parse_peers",
]
