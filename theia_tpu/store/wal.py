"""Write-ahead log: bounded-loss durability for the flow store.

The periodic snapshot (store/checkpoint.py) bounds kill -9 loss to one
checkpoint interval — 60 s of *acknowledged* ingest by default. The
reference deployment does not accept that: ClickHouse's
Replicated*MergeTree acknowledges an insert only once it is in the
replica log. This module closes the same gap for the in-memory store:
every table insert appends a checksummed, length-prefixed record to a
segment-rotated log *before* the rows become visible (and therefore
before the client is acknowledged), so the durability contract becomes

    acknowledged  ⇒  survives kill -9, within the sync-policy bound

instead of "survives if the 60 s timer fired".

Record framing (per segment file, little-endian):

    segment header:  "TWAL" | u8 version | u8 crc algo | u16 0 | u64 first LSN
    record frame:    u32 body length | u32 body checksum | u64 LSN |
                     u32 header checksum (over the preceding 16 bytes) |
                     body

    The body checksum is computed OUTSIDE the log's I/O lock (bodies
    are the bulk; concurrent inserts overlap their checksum work),
    while the header checksum — covering length + LSN, assigned under
    the lock — is four cheap bytes that keep a corrupt length or LSN
    from ever being trusted.
    body:            u32 n_rows | u16 n_cols | column*
    column:          u16 name length | name | u8 kind
                     kind 0 (numeric): u16 dtype length | dtype.str
                       (logical) | u16 stored-dtype length | stored
                       dtype.str | i64 base | u32 byte length | raw
                       little-endian array bytes (values - base)
                     kind 1 (string):  u32 n_unique | u32 blob length |
                       u8 code itemsize (1/2/4) | int32 utf-8 lengths
                       (4·n_unique) | utf-8 blob of the unique strings |
                       local codes (itemsize·n_rows bytes)

    Integer columns are stored WIDTH-REDUCED against a per-batch base:
    a min/max scan picks the narrowest unsigned type that holds
    (value - min) — ports and flags are int64 in the schema but fit a
    byte, and per-batch timestamps cluster within seconds of each
    other — cutting record bytes (and therefore the checksum + write
    cost on the ack path) by ~3x. The logical dtype is restored at
    replay.

String columns ship the batch's *unique* strings plus local codes, so a
record is fully self-contained: replay never depends on dictionary
state, which lets a log recorded under one topology (shard count,
replica set) replay into another. The checksum is CRC32C when the
`crc32c` accelerator module is importable, else zlib CRC32 — the
segment header records which, so a reader can verify (or loudly refuse
to) whatever wrote the file.

LSNs are monotonic per log, assigned at append under the log's I/O
lock. Snapshot coordination: `quiesce()` is a writer latch — inserts
hold the read side across (append + memory apply), `FlowDatabase.save`
holds the write side while it stamps `last_lsn` and scans the tables,
so the stamp is exact: every record with LSN ≤ stamp is in the
snapshot, every record above it is not. Recovery = load snapshot, then
`replay()` records above the stamp — tolerating (and physically
truncating) a torn tail, dropping records with bad checksums without
aborting, and logging exactly how many rows were recovered vs dropped.
Checkpoints garbage-collect segments once they fall wholly below the
PREVIOUS snapshot's stamp (`gc_below`; two generations must cover a
segment, so the `.prev` fallback snapshot keeps a replayable log),
keeping disk use bounded.

Sync policy (THEIA_WAL_SYNC, default `interval:1`):

    always          fsync before every acknowledgement (loss bound: 0)
    interval:<secs> fsync at most every <secs> seconds, on the append
                    path plus a background timer for quiescent periods
                    (loss bound: <secs> of acks)
    never           rely on the OS page cache (loss bound: unbounded;
                    bench/throwaway stores only)

Fault sites (utils/faults.py grammar): `wal.append`, `wal.fsync`,
`wal.rotate`.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import re
import struct
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..obs import metrics as _metrics
from ..schema import ColumnarBatch, StringDictionary
from ..utils.env import env_int
from ..utils.faults import fire as _fire_fault
from ..utils.logging import get_logger
from ..analysis import lockdep as _lockdep
from ..analysis.lockdep import named_lock
from . import wire as _wire

logger = get_logger("wal")

try:                                    # hardware CRC32C if present
    from crc32c import crc32c as _crc32c
except ImportError:                     # container default: zlib CRC32
    _crc32c = None

#: checksum algorithm ids stamped into the segment header
CRC_ALGO_CRC32C = 1
CRC_ALGO_ZLIB = 2

#: wire magic for ONE self-contained record body shipped as an ingest
#: payload (cluster router forwards, demoted-leader tail re-ingest):
#: `RECORD_MAGIC + encode_record_body(...)` — decodes statelessly, so
#: it never touches a stream's dictionary-delta chain
RECORD_MAGIC = b"TREC"

#: reserved column name carried by SORTED part bodies (store/parts.py
#: format v2): the sort permutation — `sorted_row[i]` was insertion row
#: `rowid[i]` of the part — rides the record encoding as an ordinary
#: numeric column (width-reduced like any other), so sorted part files
#: stay self-contained WAL record bodies. Consumers that replay a part
#: body as an ingest record (cluster resync) simply drop it at table
#: adoption: schema-driven `_adopt` never copies unknown columns.
ROWID_COLUMN = "__rowid__"

_SEG_MAGIC = b"TWAL"
_SEG_VERSION = 1
_SEG_HEADER = struct.Struct("<4sBBHQ")      # magic, ver, algo, 0, first lsn
_FRAME_HEAD = struct.Struct("<IIQ")         # body length, body crc, lsn
_FRAME = struct.Struct("<IIQI")             # ... + header crc
_SEG_RE = re.compile(r"^wal-(\d{16})\.log$")

#: sanity cap on one record's payload (a corrupt length field must not
#: make the reader allocate the file size)
MAX_RECORD_BYTES = 1 << 30

DEFAULT_SEGMENT_BYTES = 64 << 20

_M_APPENDED = _metrics.counter(
    "theia_wal_appended_bytes_total",
    "Frame bytes appended to write-ahead logs (header + payload)")
_M_FSYNC = _metrics.histogram(
    "theia_wal_fsync_seconds",
    "WAL fsync latency (the durability tax of the sync policy)")
_M_RECOVERED = _metrics.counter(
    "theia_wal_recovered_rows_total",
    "Rows re-applied from WAL records above the snapshot LSN at "
    "recovery")
_M_TORN = _metrics.counter(
    "theia_wal_torn_tail_total",
    "Torn tails truncated from the last WAL segment at recovery (a "
    "crash mid-append; the valid prefix is kept)")


class WalError(Exception):
    """The log cannot take appends (failed write, closed, broken)."""


# -- exactly-once dedup tags ----------------------------------------------
#
# A batch stamped with a producer (stream, seq) identity journals its
# WAL record under a TAGGED table name, so the acknowledgement and the
# rows are durable in the SAME frame: recovery restores the dedup
# window exactly as far as it restores the rows, and a producer
# retrying across a kill -9 cannot double-apply a replayed batch.
# The unit separator cannot appear in a real table name, so untagged
# records (and whole pre-existing logs) parse unchanged.

_DEDUP_SEP = "\x1f"


def pack_dedup_tag(table: str, stream: str, seq: int,
                   total_rows: int) -> str:
    """Encode a producer (stream, seq) identity plus the LOGICAL
    batch row count into the record's table-name field. The total
    lets recovery detect a partially-durable sharded batch (slices
    journal independently under interval sync): a recovered ack whose
    slice sum falls short of the total is loud, not silent."""
    return (f"{table}{_DEDUP_SEP}{stream}{_DEDUP_SEP}{int(seq)}"
            f"{_DEDUP_SEP}{int(total_rows)}")


def split_dedup_tag(name: str
                    ) -> Tuple[str,
                               Optional[Tuple[str, int, Optional[int]]]]:
    """Inverse of `pack_dedup_tag`: (table, (stream, seq, total) or
    None). Stream ids are PRODUCER-CONTROLLED and may themselves
    contain the separator, so the split anchors on the fields we own:
    the table name (first — real table names never contain it) and
    seq/total (the last two); everything between is the stream
    verbatim. A malformed tag degrades to untagged (the rows still
    replay; only the dedup entry is lost — at-least-once, the pre-tag
    contract)."""
    if _DEDUP_SEP not in name:
        return name, None
    parts = name.split(_DEDUP_SEP)
    if len(parts) < 3:
        return parts[0], None
    try:
        if len(parts) == 3:   # early tag layout without the total
            return parts[0], (parts[1], int(parts[2]), None)
        return parts[0], (_DEDUP_SEP.join(parts[1:-2]),
                          int(parts[-2]), int(parts[-1]))
    except ValueError:
        return parts[0], None


class WalCorruption(WalError):
    """A segment failed structural or checksum validation."""


class WalShipGap(WalError):
    """A log-shipping read asked for records this log no longer holds
    (checkpoint GC removed the covering segments) — the follower is too
    far behind to catch up frame-by-frame and must resync wholesale
    (part-manifest catch-up), then resume from the resync position."""


def _checksum_fn(algo: int) -> Optional[Callable[[bytes, int], int]]:
    if algo == CRC_ALGO_CRC32C:
        if _crc32c is None:
            return None
        return lambda data, crc=0: _crc32c(data, crc)
    if algo == CRC_ALGO_ZLIB:
        return zlib.crc32
    return None


#: algorithm used for NEW segments in this process
_WRITE_ALGO = CRC_ALGO_CRC32C if _crc32c is not None else CRC_ALGO_ZLIB
_write_crc = _checksum_fn(_WRITE_ALGO)


@dataclasses.dataclass(frozen=True)
class SyncPolicy:
    """Parsed THEIA_WAL_SYNC value."""

    mode: str                  # "always" | "interval" | "never"
    seconds: float = 0.0

    @staticmethod
    def parse(spec: str) -> "SyncPolicy":
        spec = (spec or "").strip().lower()
        if spec in ("always", "never"):
            return SyncPolicy(spec)
        if spec == "interval":
            return SyncPolicy("interval", 1.0)
        if spec.startswith("interval:"):
            try:
                secs = float(spec.split(":", 1)[1])
            except ValueError:
                raise ValueError(
                    f"THEIA_WAL_SYNC interval {spec!r}: seconds must "
                    f"be a number")
            if secs <= 0:
                raise ValueError(
                    f"THEIA_WAL_SYNC interval {spec!r}: seconds must "
                    f"be > 0")
            return SyncPolicy("interval", secs)
        raise ValueError(
            f"THEIA_WAL_SYNC {spec!r} is not always|interval:<secs>|"
            f"never")

    def __str__(self) -> str:
        if self.mode == "interval":
            return f"interval:{self.seconds:g}"
        return self.mode


def default_sync_policy() -> SyncPolicy:
    return SyncPolicy.parse(os.environ.get("THEIA_WAL_SYNC", "")
                            or "interval:1")


# -- record codec ---------------------------------------------------------
#
# A WAL record body is a table-name header + a TBLK column section
# (store/wire.py) — ONE codec shared with the producer wire format,
# the part storage format, and the router's column-gather forwards.
# `width_reduce` is re-exported here because the part builder and
# historical callers import it from this module.

width_reduce = _wire.width_reduce


def pack_table_header(table: str) -> bytes:
    """The record-body prefix for `table`: u16 length + utf-8 name
    (or a dedup TAG — see `pack_dedup_tag`). A received TBLK column
    section becomes a journalable record body by prepending exactly
    this, which is what lets the ingest path journal producer bytes
    verbatim."""
    tname = table.encode("utf-8")
    return struct.pack("<H", len(tname)) + tname


def encode_record_parts(table: str, batch: ColumnarBatch
                        ) -> List[memoryview]:
    """Serialize a (store-coded) batch into a self-contained body, as
    a list of buffers (small header bytes + zero-copy column views) —
    the appender checksums and writes them without ever concatenating.

    String columns (those with a dictionary on the batch) ship their
    unique strings + local codes, so replay never depends on
    dictionary state; numeric columns ship width-reduced little-endian
    bytes. The LSN is NOT part of the body — it is assigned at append
    time under the I/O lock and prepended there."""
    return [pack_table_header(table),
            *_wire.encode_columns_parts(batch)]


def encode_record_body(table: str, batch: ColumnarBatch) -> bytes:
    """One contiguous self-contained record body (the shippable unit:
    resync records, router-forwarded batches). The framed append path
    keeps using `encode_record_parts` to avoid the concatenation."""
    return b"".join(bytes(p) for p in encode_record_parts(table, batch))


def decode_record_body(body: bytes,
                       columns: Optional[frozenset] = None
                       ) -> Tuple[str, ColumnarBatch]:
    """Inverse of `encode_record_parts`: (table, batch with fresh
    per-record dictionaries). Raises WalCorruption on structural
    damage (the caller decides whether to drop or abort).

    `columns` restricts decoding to that column subset: the byte
    ranges of every other column are SKIPPED — no array construction,
    no string decode — which is what makes a cold part file cheap to
    query when the plan touches 4 of the 52 columns. Framing is still
    fully walked, so a truncated/corrupt record raises either way."""
    try:
        return _decode_record_body(body, columns)
    except WalCorruption:
        raise
    except Exception as e:
        raise WalCorruption(f"undecodable WAL record: {e}")


def _decode_record_body(body: bytes,
                        columns: Optional[frozenset] = None
                        ) -> Tuple[str, ColumnarBatch]:
    mv = memoryview(body)
    (tlen,) = struct.unpack_from("<H", mv, 0)
    table = bytes(mv[2:2 + tlen]).decode("utf-8")
    batch, off = _wire.decode_columns(mv, 2 + tlen, columns)
    if off != len(body):
        raise WalCorruption(
            f"record has {len(body) - off} trailing bytes")
    return table, batch


# -- snapshot/append coordination ----------------------------------------

class _Latch:
    """Tiny reader/writer latch. Inserts are readers (held across WAL
    append + memory apply); `FlowDatabase.save` is the writer (held
    across LSN stamp + table scan), so the stamp exactly partitions
    records into in-snapshot vs to-replay. Writers do not exclude each
    other (snapshots are serialized by the Checkpointer; a racing
    manual save just reads the same consistent state).

    The latch participates in the lockdep witness as a single named
    region (both sides map to `name`): a reader holding the latch and
    acquiring lock X, plus an X-holder waiting on the write side, is a
    real deadlock the moment a writer is pending — the PR-14 class —
    so read and write acquisitions both record order edges."""

    def __init__(self, name: str = "wal.latch") -> None:
        # inner coordination Condition stays bare: the witness tracks
        # the latch as one region, not its implementation detail
        self._cond = threading.Condition()
        self._readers = 0
        self._writers = 0
        self.name = name
        self._witness = _lockdep.enabled()
        if self._witness:
            _lockdep.register_name(name)

    @contextlib.contextmanager
    def read(self):
        if self._witness:
            # order validation BEFORE blocking: a raise-mode
            # inversion must propagate with the latch untouched
            _lockdep.check_before_acquire(self, self.name)
        t0 = time.monotonic() if self._witness else 0.0
        with self._cond:
            waited = False
            while self._writers:
                waited = True
                self._cond.wait()
            self._readers += 1
        if self._witness:
            _lockdep.note_acquire(
                self, self.name, blocking=True,
                wait=time.monotonic() - t0 if waited else 0.0,
                contended=waited)
        try:
            yield
        finally:
            with self._cond:
                self._readers -= 1
                if self._readers == 0:
                    self._cond.notify_all()
            if self._witness:
                _lockdep.note_release(self, self.name)

    @contextlib.contextmanager
    def write(self):
        if self._witness:
            _lockdep.check_before_acquire(self, self.name)
        t0 = time.monotonic() if self._witness else 0.0
        with self._cond:
            self._writers += 1
            waited = False
            while self._readers:
                waited = True
                self._cond.wait()
        if self._witness:
            _lockdep.note_acquire(
                self, self.name, blocking=True,
                wait=time.monotonic() - t0 if waited else 0.0,
                contended=waited)
        try:
            yield
        finally:
            with self._cond:
                self._writers -= 1
                self._cond.notify_all()
            if self._witness:
                _lockdep.note_release(self, self.name)


# -- the log --------------------------------------------------------------

class WriteAheadLog:
    """One directory of `wal-<first-lsn>.log` segments.

    Lifecycle: construct → `replay()` (apply surviving records above
    the snapshot stamp) → `open()` (start the append side) → serve
    `logged_apply` from the insert paths → `close()`. `replay` before
    `open` is deliberate: the replayed records must not re-log
    themselves, and the next LSN depends on what survived on disk."""

    def __init__(self, directory: str,
                 sync: Optional[str] = None,
                 segment_bytes: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.policy = (default_sync_policy() if sync is None
                       else SyncPolicy.parse(sync))
        self.segment_bytes = (
            env_int("THEIA_WAL_SEGMENT_BYTES", DEFAULT_SEGMENT_BYTES)
            if segment_bytes is None else int(segment_bytes))
        if self.segment_bytes < 4096:
            self.segment_bytes = 4096
        self._clock = clock
        self._io = named_lock("wal.io")
        self._latch = _Latch("wal.latch")
        self._file = None
        self._seg_path: Optional[str] = None
        self._seg_size = 0
        self._seg_records = 0
        self._next_lsn = 1
        self.last_lsn = 0
        self.synced_lsn = 0
        #: body checksum of the record at `last_lsn` — the log-matching
        #: handshake token for cluster replication (a follower whose
        #: (last_lsn, last_body_crc) matches the leader's frame resumes
        #: frame shipping; a mismatch means divergent histories →
        #: wholesale resync). None = unknown (forces resync).
        self.last_body_crc: Optional[int] = 0
        self._dirty_records = 0
        self._dirty_bytes = 0
        self._last_sync_t = clock()
        self._replayed_last = 0
        self._broken: Optional[str] = None
        self._closed = False
        self._stop = threading.Event()
        self._timer: Optional[threading.Thread] = None

    # -- segment bookkeeping ----------------------------------------------

    def _list_segments(self) -> List[Tuple[int, str]]:
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        for name in names:
            m = _SEG_RE.match(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.dir, name)))
        return sorted(out)

    def _open_segment_locked(self, first_lsn: int) -> None:
        path = os.path.join(self.dir, f"wal-{first_lsn:016d}.log")
        self._file = open(path, "ab")
        if self._file.tell() > 0:
            # Name collision with a pre-existing segment. It can hold
            # no replayable records (replay would have advanced
            # next_lsn past its name otherwise) — e.g. a crash right
            # after rotation, or a torn tail truncated back to the
            # header — so start it over rather than appending frames
            # under a header that may stamp a DIFFERENT checksum algo
            # (which a later recovery would reject wholesale).
            self._file.truncate(0)
            self._file.seek(0)
        self._file.write(_SEG_HEADER.pack(
            _SEG_MAGIC, _SEG_VERSION, _WRITE_ALGO, 0, first_lsn))
        self._file.flush()
        self._seg_path = path
        self._seg_size = self._file.tell()
        self._seg_records = 0

    # -- lifecycle ---------------------------------------------------------

    def open(self, min_next_lsn: int = 1) -> None:
        """Start the append side. The active segment is always a FRESH
        one (never an old file reopened for append): recovery may have
        truncated a torn tail, and a new header is cheaper than every
        reopen edge case. `min_next_lsn` raises the LSN floor (the
        snapshot stamp + 1, or a resync peer's position)."""
        with self._io:
            if self._file is not None:
                raise WalError("WAL already open")
            self._next_lsn = max(min_next_lsn, self._replayed_last + 1,
                                 self._next_lsn)
            self.last_lsn = self._next_lsn - 1
            self.synced_lsn = self.last_lsn
            self._open_segment_locked(self._next_lsn)
        if self.policy.mode == "interval":
            self._timer = threading.Thread(
                target=self._sync_loop, daemon=True,
                name="theia-wal-sync")
            self._timer.start()

    def close(self) -> None:
        """Final fsync + release (idempotent). Part of the graceful-
        shutdown drain: everything appended is durable after this."""
        self._stop.set()
        if self._timer is not None:
            self._timer.join(timeout=10)
            self._timer = None
        with self._io:
            self._closed = True
            if self._file is None:
                return
            try:
                self._file.flush()
                os.fsync(self._file.fileno())
                self.synced_lsn = self.last_lsn
                self._dirty_records = 0
                self._dirty_bytes = 0
            except Exception as e:   # incl. ValueError on a handle a
                logger.error(        # failed rotation already closed
                    "WAL close fsync failed: %s", e)
            with contextlib.suppress(Exception):
                self._file.close()
            self._file = None

    def _sync_loop(self) -> None:
        while not self._stop.wait(self.policy.seconds):
            try:
                if self._dirty_records:
                    self.sync()
            except Exception as e:   # keep the timer alive
                logger.error("WAL background sync failed: %s", e)

    # -- append side -------------------------------------------------------

    def quiesce(self):
        """Writer side of the snapshot latch: no append (or its memory
        apply) is in flight while held."""
        return self._latch.write()

    def logged_apply(self, table: str, adopted: ColumnarBatch,
                     apply: Callable[[ColumnarBatch], None],
                     wire: Optional[memoryview] = None) -> None:
        """The insert-path hook: append the record, then apply it to
        memory, atomically with respect to `quiesce()`; then run the
        sync policy. An append failure propagates BEFORE the memory
        apply — the row is neither visible nor acknowledged, so a
        broken log fails inserts instead of silently un-journaling
        them. `wire` (a received TBLK column section covering exactly
        these rows) is journaled verbatim instead of re-encoding the
        adopted batch."""
        with self._latch.read():
            self.append(table, adopted, wire=wire)
            apply(adopted)
        self._policy_sync()

    def append(self, table: str, batch: ColumnarBatch,
               wire: Optional[memoryview] = None) -> int:
        """Append one record; returns its LSN. The frame is written
        with a single buffered write + flush, so a crash tears at most
        the tail of this record (which recovery truncates).

        When `wire` is given it must be the TBLK column section (no
        magic) already encoding `batch`'s rows: the record body
        becomes table header + those bytes VERBATIM — the zero-copy
        half of the TBLK ingest path, where producer bytes are
        checksummed and written without a decode→re-encode round
        trip. Replay decodes the self-contained section exactly like
        a locally-encoded record."""
        _fire_fault("wal.append", table=table, dir=self.dir)
        # Encode + bulk checksum OUTSIDE the I/O lock: concurrent
        # inserts overlap the expensive part; only LSN assignment and
        # the writes serialize.
        if wire is not None:
            parts: List = [pack_table_header(table), wire]
        else:
            parts = encode_record_parts(table, batch)
        body_len = sum(len(p) for p in parts)
        body_crc = 0
        for p in parts:
            body_crc = _write_crc(p, body_crc)
        body_crc &= 0xFFFFFFFF
        with self._io:
            if self._closed:
                raise WalError("WAL is closed")
            if self._broken is not None:
                raise WalError(
                    f"WAL broken by earlier write failure: "
                    f"{self._broken}")
            if self._file is None:
                raise WalError("WAL not open (call open() first)")
            frame_len = _FRAME.size + body_len
            if (self._seg_records
                    and self._seg_size + frame_len > self.segment_bytes):
                self._rotate_locked()
            lsn = self._next_lsn
            head = _FRAME_HEAD.pack(body_len, body_crc, lsn)
            head_crc = _write_crc(head, 0) & 0xFFFFFFFF
            pre = self._seg_size
            try:
                self._file.write(head)
                self._file.write(struct.pack("<I", head_crc))
                for p in parts:
                    self._file.write(p)
                self._file.flush()
            except Exception as e:
                # Roll the partial frame back; if even that fails the
                # log is poisoned and must refuse further appends (a
                # garbage gap would silently end every future replay
                # at this offset).
                try:
                    self._file.truncate(pre)
                    self._file.seek(pre)
                except OSError:
                    self._broken = f"{type(e).__name__}: {e}"
                raise
            self._seg_size += frame_len
            self._seg_records += 1
            self._next_lsn = lsn + 1
            self.last_lsn = lsn
            self.last_body_crc = body_crc
            self._dirty_records += 1
            self._dirty_bytes += frame_len
        _M_APPENDED.inc(frame_len)
        return lsn

    def _rotate_locked(self) -> None:
        """Seal the active segment (fsync unless policy=never) and
        start the next one at the upcoming LSN. A failure opening the
        next segment (ENOSPC, EMFILE) poisons the log explicitly —
        leaving the closed handle in place would make every later
        append die with a bare 'I/O operation on closed file' that
        nothing maps back to the rotation failure."""
        _fire_fault("wal.rotate", segment=self._seg_path)
        self._file.flush()
        if self.policy.mode != "never":
            os.fsync(self._file.fileno())
            self.synced_lsn = self.last_lsn
            self._dirty_records = 0
            self._dirty_bytes = 0
        self._file.close()
        try:
            self._open_segment_locked(self._next_lsn)
        except Exception as e:
            self._file = None
            self._broken = f"segment rotation failed: {e}"
            raise WalError(self._broken)

    def _policy_sync(self) -> None:
        if self.policy.mode == "always":
            self.sync()
        elif (self.policy.mode == "interval" and self._dirty_records
                and self._clock() - self._last_sync_t
                >= self.policy.seconds):
            self.sync()

    def sync(self) -> None:
        """Flush + fsync the active segment (the durability point)."""
        _fire_fault("wal.fsync", dir=self.dir)
        with self._io:
            self._last_sync_t = self._clock()
            if self._file is None or not self._dirty_records:
                return
            t0 = time.perf_counter()
            self._file.flush()
            os.fsync(self._file.fileno())
            dt = time.perf_counter() - t0
            self.synced_lsn = self.last_lsn
            self._dirty_records = 0
            self._dirty_bytes = 0
        _M_FSYNC.observe(dt)

    def reposition(self, last_lsn: int) -> None:
        """Jump the LSN sequence forward to `last_lsn` (a resync peer's
        position): the replica's memory now reflects everything up to
        that LSN, so its next append must land above it. Leaves a gap
        in this log — recovery detects it and prefers an ungapped peer
        until a checkpoint GCs the stale segments."""
        with self._io:
            if self._file is None:
                raise WalError("WAL not open")
            if last_lsn + 1 <= self._next_lsn:
                return
            self._file.flush()
            if self.policy.mode != "never":
                os.fsync(self._file.fileno())
            self._file.close()
            self._next_lsn = last_lsn + 1
            self.last_lsn = last_lsn
            self.synced_lsn = last_lsn
            # the record AT last_lsn lives in a peer's log, not this
            # one — unknown until something lands here (a cluster
            # follower's resync sets it from the leader's token)
            self.last_body_crc = None
            self._dirty_records = 0
            self._dirty_bytes = 0
            self._open_segment_locked(self._next_lsn)

    # -- recovery ----------------------------------------------------------

    def replay(self, apply: Callable[[str, ColumnarBatch], None],
               above_lsn: int = 0) -> Dict[str, object]:
        """Apply every decodable record with LSN > `above_lsn`, in log
        order. A torn tail (truncated/bad frame at the end of the LAST
        segment) is physically truncated away; a bad frame in an
        earlier segment drops the remainder of that segment only.
        Returns recovery stats (and logs them): recovered vs dropped
        is always exact and loud, never silent."""
        stats: Dict[str, object] = {
            "recoveredRows": 0, "recoveredRecords": 0,
            "skippedRecords": 0, "droppedRecords": 0,
            "droppedBytes": 0, "tornTail": False, "gapped": False,
            "lastLsn": 0, "aboveLsn": int(above_lsn),
        }
        segs = self._list_segments()
        state = {"prev": None, "first": None, "crc": None}
        for si, (first, path) in enumerate(segs):
            last_seg = si == len(segs) - 1
            self._replay_segment(path, last_seg, above_lsn, stats,
                                 state, apply)
        if state["crc"] is not None:
            # handshake token: the physical last frame's body checksum
            self.last_body_crc = int(state["crc"])
        if (state["first"] is not None and above_lsn
                and state["first"] > above_lsn + 1):
            # records between the snapshot stamp and the oldest
            # surviving segment are missing entirely
            stats["gapped"] = True
        self._replayed_last = int(stats["lastLsn"])
        if stats["recoveredRows"]:
            _M_RECOVERED.inc(stats["recoveredRows"])
        level = (logger.warning if (stats["droppedRecords"]
                                    or stats["tornTail"]
                                    or stats["gapped"])
                 else logger.info)
        level(
            "WAL %s: recovered %d rows in %d records above LSN %d "
            "(%d records below the snapshot skipped); dropped %d "
            "records / %d bytes%s%s", self.dir,
            stats["recoveredRows"], stats["recoveredRecords"],
            above_lsn, stats["skippedRecords"],
            stats["droppedRecords"], stats["droppedBytes"],
            " [torn tail truncated]" if stats["tornTail"] else "",
            " [GAPPED: records missing above the snapshot]"
            if stats["gapped"] else "")
        return stats

    def _replay_segment(self, path: str, last_seg: bool,
                        above_lsn: int, stats: Dict[str, object],
                        state: Dict[str, Optional[int]],
                        apply) -> None:
        prev_lsn = state["prev"]
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError as e:
            logger.error("WAL segment %s unreadable: %s", path, e)
            stats["droppedRecords"] = int(stats["droppedRecords"]) + 1
            return
        off = _SEG_HEADER.size
        if len(data) < _SEG_HEADER.size:
            self._drop_rest(path, data, 0, last_seg, stats,
                            "short segment header")
            return
        magic, ver, algo, _, _first = _SEG_HEADER.unpack_from(data, 0)
        if magic != _SEG_MAGIC or ver != _SEG_VERSION:
            self._drop_rest(path, data, 0, last_seg, stats,
                            "bad segment magic/version")
            return
        crc_fn = _checksum_fn(algo)
        if crc_fn is None:
            logger.warning(
                "WAL segment %s uses checksum algo %d (crc32c) but no "
                "crc32c module is importable: records applied "
                "UNVERIFIED", path, algo)
        n_records = 0
        while off < len(data):
            if off + _FRAME.size > len(data):
                self._drop_rest(path, data, off, last_seg, stats,
                                "truncated frame header")
                break
            blen, body_crc, lsn, head_crc = _FRAME.unpack_from(data,
                                                              off)
            head = data[off:off + _FRAME_HEAD.size]
            if crc_fn is not None and \
                    (crc_fn(head, 0) & 0xFFFFFFFF) != head_crc:
                self._drop_rest(path, data, off, last_seg, stats,
                                "frame header checksum mismatch")
                break
            if blen > MAX_RECORD_BYTES \
                    or off + _FRAME.size + blen > len(data):
                self._drop_rest(path, data, off, last_seg, stats,
                                f"bad frame length {blen}")
                break
            body = data[off + _FRAME.size:off + _FRAME.size + blen]
            if crc_fn is not None and \
                    (crc_fn(body, 0) & 0xFFFFFFFF) != body_crc:
                self._drop_rest(path, data, off, last_seg, stats,
                                "checksum mismatch")
                break
            if state["first"] is None:
                state["first"] = lsn
            n_records += 1
            if prev_lsn is not None and lsn != prev_lsn + 1 \
                    and lsn > above_lsn:
                stats["gapped"] = True
            prev_lsn = lsn
            stats["lastLsn"] = max(int(stats["lastLsn"]), lsn)
            state["crc"] = body_crc
            if lsn <= above_lsn:
                # already covered by the snapshot: the frame is
                # CRC-verified above but NOT decoded — recovery over
                # a long not-yet-GC'd tail pays checksums, not
                # dictionary rebuilds (manifest-based recovery made
                # this the dominant cost)
                stats["skippedRecords"] = \
                    int(stats["skippedRecords"]) + 1
            else:
                try:
                    table, batch = decode_record_body(body)
                except WalCorruption as e:
                    self._drop_rest(path, data, off, last_seg, stats,
                                    str(e))
                    break
                apply(table, batch)
                stats["recoveredRecords"] = \
                    int(stats["recoveredRecords"]) + 1
                stats["recoveredRows"] = \
                    int(stats["recoveredRows"]) + len(batch)
            off += _FRAME.size + blen
        state["prev"] = prev_lsn

    def _drop_rest(self, path: str, data: bytes, off: int,
                   last_seg: bool, stats: Dict[str, object],
                   why: str) -> None:
        dropped = len(data) - off
        stats["droppedBytes"] = int(stats["droppedBytes"]) + dropped
        stats["droppedRecords"] = int(stats["droppedRecords"]) + 1
        if last_seg:
            # torn tail: keep the valid prefix, physically drop the
            # garbage so future replays (and appenders) never see it
            stats["tornTail"] = True
            _M_TORN.inc()
            try:
                with open(path, "r+b") as f:
                    f.truncate(off)
                logger.warning(
                    "WAL %s: torn tail truncated at byte %d (%d bytes "
                    "dropped): %s", path, off, dropped, why)
            except OSError as e:
                logger.error("WAL %s: failed to truncate torn tail: "
                             "%s", path, e)
        else:
            logger.error(
                "WAL %s: dropping remainder of segment at byte %d "
                "(%d bytes): %s — recovery continues with the next "
                "segment", path, off, dropped, why)

    # -- log shipping (leader read side / follower write side) -------------

    def read_frames(self, above_lsn: int,
                    max_bytes: int = 1 << 20
                    ) -> Tuple[bytes, int, int]:
        """Raw frames with LSN > `above_lsn`, up to ~`max_bytes` (at
        least one frame when any exists) — the replication shipper's
        read side. Returns (frames, last_lsn_shipped, checksum_algo);
        empty frames means the follower is caught up. Raises
        WalShipGap when the oldest surviving record is already past
        `above_lsn + 1` (GC collected the covering segments): the
        follower must resync wholesale instead. Reading races appends
        safely — the walk stops at the first incomplete frame (the
        appender's userspace buffer may spill mid-record)."""
        with self._io:
            segs = self._list_segments()
        if not segs:
            return b"", int(above_lsn), _WRITE_ALGO
        # start at the last segment that can contain above_lsn + 1
        start = 0
        for i, (first, _) in enumerate(segs):
            if first <= above_lsn + 1:
                start = i
        if segs[start][0] > above_lsn + 1:
            raise WalShipGap(
                f"oldest surviving WAL record is LSN {segs[start][0]} "
                f"but the follower needs {above_lsn + 1} — covering "
                f"segments were checkpoint-GCed; resync required")
        out: List[bytes] = []
        size = 0
        last = int(above_lsn)
        ship_algo: Optional[int] = None
        for first, path in segs[start:]:
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                break
            if len(data) < _SEG_HEADER.size:
                break
            magic, ver, algo, _, _f = _SEG_HEADER.unpack_from(data, 0)
            if magic != _SEG_MAGIC or ver != _SEG_VERSION:
                break
            if ship_algo is None:
                ship_algo = algo
            elif algo != ship_algo and out:
                # one ship batch carries ONE checksum algo; a mixed-
                # algo log (crc32c module came/went across restarts)
                # ships the remainder on the next call
                break
            for lsn, frame, _body in iter_frames(
                    data[_SEG_HEADER.size:], algo):
                if lsn <= above_lsn:
                    continue
                if lsn != last + 1 and last != above_lsn:
                    # a gap INSIDE the shipped range (reposition after
                    # resync): stop here; the follower acks what it
                    # got and the next read re-evaluates
                    return (b"".join(out), last,
                            ship_algo if ship_algo is not None
                            else _WRITE_ALGO)
                out.append(frame)
                size += len(frame)
                last = lsn
                if size >= max_bytes:
                    return b"".join(out), last, ship_algo
        return (b"".join(out), last,
                ship_algo if ship_algo is not None else _WRITE_ALGO)

    def shipped_apply(self, lsn: int, frame: bytes, body: bytes,
                      sender_algo: int,
                      apply: Callable[[], None]) -> bool:
        """Log-shipping twin of `logged_apply`: append one PRE-FRAMED
        record verbatim — preserving its leader-assigned LSN, so the
        follower's log stays a byte-identical continuation of the
        leader's and standard replay recovers the follower to an exact
        leader position — then run the memory apply, atomically with
        respect to quiesce(). A frame at or below `last_lsn` is a
        duplicate ship after a reconnect: skipped, returns False. A
        frame that would leave a gap raises WalError (the shipper must
        not skip records). The caller runs the sync policy once per
        shipped batch via `policy_sync()`."""
        # the HANDSHAKE token must be the sender-algo checksum (the
        # leader compares against its own frame), even when the frame
        # is re-framed under our algo for the on-disk copy below
        sender_crc = _FRAME.unpack_from(frame, 0)[1]
        if sender_algo != _WRITE_ALGO:
            # our segment header stamps OUR algo — re-frame so the
            # checksums on disk match it
            frame = build_frame(bytes(body), lsn)
        with self._latch.read():
            with self._io:
                if self._closed:
                    raise WalError("WAL is closed")
                if self._broken is not None:
                    raise WalError(
                        f"WAL broken by earlier write failure: "
                        f"{self._broken}")
                if self._file is None:
                    raise WalError("WAL not open (call open() first)")
                if lsn <= self.last_lsn:
                    return False
                if lsn != self._next_lsn:
                    raise WalError(
                        f"shipped frame LSN {lsn} would leave a gap "
                        f"(next expected {self._next_lsn})")
                if (self._seg_records and
                        self._seg_size + len(frame)
                        > self.segment_bytes):
                    self._rotate_locked()
                pre = self._seg_size
                try:
                    self._file.write(frame)
                    self._file.flush()
                except Exception as e:
                    try:
                        self._file.truncate(pre)
                        self._file.seek(pre)
                    except OSError:
                        self._broken = f"{type(e).__name__}: {e}"
                    raise
                self._seg_size += len(frame)
                self._seg_records += 1
                self._next_lsn = lsn + 1
                self.last_lsn = lsn
                self.last_body_crc = sender_crc
                self._dirty_records += 1
                self._dirty_bytes += len(frame)
            apply()
        _M_APPENDED.inc(len(frame))
        return True

    def policy_sync(self) -> None:
        """Run the sync policy once (the shipped-batch ack point)."""
        self._policy_sync()

    def body_crc_at(self, lsn: int) -> Optional[int]:
        """Body checksum of the record at `lsn`, or None when this log
        no longer holds it (GC) — the leader's side of the log-matching
        handshake."""
        if lsn <= 0:
            return 0
        try:
            frames, last, _algo = self.read_frames(lsn - 1,
                                                   max_bytes=1)
        except WalShipGap:
            return None
        if not frames:
            return None
        blen, body_crc, got, _hcrc = _FRAME.unpack_from(frames, 0)
        return body_crc if got == lsn else None

    def reset_to(self, last_lsn: int,
                 last_body_crc: Optional[int] = None) -> None:
        """Discard every record and restart the sequence at
        `last_lsn + 1` — the follower's wholesale-resync landing: its
        surviving records no longer describe its memory (which was
        just replaced by the leader's copy), so they are removed, and
        the handshake token is set from the leader's. The caller has
        already extracted any divergent tail it intends to re-ingest.
        NOTE the resync'd memory itself is NOT in this log — until the
        next checkpoint covers it, a crash re-runs the resync (loud,
        correct, wasteful — the documented window)."""
        with self._io:
            if self._file is None:
                raise WalError("WAL not open")
            self._file.close()
            for _, path in self._list_segments():
                with contextlib.suppress(OSError):
                    os.unlink(path)
            self._next_lsn = int(last_lsn) + 1
            self.last_lsn = int(last_lsn)
            self.synced_lsn = int(last_lsn)
            self.last_body_crc = last_body_crc
            self._dirty_records = 0
            self._dirty_bytes = 0
            self._open_segment_locked(self._next_lsn)

    # -- maintenance -------------------------------------------------------

    def gc_below(self, lsn: int) -> int:
        """Remove segments whose every record has LSN ≤ `lsn` (i.e.
        wholly covered by a durable snapshot stamped at `lsn`). The
        active segment is never removed. Returns segments deleted."""
        removed = 0
        with self._io:
            segs = self._list_segments()
            for (first, path), (next_first, _) in zip(segs, segs[1:]):
                if path == self._seg_path:
                    break
                if next_first <= lsn + 1:
                    try:
                        os.unlink(path)
                        removed += 1
                    except OSError as e:
                        logger.error("WAL gc failed for %s: %s",
                                     path, e)
                else:
                    break
        if removed:
            logger.v(1).info("WAL %s: gc removed %d segments below "
                             "LSN %d", self.dir, removed, lsn)
        return removed

    @property
    def lag_records(self) -> int:
        """Records appended but not yet fsynced (the syncedLsn lag) —
        cheap enough for the admission plane to poll per request,
        unlike stats() which walks the segment directory."""
        return self._dirty_records

    def stats(self) -> Dict[str, object]:
        """Health surface (served under /healthz `wal`)."""
        segs = self._list_segments()
        size = 0
        for _, path in segs:
            try:
                size += os.path.getsize(path)
            except OSError:
                pass
        return {
            "dir": self.dir,
            "policy": str(self.policy),
            "segments": len(segs),
            "bytes": size,
            "lastLsn": self.last_lsn,
            "syncedLsn": self.synced_lsn,
            "lagRecords": self._dirty_records,
            "lagBytes": self._dirty_bytes,
        }


# -- log shipping (cluster replication) -----------------------------------

def iter_frames(data: bytes, algo: int):
    """Walk a buffer of raw shipped frames, yielding (lsn, frame_bytes,
    body) for each complete, checksum-valid frame and stopping at the
    first truncated/invalid one (a reader racing the appender sees a
    clean prefix, never garbage). `algo` is the sender's checksum
    algorithm (its segment header / ship envelope); an unverifiable
    algo (crc32c frames without the module) is walked structurally,
    matching replay's applied-unverified behavior."""
    crc_fn = _checksum_fn(algo)
    off, n = 0, len(data)
    while off + _FRAME.size <= n:
        blen, body_crc, lsn, head_crc = _FRAME.unpack_from(data, off)
        if crc_fn is not None and (crc_fn(
                data[off:off + _FRAME_HEAD.size], 0)
                & 0xFFFFFFFF) != head_crc:
            return
        if blen > MAX_RECORD_BYTES or off + _FRAME.size + blen > n:
            return
        body = data[off + _FRAME.size:off + _FRAME.size + blen]
        if crc_fn is not None and \
                (crc_fn(body, 0) & 0xFFFFFFFF) != body_crc:
            return
        yield lsn, data[off:off + _FRAME.size + blen], body
        off += _FRAME.size + blen


def build_frame(body: bytes, lsn: int) -> bytes:
    """Frame one record body under THIS process's checksum algorithm —
    re-framing shipped records whose sender used a different algo, and
    framing resync/export record bodies for the ship envelope."""
    body_crc = _write_crc(body, 0) & 0xFFFFFFFF
    head = _FRAME_HEAD.pack(len(body), body_crc, lsn)
    head_crc = _write_crc(head, 0) & 0xFFFFFFFF
    return head + struct.pack("<I", head_crc) + body


def orphan_segments(directory: str) -> List[str]:
    """Rename every segment in `directory` to `<name>.orphaned` so no
    scan (replay, GC, adoption) ever touches it again, preserving the
    bytes for operator forensics. Used when a store's snapshot lineage
    broke — a non-empty snapshot with NO WAL stamp next to surviving
    segments (a run with --wal-dir off saved over a journaled store):
    there is no LSN that partitions those records into in-snapshot vs
    to-replay, so replaying would duplicate and deleting would
    destroy evidence."""
    renamed: List[str] = []
    try:
        names = os.listdir(directory)
    except OSError:
        return renamed
    for name in sorted(names):
        if _SEG_RE.match(name):
            p = os.path.join(directory, name)
            try:
                os.rename(p, p + ".orphaned")
                renamed.append(p)
            except OSError as e:
                logger.error("failed to orphan WAL segment %s: %s",
                             p, e)
    return renamed


# -- cross-topology adoption ----------------------------------------------

_SHARD_DIR_RE = re.compile(r"^shard-(\d+)$")
_REPLICA_DIR_RE = re.compile(r"^replica-(\d+)$")


def scan_positions(directory: str) -> Dict[str, object]:
    """Cheap frame-header walk over a log directory — reads only the
    24-byte frame headers and SEEKS over bodies, so ranking replica
    copies costs O(records), not O(log bytes): (first LSN, last LSN,
    gapped)."""
    first: Optional[int] = None
    last = 0
    gapped = False
    prev: Optional[int] = None
    for seg_first, path in sorted(
            (int(m.group(1)), os.path.join(directory, n))
            for n in os.listdir(directory)
            for m in (_SEG_RE.match(n),) if m):
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                head = f.read(_SEG_HEADER.size)
                if len(head) < _SEG_HEADER.size:
                    continue
                magic, ver, algo, _, _f = _SEG_HEADER.unpack(head)
                if magic != _SEG_MAGIC or ver != _SEG_VERSION:
                    continue
                crc_fn = _checksum_fn(algo)
                off = _SEG_HEADER.size
                while off + _FRAME.size <= size:
                    frame = f.read(_FRAME.size)
                    if len(frame) < _FRAME.size:
                        break
                    blen, _bcrc, lsn, hcrc = _FRAME.unpack(frame)
                    if crc_fn is not None and (crc_fn(
                            frame[:_FRAME_HEAD.size], 0)
                            & 0xFFFFFFFF) != hcrc:
                        break
                    if blen > MAX_RECORD_BYTES \
                            or off + _FRAME.size + blen > size:
                        break
                    if first is None:
                        first = lsn
                    if prev is not None and lsn != prev + 1:
                        gapped = True
                    prev = lsn
                    last = max(last, lsn)
                    off += _FRAME.size + blen
                    f.seek(off)
        except OSError:
            continue
    return {"first": first, "last": last, "gapped": gapped}


def _replay_dir_logically(db, path: str, stamp: int) -> int:
    """Replay one foreign log dir through the db's LOGICAL insert path
    with the (already attached) WAL hooks ON — rows re-journal under
    the new topology — then fsync the new log and remove the stale
    segments. The sync-before-unlink order means a crash can never
    LOSE adopted rows (they are durable in one log or the other);
    the residual is duplication — a kill -9 after the sync but before
    the unlinks re-adopts the rows at the next startup. Adoption is a
    rare, operator-driven topology change, and the window is logged."""
    logger.warning(
        "adopting WAL %s from a previous store topology (replaying "
        "above LSN %d through the logical insert path; a crash "
        "before this dir is removed re-adopts — duplicates — these "
        "rows)", path, stamp)
    scanner = WriteAheadLog(path, sync="never")

    def apply(table, batch):
        table, tag = split_dedup_tag(table)
        if tag is not None:
            # preserve the producer identity across the topology
            # change: the re-journaled record keeps its tag, and the
            # recovered ack seeds the new manager's dedup window
            note = getattr(db, "note_recovered_ack", None)
            if callable(note):
                note(tag[0], tag[1], len(batch), tag[2])
        if table == "flows":
            if tag is not None:
                db.insert_flows(batch, dedup=tag)
            else:
                db.insert_flows(batch)
        elif table in db.result_tables:
            db.result_tables[table].insert(batch)
        else:
            logger.error("foreign WAL record for unknown table %r "
                         "dropped (%d rows)", table, len(batch))
    st = scanner.replay(apply, above_lsn=stamp)
    sync = getattr(db, "wal_sync", None)
    if callable(sync):
        sync()
    for _, seg in scanner._list_segments():
        with contextlib.suppress(OSError):
            os.unlink(seg)
    return int(st["recoveredRows"])


def _remove_log_dir(path: str) -> None:
    try:
        for name in os.listdir(path):
            if _SEG_RE.match(name) or _SHARD_DIR_RE.match(name):
                p = os.path.join(path, name)
                if os.path.isdir(p):
                    _remove_log_dir(p)
                else:
                    with contextlib.suppress(OSError):
                        os.unlink(p)
        os.rmdir(path)
    except OSError:
        pass


def adopt_foreign_wal_dirs(db, root: str, own: List[str],
                           stamps: List[int],
                           replica_copies: bool = True,
                           own_position: Optional[int] = None) -> int:
    """Replay WAL content left by a DIFFERENT store topology (e.g. the
    previous run used --shards 4, this one uses 2: shard-002/003 logs
    would otherwise be silently orphaned — acknowledged rows lost).

    Two candidate classes, with opposite semantics:

    * `shard-*` subdirs (and stray segments in `root` itself) are
      disjoint PARTITIONS of the logical store: every one replays.
      Per-shard snapshot stamps apply by index.
    * `replica-*` subdirs are COPIES of the whole logical store:
      exactly ONE — the most-advanced contiguous (ungapped) one —
      replays, and every replica dir is then removed; replaying more
      than one would duplicate every acknowledged row. A replica dir
      may itself contain `shard-*` partitions (a sharded-replicated
      run); those replay with their per-shard stamps.

    `replica_copies=False` (the replicated caller, whose OWN replica
    logs already carry the logical store): stray replica dirs are not
    replayed at all — they are redundant copies of what the live
    replicas recovered — just removed, unless one is AHEAD of
    `own_position` (both replicas quarantined before the crash), in
    which case it is left on disk with a loud error for the operator.

    Rows re-journal through the attached WAL as they replay, and the
    stale files are removed. Returns rows adopted."""
    own_real = {os.path.realpath(p) for p in own}
    shard_dirs: List[Tuple[str, int]] = []
    replica_dirs: List[str] = []
    try:
        names = os.listdir(root)
    except OSError:
        return 0
    for name in sorted(names):
        p = os.path.join(root, name)
        if not os.path.isdir(p) or os.path.realpath(p) in own_real:
            continue
        m = _SHARD_DIR_RE.match(name)
        if m:
            idx = int(m.group(1))
            shard_dirs.append(
                (p, stamps[idx] if idx < len(stamps) else 0))
        elif _REPLICA_DIR_RE.match(name):
            replica_dirs.append(p)
    rows = 0
    if os.path.realpath(root) not in own_real and \
            any(_SEG_RE.match(n) for n in names):
        rows += _replay_dir_logically(db, root,
                                      stamps[0] if stamps else 0)
    for path, stamp in shard_dirs:
        rows += _replay_dir_logically(db, path, stamp)
        with contextlib.suppress(OSError):
            os.rmdir(path)
    if replica_dirs and not replica_copies:
        for p in replica_dirs:
            subs = [os.path.join(p, n) for n in os.listdir(p)
                    if _SHARD_DIR_RE.match(n)
                    and os.path.isdir(os.path.join(p, n))]
            last = sum(int(scan_positions(s)["last"])
                       for s in (subs or [p]))
            st = {"last": last}
            if own_position is not None and \
                    int(st["last"]) > own_position:
                logger.error(
                    "stray replica WAL %s is AHEAD of every live "
                    "replica (last LSN %d > %d) — left on disk for "
                    "operator recovery, NOT removed",
                    p, int(st["last"]), own_position)
                continue
            logger.warning(
                "removing stray replica WAL %s (a redundant copy of "
                "what the live replicas recovered; last LSN %d)",
                p, int(st["last"]))
            _remove_log_dir(p)
    elif replica_dirs:
        def rank(path: str):
            subs = sorted(
                os.path.join(path, n) for n in os.listdir(path)
                if _SHARD_DIR_RE.match(n)
                and os.path.isdir(os.path.join(path, n)))
            scans = [scan_positions(s) for s in (subs or [path])]
            gapped = any(s["gapped"] for s in scans)
            return (not gapped, sum(int(s["last"]) for s in scans),
                    subs)
        ranked = {p: rank(p) for p in replica_dirs}
        best = max(replica_dirs, key=lambda p: ranked[p][:2])
        logger.warning(
            "found %d replica WAL copies under %s; adopting only the "
            "most-advanced contiguous one (%s) — replicas are copies, "
            "replaying more than one would duplicate rows",
            len(replica_dirs), root, best)
        subs = ranked[best][2]
        if subs:
            for sub in subs:
                idx = int(_SHARD_DIR_RE.match(
                    os.path.basename(sub)).group(1))
                rows += _replay_dir_logically(
                    db, sub, stamps[idx] if idx < len(stamps) else 0)
        else:
            rows += _replay_dir_logically(db, best,
                                          stamps[0] if stamps else 0)
        for p in replica_dirs:
            _remove_log_dir(p)
    return rows
