"""TBLK columnar block wire format — the one shared column-walk codec.

A TBLK block is the column section of a WAL record body promoted to a
first-class producer wire format (ISSUE 16 / ROADMAP item 5): producers
encode once, and the same bytes then ride every hop of the ingest path
without re-materialization — admission charges rows/bytes from the
header without decoding (`peek_counts`), the cluster router re-slices
cross-node forwards by column gather on the encoded bytes
(`gather_parts`), the WAL journals the received column bytes verbatim
(`wal.append(..., wire=...)`), and decode happens exactly once, at the
node that owns the rows.

Layout (all little-endian)::

    block    := "TBLK" columns
    columns  := u32 n_rows  u16 n_cols  col*
    col      := u16 name_len  name_utf8  u8 kind  body
    kind 0 (numeric):
        u16 dtype_len  dtype_str  u16 stored_len  stored_str
        i64 base  u32 nbytes  stored_bytes
        (stored = width-reduced (value - base), see `width_reduce`)
    kind 1 (dictionary string):
        u32 n_uniq  u32 blob_len  u8 code_size
        i32 lens[n_uniq]  utf8_blob  codes[n_rows]  (u1/u2/i4)

``columns`` is byte-for-byte the tail of a WAL record body
(`wal.encode_record_parts` = table-name header + ``columns``) and of a
part file's record section (store/parts.py) — which is the point:
one codec, one skip-walk, no forked framing logic. Unlike the TFB2
stream format (ingest/native.py), a block is fully self-contained —
string columns carry their batch-unique strings, so decode is
STATELESS: no per-stream dictionary delta chain, no decode
serialization, shard-parallel by construction.

Fault sites: ``wire.decode`` fires on every block/record decode,
``wire.gather`` on every router column-gather — both registered in
utils/faults.KNOWN_SITES for drills.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..schema.columnar import ColumnarBatch, StringDictionary
from ..utils.faults import FaultError
from ..utils.faults import fire as _fire_fault

#: wire magic for one self-contained columnar block sent as an ingest
#: payload: ``BLOCK_MAGIC + encode_columns_body(batch)``
BLOCK_MAGIC = b"TBLK"

_HDR = struct.Struct("<IH")          # n_rows, n_cols
_CODE_DTYPES = {1: "<u1", 2: "<u2", 4: "<i4"}


class WireCorruption(ValueError):
    """A columnar block failed structural validation (bad framing,
    impossible lengths, truncation). ValueError so HTTP handlers map
    it to 400 without a dedicated ladder rung."""


def _byteview(arr: np.ndarray) -> memoryview:
    """Flat byte view of a C-contiguous array — zero-copy: appenders
    checksum and write column buffers in place instead of
    materializing a second copy of the whole batch."""
    return memoryview(np.ascontiguousarray(arr)).cast("B")


def width_reduce(a: np.ndarray) -> Tuple[np.ndarray, int]:
    """(stored, base): the narrowest unsigned representation of
    (a - min). Ports and flags are int64 in the schema but fit a byte,
    and per-batch timestamps cluster within seconds of each other —
    the ~3x byte cut behind the WAL record format, the part storage
    format (store/parts.py), and TBLK blocks. Returns (a, 0) unchanged
    when no narrower type holds the span."""
    if a.dtype.kind in "iu" and a.itemsize > 1 and len(a):
        mn, mx = int(a.min()), int(a.max())
        span = mx - mn
        for cand in ("<u1", "<u2", "<u4"):
            cdt = np.dtype(cand)
            if cdt.itemsize >= a.itemsize:
                break
            if span <= int(np.iinfo(cdt).max):
                return (a - mn).astype(cand), mn
    return a, 0


# -- encode ---------------------------------------------------------------

def encode_columns_parts(batch: ColumnarBatch) -> List[memoryview]:
    """Serialize a batch's columns into the ``columns`` section, as a
    list of buffers (small header bytes + zero-copy column views) —
    the WAL appender checksums and writes them without concatenating.

    String columns (those with a dictionary on the batch) ship their
    batch-unique strings + local codes, so decode never depends on
    receiver dictionary state; numeric columns ship width-reduced
    little-endian bytes."""
    parts: List = [_HDR.pack(len(batch), len(batch.columns))]
    for name, arr in batch.columns.items():
        bname = name.encode("utf-8")
        d = batch.dicts.get(name)
        if d is not None:
            codes = np.ascontiguousarray(arr)
            # O(n + dict) unique via occupancy mask (codes are dense
            # dictionary indices) — ~10x cheaper than sort-based
            # np.unique on large batches
            mask = np.zeros(len(d), bool)
            mask[codes] = True
            uniq = np.flatnonzero(mask)
            code_dt = ("<u1" if len(uniq) <= 0xFF
                       else "<u2" if len(uniq) <= 0xFFFF else "<i4")
            remap = (np.cumsum(mask, dtype=np.int32) - 1).astype(
                code_dt)
            local = np.ascontiguousarray(remap[codes])
            encoded = [str(s).encode("utf-8") for s in d.decode(uniq)]
            lens = np.fromiter(map(len, encoded), "<i4",
                               count=len(encoded))
            blob = b"".join(encoded)
            parts.append(struct.pack("<H", len(bname)) + bname
                         + struct.pack("<BIIB", 1, len(uniq),
                                       len(blob), local.itemsize))
            parts.append(_byteview(lens))
            parts.append(blob)
            parts.append(_byteview(local))
        else:
            a = np.ascontiguousarray(arr)
            if a.dtype.byteorder == ">":
                a = a.astype(a.dtype.newbyteorder("<"))
            dt = a.dtype.str.encode("ascii")
            stored, base = width_reduce(a)
            sdt = stored.dtype.str.encode("ascii")
            parts.append(struct.pack("<H", len(bname)) + bname
                         + struct.pack("<BH", 0, len(dt)) + dt
                         + struct.pack("<H", len(sdt)) + sdt
                         + struct.pack("<qI", base, stored.nbytes))
            parts.append(_byteview(stored))
    return parts


def encode_columns_body(batch: ColumnarBatch) -> bytes:
    """One contiguous ``columns`` section."""
    return b"".join(bytes(p) for p in encode_columns_parts(batch))


def encode_block(batch: ColumnarBatch) -> bytes:
    """A complete TBLK ingest payload for `batch` (producer side)."""
    return BLOCK_MAGIC + encode_columns_body(batch)


# -- header peek (admission) ----------------------------------------------

def peek_counts(buf, offset: int = 0) -> Tuple[int, int]:
    """(n_rows, n_cols) from a ``columns`` header at `offset`, WITHOUT
    decoding — the admission controller charges row tokens from this
    before any column work happens. Every encoded cell costs at least
    one byte (u1 planes / u1 codes), so a header whose row x col
    product exceeds the remaining payload is structurally impossible
    and raises: a 40-byte payload cannot claim 4B rows to drain the
    row bucket or park a huge allocation downstream."""
    mv = memoryview(buf)
    if len(mv) - offset < _HDR.size:
        raise WireCorruption("columnar block shorter than its header")
    n_rows, n_cols = _HDR.unpack_from(mv, offset)
    if n_rows * max(n_cols, 1) > len(mv) - offset:
        raise WireCorruption(
            f"block header claims {n_rows} rows x {n_cols} cols in "
            f"{len(mv) - offset} payload bytes")
    return n_rows, n_cols


# -- decode (the ONE column walk) -----------------------------------------

def decode_columns(buf, offset: int = 0,
                   columns: Optional[frozenset] = None
                   ) -> Tuple[ColumnarBatch, int]:
    """Inverse of `encode_columns_parts`: (batch with fresh per-block
    dictionaries, end offset). Raises WireCorruption on structural
    damage; the caller decides whether to drop or abort — and checks
    the end offset against its framing (trailing bytes are the
    CALLER's corruption, this walk only owns the column section).

    `columns` restricts decoding to that column subset: the byte
    ranges of every other column are SKIPPED — no array construction,
    no string decode — which is what makes a cold part file cheap to
    query when the plan touches 4 of the 52 columns, and a router
    forward cheap when it only needs destinationIP. Framing is still
    fully walked, so a truncated/corrupt block raises either way."""
    try:
        return _decode_columns(buf, offset, columns)
    except (WireCorruption, FaultError):
        # injected faults surface as themselves: a drill must observe
        # WHICH site fired, not a corruption it didn't inject
        raise
    except Exception as e:
        raise WireCorruption(f"undecodable columnar block: {e}")


def _decode_columns(buf, offset: int,
                    columns: Optional[frozenset]
                    ) -> Tuple[ColumnarBatch, int]:
    _fire_fault("wire.decode")
    mv = memoryview(buf)
    n_rows, n_cols = _HDR.unpack_from(mv, offset)
    off = offset + _HDR.size
    cols: Dict[str, np.ndarray] = {}
    dicts: Dict[str, StringDictionary] = {}
    for _ in range(n_cols):
        (nlen,) = struct.unpack_from("<H", mv, off)
        off += 2
        name = bytes(mv[off:off + nlen]).decode("utf-8")
        off += nlen
        (kind,) = struct.unpack_from("<B", mv, off)
        off += 1
        wanted = columns is None or name in columns
        if kind == 1:
            n_uniq, blob_len, code_size = struct.unpack_from(
                "<IIB", mv, off)
            off += 9
            code_dt = _CODE_DTYPES.get(code_size)
            if code_dt is None:
                raise WireCorruption(
                    f"bad string code itemsize {code_size}")
            if not wanted:
                off += 4 * n_uniq + blob_len + code_size * n_rows
                continue
            lens = np.frombuffer(mv, "<i4", count=n_uniq, offset=off)
            off += 4 * n_uniq
            blob = bytes(mv[off:off + blob_len])
            off += blob_len
            d = StringDictionary()
            mapping = np.empty(max(n_uniq, 1), np.int32)
            pos = 0
            for i in range(n_uniq):
                end = pos + int(lens[i])
                mapping[i] = d.encode_one(blob[pos:end].decode("utf-8"))
                pos = end
            if pos != blob_len:
                raise WireCorruption("string blob length mismatch")
            local = np.frombuffer(mv, code_dt, count=n_rows,
                                  offset=off).astype(np.int64)
            off += code_size * n_rows
            cols[name] = (mapping[:n_uniq][local] if n_uniq
                          else np.zeros(n_rows, np.int32))
            dicts[name] = d
        elif kind == 0:
            (dlen,) = struct.unpack_from("<H", mv, off)
            off += 2
            dtype = np.dtype(bytes(mv[off:off + dlen]).decode("ascii"))
            off += dlen
            (slen,) = struct.unpack_from("<H", mv, off)
            off += 2
            stored_dt = np.dtype(
                bytes(mv[off:off + slen]).decode("ascii"))
            off += slen
            base, rlen = struct.unpack_from("<qI", mv, off)
            off += 12
            if not wanted:
                off += rlen
                continue
            arr = np.frombuffer(mv, stored_dt, count=n_rows,
                                offset=off)
            arr = arr.astype(dtype) if stored_dt != dtype \
                else arr.copy()
            if base:
                arr += dtype.type(base)
            off += rlen
            cols[name] = arr
        else:
            raise WireCorruption(f"unknown column kind {kind}")
    if off > len(mv):
        raise WireCorruption("columnar block truncated")
    return ColumnarBatch(cols, dicts), off


def decode_block(payload,
                 columns: Optional[frozenset] = None) -> ColumnarBatch:
    """Decode one complete TBLK ingest payload (magic + columns),
    rejecting trailing garbage. Stateless — any thread, any shard, no
    stream slot required."""
    mv = memoryview(payload)
    if bytes(mv[:4]) != BLOCK_MAGIC:
        raise WireCorruption("not a TBLK block")
    batch, end = decode_columns(mv, 4, columns)
    if end != len(mv):
        raise WireCorruption(
            f"block has {len(mv) - end} trailing bytes")
    return batch


# -- column gather (router re-slice, no decode) ---------------------------

def gather_parts(buf, indices, offset: int = 0
                 ) -> Tuple[List, int]:
    """Re-slice an encoded ``columns`` section to `indices` WITHOUT
    decoding: numeric columns gather their width-reduced stored bytes
    (base and dtypes ride verbatim), string columns gather their local
    codes while the unique-string table ships verbatim (a superset of
    what the slice references — codes stay valid, decode is
    unaffected). Returns (buffer list forming a complete ``columns``
    section of len(indices) rows, end offset of the source walk).

    This is the router's cross-node forward path: slicing a 52-column
    batch for a peer costs ~n_cols fancy-indexes over flat bytes
    instead of a full decode → take → re-encode round trip."""
    try:
        return _gather_parts(buf, indices, offset)
    except (WireCorruption, FaultError):
        raise
    except Exception as e:
        raise WireCorruption(f"ungatherable columnar block: {e}")


def _gather_parts(buf, indices, offset: int) -> Tuple[List, int]:
    _fire_fault("wire.gather")
    mv = memoryview(buf)
    n_rows, n_cols = _HDR.unpack_from(mv, offset)
    idx = np.asarray(indices, np.int64)
    if len(idx) and (idx.min() < 0 or idx.max() >= n_rows):
        raise WireCorruption(
            f"gather indices out of range for {n_rows} rows")
    parts: List = [_HDR.pack(len(idx), n_cols)]
    off = offset + _HDR.size
    for _ in range(n_cols):
        col_start = off
        (nlen,) = struct.unpack_from("<H", mv, off)
        off += 2 + nlen
        (kind,) = struct.unpack_from("<B", mv, off)
        off += 1
        if kind == 1:
            n_uniq, blob_len, code_size = struct.unpack_from(
                "<IIB", mv, off)
            off += 9
            code_dt = _CODE_DTYPES.get(code_size)
            if code_dt is None:
                raise WireCorruption(
                    f"bad string code itemsize {code_size}")
            off += 4 * n_uniq + blob_len
            codes = np.frombuffer(mv, code_dt, count=n_rows,
                                  offset=off)
            off += code_size * n_rows
            # header + lens + blob verbatim; only the codes re-slice
            parts.append(mv[col_start:off - code_size * n_rows])
            parts.append(_byteview(codes[idx]))
        elif kind == 0:
            (dlen,) = struct.unpack_from("<H", mv, off)
            off += 2 + dlen
            (slen,) = struct.unpack_from("<H", mv, off)
            stored_dt = np.dtype(
                bytes(mv[off + 2:off + 2 + slen]).decode("ascii"))
            off += 2 + slen
            base, rlen = struct.unpack_from("<qI", mv, off)
            head_end = off
            off += 12
            stored = np.frombuffer(mv, stored_dt, count=n_rows,
                                   offset=off)
            off += rlen
            parts.append(mv[col_start:head_end])
            parts.append(struct.pack(
                "<qI", base, stored_dt.itemsize * len(idx)))
            parts.append(_byteview(stored[idx]))
        else:
            raise WireCorruption(f"unknown column kind {kind}")
    if off > len(mv):
        raise WireCorruption("columnar block truncated")
    return parts, off
