"""In-memory columnar flow database — the framework's L1 storage tier.

Plays the role ClickHouse plays in the reference (tables declared in
build/charts/theia/provisioning/datasources/create_table.sh): a `flows`
table receiving high-rate inserts, three streaming materialized views
(pod/node/policy — create_table.sh:92-351), result tables for the analytics
jobs (`tadetector` create_table.sh:363-384, `recommendations` :353-360),
TTL-based eviction (:87-88) and a retention monitor that trims the oldest
fraction of rows when a capacity threshold is exceeded (reference:
plugins/clickhouse-monitor/main.go:258-320).

Design (TPU-first): tables are append-logs of equal-schema `ColumnarBatch`es
sharing one dictionary set owned by the table, so any time-window selection
is a zero-copy concat + boolean mask over fixed-width arrays, ready for
`jax.device_put`. Materialized views are maintained *incrementally* on
insert as integer-keyed segment sums (the SummingMergeTree equivalent),
keeping the read path for dashboards O(view rows), not O(flow rows).
"""

from __future__ import annotations

import concurrent.futures
import contextlib
import os
import tempfile
import threading
import time
import zlib
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..schema import (
    DETSTATE_SCHEMA,
    DROPDETECTION_SCHEMA,
    FLOW_SCHEMA,
    FLOWPATTERNS_SCHEMA,
    METRICS_SCHEMA,
    METRICS_TABLE,
    RECOMMENDATIONS_SCHEMA,
    SPATIALNOISE_SCHEMA,
    TADETECTOR_SCHEMA,
    ColumnarBatch,
    DictionaryMapper,
    StringDictionary,
)

#: analytics result tables, in declaration order — the single list the
#: store, sharded facade, stats, persistence, and job GC iterate.
#: `__metrics__` rides it so the WAL hooks, snapshots, replication
#: fan-out, sharded facade, and resync all cover stored metrics
#: history for free.
RESULT_TABLE_SCHEMAS = (
    ("tadetector", TADETECTOR_SCHEMA),
    ("recommendations", RECOMMENDATIONS_SCHEMA),
    ("dropdetection", DROPDETECTION_SCHEMA),
    ("flowpatterns", FLOWPATTERNS_SCHEMA),
    ("spatialnoise", SPATIALNOISE_SCHEMA),
    # detector working-set spill state (ingest/state_tier.py) — riding
    # this list is what makes spilled flow state survive kill -9,
    # failover, and resync through the standard planes
    ("detstate", DETSTATE_SCHEMA),
    (METRICS_TABLE, METRICS_SCHEMA),
)
from ..obs import metrics as _metrics
from ..utils.backoff import capped_backoff
from ..utils.env import env_float
from ..utils.faults import fire as _fire_fault
from ..utils.logging import get_logger
from ..utils.pool import get_pool
from .views import MATERIALIZED_VIEWS, ViewTable
from ..analysis.lockdep import named_lock

_logger = get_logger("store")

_M_INS_ROWS = _metrics.counter(
    "theia_store_inserted_rows_total",
    "Flow rows inserted, cumulative over every physical store in the "
    "process (a replicated fan-out counts once per replica)")
_M_INS_BYTES = _metrics.counter(
    "theia_store_inserted_bytes_total",
    "Column bytes of inserted flow rows (store-coded), cumulative per "
    "physical store")
_M_DEL_ROWS = _metrics.counter(
    "theia_store_deleted_rows_total",
    "Flow rows deleted by TTL eviction or retention trims",
    labelnames=("reason",))
_M_MV_FANOUT = _metrics.histogram(
    "theia_store_mv_fanout_seconds",
    "Materialized-view fan-out time per inserted block (all views)")
_M_RET_ROUNDS = _metrics.counter(
    "theia_retention_rounds_total",
    "Retention-monitor rounds, by outcome",
    labelnames=("result",))
_M_RET_DELETED = _metrics.counter(
    "theia_retention_rows_deleted_total",
    "Flow rows trimmed by capacity-based retention rounds")
_M_RET_DEMOTED = _metrics.counter(
    "theia_retention_bytes_demoted_total",
    "Resident bytes freed by demoting parts to the cold tier instead "
    "of deleting rows (parts engine tiered retention)")
_M_SNAP_FALLBACK = _metrics.counter(
    "theia_snapshot_fallbacks_total",
    "Snapshot loads that failed verification on the primary file and "
    "fell back to the previous good snapshot (<path>.prev)")

#: snapshot payload keys outside the table namespace
WAL_LSNS_KEY = "__wal__/lsns"
INTEGRITY_KEY = "__integrity__/crc32"


class SnapshotCorruption(Exception):
    """A snapshot file failed integrity verification."""


def _view_pool() -> concurrent.futures.ThreadPoolExecutor:
    """Shared pool for parallel MV fan-out (native group-sum releases
    the GIL, so the three aggregations genuinely overlap)."""
    return get_pool("mv-fanout", 4)


class Table:
    """Append-only columnar table with store-owned dictionaries.

    All inserted batches are re-encoded (if necessary) against the table's
    dictionaries, so codes are comparable across the whole table and string
    predicates compile to integer comparisons.
    """

    def __init__(self, name: str, schema) -> None:
        self.name = name
        self.schema = schema
        self.dicts: Dict[str, StringDictionary] = {
            c.name: StringDictionary() for c in schema if c.is_string}
        self._batches: List[ColumnarBatch] = []
        self._lock = named_lock("store.table")
        #: monotonic mutation counter (inserts AND deletes) — the
        #: checkpointer's change detector; row counts alone can't see
        #: same-size churn (TTL evicts N, ingest adds N)
        self.generation = 0
        # Cumulative insert totals (rows / store-coded column bytes),
        # maintained under the table lock. Unlike net table size these
        # never decrease, so insert-rate stats based on them survive
        # retention trims (deletes used to mask real throughput).
        self.rows_inserted_total = 0
        self.bytes_inserted_total = 0
        # Cached source-dict → table-dict code mappings: a producer
        # streaming blocks with its own dictionaries pays string
        # re-encode only for NEW entries, not per block (the 6.6x
        # per-block store overhead of BENCH_r04).
        self._adopt_maps: Dict[str, DictionaryMapper] = {
            name: DictionaryMapper(d) for name, d in self.dicts.items()}
        self._adopt_lock = named_lock("store.table_adopt")
        # Cached per-batch (min, max) of the time column, aligned with
        # _batches: TTL's min_value() probe runs per insert and the
        # retention boundary runs per monitor round — both become
        # O(batches) metadata walks instead of O(rows) column scans.
        self._time_column: Optional[str] = (
            "timeInserted" if any(c.name == "timeInserted"
                                  for c in schema) else None)
        self._batch_meta: List[Tuple[int, int]] = []
        # Durability hook, installed by FlowDatabase.attach_wal:
        # called as hook(table_name, adopted, apply_fn) so the WAL can
        # journal the store-coded batch BEFORE apply_fn makes it
        # visible (and the caller acknowledges it). None = no WAL.
        self._wal_hook: Optional[Callable] = None

    def __len__(self) -> int:
        return sum(len(b) for b in self._batches)

    @property
    def nbytes(self) -> int:
        return sum(v.nbytes for b in self._batches
                   for v in b.columns.values())

    def _adopt(self, batch: ColumnarBatch,
               columns: Optional[Sequence[str]] = None
               ) -> ColumnarBatch:
        """Re-encode a batch against this table's dictionaries
        (cached incremental mappings: amortized O(new dict entries)
        per block, not O(dictionary)). `columns` adopts only that
        subset (the column-subset cold-part decode path — the batch
        then carries just those columns)."""
        cols: Dict[str, np.ndarray] = {}
        for col in self.schema:
            if columns is not None and col.name not in columns:
                continue
            arr = batch[col.name]
            if col.is_string:
                src = batch.dicts.get(col.name)
                if src is None:
                    raise ValueError(
                        f"string column {col.name} has no dictionary")
                if src is not self.dicts[col.name]:
                    with self._adopt_lock:
                        arr = self._adopt_maps[col.name].remap(arr, src)
            else:
                arr = np.asarray(arr, dtype=col.host_dtype)
            cols[col.name] = arr
        return ColumnarBatch(cols, self.dicts)

    def insert(self, batch: ColumnarBatch,
               dedup: Optional[tuple] = None,
               wire: Optional[memoryview] = None
               ) -> Optional[ColumnarBatch]:
        """Insert a batch; returns the adopted (store-coded) batch, or
        None when empty, so callers can fan out the exact inserted block
        without re-reading the append log under concurrency. With a
        WAL attached, the record is journaled before the rows become
        visible — a failed append fails the insert (no ack without
        durability). `dedup=(stream, seq[, total_rows])` stamps the
        producer's batch identity (and the logical batch size — a
        sharded insert journals per-slice) into the WAL record
        (wal.pack_dedup_tag), making the acknowledgement itself
        crash-durable: recovery replays the rows AND restores the
        dedup-window entry from the same frame, so a retried batch is
        idempotent across kill -9.

        `wire` is a received TBLK column section already encoding
        `batch`'s rows (store/wire.py): the WAL journals those bytes
        VERBATIM instead of re-encoding the adopted batch — the
        zero-copy half of the TBLK ingest path. It must cover exactly
        the same rows; a row-count mismatch falls back to re-encoding
        rather than journaling bytes that disagree with the ack."""
        if len(batch) == 0:
            return None
        adopted = self._adopt(batch)
        if wire is not None:
            from .wire import peek_counts
            try:
                w_rows, _ = peek_counts(wire)
            except ValueError:
                w_rows = -1
            if w_rows != len(adopted):
                wire = None
        hook = self._wal_hook
        if hook is None:
            self._append_adopted(adopted)
        else:
            name = self.name
            if dedup is not None:
                from .wal import pack_dedup_tag
                stream, seq = dedup[0], int(dedup[1])
                # the LOGICAL batch total (callers that know it pass
                # it; a bare slice defaults to its own length)
                total = (int(dedup[2]) if len(dedup) > 2
                         and dedup[2] is not None else len(batch))
                name = pack_dedup_tag(self.name, stream, seq, total)
            hook(name, adopted, self._append_adopted, wire=wire)
        return adopted

    def _append_adopted(self, adopted: ColumnarBatch) -> None:
        """Make an already-adopted batch visible (the memory apply)."""
        nbytes = sum(a.nbytes for a in adopted.columns.values())
        with self._lock:
            self._batches.append(adopted)
            if self._time_column is not None:
                a = adopted[self._time_column]
                self._batch_meta.append((int(a.min()), int(a.max())))
            self.generation += 1
            self.rows_inserted_total += len(adopted)
            self.bytes_inserted_total += nbytes

    def _row_count_locked(self) -> int:
        """Row count; caller holds self._lock (the sharded facade
        computes per-shard mask offsets under every shard's lock)."""
        return sum(len(b) for b in self._batches)

    def _refresh_meta_locked(self) -> None:
        """Rebuild the per-batch time metadata after a bulk rewrite of
        _batches (delete paths — already O(kept rows))."""
        if self._time_column is None:
            return
        self._batch_meta = [
            (int(b[self._time_column].min()),
             int(b[self._time_column].max()))
            for b in self._batches]

    def insert_rows(self, rows: Sequence[Mapping[str, object]]) -> int:
        if not rows:
            return 0
        adopted = self.insert(
            ColumnarBatch.from_rows(rows, self.schema, self.dicts))
        return 0 if adopted is None else len(adopted)

    def scan(self) -> ColumnarBatch:
        """Whole-table view as one batch (concat of the append log).

        Compacts the log as a side effect; the swap only happens if no
        insert raced in between (otherwise the next scan compacts)."""
        with self._lock:
            batches = list(self._batches)
        if not batches:
            return ColumnarBatch(
                {c.name: np.zeros(0, c.host_dtype) for c in self.schema},
                self.dicts)
        if len(batches) == 1:
            return batches[0]
        merged = ColumnarBatch.concat(batches)
        with self._lock:
            if len(self._batches) == len(batches) and \
                    self._batches[-1] is batches[-1]:
                self._batches = [merged]
                if self._time_column is not None:
                    self._batch_meta = [
                        (min(m[0] for m in self._batch_meta),
                         max(m[1] for m in self._batch_meta))]
        return merged

    def select(self, start_time: Optional[int] = None,
               end_time: Optional[int] = None,
               time_column: str = "flowStartSeconds",
               end_column: str = "flowEndSeconds",
               columns: Optional[Sequence[str]] = None
               ) -> ColumnarBatch:
        """Time-window select, mirroring the jobs' SQL predicates
        (`flowStartSeconds >= start AND flowEndSeconds < end`, reference
        policy_recommendation_job.py:796-798). `columns` projects the
        result to that subset (the window mask still evaluates on the
        full time columns) — the flat half of the parts engine's
        column-subset read path, so query callers are engine-agnostic."""
        data = self.scan()
        if start_time is None and end_time is None:
            return data if columns is None else data.select(columns)
        mask = np.ones(len(data), dtype=bool)
        if start_time is not None:
            mask &= data[time_column] >= start_time
        if end_time is not None:
            mask &= data[end_column] < end_time
        if columns is not None:
            data = data.select(columns)
        return data.filter(mask)

    def delete_where(self, mask: np.ndarray) -> int:
        """Delete rows matching `mask` over the current table contents.
        Runs entirely under the table lock so a concurrent insert can
        neither be dropped nor half-filtered."""
        with self._lock:
            return self._delete_where_locked(mask)

    def _delete_where_locked(self, mask: np.ndarray) -> int:
        """Body of delete_where; caller must hold self._lock (the
        sharded store holds every shard's lock to apply one logical
        mask atomically across shards)."""
        if not self._batches:
            if len(mask) != 0:
                raise ValueError(
                    f"mask length {len(mask)} != table length 0")
            return 0
        data = (self._batches[0] if len(self._batches) == 1
                else ColumnarBatch.concat(self._batches))
        if len(mask) != len(data):
            raise ValueError(
                f"mask length {len(mask)} != table length {len(data)}")
        if not mask.any():
            # No mutation → no generation bump: a spurious bump makes
            # the checkpointer rewrite an unchanged snapshot.
            return 0
        kept = data.filter(~mask)
        self._batches = [kept] if len(kept) else []
        self._refresh_meta_locked()
        self.generation += 1
        return int(mask.sum())

    def delete_ids(self, ids, column: str = "id",
                   invert: bool = False) -> int:
        """Value-based delete: rows whose `column` decodes into `ids`
        (or does NOT, with invert=True). Safe wherever a positional
        mask is not — replicas and shards hold the same logical rows
        in different physical orders. The ids resolve through the
        DICTIONARY (string → code, allocation-free lookup) so the
        match is an integer isin over the codes — the old path
        materialized the full decoded string column per call.
        Computed under the table lock (including the id→code
        resolution: with invert=True, an id whose code is minted by a
        concurrent insert between resolution and mask would otherwise
        have its fresh rows deleted as 'unlisted')."""
        d = self.dicts[column]
        with self._lock:
            codes = np.asarray(sorted(
                c for c in (d.lookup(str(s)) for s in ids)
                if c is not None), np.int32)
            if not self._batches:
                return 0
            data = (self._batches[0] if len(self._batches) == 1
                    else ColumnarBatch.concat(self._batches))
            if len(codes):
                mask = np.isin(np.asarray(data[column], np.int32),
                               codes)
            else:
                mask = np.zeros(len(data), bool)
            if invert:
                mask = ~mask
            return self._delete_where_locked(mask)

    def delete_older_than(self, boundary: int,
                          column: str = "timeInserted") -> int:
        """Atomic `column < boundary` delete (mask computed under the
        lock, so it cannot race with inserts). Batches whose cached
        max is already >= boundary skip the column scan."""
        with self._lock:
            if not self._batches:
                return 0
            if column == self._time_column and self._batch_meta and \
                    min(m[0] for m in self._batch_meta) >= boundary:
                return 0   # metadata proves nothing is evictable
            data = (self._batches[0] if len(self._batches) == 1
                    else ColumnarBatch.concat(self._batches))
            mask = np.asarray(data[column]) < boundary
            if not mask.any():
                self._batches = [data]
                self._refresh_meta_locked()
                return 0
            kept = data.filter(~mask)
            self._batches = [kept] if len(kept) else []
            self._refresh_meta_locked()
            self.generation += 1
        return int(mask.sum())

    #: columns whose (min, max) the cluster heartbeat piggybacks so a
    #: query coordinator can prune peers against a plan's time window
    TIME_BOUND_COLUMNS = ("timeInserted", "flowStartSeconds",
                          "flowEndSeconds")

    def time_bounds(self, columns: Sequence[str] = TIME_BOUND_COLUMNS
                    ) -> Dict[str, Tuple[int, int]]:
        """{column: (min, max)} over the resident rows for the
        standard query-window columns — the heartbeat piggyback behind
        cluster peer pruning (query/distributed.py). On this flat
        engine it is an O(rows) numpy scan, so the caller throttles
        (THEIA_CLUSTER_BOUNDS_INTERVAL); PartTable overrides with its
        resident part metadata. Columns absent from the schema (or an
        empty table) are omitted — 'unknown', never 'empty range'."""
        with self._lock:
            batches = list(self._batches)
        out: Dict[str, Tuple[int, int]] = {}
        for col in columns:
            pairs = [(int(b[col].min()), int(b[col].max()))
                     for b in batches if col in b and len(b)]
            if pairs:
                out[col] = (min(p[0] for p in pairs),
                            max(p[1] for p in pairs))
        return out

    def min_value(self, column: str = "timeInserted") -> Optional[int]:
        """Min over a column without concatenating (None when empty).
        For the time column this is an O(batches) walk over cached
        per-batch minima — the TTL fast path runs it every insert."""
        with self._lock:
            if column == self._time_column:
                return (min(m[0] for m in self._batch_meta)
                        if self._batch_meta else None)
            batches = list(self._batches)
        mins = [int(b[column].min()) for b in batches if len(b)]
        return min(mins) if mins else None

    def _retention_meta(self) -> List[Tuple[int, int, int, Callable]]:
        """(min, max, rows, fetch_time_column) per resident batch —
        the retention monitor's O(parts) boundary substrate."""
        col = self._time_column
        if col is None:
            return []
        with self._lock:
            pairs = list(zip(self._batches, self._batch_meta))
        return [(mn, mx, len(b),
                 (lambda b=b: np.asarray(b[col])))
                for b, (mn, mx) in pairs]

    def retention_boundary(self, delete_n: int) -> Optional[int]:
        """timeInserted value of the delete_n-th oldest row, from
        per-batch metadata (see boundary_from_meta)."""
        return boundary_from_meta(self._retention_meta(), delete_n)

    def truncate(self) -> None:
        with self._lock:
            self._batches = []
            self._batch_meta = []
            self.generation += 1


def boundary_from_meta(metas: List[Tuple[int, int, int, Callable]],
                       delete_n: int) -> Optional[int]:
    """Retention boundary (the timeInserted of the delete_n-th oldest
    row) from per-part metadata, EXACTLY and without sorting the whole
    table: sort parts by min time, accumulate row counts until a
    prefix covers the target rank, then np.partition over the time
    columns of every part whose min is ≤ that prefix's max. Parts
    excluded that way hold only values strictly above the prefix max,
    which already bounds the target from above, so the candidate-set
    k-th smallest IS the global k-th smallest — the same value the
    old O(n log n) full-column sort produced, at O(parts log parts)
    metadata work plus a linear partition over the candidate rows
    (≈ the delete fraction for in-order ingest).

    `metas` entries are (min, max, rows, fetch) where fetch() lazily
    materializes that part's time column (only candidates pay)."""
    if delete_n <= 0 or not metas:
        return None
    ordered = sorted(metas, key=lambda m: (m[0], m[1]))
    cum = 0
    upper: Optional[int] = None
    for mn, mx, rows, _ in ordered:
        cum += rows
        upper = mx if upper is None else max(upper, mx)
        if cum >= delete_n:
            break
    if cum < delete_n:
        # delete_n exceeds the metadata's row total (racing deletes):
        # everything metadata knows about is deletable
        return int(upper) + 1 if upper is not None else None
    cols = [np.asarray(fetch()) for mn, _, _, fetch in ordered
            if mn <= upper]
    col = cols[0] if len(cols) == 1 else np.concatenate(cols)
    k = delete_n - 1
    return int(np.partition(col, k)[k])


class RetentionMonitor:
    """Capacity-based retention, one round per `tick()` call.

    Reference semantics (plugins/clickhouse-monitor/main.go:258-320 and
    Helm defaults values.yaml:16-30): every interval, if used/total >
    threshold, find the timeInserted boundary below which the oldest
    `delete_percentage` of rows fall, delete rows older than the boundary
    from the flows table and all materialized views, then skip
    `skip_rounds` rounds after a successful deletion.
    """

    def __init__(self, db: "FlowDatabase", capacity_bytes: int,
                 threshold: float = 0.5, delete_percentage: float = 0.5,
                 skip_rounds: int = 3) -> None:
        self.db = db
        self.capacity_bytes = capacity_bytes
        self.threshold = threshold
        self.delete_percentage = delete_percentage
        self.skip_rounds = skip_rounds
        self._remaining_skip = 0
        #: cumulative resident bytes freed by demoting parts to the
        #: cold tier instead of deleting rows (parts engine only)
        self.bytes_demoted = 0

    def usage(self) -> float:
        return self.db.flows.nbytes / float(self.capacity_bytes)

    def tick(self) -> int:
        """Run one monitor round; returns number of flow rows deleted.

        Tiered retention (parts engine): over-threshold rounds first
        DEMOTE the oldest hot parts to the cold (disk) tier — data is
        preserved, resident bytes fall — and only delete rows when
        demotion alone cannot reach the threshold (no part directory,
        or everything already cold). The boundary for the delete comes
        from part/batch min-max metadata (retention_boundary — O(parts)),
        not a full-column sort."""
        if self._remaining_skip > 0:
            self._remaining_skip -= 1
            return 0
        if self.usage() <= self.threshold:
            return 0
        demote = getattr(self.db, "demote_cold", None)
        if callable(demote):
            freed = int(demote(
                int(self.capacity_bytes * self.threshold)))
            if freed:
                self.bytes_demoted += freed
                _M_RET_DEMOTED.inc(freed)
                if self.usage() <= self.threshold:
                    self._remaining_skip = self.skip_rounds
                    return 0
        flows = self.db.flows
        n = len(flows)
        if n == 0:
            return 0
        delete_n = int(n * self.delete_percentage)
        if delete_n == 0:
            return 0
        # timeInserted of the latest row to delete (LIMIT 1 OFFSET n-1,
        # main.go:301-318); delete strictly-older rows like the
        # reference's `timeInserted < boundary`.
        boundary = None
        rb = getattr(flows, "retention_boundary", None)
        if callable(rb):
            boundary = rb(delete_n)
        if boundary is None:
            t = np.asarray(flows.scan()["timeInserted"])
            boundary = int(np.partition(t, delete_n - 1)[delete_n - 1])
        deleted = self.db.delete_flows_older_than(int(boundary))
        if deleted:
            self._remaining_skip = self.skip_rounds
            _M_RET_DELETED.inc(deleted)
            _M_DEL_ROWS.labels(reason="retention").inc(deleted)
        return deleted


class RetentionLoop:
    """Supervised background driver for RetentionMonitor — the role of
    the reference's clickhouse-monitor sidecar loop
    (plugins/clickhouse-monitor/main.go:83-101: a ticker that runs a
    monitor round forever). The monitor itself stays a pure
    one-round-per-tick object; this loop owns the thread, the
    schedule, and the failure policy:

      * one `tick()` per THEIA_RETENTION_INTERVAL seconds (injectable
        for tests via `interval`/`run_once()` — no sleeping tests);
      * a FAILED round (e.g. every replica down mid-trim) backs off
        with the shared `capped_backoff` schedule instead of hammering
        a broken store every interval; the first clean round resets
        the cadence;
      * rounds / rows-deleted / failures are counted here (and as
        metrics), surfaced through `stats()` on GET /healthz.
    """

    def __init__(self, monitor: RetentionMonitor,
                 interval: Optional[float] = None,
                 backoff_cap: float = 300.0) -> None:
        self.monitor = monitor
        self.interval = (env_float("THEIA_RETENTION_INTERVAL", 60.0)
                         if interval is None else float(interval))
        self.backoff_cap = backoff_cap
        self.rounds = 0
        self.rows_deleted = 0
        self.failures = 0
        self.consecutive_failures = 0
        self.current_delay = self.interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="theia-retention")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=15)

    def _loop(self) -> None:
        while not self._stop.wait(self.current_delay):
            self.run_once()

    def run_once(self) -> int:
        """One supervised round; returns rows deleted (0 on a failed
        round). Public so tests drive the schedule synchronously."""
        try:
            deleted = self.monitor.tick()
        except Exception as e:   # a bad round must not kill the loop
            self.failures += 1
            self.consecutive_failures += 1
            self.current_delay = capped_backoff(
                max(self.interval, 0.001) * 2, self.backoff_cap,
                self.consecutive_failures)
            _M_RET_ROUNDS.labels(result="error").inc()
            _logger.error(
                "retention round failed (%d consecutive): %s; "
                "backing off %.1fs", self.consecutive_failures, e,
                self.current_delay)
            return 0
        if self.consecutive_failures:
            _logger.info("retention recovered after %d failed rounds",
                         self.consecutive_failures)
        self.consecutive_failures = 0
        self.current_delay = self.interval
        self.rounds += 1
        self.rows_deleted += deleted
        _M_RET_ROUNDS.labels(
            result="trimmed" if deleted else "idle").inc()
        if deleted:
            _logger.info("retention trimmed %d rows (usage %.1f%%)",
                         deleted, self.monitor.usage() * 100)
        return deleted

    def stats(self) -> Dict[str, object]:
        """Operator view (merged into GET /healthz)."""
        try:
            usage = self.monitor.usage()
        except Exception:
            usage = float("nan")
        return {
            "rounds": self.rounds,
            "rowsDeleted": self.rows_deleted,
            "bytesDemoted": getattr(self.monitor, "bytes_demoted", 0),
            "failures": self.failures,
            "intervalSeconds": self.interval,
            "capacityBytes": self.monitor.capacity_bytes,
            "usagePercent": round(usage * 100, 2),
        }


def payload_digest(payload: Mapping[str, np.ndarray]) -> int:
    """Content checksum over a snapshot payload (every key except the
    integrity stamp itself) — defense in depth over the zip
    container's per-member CRCs: one whole-payload value that covers
    cross-member consistency (a member replaced or dropped with the
    container left valid) and survives a future non-zip snapshot
    format. Object (string-table) arrays hash their joined utf-8
    contents in one pass, so the digest is stable across a save/load
    round trip and costs far less than the compression beside it."""
    crc = 0
    for key in sorted(payload):
        if key == INTEGRITY_KEY:
            continue
        arr = np.asarray(payload[key])
        crc = zlib.crc32(key.encode("utf-8"), crc)
        if arr.dtype == object:
            blob = "\x1f".join(map(str, arr.reshape(-1).tolist()))
            crc = zlib.crc32(blob.encode("utf-8", "surrogatepass"),
                             crc)
        else:
            crc = zlib.crc32(arr.dtype.str.encode("ascii"), crc)
            crc = zlib.crc32(np.ascontiguousarray(arr).tobytes(), crc)
    return crc & 0xFFFFFFFF


def write_snapshot(path: str, payload: Dict[str, np.ndarray],
                   compress: bool = True,
                   wal_lsns: Optional[Sequence[int]] = None) -> None:
    """Publish a snapshot: stamp schema version, WAL LSNs, and an
    integrity footer; write to a same-directory temp file; keep the
    previous good snapshot as `<path>.prev`; then atomically replace.
    A crash at ANY point leaves either the previous or the new
    complete snapshot reachable (possibly only as .prev — the loader
    falls back)."""
    from .migration import CURRENT_SCHEMA_VERSION, force
    force(payload, CURRENT_SCHEMA_VERSION)
    if wal_lsns is not None:
        payload[WAL_LSNS_KEY] = np.asarray(list(wal_lsns), np.int64)
    payload[INTEGRITY_KEY] = np.asarray(payload_digest(payload),
                                        np.int64)
    writer = np.savez_compressed if compress else np.savez
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-", suffix=".npz")
    os.close(fd)
    try:
        writer(tmp, **payload)
        if os.path.exists(path):
            os.replace(path, path + ".prev")
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def read_snapshot(path: str) -> Dict[str, np.ndarray]:
    """Load + verify a snapshot. A primary that fails verification
    (bad zip, short file, digest mismatch) falls back — loudly, with
    a metric — to `<path>.prev` instead of crashing or silently
    starting empty; FileNotFoundError propagates only when neither
    file exists (the caller's fresh-start signal)."""
    def _load(p: str) -> Dict[str, np.ndarray]:
        with np.load(p, allow_pickle=True) as z:
            payload = {k: z[k] for k in z.files}
        stored = payload.get(INTEGRITY_KEY)
        if stored is not None and \
                int(np.asarray(stored)) != payload_digest(payload):
            raise SnapshotCorruption(
                f"snapshot {p} failed integrity verification "
                f"(digest mismatch)")
        return payload

    prev = path + ".prev"
    try:
        return _load(path)
    except FileNotFoundError:
        if os.path.exists(prev):
            _logger.error(
                "snapshot %s missing but %s exists (crash between "
                "prev-rotation and publish?) — loading the previous "
                "snapshot", path, prev)
            _M_SNAP_FALLBACK.inc()
            return _load(prev)
        raise
    except Exception as e:
        if os.path.exists(prev):
            _logger.error(
                "snapshot %s failed verification (%s: %s) — falling "
                "back to previous good snapshot %s",
                path, type(e).__name__, e, prev)
            _M_SNAP_FALLBACK.inc()
            try:
                return _load(prev)
            except Exception:
                raise e
        raise


class FlowDatabase:
    """The full database: flows + views + result tables + retention.

    `ttl_seconds` mirrors the reference's `TTL timeInserted + INTERVAL ...`
    (default 12 HOUR, values.yaml:80); eviction runs opportunistically on
    insert (the MergeTree merge equivalent).
    """

    def __init__(self, ttl_seconds: Optional[int] = None,
                 engine: Optional[str] = None,
                 parts_dir: Optional[str] = None,
                 parts_config: Optional[Dict[str, object]] = None
                 ) -> None:
        from .parts import PartTable, default_store_engine
        self.engine = (engine or default_store_engine()).strip().lower()
        if self.engine not in ("flat", "parts"):
            raise ValueError(
                f"unknown store engine {self.engine!r} "
                f"(THEIA_STORE_ENGINE): expected flat|parts")
        if self.engine == "parts":
            cfg = dict(parts_config or {})
            if parts_dir is None and "directory" not in cfg:
                # env fallback for a directly-constructed single
                # store; sharded/replicated wrappers resolve the env
                # themselves and pass per-shard/per-replica subdirs
                parts_dir = os.environ.get("THEIA_STORE_COLD_DIR") \
                    or None
            if parts_dir is not None:
                cfg.setdefault("directory", parts_dir)
            self.flows: Table = PartTable("flows", FLOW_SCHEMA, **cfg)
            # Serializes (flows insert + view fan-out) against the
            # parts-aware snapshot: the snapshot persists VIEW
            # aggregates (flat rebuilds them from rows at load), so
            # the capture must not land between a flows append and
            # its view apply — a row ≤ the stamp would then be
            # missing from the recovered views forever.
            from .wal import _Latch
            self._ingest_latch: Optional[object] = _Latch(
                "store.ingest_latch")
        else:
            self.flows = Table("flows", FLOW_SCHEMA)
            self._ingest_latch = None
        self.result_tables: Dict[str, Table] = {
            name: (self._make_metrics_table()
                   if name == METRICS_TABLE else Table(name, schema))
            for name, schema in RESULT_TABLE_SCHEMAS}
        self.tadetector = self.result_tables["tadetector"]
        self.recommendations = self.result_tables["recommendations"]
        self.dropdetection = self.result_tables["dropdetection"]
        self.flowpatterns = self.result_tables["flowpatterns"]
        self.spatialnoise = self.result_tables["spatialnoise"]
        self.views: Dict[str, ViewTable] = {
            name: ViewTable(name, spec, self.flows.dicts)
            for name, spec in MATERIALIZED_VIEWS.items()}
        # Streaming rollup views (query/rollup.py): declarative
        # aggregate views maintained incrementally per insert block
        # into parts-backed `__rollup__:<view>` tables. Deliberately
        # OUTSIDE result_tables: rollup state is derived from the
        # journaled flows rows (the WAL-invisible PR-13 contract), so
        # it must not get a WAL hook — replaying flows records
        # re-derives it through this same insert path. Lazy import:
        # the query package is a read-plane consumer of this module.
        from ..query.rollup import RollupManager
        self.rollups = RollupManager(self)
        self.ttl_seconds = ttl_seconds
        #: attached WriteAheadLog (None = snapshot-only durability)
        self._wal = None
        #: per-log WAL stamps read from the loaded snapshot (empty =
        #: fresh store or pre-WAL snapshot); attach_wal replays above
        #: these
        self._snapshot_lsns: List[int] = []
        #: (stream, seq, rows) dedup tags recovered from replayed WAL
        #: records — the ingest layer seeds its dedup window from
        #: these so a producer retrying across a crash stays
        #: exactly-once
        self._recovered_acks: List[tuple] = []

    @staticmethod
    def _make_metrics_table():
        """The `__metrics__` history table: parts-backed REGARDLESS of
        the flows engine (sealed sorted parts are what make windowed
        history queries prune and the downsampler's tier surgery
        atomic), memory-resident (no directory — durability rides the
        WAL + snapshot like every result table), sorted
        time,metric,labels with `resolution` in the per-part min/max
        so rollup tiers prune and EXPLAIN can name them."""
        from .parts import PartTable
        return PartTable(
            METRICS_TABLE, METRICS_SCHEMA,
            sort_key=("timeInserted", "metric", "labels"),
            time_column="timeInserted",
            prune_columns=("timeInserted", "resolution"))

    # -- ingest ------------------------------------------------------------

    def insert_flows(self, batch: ColumnarBatch,
                     now: Optional[int] = None,
                     dedup: Optional[tuple] = None,
                     wire: Optional[memoryview] = None) -> int:
        """Insert a flow batch; fan out to materialized views; evict
        TTL. `dedup=(stream, seq)` journals the producer's batch
        identity with the rows; `wire` (a received TBLK column
        section for exactly these rows) makes the WAL journal the
        producer's bytes verbatim (see Table.insert)."""
        latch = self._ingest_latch
        with (latch.read() if latch is not None
              else contextlib.nullcontext()):
            return self._insert_flows_inner(batch, now, dedup, wire)

    def _insert_flows_inner(self, batch: ColumnarBatch,
                            now: Optional[int],
                            dedup: Optional[tuple],
                            wire: Optional[memoryview] = None) -> int:
        # fires once per PHYSICAL store: once per replica in a
        # replicated fan-out, once per resync re-insert
        _fire_fault("store.insert", table="flows")
        adopted = self.flows.insert(batch, dedup=dedup, wire=wire)
        if adopted is None:
            return 0
        # Views consume the adopted (store-coded) batch so their group
        # keys share the store dictionaries. The three aggregations are
        # independent and the native group-sum releases the GIL, so fan
        # out in parallel for large blocks (ClickHouse runs MV pipelines
        # per insert block concurrently too).
        views = list(self.views.values())
        t_mv = time.perf_counter()
        if (len(adopted) >= 16384 and len(views) > 1
                and (os.cpu_count() or 1) > 2):
            # Parallel only where cores exist (TPU hosts); on small
            # boxes the three aggregations just fight over one core.
            list(_view_pool().map(
                lambda v: v.apply_insert_block(adopted), views))
        else:
            for view in views:
                view.apply_insert_block(adopted)
        _M_MV_FANOUT.observe(time.perf_counter() - t_mv)
        rollups = getattr(self, "rollups", None)
        if rollups is not None and rollups.active:
            # rollup views fold the same adopted block (and recovery
            # replays reach here too, re-deriving identical state)
            rollups.apply_insert_block(adopted)
        _M_INS_ROWS.inc(len(adopted))
        _M_INS_BYTES.inc(sum(a.nbytes
                             for a in adopted.columns.values()))
        if self.ttl_seconds is not None:
            now = int(now if now is not None
                      else np.max(adopted["timeInserted"]))
            self.evict_ttl(now)
        return len(adopted)

    def insert_flow_rows(self, rows, now: Optional[int] = None) -> int:
        return self.insert_flows(
            ColumnarBatch.from_rows(rows, FLOW_SCHEMA, self.flows.dicts),
            now=now)

    @property
    def rows_inserted_total(self) -> int:
        """Cumulative flow rows ever inserted (monotone — deletes do
        not decrease it); the insert-rate substrate."""
        return self.flows.rows_inserted_total

    @property
    def bytes_inserted_total(self) -> int:
        return self.flows.bytes_inserted_total

    # -- storage engine ----------------------------------------------------

    def store_stats(self) -> Dict[str, object]:
        """Engine + tier summary for /healthz `store` and the parts
        gauges on /metrics."""
        doc: Dict[str, object] = {
            "engine": self.engine,
            "flowRows": len(self.flows),
            "flowBytes": self.flows.nbytes,
        }
        ps = getattr(self.flows, "parts_stats", None)
        if callable(ps):
            doc["parts"] = ps()
        return doc

    def demote_cold(self, target_bytes: int) -> int:
        """Demote the oldest hot parts to the cold (disk) tier until
        resident flow bytes fall to `target_bytes` (0 on the flat
        engine, which has no tiering). The retention monitor's
        delete-avoidance step."""
        fn = getattr(self.flows, "demote_oldest", None)
        return int(fn(target_bytes)) if callable(fn) else 0

    def maintenance_tick(self) -> int:
        """One background-compaction pass over the flows table (parts
        engine; 0 merges on flat) plus rollup-view maintenance
        (config hot reload, tier downsampling cascade, rollup-part
        compaction — the rollup tables are parts-backed regardless of
        the flows engine). Driven by PartMaintenanceLoop."""
        fn = getattr(self.flows, "maintain", None)
        merges = int(fn()) if callable(fn) else 0
        rollups = getattr(self, "rollups", None)
        if rollups is not None and rollups.active:
            merges += rollups.maintain()
        return merges

    # -- write-ahead log ---------------------------------------------------

    def attach_wal(self, wal_dir: str, sync: Optional[str] = None,
                   segment_bytes: Optional[int] = None
                   ) -> Dict[str, object]:
        """Recover from and then journal into a WAL at `wal_dir`:
        replay surviving records above the loaded snapshot's stamp,
        open the append side, install the insert-path hooks, and adopt
        any log content left by a different store topology. Returns
        the replay stats."""
        stamps = self._snapshot_lsns
        stats = self._attach_wal_at(
            wal_dir, stamps[0] if stamps else 0, sync, segment_bytes)
        from .wal import adopt_foreign_wal_dirs
        adopted = adopt_foreign_wal_dirs(self, wal_dir, [wal_dir],
                                         stamps)
        if adopted:
            stats["adoptedRows"] = adopted
        return stats

    def _attach_wal_at(self, wal_dir: str, stamp: int,
                       sync: Optional[str] = None,
                       segment_bytes: Optional[int] = None
                       ) -> Dict[str, object]:
        """Core attach (no foreign-topology scan): replay → open →
        hook. Split out so ShardedFlowDatabase can attach one log per
        shard with per-shard stamps."""
        from .wal import WriteAheadLog, orphan_segments
        if self._wal is not None:
            raise RuntimeError("WAL already attached")
        if stamp <= 0 and (len(self.flows) or any(
                len(t) for t in self.result_tables.values())):
            # Lineage break: this store holds rows from a snapshot
            # that carries NO WAL stamp (saved by a run with the WAL
            # off), yet segments survive here. No LSN can partition
            # those records into in-snapshot vs to-replay — replaying
            # them would duplicate rows — so quarantine them for the
            # operator instead.
            orphaned = orphan_segments(wal_dir)
            if orphaned:
                _logger.error(
                    "WAL %s: %d segments predate an UNSTAMPED "
                    "snapshot (a run without --wal-dir saved over a "
                    "journaled store); renamed to *.orphaned instead "
                    "of replaying them into rows the snapshot may "
                    "already hold", wal_dir, len(orphaned))
        wal = WriteAheadLog(wal_dir, sync=sync,
                            segment_bytes=segment_bytes)
        stats = wal.replay(self._replay_record, above_lsn=stamp)
        wal.open(min_next_lsn=stamp + 1)
        self._wal = wal
        for t in (self.flows, *self.result_tables.values()):
            t._wal_hook = wal.logged_apply
        return stats

    def _replay_record(self, table: str, batch) -> None:
        """Apply one recovered WAL record. Runs before the hooks are
        installed, so nothing re-journals; flows go through the full
        insert path (views, TTL) exactly like live ingest. A dedup tag
        in the record's table field restores the producer's ack to
        `_recovered_acks` — rows and idempotency recover together."""
        from .wal import split_dedup_tag
        table, tag = split_dedup_tag(table)
        if tag is not None:
            self._recovered_acks.append((tag[0], tag[1], len(batch),
                                         tag[2]))
        if table == "flows":
            self.insert_flows(batch)
        elif table in self.result_tables:
            self.result_tables[table].insert(batch)
        else:
            _logger.error("WAL record for unknown table %r dropped "
                          "(%d rows)", table, len(batch))

    def note_recovered_ack(self, stream: str, seq: int, rows: int,
                           total: Optional[int] = None) -> None:
        """Record an acknowledged (stream, seq) recovered outside the
        normal replay path (foreign-topology WAL adoption)."""
        self._recovered_acks.append((stream, int(seq), int(rows),
                                     total))

    def recovered_acks(self) -> List[tuple]:
        """(stream, seq, recovered_rows, logical_total) tags restored
        from WAL replay — the ingest layer's dedup-window seed after a
        crash. recovered_rows < logical_total means part of the batch
        was not durable at the crash (possible for sharded stores
        under interval sync — slices fsync independently); the seeder
        logs that loudly."""
        return list(self._recovered_acks)

    def wal_lag(self) -> int:
        """Records appended but not yet fsynced (0 without a WAL) —
        the admission plane's syncedLsn-lag pressure signal."""
        wal = self._wal
        return 0 if wal is None else wal.lag_records

    @contextlib.contextmanager
    def wal_suspended(self):
        """Temporarily disable journaling (replica resync re-inserts
        state that is already durable on the peer — re-logging it
        would corrupt the LSN sequence)."""
        tables = (self.flows, *self.result_tables.values())
        saved = [t._wal_hook for t in tables]
        for t in tables:
            t._wal_hook = None
        try:
            yield
        finally:
            for t, hook in zip(tables, saved):
                t._wal_hook = hook

    def wal_stats(self) -> Optional[Dict[str, object]]:
        wal = self._wal
        return None if wal is None else wal.stats()

    def wal_position(self) -> Optional[int]:
        """Last appended LSN (None when no WAL attached)."""
        wal = self._wal
        return None if wal is None else wal.last_lsn

    def wal_reposition(self, position) -> None:
        """Jump the log forward to a resync peer's position."""
        wal = self._wal
        if wal is not None and position is not None:
            if isinstance(position, (list, tuple)):
                position = position[0] if position else 0
            wal.reposition(int(position))

    def wal_sync(self) -> None:
        wal = self._wal
        if wal is not None:
            wal.sync()

    def wal_gc(self, stamp) -> int:
        """GC segments wholly covered by a snapshot stamped at
        `stamp` (the value save() returned)."""
        wal = self._wal
        if wal is None or stamp is None:
            return 0
        if isinstance(stamp, (list, tuple)):
            stamp = stamp[0] if stamp else 0
        return wal.gc_below(int(stamp))

    def close_wal(self) -> None:
        """Final fsync + detach (part of graceful shutdown)."""
        wal = self._wal
        if wal is None:
            return
        for t in (self.flows, *self.result_tables.values()):
            t._wal_hook = None
        self._wal = None
        wal.close()

    # -- cluster replication (log shipping; theia_tpu/cluster) -------------
    #
    # The cluster tier replicates THIS store by shipping its WAL to
    # follower nodes and applying the frames verbatim on their side —
    # every method below requires an attached WAL (--wal-dir) and an
    # UNWRAPPED FlowDatabase (cross-node replication replaces the
    # in-process --replicas fan-out; cross-node sharding is the ingest
    # router's job, replacing --shards).

    def wal_read_frames(self, above_lsn: int,
                        max_bytes: int = 1 << 20):
        """(frames, last_lsn, algo) above `above_lsn` — the leader's
        shipper read. Raises WalShipGap when the follower is beyond
        frame catch-up (→ resync)."""
        from .wal import WalError
        wal = self._wal
        if wal is None:
            raise WalError(
                "cluster replication requires an attached WAL "
                "(--wal-dir)")
        return wal.read_frames(above_lsn, max_bytes=max_bytes)

    def wal_handshake(self) -> Dict[str, object]:
        """This store's log-matching position: the follower reports it
        on /cluster/ping; the leader verifies it against its own log
        before streaming (crc mismatch / unknown → resync)."""
        wal = self._wal
        if wal is None:
            return {"lsn": 0, "crc": None}
        return {"lsn": wal.last_lsn, "crc": wal.last_body_crc}

    def wal_body_crc_at(self, lsn: int):
        wal = self._wal
        return None if wal is None else wal.body_crc_at(lsn)

    def apply_replicated_frames(self, data: bytes,
                                algo: int) -> Dict[str, object]:
        """Follower-side log shipping: append each shipped frame
        VERBATIM to this store's own WAL (leader LSNs preserved — the
        follower's log is a byte-identical continuation, so standard
        replay recovers it to an exact leader position), then apply the
        record to memory, per record, under the same durability-first
        discipline as live ingest. Frames at or below the current
        position (duplicate ship after a reconnect) are skipped.
        Returns {"ackedLsn", "rows", "acks"}: `acks` carries the dedup
        tags seen, so the caller seeds the live dedup window — a
        producer retrying against this node after a failover collects
        duplicate:true instead of double-inserting."""
        from .wal import (WalError, decode_record_body, iter_frames,
                          split_dedup_tag)
        wal = self._wal
        if wal is None:
            raise WalError(
                "cluster replication requires an attached WAL "
                "(--wal-dir)")
        rows = 0
        applied = 0
        acks: List[tuple] = []
        with self.wal_suspended():
            for lsn, frame, body in iter_frames(data, algo):
                if lsn <= wal.last_lsn:
                    continue
                table, batch = decode_record_body(bytes(body))
                table, tag = split_dedup_tag(table)
                if tag is not None:
                    acks.append((tag[0], tag[1], len(batch), tag[2]))

                def _apply(table=table, batch=batch):
                    if table == "flows":
                        self.insert_flows(batch)
                    elif table in self.result_tables:
                        self.result_tables[table].insert(batch)
                    else:
                        _logger.error(
                            "replicated record for unknown table %r "
                            "dropped (%d rows)", table, len(batch))

                if wal.shipped_apply(lsn, frame, body, algo, _apply):
                    applied += 1
                    rows += len(batch)
        wal.policy_sync()
        return {"ackedLsn": wal.last_lsn, "rows": rows,
                "applied": applied, "acks": acks}

    def resync_export(self, chunk_rows: int = 65536):
        """Leader-side wholesale catch-up capture: (position,
        position_crc, record-body iterator). Captured under the WAL
        quiesce latch, so `position` exactly covers the captured rows;
        the (cheap) ref capture happens inside, the encoding outside.
        Sealed cold parts ship their file bodies verbatim (PR-7 part
        manifest catch-up); everything else encodes from scan refs."""
        from .wal import encode_record_body
        wal = self._wal
        ctx = wal.quiesce() if wal is not None \
            else contextlib.nullcontext()
        with ctx:
            position = wal.last_lsn if wal is not None else 0
            position_crc = wal.last_body_crc if wal is not None else 0
            flows = self.flows
            if hasattr(flows, "_snapshot_refs"):
                flows_cap = flows._snapshot_refs()
            else:
                flows_cap = flows.scan()
            results = {name: t.scan()
                       for name, t in self.result_tables.items()
                       if len(t)}

        def records():
            if isinstance(flows_cap, tuple):
                parts, mem = flows_cap
                yield from self.flows.export_encoded_records(
                    parts, mem, chunk_rows)
            else:
                for i in range(0, len(flows_cap), chunk_rows):
                    idx = np.arange(i, min(i + chunk_rows,
                                           len(flows_cap)))
                    yield encode_record_body("flows",
                                             flows_cap.take(idx))
            for name, batch in results.items():
                for i in range(0, len(batch), chunk_rows):
                    idx = np.arange(i, min(i + chunk_rows, len(batch)))
                    yield encode_record_body(name, batch.take(idx))

        return position, position_crc, records()

    def resync_apply(self, records, position: int,
                     position_crc) -> int:
        """Follower-side wholesale catch-up: truncate, apply each
        self-contained record body, then RESET the WAL to the leader's
        position (the old records no longer describe this memory; any
        divergent tail worth re-ingesting was extracted by the caller
        first — wal_tail_tagged_records). Until the next checkpoint
        covers the copied rows, a crash re-runs the resync (loud,
        correct). Returns rows applied."""
        from .wal import decode_record_body, split_dedup_tag
        rows = 0
        with self.wal_suspended():
            self.flows.truncate()
            for view in self.views.values():
                view.truncate()
            if self.rollups is not None:
                # re-derived below: every applied flows record runs
                # the full insert path, rollup fold included
                self.rollups.truncate_all()
            for t in self.result_tables.values():
                t.truncate()
            for body in records:
                table, batch = decode_record_body(bytes(body))
                table, _tag = split_dedup_tag(table)
                if table == "flows":
                    self.insert_flows(batch)
                elif table in self.result_tables:
                    self.result_tables[table].insert(batch)
                else:
                    _logger.error(
                        "resync record for unknown table %r dropped "
                        "(%d rows)", table, len(batch))
                rows += len(batch)
        wal = self._wal
        if wal is not None:
            wal.reset_to(int(position), position_crc)
        return rows

    def wal_tail_tagged_records(self, above_lsn: int) -> List[tuple]:
        """(stream, seq, body) for every DEDUP-TAGGED flows record
        above `above_lsn` in this store's log — the demoted leader's
        unacked tail. The rejoining node re-posts these through the
        new leader's /ingest with their original (stream, seq): batches
        the cluster already acknowledged resolve duplicate:true via the
        dedup window; genuinely unreplicated ones land — instead of
        duplicating or silently dropping the tail. Untagged records
        (job results, synth seeds) stay at-least-once and are not
        re-posted."""
        from .wal import (_SEG_HEADER, _SEG_MAGIC, _SEG_VERSION,
                          decode_record_body, iter_frames,
                          split_dedup_tag)
        wal = self._wal
        if wal is None:
            return []
        out: List[tuple] = []
        # direct segment walk (not read_frames): checkpoint GC has
        # usually removed the oldest segments of a long-lived leader,
        # and the tail that matters is whatever SURVIVES — a gap at
        # the front must not abort the extraction
        with wal._io:
            segs = wal._list_segments()
        for _first, path in segs:
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                continue
            if len(data) < _SEG_HEADER.size:
                continue
            magic, ver, algo, _, _f = _SEG_HEADER.unpack_from(data, 0)
            if magic != _SEG_MAGIC or ver != _SEG_VERSION:
                continue
            for lsn, _frame, body in iter_frames(
                    data[_SEG_HEADER.size:], algo):
                if lsn <= above_lsn:
                    continue
                body = bytes(body)
                try:
                    table, _batch = decode_record_body(body)
                except Exception:
                    continue
                table, tag = split_dedup_tag(table)
                if table == "flows" and tag is not None:
                    out.append((tag[0], tag[1], body))
        return out

    # -- retention ---------------------------------------------------------

    def evict_ttl(self, now: int) -> int:
        if self.ttl_seconds is None:
            return 0
        boundary = now - self.ttl_seconds
        # Fast path: nothing evictable — min() over parts is O(parts),
        # not a full-table concat, so steady ingest stays O(batch).
        oldest = self.flows.min_value("timeInserted")
        if oldest is None or oldest >= boundary:
            return 0
        deleted = self.delete_flows_older_than(boundary)
        if deleted:
            _M_DEL_ROWS.labels(reason="ttl").inc(deleted)
        return deleted

    def delete_flows_older_than(self, boundary: int) -> int:
        """timeInserted < boundary, applied to flows and every view
        (monitor main.go:284-293 deletes from table + MVs)."""
        deleted = self.flows.delete_older_than(boundary)
        for view in self.views.values():
            view.delete_older_than(boundary)
        rollups = getattr(self, "rollups", None)
        if rollups is not None and rollups.active:
            # whole buckets below the trim drop with their parts;
            # boundary-straddling buckets re-derive from the
            # SURVIVING raw rows so rollup answers track the trim
            # exactly
            rollups.apply_delete(boundary)
        return deleted

    def monitor(self, capacity_bytes: int, **kw) -> RetentionMonitor:
        return RetentionMonitor(self, capacity_bytes, **kw)

    # -- persistence -------------------------------------------------------

    def save(self, path: str, tables: Optional[Sequence[str]] = None,
             compress: bool = True) -> Optional[int]:
        """Persist tables to one .npz (columns + dictionary tables),
        stamped with the current schema version (store/migration.py).

        `tables` restricts the snapshot (e.g. result tables only for a
        job's write-back); `compress=False` trades disk for CPU —
        right for short-lived job snapshots, wrong for durable
        checkpoints. The write is ATOMIC (temp file + rename) and
        keeps the previous snapshot as `<path>.prev`: a crash mid-save
        never tears an existing snapshot, and a later-corrupted
        primary still has a verified fallback.

        With a WAL attached, a FULL snapshot quiesces appends while it
        stamps the log position and scans the tables (so the stamp is
        exact), and returns that stamp — the caller passes it to
        `wal_gc()` once the snapshot is known durable. Partial
        (tables=...) snapshots stamp nothing: they are not recovery
        points.

        Parts engine with a part directory: the sealed parts SUBSUME
        the bulk of the snapshot. The npz carries only the memtable
        rows, result tables, dictionaries, and view aggregates; the
        sealed parts stay on disk behind a generational manifest
        published atomically (with a `.prev` fallback pair, lag-one
        with the npz — the PR-4 GC discipline), so a checkpoint costs
        O(memtable), not O(table), and recovery is manifest load +
        WAL tail replay."""
        wal = self._wal
        flows = self.flows
        parts_aware = (tables is None
                       and getattr(flows, "directory", None)
                       and hasattr(flows, "snapshot_parts_state"))
        if not parts_aware:
            if wal is not None and tables is None:
                with wal.quiesce():
                    stamp = wal.last_lsn
                    payload = self._snapshot_payload(tables)
            else:
                stamp = None
                payload = self._snapshot_payload(tables)
            write_snapshot(
                path, payload, compress=compress,
                wal_lsns=[stamp] if stamp is not None else None)
            return stamp
        # The ingest latch (writer side) excludes in-flight
        # insert_flows across BOTH legs (flows append + view apply);
        # the WAL quiesce additionally freezes result-table appends so
        # the stamp partitions every table's records exactly.
        with contextlib.ExitStack() as stack:
            if self._ingest_latch is not None:
                stack.enter_context(self._ingest_latch.write())
            if wal is not None:
                stack.enter_context(wal.quiesce())
            stamp = wal.last_lsn if wal is not None else None
            entries, payload = flows.snapshot_parts_state()
            for table in self.result_tables.values():
                data = table.scan()
                for col in table.schema:
                    payload[f"{table.name}/{col.name}"] = data[col.name]
            for table in (flows, *self.result_tables.values()):
                for name, d in table.dicts.items():
                    payload[f"{table.name}/__dict__/{name}"] = \
                        np.asarray(d._strings, dtype=object)
            for name, view in self.views.items():
                keys, values = view._merged()
                payload[f"__view__/{name}/keys"] = keys
                payload[f"__view__/{name}/values"] = values
            rollups = getattr(self, "rollups", None)
            if rollups is not None and rollups.active:
                # rollup aggregates persist like the view aggregates
                # (captured under the same latch, so the stamp
                # partitions flows records exactly); flat snapshots
                # skip this — their load rebuilds through the insert
                # path
                payload.update(rollups.snapshot_payload())
        gen = flows.publish_manifest(entries, stamp)
        payload["__parts__/generation"] = np.asarray(gen, np.int64)
        payload["__parts__/dir"] = np.asarray(
            os.path.abspath(flows.directory), dtype=object)
        write_snapshot(path, payload, compress=compress,
                       wal_lsns=[stamp] if stamp is not None else None)
        flows.gc_part_files()
        return stamp

    def _snapshot_payload(self, tables: Optional[Sequence[str]] = None
                          ) -> Dict[str, np.ndarray]:
        payload: Dict[str, np.ndarray] = {}
        for table in (self.flows, *self.result_tables.values()):
            if tables is not None and table.name not in tables:
                continue
            data = table.scan()
            for col in table.schema:
                payload[f"{table.name}/{col.name}"] = data[col.name]
            for name, d in table.dicts.items():
                payload[f"{table.name}/__dict__/{name}"] = np.asarray(
                    d._strings, dtype=object)
        return payload

    @classmethod
    def load(cls, path: str,
             ttl_seconds: Optional[int] = None,
             build_views: bool = True,
             engine: Optional[str] = None,
             parts_dir: Optional[str] = None,
             parts_config: Optional[Dict[str, object]] = None
             ) -> "FlowDatabase":
        """Load a persisted database, migrating older schema versions
        up to current first (the reference's schema-management init
        container runs before the server the same way).

        build_views=False skips materialized-view fan-out — for callers
        that immediately re-insert the rows elsewhere (sharded load)
        and would otherwise pay the O(rows) view build twice.

        A parts-aware snapshot (engine=parts with a part directory)
        loads as: manifest adoption (parts register LAZILY — metadata
        resident, columns decoded on first touch) + memtable rows +
        restored view aggregates. An unloadable manifest generation
        falls back — loudly, with the snapshot-fallback metric — to
        the `<path>.prev` snapshot and ITS manifest generation, which
        the lag-one part/WAL GC keeps recoverable."""
        from .parts import PartsManifestError
        payload = read_snapshot(path)
        try:
            return cls._from_payload(payload, ttl_seconds, build_views,
                                     engine, parts_dir, parts_config)
        except PartsManifestError as e:
            prev = path + ".prev"
            if not os.path.exists(prev):
                raise
            _logger.error(
                "snapshot %s pairs with an unloadable part manifest "
                "(%s) — falling back to previous snapshot %s",
                path, e, prev)
            _M_SNAP_FALLBACK.inc()
            payload = read_snapshot(prev)
            return cls._from_payload(payload, ttl_seconds, build_views,
                                     engine, parts_dir, parts_config)

    @classmethod
    def _from_payload(cls, payload: Dict[str, np.ndarray],
                      ttl_seconds: Optional[int],
                      build_views: bool,
                      engine: Optional[str],
                      parts_dir: Optional[str],
                      parts_config: Optional[Dict[str, object]]
                      ) -> "FlowDatabase":
        from .migration import migrate
        from .parts import PartTable
        parts_gen = payload.get("__parts__/generation")
        if parts_gen is not None and parts_dir is None and \
                "__parts__/dir" in payload:
            # The snapshot records the EXACT directory its manifest
            # generation lives in — a replica/shard subdir, not the
            # THEIA_STORE_COLD_DIR base — so the recorded path beats
            # the env var here (a replicated restart with the env set
            # would otherwise look for manifest.json one level up and
            # fail). Callers relocating data pass parts_dir
            # explicitly.
            parts_dir = str(np.asarray(
                payload["__parts__/dir"]).item())
        if parts_gen is not None and engine is None and \
                not os.environ.get("THEIA_STORE_ENGINE"):
            # a parts-aware snapshot self-describes its engine when
            # neither the caller nor the environment says otherwise
            engine = "parts"
        db = cls(ttl_seconds=None, engine=engine, parts_dir=parts_dir,
                 parts_config=parts_config)
        if WAL_LSNS_KEY in payload:
            db._snapshot_lsns = [
                int(v) for v in np.asarray(payload[WAL_LSNS_KEY])]
        migrate(payload)
        if parts_gen is not None and \
                not isinstance(db.flows, PartTable):
            # Cross-engine load (parts snapshot → flat store, the
            # engine-flip escape hatch): materialize through a donor
            # parts database, then feed the rows down the flat path.
            donor = cls._from_payload(payload, None, False, "parts",
                                      parts_dir, parts_config)
            flows = donor.flows.scan()
            if len(flows):
                if build_views:
                    db.insert_flows(flows)
                else:
                    db.flows.insert(flows)
            for name, src in donor.result_tables.items():
                data = src.scan()
                if len(data):
                    db.result_tables[name].insert(data)
            db.ttl_seconds = ttl_seconds
            return db
        for table in (db.flows, *db.result_tables.values()):
            cols: Dict[str, np.ndarray] = {}
            for name, d in table.dicts.items():
                key = f"{table.name}/__dict__/{name}"
                if key in payload:
                    for s in payload[key]:
                        d.encode_one(str(s))
            for col in table.schema:
                key = f"{table.name}/{col.name}"
                if key in payload:
                    cols[col.name] = payload[key]
            if table is db.flows and parts_gen is not None:
                # manifest parts first (insertion order), then the
                # npz-carried memtable tail — no seal, no view work
                # (views restore below); raises PartsManifestError
                # for the caller's .prev fallback
                db.flows.load_manifest(int(np.asarray(parts_gen)))
                if cols and len(next(iter(cols.values()))):
                    n = len(next(iter(cols.values())))
                    batch = ColumnarBatch(
                        {c.name: cols.get(c.name, np.zeros(
                            n, c.host_dtype)) for c in table.schema},
                        table.dicts)
                    db.flows._append_adopted(batch, seal=False)
                continue
            if cols and len(next(iter(cols.values()))):
                batch = ColumnarBatch(
                    {c.name: cols.get(c.name, np.zeros(
                        len(next(iter(cols.values()))), c.host_dtype))
                     for c in table.schema}, table.dicts)
                if table is db.flows and build_views:
                    db.insert_flows(batch)
                else:
                    table.insert(batch)
        if parts_gen is not None and build_views:
            restored = 0
            for name, view in db.views.items():
                kk = f"__view__/{name}/keys"
                vk = f"__view__/{name}/values"
                if kk in payload and vk in payload:
                    view.restore(payload[kk], payload[vk])
                    restored += 1
            if restored < len(db.views) and len(db.flows):
                # older/partial parts snapshot without view payloads:
                # rebuild the aggregates from the rows (the flat-load
                # discipline — decodes every part once)
                data = db.flows.scan()
                for view in db.views.values():
                    view.truncate()
                    view.apply_insert_block(data)
            if db.rollups.active:
                # rollup aggregates: restore views whose persisted
                # definition still matches; rebuild the rest from the
                # loaded flows (definition drift / older snapshot)
                db.rollups.restore_or_rebuild(payload)
        db.ttl_seconds = ttl_seconds
        return db
