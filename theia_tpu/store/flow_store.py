"""In-memory columnar flow database — the framework's L1 storage tier.

Plays the role ClickHouse plays in the reference (tables declared in
build/charts/theia/provisioning/datasources/create_table.sh): a `flows`
table receiving high-rate inserts, three streaming materialized views
(pod/node/policy — create_table.sh:92-351), result tables for the analytics
jobs (`tadetector` create_table.sh:363-384, `recommendations` :353-360),
TTL-based eviction (:87-88) and a retention monitor that trims the oldest
fraction of rows when a capacity threshold is exceeded (reference:
plugins/clickhouse-monitor/main.go:258-320).

Design (TPU-first): tables are append-logs of equal-schema `ColumnarBatch`es
sharing one dictionary set owned by the table, so any time-window selection
is a zero-copy concat + boolean mask over fixed-width arrays, ready for
`jax.device_put`. Materialized views are maintained *incrementally* on
insert as integer-keyed segment sums (the SummingMergeTree equivalent),
keeping the read path for dashboards O(view rows), not O(flow rows).
"""

from __future__ import annotations

import concurrent.futures
import os
import threading
import time
from typing import Dict, List, Mapping, Optional, Sequence

import numpy as np

from ..schema import (
    DROPDETECTION_SCHEMA,
    FLOW_SCHEMA,
    FLOWPATTERNS_SCHEMA,
    RECOMMENDATIONS_SCHEMA,
    SPATIALNOISE_SCHEMA,
    TADETECTOR_SCHEMA,
    ColumnarBatch,
    DictionaryMapper,
    StringDictionary,
)

#: analytics result tables, in declaration order — the single list the
#: store, sharded facade, stats, persistence, and job GC iterate
RESULT_TABLE_SCHEMAS = (
    ("tadetector", TADETECTOR_SCHEMA),
    ("recommendations", RECOMMENDATIONS_SCHEMA),
    ("dropdetection", DROPDETECTION_SCHEMA),
    ("flowpatterns", FLOWPATTERNS_SCHEMA),
    ("spatialnoise", SPATIALNOISE_SCHEMA),
)
from ..obs import metrics as _metrics
from ..utils.backoff import capped_backoff
from ..utils.env import env_float
from ..utils.faults import fire as _fire_fault
from ..utils.logging import get_logger
from ..utils.pool import get_pool
from .views import MATERIALIZED_VIEWS, ViewTable

_logger = get_logger("store")

_M_INS_ROWS = _metrics.counter(
    "theia_store_inserted_rows_total",
    "Flow rows inserted, cumulative over every physical store in the "
    "process (a replicated fan-out counts once per replica)")
_M_INS_BYTES = _metrics.counter(
    "theia_store_inserted_bytes_total",
    "Column bytes of inserted flow rows (store-coded), cumulative per "
    "physical store")
_M_DEL_ROWS = _metrics.counter(
    "theia_store_deleted_rows_total",
    "Flow rows deleted by TTL eviction or retention trims",
    labelnames=("reason",))
_M_MV_FANOUT = _metrics.histogram(
    "theia_store_mv_fanout_seconds",
    "Materialized-view fan-out time per inserted block (all views)")
_M_RET_ROUNDS = _metrics.counter(
    "theia_retention_rounds_total",
    "Retention-monitor rounds, by outcome",
    labelnames=("result",))
_M_RET_DELETED = _metrics.counter(
    "theia_retention_rows_deleted_total",
    "Flow rows trimmed by capacity-based retention rounds")


def _view_pool() -> concurrent.futures.ThreadPoolExecutor:
    """Shared pool for parallel MV fan-out (native group-sum releases
    the GIL, so the three aggregations genuinely overlap)."""
    return get_pool("mv-fanout", 4)


class Table:
    """Append-only columnar table with store-owned dictionaries.

    All inserted batches are re-encoded (if necessary) against the table's
    dictionaries, so codes are comparable across the whole table and string
    predicates compile to integer comparisons.
    """

    def __init__(self, name: str, schema) -> None:
        self.name = name
        self.schema = schema
        self.dicts: Dict[str, StringDictionary] = {
            c.name: StringDictionary() for c in schema if c.is_string}
        self._batches: List[ColumnarBatch] = []
        self._lock = threading.Lock()
        #: monotonic mutation counter (inserts AND deletes) — the
        #: checkpointer's change detector; row counts alone can't see
        #: same-size churn (TTL evicts N, ingest adds N)
        self.generation = 0
        # Cumulative insert totals (rows / store-coded column bytes),
        # maintained under the table lock. Unlike net table size these
        # never decrease, so insert-rate stats based on them survive
        # retention trims (deletes used to mask real throughput).
        self.rows_inserted_total = 0
        self.bytes_inserted_total = 0
        # Cached source-dict → table-dict code mappings: a producer
        # streaming blocks with its own dictionaries pays string
        # re-encode only for NEW entries, not per block (the 6.6x
        # per-block store overhead of BENCH_r04).
        self._adopt_maps: Dict[str, DictionaryMapper] = {
            name: DictionaryMapper(d) for name, d in self.dicts.items()}
        self._adopt_lock = threading.Lock()

    def __len__(self) -> int:
        return sum(len(b) for b in self._batches)

    @property
    def nbytes(self) -> int:
        return sum(v.nbytes for b in self._batches
                   for v in b.columns.values())

    def _adopt(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Re-encode a batch against this table's dictionaries
        (cached incremental mappings: amortized O(new dict entries)
        per block, not O(dictionary))."""
        cols: Dict[str, np.ndarray] = {}
        for col in self.schema:
            arr = batch[col.name]
            if col.is_string:
                src = batch.dicts.get(col.name)
                if src is None:
                    raise ValueError(
                        f"string column {col.name} has no dictionary")
                if src is not self.dicts[col.name]:
                    with self._adopt_lock:
                        arr = self._adopt_maps[col.name].remap(arr, src)
            else:
                arr = np.asarray(arr, dtype=col.host_dtype)
            cols[col.name] = arr
        return ColumnarBatch(cols, self.dicts)

    def insert(self, batch: ColumnarBatch) -> Optional[ColumnarBatch]:
        """Insert a batch; returns the adopted (store-coded) batch, or
        None when empty, so callers can fan out the exact inserted block
        without re-reading the append log under concurrency."""
        if len(batch) == 0:
            return None
        adopted = self._adopt(batch)
        nbytes = sum(a.nbytes for a in adopted.columns.values())
        with self._lock:
            self._batches.append(adopted)
            self.generation += 1
            self.rows_inserted_total += len(adopted)
            self.bytes_inserted_total += nbytes
        return adopted

    def insert_rows(self, rows: Sequence[Mapping[str, object]]) -> int:
        if not rows:
            return 0
        adopted = self.insert(
            ColumnarBatch.from_rows(rows, self.schema, self.dicts))
        return 0 if adopted is None else len(adopted)

    def scan(self) -> ColumnarBatch:
        """Whole-table view as one batch (concat of the append log).

        Compacts the log as a side effect; the swap only happens if no
        insert raced in between (otherwise the next scan compacts)."""
        with self._lock:
            batches = list(self._batches)
        if not batches:
            return ColumnarBatch(
                {c.name: np.zeros(0, c.host_dtype) for c in self.schema},
                self.dicts)
        if len(batches) == 1:
            return batches[0]
        merged = ColumnarBatch.concat(batches)
        with self._lock:
            if len(self._batches) == len(batches) and \
                    self._batches[-1] is batches[-1]:
                self._batches = [merged]
        return merged

    def select(self, start_time: Optional[int] = None,
               end_time: Optional[int] = None,
               time_column: str = "flowStartSeconds",
               end_column: str = "flowEndSeconds") -> ColumnarBatch:
        """Time-window select, mirroring the jobs' SQL predicates
        (`flowStartSeconds >= start AND flowEndSeconds < end`, reference
        policy_recommendation_job.py:796-798)."""
        data = self.scan()
        if start_time is None and end_time is None:
            return data
        mask = np.ones(len(data), dtype=bool)
        if start_time is not None:
            mask &= data[time_column] >= start_time
        if end_time is not None:
            mask &= data[end_column] < end_time
        return data.filter(mask)

    def delete_where(self, mask: np.ndarray) -> int:
        """Delete rows matching `mask` over the current table contents.
        Runs entirely under the table lock so a concurrent insert can
        neither be dropped nor half-filtered."""
        with self._lock:
            return self._delete_where_locked(mask)

    def _delete_where_locked(self, mask: np.ndarray) -> int:
        """Body of delete_where; caller must hold self._lock (the
        sharded store holds every shard's lock to apply one logical
        mask atomically across shards)."""
        if not self._batches:
            if len(mask) != 0:
                raise ValueError(
                    f"mask length {len(mask)} != table length 0")
            return 0
        data = (self._batches[0] if len(self._batches) == 1
                else ColumnarBatch.concat(self._batches))
        if len(mask) != len(data):
            raise ValueError(
                f"mask length {len(mask)} != table length {len(data)}")
        if not mask.any():
            # No mutation → no generation bump: a spurious bump makes
            # the checkpointer rewrite an unchanged snapshot.
            return 0
        kept = data.filter(~mask)
        self._batches = [kept] if len(kept) else []
        self.generation += 1
        return int(mask.sum())

    def delete_ids(self, ids, column: str = "id",
                   invert: bool = False) -> int:
        """Value-based delete: rows whose `column` decodes into `ids`
        (or does NOT, with invert=True). Safe wherever a positional
        mask is not — replicas and shards hold the same logical rows
        in different physical orders. Computed under the table lock."""
        with self._lock:
            if not self._batches:
                return 0
            data = (self._batches[0] if len(self._batches) == 1
                    else ColumnarBatch.concat(self._batches))
            mask = np.isin(data.strings(column), list(ids))
            if invert:
                mask = ~mask
            return self._delete_where_locked(mask)

    def delete_older_than(self, boundary: int,
                          column: str = "timeInserted") -> int:
        """Atomic `column < boundary` delete (mask computed under the
        lock, so it cannot race with inserts)."""
        with self._lock:
            if not self._batches:
                return 0
            data = (self._batches[0] if len(self._batches) == 1
                    else ColumnarBatch.concat(self._batches))
            mask = np.asarray(data[column]) < boundary
            if not mask.any():
                self._batches = [data]
                return 0
            kept = data.filter(~mask)
            self._batches = [kept] if len(kept) else []
            self.generation += 1
        return int(mask.sum())

    def min_value(self, column: str = "timeInserted") -> Optional[int]:
        """Min over a column without concatenating (None when empty)."""
        with self._lock:
            batches = list(self._batches)
        mins = [int(b[column].min()) for b in batches if len(b)]
        return min(mins) if mins else None

    def truncate(self) -> None:
        with self._lock:
            self._batches = []
            self.generation += 1


class RetentionMonitor:
    """Capacity-based retention, one round per `tick()` call.

    Reference semantics (plugins/clickhouse-monitor/main.go:258-320 and
    Helm defaults values.yaml:16-30): every interval, if used/total >
    threshold, find the timeInserted boundary below which the oldest
    `delete_percentage` of rows fall, delete rows older than the boundary
    from the flows table and all materialized views, then skip
    `skip_rounds` rounds after a successful deletion.
    """

    def __init__(self, db: "FlowDatabase", capacity_bytes: int,
                 threshold: float = 0.5, delete_percentage: float = 0.5,
                 skip_rounds: int = 3) -> None:
        self.db = db
        self.capacity_bytes = capacity_bytes
        self.threshold = threshold
        self.delete_percentage = delete_percentage
        self.skip_rounds = skip_rounds
        self._remaining_skip = 0

    def usage(self) -> float:
        return self.db.flows.nbytes / float(self.capacity_bytes)

    def tick(self) -> int:
        """Run one monitor round; returns number of flow rows deleted."""
        if self._remaining_skip > 0:
            self._remaining_skip -= 1
            return 0
        if self.usage() <= self.threshold:
            return 0
        flows = self.db.flows.scan()
        n = len(flows)
        if n == 0:
            return 0
        delete_n = int(n * self.delete_percentage)
        if delete_n == 0:
            return 0
        t = np.sort(np.asarray(flows["timeInserted"]))
        # timeInserted of the latest row to delete (LIMIT 1 OFFSET n-1,
        # main.go:301-318); delete strictly-older rows like the reference's
        # `timeInserted < boundary`.
        boundary = t[delete_n - 1]
        deleted = self.db.delete_flows_older_than(int(boundary))
        if deleted:
            self._remaining_skip = self.skip_rounds
            _M_RET_DELETED.inc(deleted)
            _M_DEL_ROWS.labels(reason="retention").inc(deleted)
        return deleted


class RetentionLoop:
    """Supervised background driver for RetentionMonitor — the role of
    the reference's clickhouse-monitor sidecar loop
    (plugins/clickhouse-monitor/main.go:83-101: a ticker that runs a
    monitor round forever). The monitor itself stays a pure
    one-round-per-tick object; this loop owns the thread, the
    schedule, and the failure policy:

      * one `tick()` per THEIA_RETENTION_INTERVAL seconds (injectable
        for tests via `interval`/`run_once()` — no sleeping tests);
      * a FAILED round (e.g. every replica down mid-trim) backs off
        with the shared `capped_backoff` schedule instead of hammering
        a broken store every interval; the first clean round resets
        the cadence;
      * rounds / rows-deleted / failures are counted here (and as
        metrics), surfaced through `stats()` on GET /healthz.
    """

    def __init__(self, monitor: RetentionMonitor,
                 interval: Optional[float] = None,
                 backoff_cap: float = 300.0) -> None:
        self.monitor = monitor
        self.interval = (env_float("THEIA_RETENTION_INTERVAL", 60.0)
                         if interval is None else float(interval))
        self.backoff_cap = backoff_cap
        self.rounds = 0
        self.rows_deleted = 0
        self.failures = 0
        self.consecutive_failures = 0
        self.current_delay = self.interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="theia-retention")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=15)

    def _loop(self) -> None:
        while not self._stop.wait(self.current_delay):
            self.run_once()

    def run_once(self) -> int:
        """One supervised round; returns rows deleted (0 on a failed
        round). Public so tests drive the schedule synchronously."""
        try:
            deleted = self.monitor.tick()
        except Exception as e:   # a bad round must not kill the loop
            self.failures += 1
            self.consecutive_failures += 1
            self.current_delay = capped_backoff(
                max(self.interval, 0.001) * 2, self.backoff_cap,
                self.consecutive_failures)
            _M_RET_ROUNDS.labels(result="error").inc()
            _logger.error(
                "retention round failed (%d consecutive): %s; "
                "backing off %.1fs", self.consecutive_failures, e,
                self.current_delay)
            return 0
        if self.consecutive_failures:
            _logger.info("retention recovered after %d failed rounds",
                         self.consecutive_failures)
        self.consecutive_failures = 0
        self.current_delay = self.interval
        self.rounds += 1
        self.rows_deleted += deleted
        _M_RET_ROUNDS.labels(
            result="trimmed" if deleted else "idle").inc()
        if deleted:
            _logger.info("retention trimmed %d rows (usage %.1f%%)",
                         deleted, self.monitor.usage() * 100)
        return deleted

    def stats(self) -> Dict[str, object]:
        """Operator view (merged into GET /healthz)."""
        try:
            usage = self.monitor.usage()
        except Exception:
            usage = float("nan")
        return {
            "rounds": self.rounds,
            "rowsDeleted": self.rows_deleted,
            "failures": self.failures,
            "intervalSeconds": self.interval,
            "capacityBytes": self.monitor.capacity_bytes,
            "usagePercent": round(usage * 100, 2),
        }


class FlowDatabase:
    """The full database: flows + views + result tables + retention.

    `ttl_seconds` mirrors the reference's `TTL timeInserted + INTERVAL ...`
    (default 12 HOUR, values.yaml:80); eviction runs opportunistically on
    insert (the MergeTree merge equivalent).
    """

    def __init__(self, ttl_seconds: Optional[int] = None) -> None:
        self.flows = Table("flows", FLOW_SCHEMA)
        self.result_tables: Dict[str, Table] = {
            name: Table(name, schema)
            for name, schema in RESULT_TABLE_SCHEMAS}
        self.tadetector = self.result_tables["tadetector"]
        self.recommendations = self.result_tables["recommendations"]
        self.dropdetection = self.result_tables["dropdetection"]
        self.flowpatterns = self.result_tables["flowpatterns"]
        self.spatialnoise = self.result_tables["spatialnoise"]
        self.views: Dict[str, ViewTable] = {
            name: ViewTable(name, spec, self.flows.dicts)
            for name, spec in MATERIALIZED_VIEWS.items()}
        self.ttl_seconds = ttl_seconds

    # -- ingest ------------------------------------------------------------

    def insert_flows(self, batch: ColumnarBatch,
                     now: Optional[int] = None) -> int:
        """Insert a flow batch; fan out to materialized views; evict TTL."""
        # fires once per PHYSICAL store: once per replica in a
        # replicated fan-out, once per resync re-insert
        _fire_fault("store.insert", table="flows")
        adopted = self.flows.insert(batch)
        if adopted is None:
            return 0
        # Views consume the adopted (store-coded) batch so their group
        # keys share the store dictionaries. The three aggregations are
        # independent and the native group-sum releases the GIL, so fan
        # out in parallel for large blocks (ClickHouse runs MV pipelines
        # per insert block concurrently too).
        views = list(self.views.values())
        t_mv = time.perf_counter()
        if (len(adopted) >= 16384 and len(views) > 1
                and (os.cpu_count() or 1) > 2):
            # Parallel only where cores exist (TPU hosts); on small
            # boxes the three aggregations just fight over one core.
            list(_view_pool().map(
                lambda v: v.apply_insert_block(adopted), views))
        else:
            for view in views:
                view.apply_insert_block(adopted)
        _M_MV_FANOUT.observe(time.perf_counter() - t_mv)
        _M_INS_ROWS.inc(len(adopted))
        _M_INS_BYTES.inc(sum(a.nbytes
                             for a in adopted.columns.values()))
        if self.ttl_seconds is not None:
            now = int(now if now is not None
                      else np.max(adopted["timeInserted"]))
            self.evict_ttl(now)
        return len(adopted)

    def insert_flow_rows(self, rows, now: Optional[int] = None) -> int:
        return self.insert_flows(
            ColumnarBatch.from_rows(rows, FLOW_SCHEMA, self.flows.dicts),
            now=now)

    @property
    def rows_inserted_total(self) -> int:
        """Cumulative flow rows ever inserted (monotone — deletes do
        not decrease it); the insert-rate substrate."""
        return self.flows.rows_inserted_total

    @property
    def bytes_inserted_total(self) -> int:
        return self.flows.bytes_inserted_total

    # -- retention ---------------------------------------------------------

    def evict_ttl(self, now: int) -> int:
        if self.ttl_seconds is None:
            return 0
        boundary = now - self.ttl_seconds
        # Fast path: nothing evictable — min() over parts is O(parts),
        # not a full-table concat, so steady ingest stays O(batch).
        oldest = self.flows.min_value("timeInserted")
        if oldest is None or oldest >= boundary:
            return 0
        deleted = self.delete_flows_older_than(boundary)
        if deleted:
            _M_DEL_ROWS.labels(reason="ttl").inc(deleted)
        return deleted

    def delete_flows_older_than(self, boundary: int) -> int:
        """timeInserted < boundary, applied to flows and every view
        (monitor main.go:284-293 deletes from table + MVs)."""
        deleted = self.flows.delete_older_than(boundary)
        for view in self.views.values():
            view.delete_older_than(boundary)
        return deleted

    def monitor(self, capacity_bytes: int, **kw) -> RetentionMonitor:
        return RetentionMonitor(self, capacity_bytes, **kw)

    # -- persistence -------------------------------------------------------

    def save(self, path: str, tables: Optional[Sequence[str]] = None,
             compress: bool = True) -> None:
        """Persist tables to one .npz (columns + dictionary tables),
        stamped with the current schema version (store/migration.py).

        `tables` restricts the snapshot (e.g. result tables only for a
        job's write-back); `compress=False` trades disk for CPU —
        right for short-lived job snapshots, wrong for durable
        checkpoints. The write is ATOMIC (temp file + rename): a crash
        mid-save never tears an existing snapshot."""
        from ..utils import atomic_write
        from .migration import CURRENT_SCHEMA_VERSION, force
        payload: Dict[str, np.ndarray] = {}
        for table in (self.flows, *self.result_tables.values()):
            if tables is not None and table.name not in tables:
                continue
            data = table.scan()
            for col in table.schema:
                payload[f"{table.name}/{col.name}"] = data[col.name]
            for name, d in table.dicts.items():
                payload[f"{table.name}/__dict__/{name}"] = np.asarray(
                    d._strings, dtype=object)
        force(payload, CURRENT_SCHEMA_VERSION)
        writer = np.savez_compressed if compress else np.savez
        atomic_write(path, lambda tmp: writer(tmp, **payload),
                     suffix=".npz")

    @classmethod
    def load(cls, path: str,
             ttl_seconds: Optional[int] = None,
             build_views: bool = True) -> "FlowDatabase":
        """Load a persisted database, migrating older schema versions
        up to current first (the reference's schema-management init
        container runs before the server the same way).

        build_views=False skips materialized-view fan-out — for callers
        that immediately re-insert the rows elsewhere (sharded load)
        and would otherwise pay the O(rows) view build twice."""
        from .migration import migrate
        db = cls(ttl_seconds=None)
        with np.load(path, allow_pickle=True) as z:
            payload = {k: z[k] for k in z.files}
        migrate(payload)
        for table in (db.flows, *db.result_tables.values()):
            cols: Dict[str, np.ndarray] = {}
            for name, d in table.dicts.items():
                key = f"{table.name}/__dict__/{name}"
                if key in payload:
                    for s in payload[key]:
                        d.encode_one(str(s))
            for col in table.schema:
                key = f"{table.name}/{col.name}"
                if key in payload:
                    cols[col.name] = payload[key]
            if cols and len(next(iter(cols.values()))):
                batch = ColumnarBatch(
                    {c.name: cols.get(c.name, np.zeros(
                        len(next(iter(cols.values()))), c.host_dtype))
                     for c in table.schema}, table.dicts)
                if table is db.flows and build_views:
                    db.insert_flows(batch)
                else:
                    table.insert(batch)
        db.ttl_seconds = ttl_seconds
        return db
